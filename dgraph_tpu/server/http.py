"""HTTP API server — the Alpha's public surface.

Endpoint map mirrors the reference (dgraph/cmd/alpha/run.go:415-436):

    POST /query     GraphQL± query; body is DQL text or JSON
                    {"query": ..., "variables": {...}}
                    ?explain=true|plan|analyze attaches the compiled
                    plan tree (+ measured actuals for analyze) under
                    extensions.explain — same as the in-query
                    `@explain` directive
                    (ref dgraph/cmd/alpha/http.go:162 queryHandler)
    POST /mutate    RDF or JSON mutation; ?commitNow=true commits
                    immediately, otherwise the response's
                    extensions.txn.start_ts names the open txn
                    (ref http.go:298 mutationHandler)
    POST /commit    ?startTs=N finishes a txn; ?abort=true discards
                    (ref http.go:446 commitHandler)
    POST /alter     schema text, or JSON {"drop_all": true} /
                    {"drop_attr": "name"} (ref http.go:528 alterHandler)
    GET  /health    liveness probe (ref x/health.go)
    GET  /state     cluster/engine introspection (ref edgraph/server.go:602)
    GET  /admin/schema        current schema text
    POST /admin/schema        same as /alter with schema text
    GET  /debug/prometheus_metrics   metrics text format (x/metrics.go)
    GET  /debug/stats         the always-on statistics plane: full
                              per-predicate tablet statistics, the
                              observed-cost store, engine cache states
                              (tools/dgtop.py polls this)

Transactions over HTTP are keyed by startTs exactly like the reference's
stateless protocol: /mutate without commitNow returns start_ts, the
client replays it to /mutate (more writes) or /commit.

Concurrency: a ThreadingHTTPServer front end over a reader-writer
lock — queries (MVCC snapshot reads) share the read side, mutations /
commits / alters take the write side, so a slow analytical query no
longer serializes the whole server (the reference gets the same shape
from goroutines + per-list RWMutex, posting/list.go). A small `meta`
mutex guards the txn table and ACL cache; lock order is rw -> meta,
never the reverse. Rollup (folds MVCC overlays — a write) is kept OFF
the read path (db.rollup_in_read=False) and runs throttled from the
write path instead.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from dgraph_tpu.cdc.changelog import OffsetTruncated
from dgraph_tpu.cluster.coordinator import TxnAborted
from dgraph_tpu.engine.db import GraphDB, Mutation, Txn
from dgraph_tpu.server.acl import AclError
from dgraph_tpu.utils import metrics, reqlog, tracing
from dgraph_tpu.utils.logger import log
from dgraph_tpu.utils.reqctx import (
    Cancelled, DeadlineExceeded, Overloaded, RequestContext,
)

# startTs -> open server-side txn (the reference keeps this state in the
# client + oracle; our engine txns are server objects, so the server maps)
_MAX_OPEN_TXNS = 4096


class AlphaServer:
    """Engine + txn table behind the HTTP front end."""

    def __init__(self, db: Optional[GraphDB] = None,
                 txn_ttl_s: float = 300.0,
                 acl_secret: Optional[bytes] = None,
                 mutations_mode: str = "allow",
                 max_pending: int = 0,
                 batch_window_us: int = 0,
                 tenant_rate: float = 0.0,
                 tenant_burst: float = 0.0):
        if mutations_mode not in ("allow", "disallow", "strict"):
            raise ValueError(
                "--mutations argument must be one of allow, disallow, "
                "or strict")
        # ref --mutations (alpha/run.go:502): disallow rejects every
        # mutation and alter; strict rejects mutations naming
        # predicates with no schema entry (worker/mutation.go:693)
        self.mutations_mode = mutations_mode
        self.db = db or GraphDB()
        from dgraph_tpu.utils.rwlock import RWLock
        self.rw = RWLock()
        self.meta = threading.RLock()
        # concurrent readers must not trigger rollup (it rewrites the
        # tablet base arrays); the write path folds instead
        self.db.rollup_in_read = False
        self._commits_since_rollup = 0
        # draining: reject writes, keep serving reads (ref x/health.go
        # drainingMode + /admin/draining handler, alpha/admin.go)
        self.draining = False
        # admission control (ref edgraph/server.go pending-query
        # throttle answering RESOURCE_EXHAUSTED): a bounded in-flight
        # gauge over every work-bearing endpoint. 0 = unbounded.
        # Excess load sheds with HTTP 429 (retryable) instead of
        # queuing unboundedly in the thread-per-request front end.
        self.max_pending = max_pending
        self._admission = threading.Lock()
        self._inflight = 0
        # per-tenant QoS layered UNDER max_pending (server/qos.py):
        # one hot tenant exhausts its own token bucket and degrades
        # to 429s while the shared in-flight budget stays available
        # to every other tenant. 0 = off.
        self.qos = None
        if tenant_rate > 0:
            from dgraph_tpu.server.qos import TenantQos
            self.qos = TenantQos(rate=tenant_rate, burst=tenant_burst)
        # trace id -> live RequestContexts, for /admin/cancel. A LIST:
        # trace ids are client-chosen, so an impatient retry can put
        # two live requests under one id — cancel hits them all, and
        # each request removes only its own handle on exit
        self._live_ctx: dict[str, list[RequestContext]] = {}
        self.txns: dict[int, Txn] = {}
        self._touched: dict[int, float] = {}
        # startTs -> userid that opened the txn (ACL mode only): /commit
        # must not let one login commit/abort another login's txn
        self._txn_owner: dict[int, str] = {}
        self.txn_ttl_s = txn_ttl_s
        # monotonic: /health uptime is a DURATION — an NTP step must
        # not make it jump (same for the txn idle clocks below)
        self.started_at = time.monotonic()
        # server-side micro-batching (engine/batcher.py): concurrent
        # best-effort queries sharing a plan-cache key coalesce into
        # one dispatch under ONE read-lock hold. 0 = off.
        self.batcher = None
        if batch_window_us > 0:
            from dgraph_tpu.engine.batcher import MicroBatcher
            self.batcher = MicroBatcher(
                self.db, window_us=batch_window_us,
                read_lock=lambda: self.rw.read)
        # ACL enforcement turns on when a secret is configured
        # (ref --acl_secret_file, dgraph/cmd/alpha/run.go flags)
        self.acl = None
        if acl_secret is not None:
            from dgraph_tpu.server.acl import AclManager
            self.acl = AclManager(self.db, acl_secret)

    def handle_login(self, body: dict) -> dict:
        if self.acl is None:
            raise ValueError("ACL is not enabled on this server")
        with self.meta:
            return {"data": self.acl.login(
                userid=body.get("userid", ""),
                password=body.get("password", ""),
                refresh_token=body.get("refresh_token", ""))}

    def _evict_idle(self):
        """Abort txns idle past the TTL (ref --abort_older_than,
        worker/draft.go:1166 abortOldTransactions)."""
        now = time.monotonic()
        for ts, t in list(self._touched.items()):
            if now - t > self.txn_ttl_s:
                txn = self.txns.pop(ts, None)
                self._touched.pop(ts, None)
                self._txn_owner.pop(ts, None)
                if txn is not None:
                    self.db.discard(txn)

    def _check_txn_owner(self, start_ts: int, claims: dict | None):
        """ACL mode: only the login that opened a txn (or a guardian)
        may touch it by startTs — they are guessable sequential ints
        (advisor finding; ref access_ee.go). Caller holds the lock."""
        if self.acl is None or claims is None:
            return
        from dgraph_tpu.server.acl import GUARDIANS, AclError
        owner = self._txn_owner.get(start_ts)
        if (owner is not None
                and claims.get("userid", "") != owner
                and GUARDIANS not in claims.get("groups", [])):
            raise AclError(
                f"txn at startTs={start_ts} belongs to another user")

    def _maybe_rollup(self, every: int = 16):
        """Throttled overlay fold, called from the write path (caller
        holds the write lock). Replaces lazy rollup-in-read, which is
        unsafe once queries run concurrently."""
        self._commits_since_rollup += 1
        if self._commits_since_rollup >= every:
            self._commits_since_rollup = 0
            self.db.rollup_all()

    @contextmanager
    def _admit(self, ctx: Optional[RequestContext] = None):
        """One admission slot for the duration of a request. Sheds
        with Overloaded (-> 429, retryable) when max_pending slots are
        taken; a request that dies mid-flight (deadline, cancellation,
        any error) releases its slot in the finally. An already-dead
        context is rejected before it takes a slot.

        Tenant QoS runs before the shared gate: a tenant over its own
        rate sheds on its bucket without consuming an in-flight slot
        (untagged requests bill to "default"), so one hot tenant
        degrades to 429s while the rest keep their budget."""
        if ctx is not None:
            ctx.check("admission")
        if self.qos is not None:
            tenant = getattr(ctx, "tenant", "") or "default"
            if not self.qos.admit(tenant):
                metrics.inc_counter("dgraph_tenant_shed_total",
                                    labels={"tenant": tenant})
                raise Overloaded(
                    f"tenant {tenant!r} exceeded its admission rate; "
                    "retry with jittered backoff")
        with self._admission:
            if self.max_pending and self._inflight >= self.max_pending:
                metrics.inc_counter("dgraph_queries_shed_total")
                raise Overloaded(
                    f"server is overloaded: {self._inflight} requests "
                    f"in flight (max_pending={self.max_pending}); "
                    "retry with jittered backoff")
            self._inflight += 1
            metrics.set_gauge("dgraph_pending_queries", self._inflight)
            if ctx is not None:
                self._live_ctx.setdefault(ctx.trace_id, []).append(ctx)
        try:
            yield
        finally:
            with self._admission:
                self._inflight -= 1
                metrics.set_gauge("dgraph_pending_queries",
                                  self._inflight)
                if ctx is not None:
                    live = self._live_ctx.get(ctx.trace_id)
                    if live is not None:
                        if ctx in live:
                            live.remove(ctx)
                        if not live:
                            del self._live_ctx[ctx.trace_id]

    @contextmanager
    def _logged(self, op: str, ctx: Optional[RequestContext]):
        """Feed the /debug/requests ring: the ENGINE records
        successful query/mutate completions (it owns the per-phase
        breakdown), so this edge wrapper records successes only for
        ops the engine never sees (commit/alter) — and EVERY failure,
        with its outcome: a shed request (429) dies right here in
        admission and would otherwise be invisible."""
        t0 = time.perf_counter()
        tid = ctx.trace_id if ctx is not None else ""
        tenant = getattr(ctx, "tenant", "")
        try:
            yield
        except Exception as e:
            reqlog.record(op, trace_id=tid,
                          latency_ms=(time.perf_counter() - t0) * 1e3,
                          outcome=reqlog.outcome_of(e),
                          tenant=tenant)
            raise
        else:
            if op in ("commit", "alter"):
                reqlog.record(
                    op, trace_id=tid,
                    latency_ms=(time.perf_counter() - t0) * 1e3,
                    tenant=tenant)

    def pending(self) -> int:
        with self._admission:
            return self._inflight

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Graceful-drain helper: True once every admitted request has
        finished. Callers enable draining mode first so no new writes
        arrive, then wait here before shutting the engine down."""
        deadline = time.monotonic() + timeout_s
        while True:
            if self.pending() == 0:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def handle_cancel(self, params: dict, token: str = "") -> dict:
        """Cancel an in-flight request by trace id (guardians only
        under ACL). The cooperative flag fires at the executor's next
        block/level boundary and the request dies with 499, freeing
        its admission slot."""
        self._require_guardian(token, "/admin/cancel")
        tid = params.get("traceId", "")
        with self._admission:
            ctxs = list(self._live_ctx.get(tid, ()))
        if not ctxs:
            raise KeyError(f"no in-flight request with traceId={tid!r}")
        for ctx in ctxs:
            ctx.cancel()
        return {"code": "Success",
                "message": f"cancelled {len(ctxs)} request(s) "
                           f"with traceId {tid}"}

    # -- request handlers (transport-independent) --

    def _query_prologue(self, body: dict | str, params: dict,
                        token: str):
        """Shared /query front matter: body shapes, ACL authorization,
        read-only txn attachment."""
        if isinstance(body, dict):
            q = body.get("query", "")
            variables = body.get("variables")
        else:
            q, variables = body, None
        claims = None
        if self.acl is not None:
            from dgraph_tpu.gql import parse as gql_parse
            from dgraph_tpu.server.acl import query_predicates
            with self.meta:
                claims = self.acl.authorize(token)
                self.acl.authorize_query(
                    token, query_predicates(gql_parse(q, variables)),
                    claims=claims)
        ro_txn = None
        pin_ts = None
        start_ts = int(params.get("startTs", 0))
        with self.meta:
            if start_ts:
                self._check_txn_owner(start_ts, claims)
                ro_txn = self.txns.get(start_ts)
                if ro_txn is None:
                    # read-only snapshot at an explicit ts: no open txn
                    # exists for pure reads, so pin the MVCC read
                    # point directly — startTs=T must mean "read at T"
                    # (ref edgraph/server.go attaching ReadTs), not
                    # "allocate something newer"
                    pin_ts = start_ts
        be = params.get("be", "false") == "true"
        return q, variables, ro_txn, \
            (be if ro_txn is None else False), pin_ts

    @staticmethod
    def _explain_param(params: dict) -> Optional[str]:
        """`?explain=true|plan` -> "plan", `?explain=analyze` ->
        "analyze", absent/false -> None (the in-query `@explain`
        directive still applies either way)."""
        raw = str(params.get("explain", "")).lower()
        if raw in ("", "false", "0"):
            return None
        if raw in ("true", "plan"):
            return "plan"
        if raw == "analyze":
            return "analyze"
        raise ValueError(
            f"explain must be true/plan/analyze, got {raw!r}")

    def handle_query(self, body: dict | str, params: dict,
                     token: str = "", ctx=None) -> dict:
        with self._logged("query", ctx), self._admit(ctx):
            q, variables, ro_txn, be, pin_ts = self._query_prologue(
                body, params, token)
            with self.rw.read:
                return self.db.query(q, variables, txn=ro_txn,
                                     best_effort=be, read_ts=pin_ts,
                                     ctx=ctx,
                                     explain=self._explain_param(params))

    def handle_query_json(self, body: dict | str, params: dict,
                          token: str = "", ctx=None) -> str:
        """handle_query returning the serialized response body — flat
        blocks take the native columnar emitter (db.query_json), so
        the HTTP layer never re-serializes what the engine already
        encoded (ref query/outputnode.go fastJsonNode feeding the
        response writer directly)."""
        with self._logged("query", ctx), self._admit(ctx):
            q, variables, ro_txn, be, pin_ts = self._query_prologue(
                body, params, token)
            explain = self._explain_param(params)
            if self.batcher is not None and ro_txn is None \
                    and pin_ts is None and explain is None:
                # snapshot-unpinned, txn-free reads coalesce with
                # concurrent same-plan requests; the batcher takes the
                # read lock itself, once per batch, and serves every
                # member at one shared read_ts drawn from the SAME
                # source an unbatched dispatch would use now (strict:
                # one fresh coordinator ts; best-effort: the
                # watermark) — dispatch follows arrival, so each
                # member still observes every commit that completed
                # before it arrived
                return self.batcher.query_json(q, variables, ctx=ctx,
                                               best_effort=be)
            with self.rw.read:
                return self.db.query_json(q, variables, txn=ro_txn,
                                          best_effort=be,
                                          read_ts=pin_ts, ctx=ctx,
                                          explain=explain)

    def handle_mutate(self, body: bytes, content_type: str,
                      params: dict, token: str = "", ctx=None) -> dict:
        if self.draining:
            raise RuntimeError(
                "the server is in draining mode; write operations are "
                "rejected")
        if self.mutations_mode == "disallow":
            raise ValueError("no mutations allowed")
        with self._logged("mutate", ctx), self._admit(ctx):
            return self._mutate_admitted(body, content_type, params,
                                         token, ctx)

    def _mutate_admitted(self, body: bytes, content_type: str,
                         params: dict, token: str, ctx) -> dict:
        commit_now = params.get("commitNow", "false") == "true"
        start_ts = int(params.get("startTs", 0))
        muts, query, variables = _parse_mutation_body(body, content_type)
        owner = None
        preds: set[str] = set()
        if self.acl is not None or self.mutations_mode == "strict":
            from dgraph_tpu.server.acl import nquad_predicates
            for mut in muts:
                preds |= set(nquad_predicates(
                    mut.set_nquads, mut.del_nquads,
                    mut.set_json, mut.delete_json))
        if self.acl is not None:
            from dgraph_tpu.gql import parse as gql_parse
            from dgraph_tpu.server.acl import query_predicates
            with self.meta:
                claims = self.acl.authorize(token)
                owner = claims.get("userid", "")
                self.acl.authorize_mutation(token, preds, claims=claims)
                if query:
                    self.acl.authorize_query(
                        token,
                        query_predicates(gql_parse(query, variables)),
                        claims=claims)
                if start_ts:
                    # attaching to an existing txn by startTs needs the
                    # same ownership check as /commit — startTs values
                    # are guessable sequential ints
                    self._check_txn_owner(start_ts, claims)
        with self.rw.write:
            if self.mutations_mode == "strict":
                # AFTER authorization (an unauthenticated client must
                # not probe which predicates exist) and UNDER the
                # write lock (a concurrent drop_attr/drop_all must not
                # race this check; ref worker/mutation.go:693 checks
                # in the worker, post-auth)
                for pred in sorted(preds):
                    if not self.db.schema.has(pred.lstrip("~")):
                        raise ValueError(
                            "Schema not defined for predicate: "
                            f"{pred.lstrip('~')}.")
            with self.meta:
                self._evict_idle()
                created = False
                if start_ts:
                    txn = self.txns.get(start_ts)
                    if txn is None:
                        # attach to a ts a previous /query handed out
                        txn = self.db.new_txn_at(start_ts)
                        created = True
                else:
                    txn = self.db.new_txn()
                    created = True
            try:
                out = self.db.mutate(txn, mutations=muts, query=query,
                                     variables=variables,
                                     commit_now=commit_now, ctx=ctx)
            except Exception:
                # a failed mutation aborts the whole txn (fail fast; the
                # reference marks the txn context aborted)
                with self.meta:
                    self.txns.pop(txn.start_ts, None)
                    self._touched.pop(txn.start_ts, None)
                    self._txn_owner.pop(txn.start_ts, None)
                self.db.discard(txn)
                raise
            ext_txn = {"start_ts": txn.start_ts}
            with self.meta:
                if commit_now:
                    self.txns.pop(txn.start_ts, None)
                    self._touched.pop(txn.start_ts, None)
                    self._txn_owner.pop(txn.start_ts, None)
                    if not txn.done:  # all conds failed: discard
                        self.db.discard(txn)
                else:
                    if created and len(self.txns) >= _MAX_OPEN_TXNS:
                        self.db.discard(txn)
                        raise RuntimeError("too many open transactions")
                    self.txns[txn.start_ts] = txn
                    self._touched[txn.start_ts] = time.monotonic()
                    if self.acl is not None and owner is not None:
                        self._txn_owner.setdefault(txn.start_ts, owner)
            if commit_now:
                self._maybe_rollup()
            out.setdefault("extensions", {})["txn"] = ext_txn
            return out

    def handle_commit(self, params: dict, token: str = "",
                      ctx=None) -> dict:
        start_ts = int(params.get("startTs", 0))
        abort = params.get("abort", "false") == "true"
        with self._logged("commit", ctx), self._admit(ctx), \
                self.rw.write:
            with self.meta:
                if self.acl is not None:
                    self._check_txn_owner(start_ts,
                                          self.acl.authorize(token))
                txn = self.txns.pop(start_ts, None)
                self._touched.pop(start_ts, None)
                self._txn_owner.pop(start_ts, None)
            if txn is None:
                raise KeyError(f"no open transaction at startTs={start_ts}")
            if abort:
                self.db.discard(txn)
                return {"code": "Success", "message": "Done",
                        "extensions": {"txn": {"start_ts": start_ts,
                                               "aborted": True}}}
            commit_ts = self.db.commit(txn)
            self._maybe_rollup()
            return {"code": "Success", "message": "Done",
                    "extensions": {"txn": {"start_ts": start_ts,
                                           "commit_ts": commit_ts}}}

    def handle_alter(self, body: bytes, token: str = "",
                     ctx=None) -> dict:
        if self.draining:
            raise RuntimeError(
                "the server is in draining mode; write operations are "
                "rejected")
        if self.mutations_mode == "disallow":
            # the reference gates Alter behind the same check
            # (edgraph/server.go:99 isMutationAllowed)
            raise ValueError("no mutations allowed")
        text = body.decode()
        drop_all = False
        drop_attr = ""
        schema = text
        try:
            j = json.loads(text)
            if isinstance(j, dict):
                drop_all = bool(j.get("drop_all"))
                drop_attr = j.get("drop_attr", "")
                schema = j.get("schema", "")
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
        if self.acl is not None:
            from dgraph_tpu.server.acl import schema_predicates
            preds = [drop_attr] if drop_attr else (
                schema_predicates(schema) if schema else [])
            with self.meta:
                self.acl.authorize_alter(token, preds,
                                         drop=drop_all or bool(drop_attr))
        with self._logged("alter", ctx), self._admit(ctx), \
                self.rw.write:
            self.db.alter(schema_text=schema, drop_all=drop_all,
                          drop_attr=drop_attr, ctx=ctx)
        return {"code": "Success", "message": "Done"}

    def handle_state(self, token: str = "") -> dict:
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)  # any valid login may inspect
        with self.rw.read:
            return self.db.state()

    def handle_traces(self, token: str = "",
                      params: Optional[dict] = None) -> dict:
        """Recent spans as a Chrome trace (load in chrome://tracing /
        Perfetto). `?trace_id=` narrows to one trace's node-local
        slice — collect the same id from every node and stitch with
        tools/trace_merge.py for the cluster-wide timeline.
        ACL-gated like /state: span args carry query shapes."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        from dgraph_tpu.utils.tracing import export_chrome_trace
        tid = (params or {}).get("trace_id") or None
        return {"traceEvents": export_chrome_trace(trace_id=tid)}

    def handle_subscribe(self, params: dict, token: str = "") -> dict:
        """GET /subscribe?pred=&offset=&waitMs=&limit=&id= — the CDC
        long-poll surface (cdc/changelog.py). Returns entries with
        offset > `offset` (at-least-once, resumable); an empty batch
        after waitMs is a heartbeat. A stale offset (below the log
        floor) raises OffsetTruncated — the HTTP edge maps it to 410
        with the re-sync coordinates. ACL: subscribing to a predicate
        is reading it. No admission slot: a long-poll parks a thread,
        not the engine — it must not starve query admission."""
        pred = params.get("pred", "")
        if not pred:
            raise ValueError("subscribe needs ?pred=")
        if self.acl is not None:
            with self.meta:
                self.acl.authorize_query(token, [pred])
        return self.db.cdc.read(
            pred,
            after=int(params.get("offset", 0)),
            limit=int(params.get("limit", 256)),
            wait_s=int(params.get("waitMs", 0)) / 1000.0,
            sub_id=str(params.get("id", "")))

    def handle_debug_stats(self, token: str = "") -> dict:
        """/debug/stats: the always-on statistics plane — every
        resident tablet's full statistics (storage/tabstats.py), the
        observed-cost summaries (utils/coststore.py), metrics
        histogram state, and the engine cache states. ACL-gated like
        /state: predicate names and fan-out shapes are data-shaped."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        # no rw.read hold: a cold stats cache recomputes O(postings)
        # aggregates, and the rwlock's writer preference would park
        # every query arriving after one mutate behind the walk.
        # debug_stats retries/degrades on concurrent-mutation races.
        out = self.db.debug_stats()
        metrics.collect_process_gauges()
        out["histograms"] = metrics.histograms_snapshot()
        out["counters"] = metrics.counters_snapshot()
        out["gauges"] = metrics.gauges_snapshot()
        return out

    def handle_pprof(self, params: Optional[dict] = None,
                     token: str = "") -> dict:
        """/debug/pprof?seconds=N&hz=H&format=collapsed|speedscope|
        both — the on-demand wall-clock sampling profiler
        (utils/pprof.py). The request thread blocks for the sampling
        window (the Go pprof ?seconds= contract) and the response
        carries collapsed-stack text and/or speedscope JSON.
        ACL-gated like /state: stacks name code paths and predicates."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        from dgraph_tpu.utils import pprof, tracing
        return pprof.handle_params(params or {}, node=tracing.node())

    def handle_requests(self, token: str = "") -> dict:
        """/debug/requests: the bounded recent + slowest request log
        (trace_id, latency breakdown, shed/abort outcome). ACL-gated
        like /state."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        return reqlog.snapshot()

    def handle_alerts(self, params: Optional[dict] = None,
                      token: str = "") -> dict:
        """/debug/alerts: the watchdog's rule catalog, firing set and
        recent transition events (utils/watchdog.py). `?ack=<series>`
        acknowledges a firing alert; `?silence=<series>&ttlS=<s>`
        suppresses new firings. ACL-gated like /state: rule series
        carry tenant and op names."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        from dgraph_tpu.utils import watchdog
        p = params or {}
        if p.get("ack"):
            return {"acked": watchdog.ack(p["ack"])}
        if p.get("silence"):
            watchdog.silence(p["silence"],
                             float(p.get("ttlS", 3600)))
            return {"silenced": True}
        return watchdog.alerts_payload()

    def handle_incidents(self, params: Optional[dict] = None,
                         token: str = "") -> dict:
        """/debug/incidents: the flight recorder's bundle ring —
        manifests by default, one full bundle with `?id=<bundle>`.
        ACL-gated like /state: bundles embed queries and stacks."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        from dgraph_tpu.utils import watchdog
        p = params or {}
        return watchdog.incidents_payload(
            limit=int(p.get("limit", 16)), bundle=p.get("id"))

    def handle_assign(self, params: dict, token: str = "") -> dict:
        """Lease a uid block (ref zero.go /assign?what=uids): clients
        like the live loader pre-allocate so blank nodes render as
        concrete uids and batches stay fully concurrent. Any valid
        login may lease (it is a write-path primitive)."""
        if self.acl is not None:
            with self.meta:
                self.acl.authorize(token)
        num = int(params.get("num", 1))
        if not 0 < num <= 1_000_000:
            raise ValueError("num must be in [1, 1000000]")
        first, last = self.db.coordinator.assign_uids(num)
        return {"startId": str(first), "endId": str(last)}

    def _require_guardian(self, token: str, what: str):
        if self.acl is not None:
            from dgraph_tpu.server.acl import GUARDIANS
            with self.meta:
                claims = self.acl.authorize(token)
                if GUARDIANS not in claims.get("groups", []):
                    raise AclError(f"{what} needs guardian membership")

    def handle_export(self, params: dict, token: str = "") -> dict:
        """Server-side export to a directory on the ALPHA's filesystem
        (ref /admin { export(...) }, worker/export.go:376). Guardians
        only under ACL."""
        import os
        self._require_guardian(token, "/admin/export")
        fmt = params.get("format", "rdf")
        if fmt not in ("rdf", "json"):
            raise ValueError(f"format must be rdf or json, not {fmt!r}")
        dest = params.get("destination", "export")
        from dgraph_tpu.ingest.export import (
            export_json, export_rdf, export_schema,
        )
        with self.rw.read:
            os.makedirs(dest, exist_ok=True)
            spath = os.path.join(dest, "g01.schema")
            with open(spath, "w") as f:
                f.write(export_schema(self.db))
            if fmt == "rdf":
                dpath = os.path.join(dest, "g01.rdf")
                with open(dpath, "w") as f:
                    for line in export_rdf(self.db):
                        f.write(line + "\n")
            else:
                dpath = os.path.join(dest, "g01.json")
                with open(dpath, "w") as f:
                    json.dump(export_json(self.db), f)
        return {"code": "Success",
                "message": "Export completed.",
                "files": [dpath, spath]}

    def handle_backup(self, params: dict, token: str = "") -> dict:
        """Server-side incremental backup (ref /admin { backup(...) },
        ee/backup). Guardians only under ACL; the manifest chain lives
        at the destination like the offline CLI's."""
        self._require_guardian(token, "/admin/backup")
        dest = params.get("destination", "")
        if not dest:
            raise ValueError("destination is required")
        force_full = params.get("forceFull", "false") == "true"
        from dgraph_tpu.storage.backup import backup as do_backup
        with self.rw.write:
            # the rollup (a write) is quick; the expensive serialization
            # below runs under the READ lock so queries keep flowing.
            # window=0: the backup must capture EVERY commit
            self.db.rollup_all(window=0)
        with self.rw.read:
            entry = do_backup(self.db, dest, force_full=force_full)
        return {"code": "Success", "message": "Backup completed.",
                "entry": entry}

    def handle_health(self) -> dict:
        return {"status": "draining" if self.draining else "healthy",
                "uptime_s": round(time.monotonic() - self.started_at, 3),
                "openTxns": len(self.txns),
                "pendingQueries": self.pending(),
                "maxPending": self.max_pending}

    def handle_draining(self, enable: bool, token: str = "") -> dict:
        """Toggle draining (guardians only under ACL) — ref
        alpha/admin.go drainingHandler."""
        self._require_guardian(token, "/admin/draining")
        self.draining = enable
        log.info("draining", enable=enable)
        return {"code": "Success",
                "message": f"draining mode is now {enable}"}

    def handle_get_schema(self, token: str = "") -> dict:
        self._require_guardian(token, "/admin/schema")
        with self.rw.read:
            return {"schema": self.db.schema.describe_all()}


def _parse_mutation_body(body: bytes, content_type: str
                         ) -> tuple[list[Mutation], str, dict | None]:
    """Body formats (ref http.go:298 mutationHandler):
    application/rdf: raw N-Quads in {set {...} delete {...}} or plain
    sets; application/json: {"set": [...], "delete": [...],
    "query": "...", "cond": "..."} upsert envelope, or
    {"mutations": [ {...}, ... ], "query": "..."} with SEVERAL
    independently @if-gated mutations in one transaction (the
    reference's multi-mutation upsert request shape)."""
    if "json" in content_type:
        j = json.loads(body.decode())

        def one(m: dict) -> Mutation:
            mut = Mutation(cond=m.get("cond", ""))
            if "set" in m:
                mut.set_json = m["set"]
            if "delete" in m:
                mut.delete_json = m["delete"]
            if "setNquads" in m:
                mut.set_nquads = m["setNquads"]
            if "delNquads" in m:
                mut.del_nquads = m["delNquads"]
            return mut

        if "mutations" in j:
            muts = [one(m) for m in j["mutations"]]
        else:
            muts = [one(j)]
        return muts, j.get("query", ""), j.get("variables")
    text = body.decode()
    set_part, del_part, query, cond = _split_rdf_blocks(text)
    return [Mutation(set_nquads=set_part, del_nquads=del_part,
                     cond=cond)], query, None


def _split_rdf_blocks(text: str) -> tuple[str, str, str, str]:
    """Parse the RDF mutation envelope:
    `upsert { query {...} mutation [@if(...)] { set {...} delete {...} } }`
    or bare `{ set {...} delete {...} }` or raw triples."""
    s = text.strip()
    if not s.startswith(("upsert", "{")):
        return s, "", "", ""  # raw triples = set
    query = ""
    cond = ""
    body = s
    if s.startswith("upsert"):
        inner = _brace_body(s[len("upsert"):].lstrip())
        qpos = inner.find("query")
        mpos = inner.find("mutation")
        if qpos >= 0:
            qbody = _brace_body(inner[qpos + len("query"):].lstrip())
            query = "{" + qbody + "}"
        if mpos < 0:
            raise ValueError("upsert block without mutation")
        after = inner[mpos + len("mutation"):].lstrip()
        if after.startswith("@if"):
            depth = 0
            for i, ch in enumerate(after):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        cond = after[: i + 1]
                        after = after[i + 1:].lstrip()
                        break
        body = "{" + _brace_body(after) + "}"
    inner = _brace_body(body)
    parts = _scan_set_delete(inner)
    if parts is None:  # bare triples inside outer braces = set block
        return inner, "", query, cond
    return parts[0], parts[1], query, cond


def _scan_set_delete(inner: str) -> Optional[tuple[str, str]]:
    """Scan `set { ... } delete { ... }` sections; None if the content is
    bare triples instead."""
    set_part: list[str] = []
    del_part: list[str] = []
    i = 0
    n = len(inner)
    while True:
        while i < n and inner[i].isspace():
            i += 1
        if i >= n:
            break
        for kw, sink in (("set", set_part), ("delete", del_part)):
            if inner.startswith(kw, i) and \
                    inner[i + len(kw):].lstrip().startswith("{"):
                j = inner.index("{", i + len(kw))
                blk = _brace_body(inner[j:])
                sink.append(blk)
                i = j + len(blk) + 2
                break
        else:
            return None
    return "\n".join(set_part), "\n".join(del_part)


def _brace_body(s: str) -> str:
    """Content of the first balanced {...} (quote-aware)."""
    if not s.startswith("{"):
        raise ValueError(f"expected '{{' at {s[:20]!r}")
    depth = 0
    in_str = False
    esc = False
    for i, ch in enumerate(s):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return s[1:i]
    raise ValueError("unbalanced braces")


class _Handler(BaseHTTPRequestHandler):
    server_version = "dgraph-tpu/0.1"
    alpha: AlphaServer  # set by serve()

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, obj: Any):
        self._send_raw(code, json.dumps(obj).encode())

    def _send_raw(self, code: int, data: bytes):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            # traceparent OUT: the caller (or its collector) learns
            # which trace id to pull from /debug/traces on every node
            self.send_header("X-Dgraph-Trace-Id", ctx.trace_id)
            self.send_header("traceparent", tracing.format_traceparent(
                ctx.trace_id, ctx.parent_span))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, msg: str, code: int = 400, ecode: str = "Error",
               retryable: bool = False):
        ext: dict[str, Any] = {"code": ecode}
        if retryable:
            ext["retryable"] = True
        self._send(code, {"errors": [{"message": msg,
                                      "extensions": ext}]})

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    def _ctx(self) -> Optional[RequestContext]:
        """RequestContext from the request headers: the remaining
        budget in X-Dgraph-Deadline-Ms (the HTTP analogue of the gRPC
        timeout field), a W3C `traceparent` (trace id + the caller's
        span id — this request's spans, on every node it touches,
        join that trace), and/or a caller-chosen X-Dgraph-Trace-Id
        (echoed in errors; the /admin/cancel handle). No headers, no
        context — zero overhead for plain requests."""
        dl = self.headers.get("X-Dgraph-Deadline-Ms", "")
        tid = self.headers.get("X-Dgraph-Trace-Id", "")
        # QoS accounting namespace: the tenant rides the context into
        # admission (token buckets), reqlog and metrics
        tenant = self.headers.get("X-Dgraph-Tenant", "").strip()
        parent = ""
        got = tracing.parse_traceparent(
            self.headers.get("traceparent", ""))
        if got is not None:
            tid = tid or got[0]
            parent = got[1]
        if dl:
            try:
                return RequestContext.from_deadline_ms(
                    int(dl), trace_id=tid, parent_span=parent,
                    tenant=tenant)
            except ValueError:
                raise ValueError(
                    f"X-Dgraph-Deadline-Ms must be an integer ms "
                    f"budget, got {dl!r}") from None
        if tid or tenant:
            return RequestContext.background(trace_id=tid,
                                             parent_span=parent,
                                             tenant=tenant)
        return None

    def do_GET(self):
        u = urlparse(self.path)
        path = u.path
        params = {k: v[-1] for k, v in parse_qs(u.query).items()}
        token = self.headers.get("X-Dgraph-AccessToken", "")
        self._trace_ctx = None  # keep-alive: don't echo a stale trace
        try:
            if path == "/health":
                self._send(200, self.alpha.handle_health())
            elif path == "/subscribe":
                self._send(200, self.alpha.handle_subscribe(params,
                                                            token))
            elif path == "/state":
                self._send(200, self.alpha.handle_state(token))
            elif path == "/admin/schema":
                self._send(200,
                           {"data": self.alpha.handle_get_schema(token)})
            elif path == "/debug/traces":
                self._send(200, self.alpha.handle_traces(token, params))
            elif path == "/debug/requests":
                self._send(200, self.alpha.handle_requests(token))
            elif path == "/debug/stats":
                self._send(200, self.alpha.handle_debug_stats(token))
            elif path == "/debug/alerts":
                self._send(200, self.alpha.handle_alerts(params,
                                                         token))
            elif path == "/debug/incidents":
                self._send(200, self.alpha.handle_incidents(params,
                                                            token))
            elif path == "/debug/pprof":
                self._send(200, self.alpha.handle_pprof(params, token))
            elif path == "/debug/prometheus_metrics":
                from dgraph_tpu.utils.metrics import render_prometheus

                text = render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            else:
                self._error(f"no handler for GET {path}", 404)
        except AclError as e:
            self._error(str(e), 401)
        except OffsetTruncated as e:
            # 410 Gone carries the re-sync coordinates: snapshot-read
            # the predicate at resyncTs, resubscribe from
            # offset_for_ts(resyncTs) (docs/deployment.md runbook)
            self._send(410, {"errors": [{
                "message": str(e),
                "extensions": {"code": "OffsetTruncated",
                               "pred": e.pred, "floor": e.floor,
                               "resyncTs": e.resync_ts}}]})
        except DeadlineExceeded as e:
            # GET handlers take no RequestContext today, but the same
            # typed mapping as do_POST keeps cancellation from ever
            # collapsing into a 500 if one grows a deadline
            self._error(str(e), 408, ecode="DeadlineExceeded",
                        retryable=True)
        except Cancelled as e:
            self._error(str(e), 499, ecode="Cancelled")
        except (ValueError, KeyError) as e:
            # bad debug params (pprof format=, malformed seconds=)
            self._error(str(e), 400)
        except Exception as e:  # noqa: BLE001 — surface as API error
            log.error("http_internal_error", path=path, error=str(e),
                      trace=traceback.format_exc()[-800:])
            self._error(str(e), 500)

    def do_POST(self):
        u = urlparse(self.path)
        path = u.path
        params = {k: v[-1] for k, v in parse_qs(u.query).items()}
        ctype = self.headers.get("Content-Type", "")
        token = self.headers.get("X-Dgraph-AccessToken", "")
        # reset BEFORE _ctx() can raise: a malformed deadline header's
        # 400 must not echo a previous request's trace on a reused
        # connection
        self._trace_ctx = None
        try:
            ctx = self._ctx()
            self._trace_ctx = ctx
            body = self._body()
            if path == "/query":
                if "json" in ctype:
                    payload: Any = json.loads(body.decode())
                else:
                    payload = body.decode()
                debug = params.get("debug", "false") == "true" \
                    or self.headers.get("X-Dgraph-Debug", ""
                                        ).lower() not in ("", "false",
                                                          "0")
                if debug:
                    # per-request tier-routing profile: a metrics
                    # counter diff around the (dict-path) query shows
                    # where it routed — columnar hits, device ops,
                    # postings fallbacks, cache evictions. Counters
                    # are process-global, so concurrent traffic
                    # bleeds in; use on a quiet node or repeat.
                    before = metrics.counters_snapshot()
                    out = self.alpha.handle_query(payload, params,
                                                  token, ctx=ctx)
                    out.setdefault("extensions", {})["profile"] = {
                        "counters": metrics.counters_delta(before)}
                    self._send(200, out)
                else:
                    self._send_raw(200, self.alpha.handle_query_json(
                        payload, params, token, ctx=ctx).encode())
            elif path == "/mutate":
                self._send(200, self.alpha.handle_mutate(
                    body, ctype, params, token, ctx=ctx))
            elif path == "/commit":
                self._send(200, self.alpha.handle_commit(params, token,
                                                         ctx=ctx))
            elif path in ("/alter", "/admin/schema"):
                self._send(200, self.alpha.handle_alter(body, token,
                                                        ctx=ctx))
            elif path == "/admin/cancel":
                self._send(200, self.alpha.handle_cancel(params, token))
            elif path == "/assign":
                self._send(200, self.alpha.handle_assign(params, token))
            elif path == "/admin/export":
                self._send(200, self.alpha.handle_export(params, token))
            elif path == "/admin/backup":
                self._send(200, self.alpha.handle_backup(params, token))
            elif path == "/admin/draining":
                enable = params.get("enable", "true") == "true"
                self._send(200, self.alpha.handle_draining(enable, token))
            elif path == "/login":
                self._send(200, self.alpha.handle_login(
                    json.loads(body.decode()) if body else {}))
            else:
                self._error(f"no handler for POST {path}", 404)
        except TxnAborted as e:
            self._error(f"Transaction has been aborted. Please retry: {e}",
                        409)
        except Overloaded as e:
            self._error(str(e), 429, ecode="ResourceExhausted",
                        retryable=True)
        except DeadlineExceeded as e:
            self._error(str(e), 408, ecode="DeadlineExceeded",
                        retryable=True)
        except Cancelled as e:
            self._error(str(e), 499, ecode="Cancelled")
        except AclError as e:
            self._error(str(e), 401)
        except (ValueError, KeyError) as e:
            self._error(str(e), 400)
        except Exception as e:  # noqa: BLE001
            log.error("http_internal_error", path=path, error=str(e),
                      trace=traceback.format_exc()[-800:])
            self._error(str(e), 500)


def serve(db: Optional[GraphDB] = None, host: str = "127.0.0.1",
          port: int = 8080, block: bool = True,
          acl_secret: Optional[bytes] = None,
          tls_context=None, mutations_mode: str = "allow",
          max_pending: int = 0, batch_window_us: int = 0,
          tenant_rate: float = 0.0, tenant_burst: float = 0.0
          ) -> tuple[ThreadingHTTPServer, AlphaServer]:
    """Start the Alpha HTTP server. With block=False, runs in a daemon
    thread and returns (httpd, alpha) for tests/embedding. Pass an
    ssl.SSLContext (server/tls.py server_context) to serve HTTPS/mTLS
    like the reference's --tls options (x/tls_helper.go).
    `max_pending` bounds concurrently admitted requests (0 = off);
    excess load sheds with 429. `batch_window_us` coalesces concurrent
    same-plan queries into one dispatch (0 = off). `tenant_rate`/
    `tenant_burst` enable per-tenant QoS token buckets keyed on the
    X-Dgraph-Tenant header (0 = off)."""
    alpha = AlphaServer(db, acl_secret=acl_secret,
                        mutations_mode=mutations_mode,
                        max_pending=max_pending,
                        batch_window_us=batch_window_us,
                        tenant_rate=tenant_rate,
                        tenant_burst=tenant_burst)
    handler = type("BoundHandler", (_Handler,), {"alpha": alpha})
    httpd = ThreadingHTTPServer((host, port), handler)
    if tls_context is not None:
        # defer the handshake to the per-request handler thread: with
        # the default handshake-on-accept, one client that connects and
        # never sends a ClientHello would block the single accept loop
        # for everyone
        httpd.socket = tls_context.wrap_socket(
            httpd.socket, server_side=True,
            do_handshake_on_connect=False)
    if block:
        httpd.serve_forever()
    else:
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
    return httpd, alpha
