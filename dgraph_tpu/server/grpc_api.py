"""gRPC API surface for the Alpha.

The reference's primary client protocol is gRPC (api.Dgraph service:
Login/Query/Mutate/Alter/CommitOrAbort/CheckVersion —
dgraph/cmd/alpha/run.go:362 serveGRPC, protos/api). Same service shape
here over grpc's generic handlers: method names match the reference,
message bodies are wire-format dicts (dgraph_tpu/wire) instead of
protobuf — the framework's one stable encoding everywhere. Status
codes map like the reference: ABORTED for txn conflicts,
PERMISSION_DENIED for ACL, INVALID_ARGUMENT for bad requests.

Serving and the HTTP front end share AlphaServer's
transport-independent handlers, so every feature (ACL, txns by
startTs, draining, upserts) behaves identically on both transports.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from dgraph_tpu import wire
from dgraph_tpu.cluster.coordinator import TxnAborted
from dgraph_tpu.server.acl import AclError
from dgraph_tpu.server.http import AlphaServer

_SERVICE = "dgraph.tpu.Alpha"


def _wrap(fn):
    def method(request, context):
        try:
            return fn(request or {})
        except TxnAborted as e:
            context.abort(grpc.StatusCode.ABORTED,
                          f"Transaction has been aborted. "
                          f"Please retry: {e}")
        except AclError as e:
            context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        except (ValueError, KeyError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")

    return method


def _handlers(alpha: AlphaServer) -> dict:
    def login(req):
        return alpha.handle_login(req.get("body", {}))

    def query(req):
        return alpha.handle_query(req.get("q", ""),
                                  req.get("params", {}),
                                  req.get("token", ""))

    def mutate(req):
        return alpha.handle_mutate(req.get("body", b""),
                                   req.get("content_type",
                                           "application/rdf"),
                                   req.get("params", {}),
                                   req.get("token", ""))

    def alter(req):
        return alpha.handle_alter(req.get("body", b""),
                                  req.get("token", ""))

    def commit(req):
        return alpha.handle_commit(req.get("params", {}),
                                   req.get("token", ""))

    def check_version(req):
        from dgraph_tpu.cli import __version__
        return {"tag": f"dgraph-tpu-{__version__}"}

    return {"Login": login, "Query": query, "Mutate": mutate,
            "Alter": alter, "CommitOrAbort": commit,
            "CheckVersion": check_version}


def serve_grpc(alpha: AlphaServer, host: str = "127.0.0.1",
               port: int = 9080, max_workers: int = 16,
               tls_dir: str = "", require_client_cert: bool = False
               ) -> tuple[grpc.Server, int]:
    """Start the gRPC front end; -> (server, bound port). With
    tls_dir, serves over TLS from the same cert dir as the HTTP front
    end (x/tls_helper.go applies one TLS config to both listeners);
    require_client_cert turns on mTLS."""
    import os

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    rpcs = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(fn), request_deserializer=wire.loads,
            response_serializer=wire.dumps)
        for name, fn in _handlers(alpha).items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, rpcs),))
    addr = f"{host}:{port}"
    if tls_dir:
        with open(os.path.join(tls_dir, "node.key"), "rb") as f:
            key = f.read()
        with open(os.path.join(tls_dir, "node.crt"), "rb") as f:
            crt = f.read()
        root = None
        if require_client_cert:
            with open(os.path.join(tls_dir, "ca.crt"), "rb") as f:
                root = f.read()
        creds = grpc.ssl_server_credentials(
            [(key, crt)], root_certificates=root,
            require_client_auth=require_client_cert)
        bound = server.add_secure_port(addr, creds)
    else:
        bound = server.add_insecure_port(addr)
    if bound == 0:
        raise OSError(f"gRPC could not bind {addr}")
    server.start()
    return server, bound


class GrpcClient:
    """The dgo-shaped client: Login/Query/Mutate/Alter/CommitOrAbort
    over the gRPC channel."""

    def __init__(self, addr: str, token: str = ""):
        self.channel = grpc.insecure_channel(addr)
        self.token = token
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{_SERVICE}/{name}", request_serializer=wire.dumps,
                response_deserializer=wire.loads)
            for name in ("Login", "Query", "Mutate", "Alter",
                         "CommitOrAbort", "CheckVersion")
        }

    def login(self, userid: str, password: str) -> dict:
        out = self._stubs["Login"](
            {"body": {"userid": userid, "password": password}})
        self.token = out["data"]["accessJWT"]
        return out

    def query(self, q: str, variables: Optional[dict] = None,
              start_ts: int = 0, best_effort: bool = False) -> dict:
        params = {}
        if start_ts:
            params["startTs"] = str(start_ts)
        if best_effort:
            params["be"] = "true"
        # handle_query accepts either DQL text or the JSON envelope
        payload = {"query": q, "variables": variables} if variables else q
        return self._stubs["Query"](
            {"q": payload, "params": params, "token": self.token})

    def mutate(self, body: bytes | str,
               content_type: str = "application/rdf",
               commit_now: bool = True, start_ts: int = 0) -> dict:
        params = {"commitNow": "true" if commit_now else "false"}
        if start_ts:
            params["startTs"] = str(start_ts)
        if isinstance(body, str):
            body = body.encode()
        return self._stubs["Mutate"](
            {"body": body, "content_type": content_type,
             "params": params, "token": self.token})

    def alter(self, schema_text: str) -> dict:
        return self._stubs["Alter"](
            {"body": schema_text.encode(), "token": self.token})

    def commit(self, start_ts: int, abort: bool = False) -> dict:
        return self._stubs["CommitOrAbort"](
            {"params": {"startTs": str(start_ts),
                        "abort": "true" if abort else "false"},
             "token": self.token})

    def check_version(self) -> dict:
        return self._stubs["CheckVersion"]({})

    def close(self):
        self.channel.close()
