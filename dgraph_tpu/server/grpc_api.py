"""gRPC API surface for the Alpha.

The reference's primary client protocol is gRPC (api.Dgraph service:
Login/Query/Mutate/Alter/CommitOrAbort/CheckVersion —
dgraph/cmd/alpha/run.go:362 serveGRPC, protos/api). Same service shape
here over grpc's generic handlers: method names match the reference,
message bodies are wire-format dicts (dgraph_tpu/wire) instead of
protobuf — the framework's one stable encoding everywhere. Status
codes map like the reference: ABORTED for txn conflicts,
PERMISSION_DENIED for ACL, INVALID_ARGUMENT for bad requests.

Serving and the HTTP front end share AlphaServer's
transport-independent handlers, so every feature (ACL, txns by
startTs, draining, upserts) behaves identically on both transports.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from dgraph_tpu import wire
from dgraph_tpu.cluster.coordinator import TxnAborted
from dgraph_tpu.server.acl import AclError
from dgraph_tpu.server.http import AlphaServer
from dgraph_tpu.utils.reqctx import (
    Cancelled, DeadlineExceeded, Overloaded, RequestContext,
)

_SERVICE = "dgraph.tpu.Alpha"


def _abort_for(context, e):
    """One exception -> gRPC status table for BOTH services (status
    codes as the reference maps them: ABORTED for txn conflicts,
    PERMISSION_DENIED for ACL, INVALID_ARGUMENT for bad requests,
    DEADLINE_EXCEEDED / CANCELLED / RESOURCE_EXHAUSTED for the
    request-context + admission-control layer)."""
    if isinstance(e, TxnAborted):
        context.abort(grpc.StatusCode.ABORTED,
                      f"Transaction has been aborted. Please retry: {e}")
    if isinstance(e, DeadlineExceeded):
        context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
    if isinstance(e, Cancelled):
        context.abort(grpc.StatusCode.CANCELLED, str(e))
    if isinstance(e, Overloaded):
        # retryable by contract (the reference's rate limiter answers
        # the same status; clients back off with jitter)
        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
    if isinstance(e, AclError):
        context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
    if isinstance(e, (ValueError, KeyError)):
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
    context.abort(grpc.StatusCode.INTERNAL,
                  f"{type(e).__name__}: {e}")


def _ctx_of(context) -> Optional[RequestContext]:
    """RequestContext from the gRPC deadline + trace metadata:
    time_remaining() carries the client's timeout field through every
    hop (the reference's context.Context), and a W3C `traceparent`
    metadata entry (or x-dgraph-trace-id) joins this request's spans
    — on every node it touches — to the caller's trace; None when the
    client sent neither."""
    from dgraph_tpu.utils import tracing

    if context is None:
        return None
    tr = context.time_remaining()
    tid = parent = ""
    md = dict(context.invocation_metadata() or ())
    got = tracing.parse_traceparent(md.get("traceparent", ""))
    if got is not None:
        tid, parent = got
    tid = md.get("x-dgraph-trace-id", "") or tid
    if tr is None:
        if tid:
            return RequestContext.background(trace_id=tid,
                                             parent_span=parent)
        return None
    return RequestContext.with_timeout(tr, trace_id=tid,
                                       parent_span=parent)


def _wrap(fn):
    def method(request, context):
        try:
            return fn(request or {}, _ctx_of(context))
        except Exception as e:  # noqa: BLE001  # dglint: disable=DG07 (_abort_for maps RequestAborted to typed gRPC status then raises via context.abort)
            _abort_for(context, e)

    return method


def _handlers(alpha: AlphaServer) -> dict:
    def login(req, ctx):
        return alpha.handle_login(req.get("body", {}))

    def query(req, ctx):
        return alpha.handle_query(req.get("q", ""),
                                  req.get("params", {}),
                                  req.get("token", ""), ctx=ctx)

    def mutate(req, ctx):
        return alpha.handle_mutate(req.get("body", b""),
                                   req.get("content_type",
                                           "application/rdf"),
                                   req.get("params", {}),
                                   req.get("token", ""), ctx=ctx)

    def alter(req, ctx):
        return alpha.handle_alter(req.get("body", b""),
                                  req.get("token", ""), ctx=ctx)

    def commit(req, ctx):
        return alpha.handle_commit(req.get("params", {}),
                                   req.get("token", ""), ctx=ctx)

    def check_version(req, ctx):
        from dgraph_tpu.cli import __version__
        return {"tag": f"dgraph-tpu-{__version__}"}

    return {"Login": login, "Query": query, "Mutate": mutate,
            "Alter": alter, "CommitOrAbort": commit,
            "CheckVersion": check_version}


_PB_SERVICE = "api.Dgraph"  # the reference's published service path
                            # (/api.Dgraph/Query ... — dgo/pydgraph)


def _pb_wrap(fn):
    def method(request, context):
        try:
            return fn(request, context)
        except Exception as e:  # noqa: BLE001  # dglint: disable=DG07 (_abort_for maps RequestAborted to typed gRPC status then raises via context.abort)
            _abort_for(context, e)

    return method


def _strip_dollar(vars_map) -> dict:
    """Clients send GraphQL vars keyed "$n" (the dgo convention);
    the engine's variable table is keyed bare."""
    return {(k[1:] if k.startswith("$") else k): v
            for k, v in dict(vars_map).items()}


def _rdf_escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n").replace("\t", "\\t")
            .replace("\r", "\\r"))


def _go_time_decode(data: bytes) -> str:
    """Go time.Time.MarshalBinary -> RFC3339 text. dgo clients build
    DatetimeVal/DATETIME facets with exactly these bytes (ref
    types/conversion.go DateTimeID arm); layout: version byte (1|2),
    int64 BE seconds since year 1, int32 BE nanos, int16 BE zone
    offset in minutes (-1 = UTC)."""
    import struct
    from datetime import datetime, timedelta, timezone
    if len(data) < 15 or data[0] not in (1, 2):
        # lenient fallback: some clients send RFC3339 text bytes
        return data.decode()
    sec, nsec, off = struct.unpack(">xqih", data[:15])
    unix = sec - 62135596800  # year 1 -> unix epoch
    tz = timezone.utc if off in (-1, 0) \
        else timezone(timedelta(minutes=off))
    dt = datetime.fromtimestamp(unix, tz) + timedelta(
        microseconds=nsec // 1000)
    return dt.isoformat()


def _pb_value_literal(v) -> str:
    """api.Value -> RDF object literal (typed per the oneof arm, the
    inverse of chunker/rdf_parser.go's typed-literal handling)."""
    import base64 as _b64
    which = v.WhichOneof("val")
    if which is None or which == "default_val":
        return f'"{_rdf_escape(v.default_val)}"'
    if which == "str_val":
        return f'"{_rdf_escape(v.str_val)}"'
    if which == "int_val":
        return f'"{v.int_val}"^^<xs:int>'
    if which == "bool_val":
        return f'"{"true" if v.bool_val else "false"}"^^<xs:boolean>'
    if which == "double_val":
        return f'"{v.double_val!r}"^^<xs:float>'
    if which == "password_val":
        return f'"{_rdf_escape(v.password_val)}"^^<xs:password>'
    if which == "geo_val":
        return f'"{_rdf_escape(v.geo_val.decode())}"^^<geo:geojson>'
    if which == "date_val":
        return (f'"{_rdf_escape(_go_time_decode(v.date_val))}"'
                '^^<xs:date>')
    if which == "datetime_val":
        return (f'"{_rdf_escape(_go_time_decode(v.datetime_val))}"'
                '^^<xs:dateTime>')
    if which == "bytes_val":
        return (f'"{_b64.b64encode(v.bytes_val).decode()}"'
                '^^<xs:base64Binary>')
    if which == "uid_val":
        return f"<{hex(v.uid_val)}>"
    raise ValueError(f"unsupported Value arm {which!r}")


def _pb_facet_literal(f, pb) -> str:
    """api.Facet value bytes -> facet literal text. dgraph's facet
    values travel BINARY-encoded (types/conversion.go Marshal to
    BinaryID: int64/float64 little-endian, bool one byte, datetime
    Go MarshalBinary); text is accepted too for lenient clients."""
    import struct
    raw = bytes(f.value)
    if f.val_type == pb.Facet.INT:
        if len(raw) == 8:
            return str(struct.unpack("<q", raw)[0])
        return str(int(raw.decode()))
    if f.val_type == pb.Facet.FLOAT:
        if len(raw) == 8:
            return repr(struct.unpack("<d", raw)[0])
        return raw.decode()
    if f.val_type == pb.Facet.BOOL:
        if len(raw) == 1 and raw[0] in (0, 1):
            return "true" if raw[0] else "false"
        return "true" if raw.decode().lower() in ("true", "1") \
            else "false"
    if f.val_type == pb.Facet.DATETIME:
        return f'"{_rdf_escape(_go_time_decode(raw))}"'
    # STRING renders quoted; the parser re-infers
    return f'"{_rdf_escape(raw.decode())}"'


def _pb_nquads_rdf(nqs, pb) -> str:
    """api.NQuad list -> RDF lines the chunker grammar accepts (the
    structured-mutation arm of the dgo contract: Mutation.set/del)."""
    lines = []
    for nq in nqs:
        subj = nq.subject if nq.subject.startswith(("_:", "uid(")) \
            else f"<{nq.subject}>"
        if nq.object_id:
            if nq.object_id in ("_STAR_ALL", "*"):
                obj = "*"
            elif nq.object_id.startswith(("_:", "uid(")):
                obj = nq.object_id
            else:
                obj = f"<{nq.object_id}>"
        else:
            obj = _pb_value_literal(nq.object_value)
            if nq.lang:
                obj += f"@{nq.lang}"
        line = f"{subj} <{nq.predicate}> {obj}"
        if nq.facets:
            inner = ", ".join(
                f"{f.key}={_pb_facet_literal(f, pb)}" for f in nq.facets)
            line += f" ({inner})"
        lines.append(line + " .")
    return "\n".join(lines)


def _pb_handlers(alpha: AlphaServer) -> dict:
    """The protobuf api.Dgraph service (proto/api.proto — the dgo/v2
    public contract, field numbers included) — same transport-
    independent AlphaServer handlers as HTTP and the wire-dict
    service, so stock dgo/pydgraph clients work against this server
    (ref alpha/run.go:362 registering api.Dgraph;
    edgraph/server.go:634 doQuery)."""
    import json

    from dgraph_tpu.proto import api_pb2 as pb

    def token_of(req, context):
        md = dict(context.invocation_metadata() or ())
        return md.get("accessjwt", "")

    def _latency(ext: dict) -> "pb.Latency":
        lat = ext.get("latency") or {}
        return pb.Latency(
            parsing_ns=int(lat.get("parsing_ns", 0)),
            processing_ns=int(lat.get("processing_ns", 0)),
            encoding_ns=int(lat.get("encoding_ns", 0)),
            assign_timestamp_ns=int(lat.get("assign_timestamp_ns", 0)))

    def _txn_ctx(ext: dict) -> "pb.TxnContext":
        txn = ext.get("txn") or {}
        return pb.TxnContext(
            start_ts=int(txn.get("start_ts", 0)),
            commit_ts=int(txn.get("commit_ts", 0)),
            aborted=bool(txn.get("aborted", False)),
            preds=[str(p) for p in txn.get("preds", ())])

    def login(req, context):
        out = alpha.handle_login({
            "userid": req.userid, "password": req.password,
            "refresh_token": req.refresh_token})
        data = out.get("data", {})
        # the dgo contract ships the Jwt SERIALIZED inside
        # Response.json (edgraph/access_ee.go:91 marshals api.Jwt
        # into resp.Json); dgo/pydgraph parse it from there
        jwt = pb.Jwt(
            access_jwt=data.get("accessJwt", "")
            or data.get("accessJWT", ""),
            refresh_jwt=data.get("refreshJwt", "")
            or data.get("refreshJWT", ""))
        return pb.Response(json=jwt.SerializeToString())

    def query(req, context):
        token = token_of(req, context)
        ctx = _ctx_of(context)
        params = {}
        # pb.Request carries no explain field; an `x-dgraph-explain:
        # plan|analyze` metadata entry (or the in-query `@explain`
        # directive, which needs no transport support) requests the
        # plan tree — pb.Response has no extensions slot either, so
        # the tree comes back as `x-dgraph-explain-json` trailing
        # metadata; the data payload stays byte-identical
        md = dict(context.invocation_metadata() or ()) \
            if context is not None else {}
        if md.get("x-dgraph-explain"):
            params["explain"] = md["x-dgraph-explain"]
        if req.start_ts:
            params["startTs"] = str(req.start_ts)
        if req.best_effort:
            params["be"] = "true"
        if req.read_only:
            params["ro"] = "true"
        if req.mutations:
            # mutation / upsert request (the reference's do-request
            # path: mutations ride in the same Request as the query;
            # each is independently @if-gated in ONE transaction)
            def one(m) -> dict:
                d: dict = {}
                if m.set_json:
                    d["set"] = json.loads(m.set_json.decode())
                if m.delete_json:
                    d["delete"] = json.loads(m.delete_json.decode())
                set_rdf = m.set_nquads.decode() if m.set_nquads else ""
                del_rdf = m.del_nquads.decode() if m.del_nquads else ""
                # structured NQuads (dgo's api.NQuad arm) join the
                # text arm as RDF lines
                m_del = getattr(m, "del")  # python keyword
                if m.set:
                    set_rdf = "\n".join(
                        x for x in (set_rdf, _pb_nquads_rdf(m.set, pb))
                        if x)
                if m_del:
                    del_rdf = "\n".join(
                        x for x in (del_rdf, _pb_nquads_rdf(m_del, pb))
                        if x)
                if set_rdf:
                    d["setNquads"] = set_rdf
                if del_rdf:
                    d["delNquads"] = del_rdf
                if m.cond:
                    d["cond"] = m.cond
                return d

            if len(req.mutations) == 1:
                env = one(req.mutations[0])
            else:
                env = {"mutations": [one(m) for m in req.mutations]}
            if req.query:
                env["query"] = req.query
                if req.vars:
                    env["variables"] = _strip_dollar(req.vars)
            commit_now = req.commit_now or any(
                m.commit_now for m in req.mutations)
            params["commitNow"] = "true" if commit_now else "false"
            out = alpha.handle_mutate(
                json.dumps(env).encode(), "application/json",
                params, token, ctx=ctx)
            ext = out.get("extensions", {})
            data = out.get("data", out)
            return pb.Response(
                json=json.dumps(data.get("queries", {}),
                                separators=(",", ":")).encode(),
                txn=_txn_ctx(ext), latency=_latency(ext),
                uids={k: str(v)
                      for k, v in (data.get("uids") or
                                   out.get("uids") or {}).items()})
        payload = {"query": req.query,
                   "variables": _strip_dollar(req.vars)} \
            if req.vars else req.query
        out = alpha.handle_query(payload, params, token, ctx=ctx)
        ext = out.get("extensions", {})
        if ext.get("explain") is not None and context is not None:
            context.set_trailing_metadata((
                ("x-dgraph-explain-json",
                 json.dumps(ext["explain"], separators=(",", ":"))),))
        return pb.Response(
            json=json.dumps(out.get("data", {}),
                            separators=(",", ":")).encode(),
            txn=_txn_ctx(ext), latency=_latency(ext))

    def alter(req, context):
        token = token_of(req, context)
        if req.drop_all or req.drop_op == pb.Operation.ALL:
            body = json.dumps({"drop_all": True}).encode()
        elif req.drop_attr:
            body = json.dumps({"drop_attr": req.drop_attr}).encode()
        elif req.drop_op == pb.Operation.ATTR:
            body = json.dumps({"drop_attr": req.drop_value}).encode()
        elif req.drop_op != pb.Operation.NONE or req.drop_value:
            raise ValueError(
                "this drop_op is not supported by this server; use "
                "drop_attr or drop_all")
        else:
            body = req.schema.encode()
        alpha.handle_alter(body, token, ctx=_ctx_of(context))
        return pb.Payload(Data=b"Success")

    def commit_or_abort(req, context):
        # dgo semantics: CommitOrAbort COMMITS unless the context's
        # aborted flag is set (txn.Discard sends aborted=true;
        # edgraph/server.go:920 CommitOrAbort)
        token = token_of(req, context)
        out = alpha.handle_commit(
            {"startTs": str(req.start_ts),
             "abort": "true" if req.aborted else "false"}, token,
            ctx=_ctx_of(context))
        return _txn_ctx(out.get("extensions", {}))

    def check_version(req, context):
        from dgraph_tpu.cli import __version__
        return pb.Version(tag=f"dgraph-tpu-{__version__}")

    return {"Login": (login, pb.LoginRequest),
            "Query": (query, pb.Request),
            "Alter": (alter, pb.Operation),
            "CommitOrAbort": (commit_or_abort, pb.TxnContext),
            "CheckVersion": (check_version, pb.Check)}


def serve_grpc(alpha: AlphaServer, host: str = "127.0.0.1",
               port: int = 9080, max_workers: int = 16,
               tls_dir: str = "", require_client_cert: bool = False
               ) -> tuple[grpc.Server, int]:
    """Start the gRPC front end; -> (server, bound port). With
    tls_dir, serves over TLS from the same cert dir as the HTTP front
    end (x/tls_helper.go applies one TLS config to both listeners);
    require_client_cert turns on mTLS."""
    import os

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    rpcs = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(fn), request_deserializer=wire.loads,
            response_serializer=wire.dumps)
        for name, fn in _handlers(alpha).items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, rpcs),))
    # the protobuf api.Dgraph service on the SAME listener: serialized
    # with the committed generated messages (proto/api.proto), so
    # generated clients in any language interoperate
    pb_rpcs = {
        name: grpc.unary_unary_rpc_method_handler(
            _pb_wrap(fn),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString())
        for name, (fn, req_cls) in _pb_handlers(alpha).items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_PB_SERVICE, pb_rpcs),))
    addr = f"{host}:{port}"
    if tls_dir:
        with open(os.path.join(tls_dir, "node.key"), "rb") as f:
            key = f.read()
        with open(os.path.join(tls_dir, "node.crt"), "rb") as f:
            crt = f.read()
        root = None
        if require_client_cert:
            with open(os.path.join(tls_dir, "ca.crt"), "rb") as f:
                root = f.read()
        creds = grpc.ssl_server_credentials(
            [(key, crt)], root_certificates=root,
            require_client_auth=require_client_cert)
        bound = server.add_secure_port(addr, creds)
    else:
        bound = server.add_insecure_port(addr)
    if bound == 0:
        raise OSError(f"gRPC could not bind {addr}")
    server.start()
    return server, bound


class GrpcClient:
    """The dgo-shaped client: Login/Query/Mutate/Alter/CommitOrAbort
    over the gRPC channel."""

    def __init__(self, addr: str, token: str = ""):
        self.channel = grpc.insecure_channel(addr)
        self.token = token
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{_SERVICE}/{name}", request_serializer=wire.dumps,
                response_deserializer=wire.loads)
            for name in ("Login", "Query", "Mutate", "Alter",
                         "CommitOrAbort", "CheckVersion")
        }

    def login(self, userid: str, password: str) -> dict:
        out = self._stubs["Login"](
            {"body": {"userid": userid, "password": password}})
        self.token = out["data"]["accessJWT"]
        return out

    def query(self, q: str, variables: Optional[dict] = None,
              start_ts: int = 0, best_effort: bool = False,
              timeout: Optional[float] = None) -> dict:
        params = {}
        if start_ts:
            params["startTs"] = str(start_ts)
        if best_effort:
            params["be"] = "true"
        # handle_query accepts either DQL text or the JSON envelope
        payload = {"query": q, "variables": variables} if variables else q
        # `timeout` becomes the gRPC deadline; the server reads it via
        # context.time_remaining() and aborts work past it
        return self._stubs["Query"](
            {"q": payload, "params": params, "token": self.token},
            timeout=timeout)

    def mutate(self, body: bytes | str,
               content_type: str = "application/rdf",
               commit_now: bool = True, start_ts: int = 0) -> dict:
        params = {"commitNow": "true" if commit_now else "false"}
        if start_ts:
            params["startTs"] = str(start_ts)
        if isinstance(body, str):
            body = body.encode()
        return self._stubs["Mutate"](
            {"body": body, "content_type": content_type,
             "params": params, "token": self.token})

    def alter(self, schema_text: str) -> dict:
        return self._stubs["Alter"](
            {"body": schema_text.encode(), "token": self.token})

    def commit(self, start_ts: int, abort: bool = False) -> dict:
        return self._stubs["CommitOrAbort"](
            {"params": {"startTs": str(start_ts),
                        "abort": "true" if abort else "false"},
             "token": self.token})

    def check_version(self) -> dict:
        return self._stubs["CheckVersion"]({})

    def close(self):
        self.channel.close()
