"""Debug/observability HTTP listener for cluster node processes.

A `dgraph-tpu node` process speaks the framed cluster wire protocol —
great for data traffic, useless for an operator with curl, a
Prometheus scraper, tools/dgtop.py or tools/dgbench.py's collector.
This module is the reference's debug mux (x/metrics.go wires pprof +
expvar + /debug/prometheus_metrics onto every node) for those
processes: a tiny read-only HTTP server over the SAME planes the main
Alpha surface exposes —

    GET /health                     liveness + identity
    GET /debug/stats                tablet statistics + cost store +
                                    metrics counters/gauges/histograms
    GET /debug/requests             the bounded request ring
    GET /debug/prometheus_metrics   text exposition 0.0.4
    GET /debug/traces[?trace_id=]   node-local span slice
    GET /debug/pprof?seconds=N      wall-clock sampling profile
    GET /debug/fault                active network-fault rules
    POST /debug/fault               fault control (utils/netfault.py):
                                    {"action": "set|add|remove|clear",
                                     "rules": [...]} — curl-able chaos
                                    arming/healing for an operator

It is deliberately NOT the query surface: no txn state, no ACL store,
and the single POST handler touches only the process-local fault
table — bind it to localhost (the default) or scrape-net interfaces
only. `serve_debug` takes callables so AlphaServer and ZeroServer plug
in whatever stats they have without this module importing engine
internals.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from dgraph_tpu.utils import metrics, reqlog, tracing


class _DebugHandler(BaseHTTPRequestHandler):
    server_version = "dgraph-tpu-debug/0.1"
    stats_fn: Optional[Callable[[], dict]] = None
    health_fn: Optional[Callable[[], dict]] = None
    node_name: str = "node"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, obj, ctype="application/json"):
        data = obj if isinstance(obj, bytes) else \
            json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        u = urlparse(self.path)
        params = {k: v[-1] for k, v in parse_qs(u.query).items()}
        try:
            if u.path == "/health":
                out = {"status": "healthy", "node": self.node_name}
                if self.health_fn is not None:
                    out.update(self.health_fn())
                self._send(200, out)
            elif u.path == "/debug/stats":
                out = self.stats_fn() if self.stats_fn is not None \
                    else {}
                out.setdefault("node", self.node_name)
                metrics.collect_process_gauges()
                out["counters"] = metrics.counters_snapshot()
                out["gauges"] = metrics.gauges_snapshot()
                out["histograms"] = metrics.histograms_snapshot()
                self._send(200, out)
            elif u.path == "/debug/requests":
                self._send(200, reqlog.snapshot())
            elif u.path == "/debug/prometheus_metrics":
                self._send(200, metrics.render_prometheus().encode(),
                           ctype="text/plain; version=0.0.4")
            elif u.path == "/debug/traces":
                tid = params.get("trace_id") or None
                self._send(200, {"traceEvents":
                                 tracing.export_chrome_trace(
                                     trace_id=tid)})
            elif u.path == "/debug/pprof":
                from dgraph_tpu.utils import pprof
                self._send(200, pprof.handle_params(
                    params, node=self.node_name))
            elif u.path == "/debug/fault":
                from dgraph_tpu.utils import netfault
                self._send(200, {"node": self.node_name,
                                 "rules": netfault.rules()})
            elif u.path == "/debug/alerts":
                from dgraph_tpu.utils import watchdog
                if params.get("ack"):
                    out: dict = {"acked":
                                 watchdog.ack(params["ack"])}
                elif params.get("silence"):
                    watchdog.silence(params["silence"],
                                     float(params.get("ttlS", 3600)))
                    out = {"silenced": True}
                else:
                    out = watchdog.alerts_payload()
                out["node"] = self.node_name
                self._send(200, out)
            elif u.path == "/debug/incidents":
                from dgraph_tpu.utils import watchdog
                out = watchdog.incidents_payload(
                    limit=int(params.get("limit", 16)),
                    bundle=params.get("id"))
                out["node"] = self.node_name
                self._send(200, out)
            else:
                self._send(404, {"errors": [
                    {"message": f"no handler for GET {u.path}"}]})
        except (ValueError, KeyError) as e:
            self._send(400, {"errors": [{"message": str(e)}]})
        except Exception as e:  # noqa: BLE001 — debug surface: report  # dglint: disable=DG07 (read-only debug listener; no request ctx flows here)
            self._send(500, {"errors": [{"message": str(e)}]})

    def do_POST(self):
        """The one control surface on this listener: the network fault
        table (chaos arming/healing with nothing but curl). Everything
        else stays read-only GET."""
        u = urlparse(self.path)
        if u.path != "/debug/fault":
            self._send(404, {"errors": [
                {"message": f"no handler for POST {u.path}"}]})
            return
        from dgraph_tpu.utils import netfault
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            out = netfault.handle_control(body)
            out["node"] = self.node_name
            self._send(200, out)
        except (ValueError, KeyError, TypeError) as e:
            self._send(400, {"errors": [{"message": str(e)}]})


def serve_debug(stats_fn: Optional[Callable[[], dict]] = None,
                health_fn: Optional[Callable[[], dict]] = None,
                node_name: str = "node",
                host: str = "127.0.0.1", port: int = 0
                ) -> tuple[ThreadingHTTPServer, int]:
    """Start the debug listener in a daemon thread; returns
    (httpd, bound_port) — port 0 binds an ephemeral port, the caller
    prints/records the real one."""
    handler = type("BoundDebugHandler", (_DebugHandler,), {
        "stats_fn": staticmethod(stats_fn) if stats_fn else None,
        "health_fn": staticmethod(health_fn) if health_fn else None,
        "node_name": node_name})
    httpd = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name=f"debug-http-{node_name}")
    t.start()
    return httpd, httpd.server_address[1]
