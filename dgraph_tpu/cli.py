"""Command-line interface.

Mirrors the reference's cobra command tree (dgraph/main.go:29,
dgraph/cmd/root.go:75-78): `alpha` serves the engine, plus the smaller
operational tools. Flags can also come from DGRAPH_TPU_<CMD>_<FLAG>
environment variables, like the reference's DGRAPH_ALPHA_* viper prefixes
(dgraph/cmd/root.go:104-143).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__version__ = "0.1.0"


def _coerce(v, default):
    if isinstance(default, bool):
        return str(v).lower() in ("1", "true", "yes")
    if isinstance(default, int) and not isinstance(default, bool):
        return int(v)
    return v


def _apply_config_layers(sub_choices: dict, argv: list) -> list:
    """Flag layering, lowest to highest precedence: parser defaults <
    --config FILE (JSON {subcommand: {flag: value}}) <
    DGRAPH_TPU_<CMD>_<FLAG> env vars < explicit CLI flags — the
    reference's viper config/env/flag stack (dgraph/cmd/root.go:104).
    Mutates the chosen subparser's defaults; returns argv without the
    --config pair."""
    argv = list(argv)
    cfg = {}
    path = None
    for i, a in enumerate(argv):
        if a == "--config":
            if i + 1 >= len(argv):
                print("--config needs a file argument", file=sys.stderr)
                raise SystemExit(2)
            path = argv[i + 1]
            del argv[i:i + 2]
            break
        if a.startswith("--config="):
            path = a.split("=", 1)[1]
            del argv[i]
            break
    if path is not None:
        try:
            with open(path) as f:
                cfg = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"--config {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
    cmd = next((a for a in argv if not a.startswith("-")), None)
    sp = sub_choices.get(cmd)
    if sp is None:
        return argv
    file_vals = cfg.get(cmd, {})
    if not isinstance(file_vals, dict):
        print(f"--config: section {cmd!r} must be an object",
              file=sys.stderr)
        raise SystemExit(2)

    def usage_err(dest, raw, why):
        print(f"config/env value for --{dest.replace('_', '-')}: "
              f"{raw!r} {why}", file=sys.stderr)
        raise SystemExit(2)

    layer = {}
    for action in sp._actions:
        dest = action.dest
        if dest in ("help",):
            continue
        fkey = dest.replace("_", "-")
        raw = None
        if fkey in file_vals or dest in file_vals:
            raw = file_vals.get(fkey, file_vals.get(dest))
        env = os.environ.get(f"DGRAPH_TPU_{cmd.upper()}_{dest.upper()}")
        if env is not None:
            raw = env
        if raw is None:
            continue
        try:
            # run the action's own converter when it has one, else
            # coerce toward the default's type — and honor `choices`,
            # which argparse only checks for CLI-supplied values
            val = action.type(raw) if callable(action.type)                 else _coerce(raw, action.default)
        except (TypeError, ValueError) as e:
            usage_err(dest, raw, f"is invalid ({e})")
        if action.choices is not None and val not in action.choices:
            usage_err(dest, raw,
                      f"not one of {sorted(action.choices)}")
        layer[dest] = val
        # a layered value SATISFIES a required flag (viper semantics)
        action.required = False
    if layer:
        sp.set_defaults(**layer)
    return argv


def cmd_alpha(args) -> int:
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.server.http import serve

    _load_custom_toks(args)
    enc_key = _enc_key(args)
    if args.snapshot:
        from dgraph_tpu.storage.snapshot import load_snapshot

        db = load_snapshot(args.snapshot,
                           GraphDB(wal_path=args.wal or None,
                                   prefer_device=not args.no_device,
                                   enc_key=enc_key,
                                   plan_cache_size=args.plan_cache_size,
                                   result_cache_entries=args.result_cache))
    else:
        db = GraphDB(wal_path=args.wal or None,
                     prefer_device=not args.no_device, enc_key=enc_key,
                     plan_cache_size=args.plan_cache_size,
                     result_cache_entries=args.result_cache)
    secret = None
    if args.acl_secret_file:
        with open(args.acl_secret_file, "rb") as f:
            secret = f.read().strip()
    print(f"dgraph-tpu alpha listening on http://{args.host}:{args.port}"
          + (" (ACL on)" if secret else ""), file=sys.stderr)
    tls_ctx = None
    if args.tls_dir:
        from dgraph_tpu.server.tls import server_context
        tls_ctx = server_context(args.tls_dir,
                                 require_client_cert=args.tls_mtls)
    httpd, alpha = serve(db, host=args.host, port=args.port, block=False,
                         acl_secret=secret, tls_context=tls_ctx,
                         mutations_mode=args.mutations,
                         max_pending=args.max_pending,
                         batch_window_us=args.batch_window_us,
                         tenant_rate=args.tenant_rate,
                         tenant_burst=args.tenant_burst)
    _start_watchdog(alpha, "alpha", wal_path=args.wal or "")
    grpc_srv = None
    if args.grpc_port:
        from dgraph_tpu.server.grpc_api import serve_grpc
        # the gRPC listener inherits the SAME TLS posture as HTTP —
        # --tls-dir must never leave a cleartext side door open
        grpc_srv, gport = serve_grpc(
            alpha, host=args.host, port=args.grpc_port,
            tls_dir=args.tls_dir, require_client_cert=args.tls_mtls)
        print(f"dgraph-tpu alpha gRPC on {args.host}:{gport}"
              + (" (TLS)" if args.tls_dir else ""), file=sys.stderr)
    try:
        import time as _time
        while True:  # interruptible on every platform
            _time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        # graceful drain: stop admitting writes, let in-flight
        # requests finish (bounded), then tear the listeners down
        alpha.draining = True
        alpha.wait_idle(timeout_s=10.0)
        httpd.shutdown()
        if grpc_srv is not None:
            grpc_srv.stop(grace=2).wait()
    return 0


def _parse_peers(spec: str) -> dict[int, tuple[str, int]]:
    """'1=127.0.0.1:7101,2=127.0.0.1:7102' -> {1: (host, port), ...}"""
    out: dict[int, tuple[str, int]] = {}
    for part in spec.split(","):
        nid, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        out[int(nid)] = (host, int(port))
    return out


def cmd_node(args) -> int:
    """A Raft replica process: alpha (replicated GraphDB group member)
    or zero (replicated coordinator quorum member). Ref: dgraph alpha
    --raft / dgraph zero (worker/draft.go, dgraph/cmd/zero/zero.go)."""
    if getattr(args, "skew_s", 0.0):
        # skew-clock nemesis: wall-clock reads in THIS process (TTL
        # reconciliation, stage ages, logs) are offset; raft ticks use
        # time.monotonic and are untouched
        import time as _time
        _real_time = _time.time
        _off = args.skew_s
        _time.time = lambda: _real_time() + _off

    from dgraph_tpu.cluster.service import AlphaServer, ZeroServer

    peers = _parse_peers(args.raft_peers)
    chost, cport = args.client_addr.rsplit(":", 1)
    storage = None
    if args.wal:
        from dgraph_tpu.cluster.raft import DiskStorage
        storage = DiskStorage(args.wal, sync=args.sync)
    kw = dict(storage=storage, tick_s=args.tick_ms / 1000.0,
              election_ticks=args.election_ticks,
              debug_port=args.debug_port, debug_host=args.debug_host)
    if args.kind == "alpha":
        zero_addrs = _parse_peers(args.zero) if args.zero else None
        db_kw = {}
        if getattr(args, "result_cache", 0):
            db_kw["result_cache_entries"] = args.result_cache
        srv = AlphaServer(args.id, peers, (chost, int(cport)),
                          group=args.group, replicas=args.replicas,
                          zero_addrs=zero_addrs,
                          max_pending=args.max_pending,
                          learner=getattr(args, "learner", False),
                          tenant_rate=getattr(args, "tenant_rate", 0.0),
                          tenant_burst=getattr(args, "tenant_burst",
                                               0.0),
                          db_kw=db_kw or None,
                          snapshot=getattr(args, "snapshot", ""), **kw)
    else:
        srv = ZeroServer(
            args.id, peers, (chost, int(cport)),
            move_throttle_mb_s=args.move_throttle_mb_s,
            move_fence_lag=args.move_fence_lag,
            move_fence_timeout_s=args.move_fence_timeout_s,
            rebalance_interval_s=args.rebalance_interval,
            rebalance_band=args.rebalance_band,
            split_heat=args.split_heat,
            rebalance_pin=args.rebalance_pin,
            rebalance_cooldown_s=args.rebalance_cooldown_s,
            standby_of=_parse_peers(args.standby_of)
            if getattr(args, "standby_of", "") else None, **kw)
    print(f"dgraph-tpu {args.kind} node {args.id}: raft "
          f"{peers[args.id]}, client {srv.client_addr}"
          + (f", debug http {args.debug_host}:{args.debug_port}"
             if args.debug_port else ""), file=sys.stderr,
          flush=True)
    _start_watchdog(srv, getattr(srv, "node_name",
                                 f"{args.kind}-{args.id}"),
                    wal_path=args.wal)
    srv.serve_forever()
    return 0


def _start_watchdog(srv, node_name: str, wal_path: str = ""):
    """Start the per-process alert watchdog (utils/watchdog.py) for a
    long-lived server process. DGRAPH_TPU_WATCHDOG=0 disables; bare
    library embeddings never pass through here so they pay nothing.
    Incident bundles land under $DGRAPH_TPU_INCIDENT_DIR/<node> when
    set, else beside the WAL, else stay in-memory-only (no recorder)."""
    if os.environ.get("DGRAPH_TPU_WATCHDOG", "1") == "0":
        return None
    from dgraph_tpu.utils import watchdog
    base = os.environ.get("DGRAPH_TPU_INCIDENT_DIR", "")
    if base:
        inc_dir = os.path.join(base, node_name)
    elif wal_path:
        root = wal_path if os.path.isdir(wal_path) \
            else os.path.dirname(os.path.abspath(wal_path))
        inc_dir = os.path.join(root, "incidents")
    else:
        inc_dir = None
    wd = watchdog.ensure_started(incident_dir=inc_dir, node=node_name)
    if hasattr(srv, "attach_watchdog"):
        srv.attach_watchdog(wd)
    return wd


def _enc_key(args):
    if getattr(args, "encryption_key_file", ""):
        from dgraph_tpu.storage.enc import load_key
        return load_key(args.encryption_key_file)
    return None


def _load_custom_toks(args):
    paths = getattr(args, "custom_tokenizers", "")
    if paths:
        from dgraph_tpu.models.tokenizer import load_custom_tokenizers
        for spec in load_custom_tokenizers(paths.split(",")):
            print(f"loaded custom tokenizer {spec.name!r} "
                  f"(id {spec.ident:#x})", file=sys.stderr)


def cmd_backup(args) -> int:
    """Binary backup with incremental manifest chain
    (ref `dgraph backup` -> ee/backup/backup.go)."""
    from dgraph_tpu.engine.db import GraphDB

    db = GraphDB(wal_path=args.wal or None, prefer_device=False,
                 enc_key=_enc_key(args))
    from dgraph_tpu.storage.backup import backup

    entry = backup(db, args.destination, force_full=args.full,
                   key=_enc_key(args))
    print(json.dumps(entry, indent=2))
    return 0


def cmd_restore(args) -> int:
    """Restore a backup chain into a fresh store
    (ref `dgraph restore` -> ee/backup/restore.go). With --to-ts,
    point-in-time restore: the chain base plus the captured change
    tail replayed up to that exact commit_ts (storage/backup.py
    restore_to_ts; docs/deployment.md "Disaster recovery")."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.storage.backup import restore, restore_to_ts

    db = GraphDB(wal_path=args.wal or None, prefer_device=False,
                 enc_key=_enc_key(args))
    if args.to_ts:
        restore_to_ts(args.location, args.to_ts, db=db,
                      key=_enc_key(args))
    else:
        restore(args.location, db=db, key=_enc_key(args))
    if args.snapshot_out:
        from dgraph_tpu.storage.snapshot import save_snapshot
        save_snapshot(db, args.snapshot_out)
    print(f"restored {len(db.tablets)} predicates, "
          f"max_ts={db.coordinator.max_assigned()}", file=sys.stderr)
    return 0


def cmd_acl(args) -> int:
    """ACL admin against a store directory (ref `dgraph acl` subcommands,
    ee/acl/acl.go: useradd/userdel/groupadd/groupdel/usermod/chmod/info)."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.server.acl import AclManager

    if not args.wal:
        # without a WAL every change silently dies with the process
        # (advisor finding) — refuse rather than print a false success
        print("acl: --wal is required (changes must persist)",
              file=sys.stderr)
        return 2
    db = GraphDB(wal_path=args.wal, prefer_device=False,
                 enc_key=_enc_key(args))
    mgr = AclManager(db, secret=b"cli")
    op = args.acl_op
    if op == "useradd":
        mgr.add_user(args.user, args.password)
    elif op == "userdel":
        mgr.delete_principal(args.user)
    elif op == "groupadd":
        mgr.add_group(args.group)
    elif op == "groupdel":
        mgr.delete_principal(args.group)
    elif op == "usermod":
        mgr.set_groups(args.user, [g for g in args.groups.split(",") if g])
    elif op == "chmod":
        mgr.chmod(args.group, args.pred, args.perm)
    elif op == "info":
        print(json.dumps(mgr.info(), indent=2))
    return 0


def cmd_version(args) -> int:
    print(f"dgraph-tpu {__version__}")
    import jax

    print(f"jax {jax.__version__}; backend devices: "
          f"{[str(d) for d in jax.devices()]}")
    return 0


def cmd_increment(args) -> int:
    """Txn smoke-test canary: read-increment-write a counter N times,
    read and write inside ONE transaction so concurrent canaries
    conflict-abort instead of losing updates
    (ref dgraph/cmd/counter/increment.go:109)."""
    import urllib.error
    import urllib.request

    base = f"http://{args.addr}"

    def post(path, data, ctype):
        req = urllib.request.Request(
            base + path, data.encode(), {"Content-Type": ctype})
        return json.loads(urllib.request.urlopen(req).read())

    done = 0
    while done < args.num:
        # the query's read ts names the txn; mutate+commit attach to it
        r = post("/query", '{ q(func: has(counter.val)) { uid counter.val } }',
                 "application/dql")
        ts = r["extensions"]["txn"]["start_ts"]
        rows = r["data"]["q"]
        if rows:
            uid, val = rows[0]["uid"], rows[0]["counter.val"] + 1
            sub = f"<{uid}>"
        else:
            sub, val = "_:c", 1
        try:
            post(f"/mutate?startTs={ts}",
                 f'{sub} <counter.val> "{val}"^^<xs:int> .',
                 "application/rdf")
            post(f"/commit?startTs={ts}", "", "application/json")
        except urllib.error.HTTPError as e:
            if e.code == 409:  # conflict: retry the whole read-modify-write
                continue
            raise
        done += 1
        print(f"counter.val = {val}")
    return 0


def cmd_bulk(args) -> int:
    """Offline bulk loader (ref dgraph/cmd/bulk/run.go:106). With
    --workers N the load runs cluster-parallel (map workers + one
    reduce process per --reduce-shards group, ingest/distributed.py)
    writing bootable group snapshots directly."""
    import time

    from dgraph_tpu.ingest.bulk import bulk_load

    _load_custom_toks(args)
    schema = open(args.schema).read() if args.schema else ""
    if args.workers > 0:
        if not args.out:
            print("error: --workers needs --out (a directory of "
                  "group snapshots)", file=sys.stderr)
            return 2
        from dgraph_tpu.ingest.distributed import distributed_load
        toks = tuple(p for p in getattr(
            args, "custom_tokenizers", "").split(",") if p)
        manifest = distributed_load(
            args.files, schema=schema,
            groups=max(1, args.reduce_shards),
            workers=args.workers, outdir=args.out,
            custom_tokenizers=toks)
        st = manifest["stats"]
        print(f"mapped {st['mapped']} nquads in {st['map_s']}s, "
              f"reduced {st['reduced']} in {st['reduce_s']}s "
              f"({st['mapped'] / max(st['total_s'], 1e-9):.0f} "
              f"RDF/s end to end)")
        for g, ps in sorted(manifest["groups"].items(),
                            key=lambda kv: int(kv[0])):
            print(f"group {g}: {len(ps)} tablets -> "
                  f"{args.out}/g{g}/p.snap")
        print(f"manifest written to {args.out}/manifest.json")
        return 0
    t0 = time.monotonic()
    db = bulk_load(args.files, schema=schema)
    dt = time.monotonic() - t0
    n = sum(sum(len(v) for v in t.edges.values()) +
            sum(len(v) for v in t.values.values())
            for t in db.tablets.values())
    print(f"loaded {n} edges across {len(db.tablets)} predicates "
          f"in {dt:.2f}s ({n / max(dt, 1e-9):.0f} edges/s)")
    if args.out and args.reduce_shards > 1:
        from dgraph_tpu.ingest.bulk import bulk_shard_outputs

        manifest = bulk_shard_outputs(db, args.reduce_shards, args.out)
        for g, ps in sorted(manifest["groups"].items(),
                            key=lambda kv: int(kv[0])):
            print(f"group {g}: {len(ps)} tablets -> "
                  f"{args.out}/g{g}/p.snap")
        print(f"manifest written to {args.out}/manifest.json")
    elif args.out:
        from dgraph_tpu.storage.snapshot import save_snapshot

        save_snapshot(db, args.out)
        print(f"snapshot written to {args.out}")
    else:
        print("warning: no --out given; load was a dry run "
              "(nothing persisted)", file=sys.stderr)
    return 0


def cmd_live(args) -> int:
    """Online live loader (ref dgraph/cmd/live/run.go:238). With
    --alpha, streams into a RUNNING server over HTTP (the reference's
    defining mode); otherwise loads an embedded store."""
    schema = open(args.schema).read() if args.schema else ""
    if args.alpha:
        from dgraph_tpu.ingest.live import remote_live_load
        stats = remote_live_load(args.alpha, args.files, schema=schema,
                                 batch_size=args.batch,
                                 concurrency=args.conc,
                                 token=args.token)
        print(json.dumps(stats))
        return 0
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.ingest.live import live_load

    if not args.wal:
        print("warning: no --wal given; loaded data dies with the process",
              file=sys.stderr)
    db = GraphDB(wal_path=args.wal or None)
    stats = live_load(db, args.files, schema=schema,
                      batch_size=args.batch, concurrency=args.conc)
    print(json.dumps(stats))
    return 0


def cmd_export(args) -> int:
    """Full-store export (ref worker/export.go:376)."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.ingest.export import (
        export_json, export_rdf, export_schema,
    )

    if args.snapshot:
        from dgraph_tpu.storage.snapshot import load_snapshot

        db = load_snapshot(args.snapshot)
    elif args.wal:
        db = GraphDB(wal_path=args.wal)
    else:
        print("export: need --wal or --snapshot", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        if args.format == "rdf":
            for line in export_rdf(db):
                f.write(line + "\n")
        else:
            json.dump(export_json(db), f)
    with open(args.out + ".schema", "w") as f:
        f.write(export_schema(db))
    print(f"exported to {args.out} (+.schema)")
    return 0


def cmd_debug(args) -> int:
    """Offline store inspector over a WAL file
    (ref dgraph/cmd/debug/run.go)."""
    from dgraph_tpu.engine.db import GraphDB

    db = GraphDB(wal_path=args.wal)
    if args.what == "jepsen":
        # bank-invariant checker (ref dgraph/cmd/debug/run.go:323
        # --jepsen seekTotal): deltas stay UNFOLDED so every commit in
        # the WAL is a readable MVCC snapshot; the balance total must
        # be identical at each one
        pred = args.pred or "bal"
        tab = db.tablets.get(pred)
        if tab is None:
            print(f"no tablet {pred!r}", file=sys.stderr)
            return 1
        tss = sorted({ts for ts, _ in tab.deltas})
        if tab.base_ts:
            tss.insert(0, tab.base_ts)
        report: dict = {"pred": pred, "snapshots": len(tss),
                        "violations": []}
        want = None
        for ts in tss:
            total = 0
            for uid in tab.src_uids(ts).tolist():
                ps = tab.get_postings(int(uid), ts)
                if ps:
                    try:
                        total += int(ps[0].value.value)
                    except (TypeError, ValueError):
                        pass
            if want is None:
                want = total
            elif total != want:
                report["violations"].append(
                    {"ts": ts, "total": total, "expected": want})
        report["ok"] = not report["violations"]
        report["total"] = want
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    db.rollup_all(window=0)  # fold replayed deltas so counts reflect the store
    st = db.state()
    if args.what == "state":
        print(json.dumps(st, indent=2, default=str))
    elif args.what == "schema":
        print(db.schema.describe_all())
    elif args.what == "histogram":
        for pred, tab in sorted(db.tablets.items()):
            n = sum(len(v) for v in tab.edges.values()) + \
                sum(len(v) for v in tab.values.values())
            print(f"{pred}\t{n}")
    elif args.what == "posting":
        # posting inspector (ref dgraph/cmd/debug/run.go lookup mode:
        # dump one uid's postings + the index tokens covering them)
        from dgraph_tpu.models.tokenizer import get_tokenizer, tokens_for
        if not args.pred or not args.uid:
            print("debug posting needs --pred and --uid",
                  file=sys.stderr)
            return 2
        tab = db.tablets.get(args.pred)
        if tab is None:
            print(f"no tablet {args.pred!r}", file=sys.stderr)
            return 1
        uid = int(args.uid, 0)
        ts = db.coordinator.max_assigned()
        out: dict = {"pred": args.pred, "uid": hex(uid)}
        dsts = tab.get_dst_uids(uid, ts)
        if len(dsts):
            out["edges"] = [hex(int(d)) for d in dsts.tolist()]
        rev = tab.get_reverse_uids(uid, ts)
        if len(rev):
            out["reverse"] = [hex(int(s)) for s in rev.tolist()]
        ps = tab.get_postings(uid, ts)
        if ps:
            out["postings"] = [
                {"value": str(p.value.value), "type": p.value.tid.name,
                 "lang": p.lang,
                 "facets": {k: str(v.value)
                            for k, v in p.facets.items()},
                 "tokens": [str(t) for tname in tab.schema.tokenizers
                            for t in tokens_for(
                                p.value, get_tokenizer(tname), p.lang)]}
                for p in ps]
        print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_cert(args) -> int:
    """TLS certificate management (ref `dgraph cert`, dgraph/cmd/cert/)."""
    from dgraph_tpu.server import tls as tlsmod

    if args.cert_op == "ls":
        print(json.dumps(tlsmod.describe(args.dir), indent=2))
        return 0
    import os as _os
    if not _os.path.exists(_os.path.join(args.dir, "ca.crt")):
        tlsmod.create_ca(args.dir, days=args.duration)
        print(f"created CA in {args.dir}", file=sys.stderr)
    if args.cert_op in ("node", "create"):
        hosts = tuple(h for h in args.nodes.split(",") if h)
        crt, key = tlsmod.create_pair(args.dir, "node", hosts=hosts,
                                      days=args.duration)
        print(f"node pair: {crt}, {key}", file=sys.stderr)
    if args.client:
        crt, key = tlsmod.create_pair(args.dir, "client", args.client,
                                      days=args.duration)
        print(f"client pair: {crt}, {key}", file=sys.stderr)
    return 0


def cmd_conv(args) -> int:
    """GeoJSON -> RDF (ref `dgraph conv`, dgraph/cmd/conv/)."""
    from dgraph_tpu.ingest.convert import convert_geojson

    with open(args.geo) as fin, open(args.out, "w") as fout:
        stats = convert_geojson(fin, fout, geopred=args.geopred)
    print(json.dumps(stats))
    return 0


def cmd_migrate(args) -> int:
    """SQL -> RDF + schema (ref `dgraph migrate`, dgraph/cmd/migrate/;
    sqlite is the SQL source here — the table/row/foreign-key mapping
    matches the reference's MySQL walker)."""
    from dgraph_tpu.ingest.convert import migrate_sqlite

    with open(args.output_data, "w") as rdf, \
            open(args.output_schema, "w") as sch:
        stats = migrate_sqlite(args.db, rdf, sch,
                               separator=args.separator)
    print(json.dumps(stats))
    return 0


def cmd_debuginfo(args) -> int:
    """Collect a diagnostics archive (ref `dgraph debuginfo`,
    dgraph/cmd/debuginfo: pprof + state; here: /health /state /metrics
    + thread stacks + env)."""
    import faulthandler
    import io
    import platform
    import tarfile
    import time as _time
    import urllib.request

    files: dict[str, bytes] = {}
    if args.alpha:
        base = f"http://{args.alpha}"
        for path in ("/health", "/state", "/debug/prometheus_metrics"):
            try:
                files[path.strip("/").replace("/", "_")] = \
                    urllib.request.urlopen(base + path, timeout=5).read()
            except Exception as e:  # noqa: BLE001 — capture what we can
                files[path.strip("/").replace("/", "_") + ".error"] = \
                    str(e).encode()
    import tempfile
    with tempfile.TemporaryFile(mode="w+") as tf:
        faulthandler.dump_traceback(file=tf)
        tf.seek(0)
        files["threads.txt"] = tf.read().encode()
    files["platform.txt"] = "\n".join([
        platform.platform(), platform.python_version(),
        f"argv={sys.argv}"]).encode()
    # wall clock: the archive NAME is a user-visible timestamp
    out = args.archive or f"debuginfo-{int(_time.time())}.tar.gz"  # dglint: disable=DG06
    with tarfile.open(out, "w:gz") as tar:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    print(out)
    return 0


def cmd_compose(args) -> int:
    """Generate a cluster topology launcher (ref compose/compose.go:
    the reference emits docker-compose.yml for N zeros x G groups x R
    replicas; here the artifact is a runnable shell script plus a JSON
    topology map for RoutedCluster)."""
    zeros = args.num_zeros
    groups = args.num_groups
    replicas = args.num_replicas
    port = args.base_port
    lines = ["#!/bin/sh", "# generated by dgraph-tpu compose",
             "set -e", 'mkdir -p "$(dirname "$0")/wal"', ""]
    topo: dict = {"zero": {}, "groups": {}}

    def alloc():
        nonlocal port
        port += 1
        return port

    zraft = {i: f"127.0.0.1:{alloc()}" for i in range(1, zeros + 1)}
    zpeers = ",".join(f"{i}={a}" for i, a in zraft.items())
    for i in range(1, zeros + 1):
        caddr = f"127.0.0.1:{alloc()}"
        topo["zero"][i] = caddr
        lines.append(
            f"python -m dgraph_tpu node --kind zero --id {i} "
            f"--raft-peers {zpeers} --client-addr {caddr} "
            f'--wal "$(dirname "$0")/wal/zero{i}" &')
    zero_clients = ",".join(f"{i}={a}" for i, a in topo["zero"].items())
    for g in range(1, groups + 1):
        graft = {i: f"127.0.0.1:{alloc()}"
                 for i in range(1, replicas + 1)}
        gpeers = ",".join(f"{i}={a}" for i, a in graft.items())
        topo["groups"][g] = {}
        for i in range(1, replicas + 1):
            caddr = f"127.0.0.1:{alloc()}"
            topo["groups"][g][i] = caddr
            lines.append(
                f"python -m dgraph_tpu node --kind alpha --id {i} "
                f"--group {g} --raft-peers {gpeers} "
                f"--client-addr {caddr} --zero {zero_clients} "
                f'--wal "$(dirname "$0")/wal/g{g}n{i}" &')
    lines += ["", "wait"]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.chmod(args.out, 0o755)
    with open(args.out + ".topology.json", "w") as f:
        json.dump(topo, f, indent=2)
    print(f"wrote {args.out} and {args.out}.topology.json "
          f"({zeros} zeros, {groups} groups x {replicas} replicas)")
    return 0


def cmd_standby(args) -> int:
    """Standby-cluster admin against the STANDBY's zero quorum
    (cluster/replication.py): `status` prints per-predicate
    replication lag; `promote` fails the standby over to a writable
    primary — fencing the old primary, draining to its post-fence CDC
    heads, and reporting measured RPO/RTO (docs/deployment.md
    "Disaster recovery & upgrades")."""
    from dgraph_tpu.cluster.client import ClusterClient

    zero = ClusterClient(_parse_peers(args.zero), timeout=60.0)
    try:
        if args.standby_op == "status":
            out = zero._unwrap(zero.request({"op": "repl_status"}))
            print(json.dumps(out, indent=2))
            return 0
        out = zero.request({"op": "standby_promote",
                            "force": args.force})
        if not out.get("ok"):
            print(f"promote failed: {out.get('error')}",
                  file=sys.stderr)
            return 1
        res = out["result"]
        print(json.dumps(res, indent=2))
        print(f"promoted: rpo_clean={res['rpo_clean']} "
              f"drained={res['rpo_commits_drained']} commits, "
              f"rto={res['rto_ms']}ms", file=sys.stderr)
        return 0
    finally:
        zero.close()


def cmd_rebalance(args) -> int:
    """Tablet rebalancing (ref zero/tablet.go:62 rebalanceTablets; the
    reference runs it inside zero every --rebalance_interval 8m). Takes
    the compose topology map, moves one tablet heaviest->lightest per
    tick until converged; --once for a single pass."""
    import time as _time

    from dgraph_tpu.cluster.client import ClusterClient
    from dgraph_tpu.cluster.topology import Rebalancer, RoutedCluster

    with open(args.topology) as f:
        topo = json.load(f)

    def addrs(d: dict) -> dict:
        out = {}
        for i, a in d.items():
            host, port = a.rsplit(":", 1)
            out[int(i)] = (host, int(port))
        return out

    zero = ClusterClient(addrs(topo["zero"]), timeout=30.0)
    groups = {int(g): ClusterClient(addrs(members), timeout=30.0)
              for g, members in topo["groups"].items()}
    rc = RoutedCluster(zero, groups)
    reb = Rebalancer(rc, interval_s=args.interval,
                     threshold=args.threshold)
    try:
        while True:
            try:
                move = reb.tick()
            except Exception as e:  # noqa: BLE001 — daemon keeps going
                if args.once:
                    raise
                # transient (zero election, concurrent operator move):
                # log and retry next interval, like the in-zero loop
                print(f"rebalance pass failed: {e}", file=sys.stderr)
                move = None
            if move:
                pred, src, dst = move
                print(f"moved tablet {pred!r}: group {src} -> {dst}")
            elif args.once:
                print("balanced")
            if args.once:
                if move is None:
                    return 0
                continue  # --once converges without pacing
            # daemon mode paces ONE move per interval so the cluster
            # absorbs each export/import before the next (the
            # reference's rebalance_interval exists for exactly this)
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        rc.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dgraph-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    a = sub.add_parser("alpha", help="serve the engine over HTTP")
    a.add_argument("--host", default="0.0.0.0")
    a.add_argument("--port", type=int,
                   default=8080)
    a.add_argument("--wal", default="")
    a.add_argument("--snapshot", default="")
    a.add_argument("--no-device", action="store_true",
                   default=False)
    a.add_argument("--max-pending", type=int, default=0,
                   help="admission control: max concurrently admitted "
                        "requests; excess sheds with HTTP 429 "
                        "(retryable). 0 = unbounded (ref the "
                        "reference's pending-query throttle)")
    a.add_argument("--mutations", default="allow",
                   choices=["allow", "disallow", "strict"],
                   help="mutation mode (ref --mutations, "
                        "alpha/run.go:502)")
    a.add_argument("--plan-cache-size", type=int, default=128,
                   help="compiled query plan cache entries "
                        "(query/plan.py); 0 disables and every "
                        "request takes the interpreted path")
    a.add_argument("--batch-window-us", type=int, default=0,
                   help="micro-batching window in microseconds: "
                        "concurrent queries sharing a plan-cache key "
                        "coalesce into one dispatch. 0 = off")
    a.add_argument("--result-cache", type=int, default=0,
                   help="CDC-invalidated query result cache entries "
                        "(engine/result_cache.py): best-effort reads "
                        "serve byte-identical cached responses until "
                        "a write touches their predicate footprint. "
                        "0 = off")
    a.add_argument("--tenant-rate", type=float, default=0.0,
                   help="per-tenant QoS: admission tokens/second per "
                        "X-Dgraph-Tenant namespace; a tenant over its "
                        "rate sheds 429 without starving the rest. "
                        "0 = off")
    a.add_argument("--tenant-burst", type=float, default=0.0,
                   help="per-tenant QoS bucket depth (defaults to "
                        "--tenant-rate when 0)")
    a.add_argument("--acl_secret_file",
                   default="",
                   help="enables ACL; file holds the HMAC jwt secret")
    a.add_argument("--encryption_key_file",
                   default="",
                   help="AES key file: encrypts WAL records at rest")
    a.add_argument("--grpc-port", type=int, default=0,
                   help="also serve the gRPC API on this port (ref "
                        "dgraph alpha's 9080)")
    a.add_argument("--tls-dir", default="",
                   help="serve HTTPS from this cert dir (see `cert`)")
    a.add_argument("--tls-mtls", action="store_true",
                   help="require client certificates (mTLS)")
    a.add_argument("--custom_tokenizers", default="",
                   help="comma-separated Python plugin files, each "
                        "exporting tokenizer() (ref tok/tok.go:116 "
                        "LoadCustomTokenizer)")
    a.set_defaults(fn=cmd_alpha)

    acl = sub.add_parser("acl", help="ACL admin on a store directory")
    acl.add_argument("acl_op", choices=["useradd", "userdel", "groupadd",
                                        "groupdel", "usermod", "chmod",
                                        "info"])
    acl.add_argument("--wal", default="", help="store WAL path")
    acl.add_argument("--encryption_key_file", default="")
    acl.add_argument("-a", "--user", default="")
    acl.add_argument("-g", "--group", default="")
    acl.add_argument("-p", "--password", default="")
    acl.add_argument("-l", "--groups", default="",
                     help="comma-separated groups for usermod")
    acl.add_argument("--pred", default="", help="predicate for chmod")
    acl.add_argument("-m", "--perm", type=int, default=0,
                     help="perm bits for chmod: Read=4 Write=2 Modify=1")
    acl.set_defaults(fn=cmd_acl)

    bk = sub.add_parser("backup", help="binary backup (manifest chain)")
    bk.add_argument("--wal", default="", help="store WAL path")
    bk.add_argument("destination", help="backup dir or file:// URI")
    bk.add_argument("--full", action="store_true",
                    help="force a full backup instead of incremental")
    bk.add_argument("--encryption_key_file", default="")
    bk.set_defaults(fn=cmd_backup)

    rs = sub.add_parser("restore", help="restore a backup chain")
    rs.add_argument("location", help="backup dir or file:// URI")
    rs.add_argument("--wal", default="",
                    help="WAL path for the restored store")
    rs.add_argument("--snapshot_out", default="",
                    help="also write a snapshot file")
    rs.add_argument("--to-ts", dest="to_ts", type=int, default=0,
                    help="point-in-time restore: materialize the "
                         "state at this commit_ts (any covered "
                         "instant, not just backup boundaries)")
    rs.add_argument("--encryption_key_file", default="")
    rs.set_defaults(fn=cmd_restore)

    v = sub.add_parser("version", help="print version info")
    v.set_defaults(fn=cmd_version)

    c = sub.add_parser("increment", help="txn canary: increment a counter")
    c.add_argument("--addr", default="127.0.0.1:8080")
    c.add_argument("--num", type=int, default=1)
    c.set_defaults(fn=cmd_increment)

    b = sub.add_parser("bulk", help="offline bulk loader")
    b.add_argument("files", nargs="+")
    b.add_argument("--schema", default="")
    b.add_argument("--out", default="",
                   help="snapshot file to write (the bulk output); "
                        "with --reduce-shards > 1, a DIRECTORY of "
                        "per-group snapshots out/g<k>/p.snap")
    b.add_argument("--reduce-shards", type=int, default=1,
                   help="shard the output across N future alpha "
                        "groups (ref dgraph bulk --reduce_shards: "
                        "one out/<i>/p per group)")
    b.add_argument("--workers", type=int, default=0,
                   help="distributed load: N map-worker processes + "
                        "one reduce process per --reduce-shards "
                        "group, streaming the shuffle over the wire "
                        "and writing bootable group snapshots "
                        "directly (0 = single-core loader)")
    b.add_argument("--custom_tokenizers", default="",
                   help="comma-separated Python plugin files, each "
                        "exporting tokenizer()")
    b.set_defaults(fn=cmd_bulk)

    lv = sub.add_parser("live", help="online live loader")
    lv.add_argument("files", nargs="+")
    lv.add_argument("--schema", default="")
    lv.add_argument("--wal", default="")
    lv.add_argument("--alpha", default="",
                    help="host:port of a running alpha: stream over "
                         "HTTP instead of loading an embedded store")
    lv.add_argument("--token", default="",
                    help="access JWT for ACL-protected alphas "
                         "(ref dgraph live --creds)")
    lv.add_argument("--batch", type=int, default=1000)
    lv.add_argument("--conc", type=int, default=4)
    lv.set_defaults(fn=cmd_live)

    e = sub.add_parser("export", help="export store to RDF/JSON")
    e.add_argument("--wal", default="")
    e.add_argument("--snapshot", default="")
    e.add_argument("--out", required=True)
    e.add_argument("--format", choices=["rdf", "json"], default="rdf")
    e.set_defaults(fn=cmd_export)

    d = sub.add_parser("debug", help="offline store inspector")
    d.add_argument("--wal", required=True)
    d.add_argument("what",
                   choices=["state", "schema", "histogram", "posting",
                            "jepsen"])
    d.add_argument("--pred", default="")
    d.add_argument("--uid", default="")
    d.set_defaults(fn=cmd_debug)

    n = sub.add_parser("node", help="raft replica (alpha group / zero)")
    n.add_argument("--kind", choices=["alpha", "zero"], default="alpha")
    n.add_argument("--id", type=int, required=True)
    n.add_argument("--raft-peers", required=True,
                   help="id=host:port,... for every group member")
    n.add_argument("--client-addr", required=True, help="host:port")
    n.add_argument("--group", type=int, default=1,
                   help="alpha group id (predicate shard); 0 = let "
                        "zero assign the least-replicated group and "
                        "raft-join it live (ref zero.go:410 Connect)")
    n.add_argument("--replicas", type=int, default=1,
                   help="replica target per group for --group 0 "
                        "placement (ref zero --replicas)")
    n.add_argument("--zero", default="",
                   help="zero quorum client addrs (id=host:port,...) — "
                        "enables multi-group mode: tablet ownership "
                        "checks + zero-leased uid blocks")
    n.add_argument("--skew-s", type=float, default=0.0,
                   help="TEST NEMESIS: offset this process's wall "
                        "clock by SKEW seconds (time.time only) — the "
                        "Jepsen skew-clock nemesis (ref contrib/"
                        "jepsen/main.go:31-43); correctness must not "
                        "depend on wall clocks (the ts oracle is "
                        "zero-issued and logical)")
    n.add_argument("--snapshot", default="",
                   help="boot the group's engine from a bulk output "
                        "snapshot (out/g<k>/p.snap); every replica of "
                        "the group must use the same file")
    n.add_argument("--wal", default="", help="raft storage directory")
    n.add_argument("--sync", action="store_true")
    n.add_argument("--tick-ms", type=int, default=50)
    n.add_argument("--election-ticks", type=int, default=10)
    n.add_argument("--debug-port", type=int, default=0,
                   help="serve the read-only debug/observability "
                        "HTTP surface (/debug/stats, /debug/requests, "
                        "/debug/prometheus_metrics, /debug/traces, "
                        "/debug/pprof) on this port — the reference's "
                        "per-node pprof/expvar mux. 0 = off")
    n.add_argument("--debug-host", default="127.0.0.1",
                   help="bind address for --debug-port (keep it "
                        "localhost/scrape-net: the surface is "
                        "unauthenticated by design)")
    n.add_argument("--max-pending", type=int, default=0,
                   help="alpha only: admission control on the wire "
                        "surface — max concurrently served "
                        "query/mutate/task ops; excess sheds typed "
                        "(retryable) like the HTTP edge's 429. "
                        "0 = unbounded")
    n.add_argument("--learner", action="store_true",
                   help="alpha only: join the group as a NON-VOTING "
                        "read replica (raft learner): receives the "
                        "replicated log, never campaigns or serves "
                        "writes, answers watermark-bounded follower "
                        "reads (with --group 0, zero places it on the "
                        "least-loaded existing group)")
    n.add_argument("--tenant-rate", type=float, default=0.0,
                   help="alpha only: per-tenant QoS admission "
                        "tokens/second per tenant namespace; a tenant "
                        "over its rate sheds typed (retryable) "
                        "without starving the rest. 0 = off")
    n.add_argument("--tenant-burst", type=float, default=0.0,
                   help="alpha only: per-tenant QoS bucket depth "
                        "(defaults to --tenant-rate when 0)")
    n.add_argument("--result-cache", type=int, default=0,
                   help="alpha only: CDC-invalidated query result "
                        "cache entries; replica-consistent change-log "
                        "offsets keep every replica's cache honest. "
                        "0 = off")
    n.add_argument("--move-throttle-mb-s", type=float, default=64.0,
                   help="zero only: tablet-move snapshot streaming "
                        "budget in MB/s (the source keeps serving; "
                        "the throttle bounds the move's bandwidth "
                        "tax). 0 = unthrottled")
    n.add_argument("--move-fence-lag", type=int, default=16,
                   help="zero only: fence the moving tablet's writes "
                        "once CDC catch-up is within this many "
                        "change-log entries of the source head")
    n.add_argument("--move-fence-timeout-s", type=float, default=5.0,
                   help="zero only: unfence (writes resume, catch-up "
                        "continues) if the fence drain hasn't "
                        "converged by then")
    n.add_argument("--rebalance-interval", type=float, default=0.0,
                   help="zero only: heat-driven rebalancer tick "
                        "seconds (ref zero --rebalance_interval 8m); "
                        "0 = disabled")
    n.add_argument("--rebalance-band", type=float, default=1.4,
                   help="zero only: hysteresis — rebalance only when "
                        "the heaviest group's load exceeds BAND x the "
                        "lightest's")
    n.add_argument("--rebalance-pin", default="",
                   help="zero only: comma list of predicates the "
                        "rebalancer must never auto-move — the "
                        "colocation knob for constraints it cannot "
                        "see (e.g. a vector predicate plus the "
                        "attributes its similar_to queries select: "
                        "cross-group vector search is unsupported)")
    n.add_argument("--rebalance-cooldown-s", type=float, default=120.0,
                   help="zero only: a just-moved tablet is frozen "
                        "this long so the heat EWMA re-equilibrates "
                        "instead of thrashing it back")
    n.add_argument("--standby-of", default="",
                   help="zero only: run this cluster as an async-"
                        "replication STANDBY tailing the primary "
                        "whose zero quorum listens at these client "
                        "addrs (id=host:port,...). The standby boots "
                        "write-fenced (client writes refused, typed); "
                        "`dgraph-tpu standby promote` fails over with "
                        "measured RPO/RTO (docs/deployment.md "
                        "\"Disaster recovery & upgrades\")")
    n.add_argument("--split-heat", type=float, default=0.0,
                   help="zero only: heat EWMA past which a group-"
                        "dominating predicate splits into hash-range "
                        "sub-tablets instead of moving whole; "
                        "0 = splitting disabled")
    n.set_defaults(fn=cmd_node)

    ct = sub.add_parser("cert", help="TLS certificate management")
    ct.add_argument("cert_op", choices=["create", "node", "ls"],
                    nargs="?", default="create")
    ct.add_argument("--dir", default="tls")
    ct.add_argument("--nodes", default="localhost,127.0.0.1",
                    help="node cert SAN hosts, comma separated")
    ct.add_argument("--client", default="", help="issue a client pair")
    ct.add_argument("--duration", type=int, default=730, help="days")
    ct.set_defaults(fn=cmd_cert)

    cv = sub.add_parser("conv", help="GeoJSON -> RDF converter")
    cv.add_argument("--geo", required=True)
    cv.add_argument("--out", default="output.rdf")
    cv.add_argument("--geopred", default="loc")
    cv.set_defaults(fn=cmd_conv)

    mg = sub.add_parser("migrate", help="SQL (sqlite) -> RDF + schema")
    mg.add_argument("--db", required=True, help="sqlite database file")
    mg.add_argument("--output-data", default="sql.rdf")
    mg.add_argument("--output-schema", default="schema.txt")
    mg.add_argument("--separator", default=".")
    mg.set_defaults(fn=cmd_migrate)

    di = sub.add_parser("debuginfo", help="collect diagnostics archive")
    di.add_argument("--alpha", default="",
                    help="alpha host:port to scrape state/metrics from")
    di.add_argument("--archive", default="")
    di.set_defaults(fn=cmd_debuginfo)

    co = sub.add_parser("compose", help="generate a cluster launcher")
    co.add_argument("--num-zeros", type=int, default=3)
    co.add_argument("--num-groups", type=int, default=2)
    co.add_argument("--num-replicas", type=int, default=3)
    co.add_argument("--base-port", type=int, default=7000)
    co.add_argument("--out", default="cluster.sh")
    co.set_defaults(fn=cmd_compose)

    sb = sub.add_parser("standby",
                        help="async-replication standby admin "
                             "(status / promote)")
    sb.add_argument("standby_op", choices=["status", "promote"],
                    help="status: per-predicate replication lag; "
                         "promote: fail over to a writable primary "
                         "with measured RPO/RTO")
    sb.add_argument("--zero", required=True,
                    help="the STANDBY cluster's zero client addrs "
                         "(id=host:port,...)")
    sb.add_argument("--force", action="store_true",
                    help="promote even if the primary is unreachable "
                         "(accepts losing the unreplicated tail; "
                         "RPO reported as unclean)")
    sb.set_defaults(fn=cmd_standby)

    rb = sub.add_parser("rebalance",
                        help="tablet rebalancer (zero/tablet.go:62)")
    rb.add_argument("topology",
                    help="topology.json from `compose`")
    rb.add_argument("--interval", type=float, default=480.0,
                    help="seconds between passes (ref "
                         "--rebalance_interval 8m)")
    rb.add_argument("--threshold", type=int, default=2,
                    help="min load spread before moving a tablet")
    rb.add_argument("--once", action="store_true",
                    help="run until balanced, then exit")
    rb.set_defaults(fn=cmd_rebalance)

    argv = _apply_config_layers(sub.choices,
                                argv if argv is not None else sys.argv[1:])
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
