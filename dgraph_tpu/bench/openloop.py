"""Open-loop arrival scheduler + latency summarizers.

Closed-loop harnesses (N workers, each firing the next request the
moment the last returns) measure service time, not latency under
offered load: when the server slows down, a closed loop *slows its own
arrival rate* and hides the queue. An open loop fixes the arrival
schedule up front — latency is measured from the SCHEDULED arrival, so
time spent queueing behind a saturated server counts (the
coordinated-omission correction; the reference load-tests the same way
with its `dgraph counter`/increment traffic tools at fixed rates,
SURVEY §4.5).

Factored out of bench_queries.py --concurrency so the single-node
batching gate, the cluster harness (tools/dgbench.py) and the CI load
smoke share ONE definition of "offered load" and "p99".
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence


def run_open_loop(submit: Callable, reqs: Sequence,
                  concurrency: int, rate_qps: float,
                  burst_of: Optional[Sequence[int]] = None,
                  results: Optional[list] = None,
                  arrivals_out: Optional[list] = None) -> list[float]:
    """Drive `submit(req)` over one global open-loop schedule.

    One arrival schedule at `rate_qps` offered load; `concurrency`
    workers pull the next request as they free up; latency[i] =
    finish - SCHEDULED arrival (queueing counts, the open-loop
    property). `burst_of[i]` assigns request i to an arrival slot —
    requests sharing a slot arrive at the same instant (fan-out
    bursts). With `results` (a caller list), submit's return value is
    appended as results[i] = (index, value) — dgbench uses it to
    classify outcomes without wrapping submit in another closure.
    With `arrivals_out` (a caller list), the absolute scheduled
    arrival times (time.perf_counter clock) are appended before
    driving starts — tools/dgchaos.py aligns them against its
    nemesis timeline instead of re-deriving the schedule.
    """
    t0 = time.perf_counter() + 0.05
    if burst_of is None:
        arrivals = [t0 + i / rate_qps for i in range(len(reqs))]
    else:
        slots = burst_of[-1] + 1
        slot_rate = rate_qps * slots / len(reqs)
        arrivals = [t0 + s / slot_rate for s in burst_of]
    if arrivals_out is not None:
        arrivals_out.extend(arrivals)
    lat = [0.0] * len(reqs)
    nxt = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = nxt[0]
                if i >= len(reqs):
                    return
                nxt[0] += 1
            wait = arrivals[i] - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            out = submit(reqs[i])
            lat[i] = time.perf_counter() - arrivals[i]
            if results is not None:
                with lock:
                    results.append((i, out))

    threads = [threading.Thread(target=worker)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lat


def percentiles(lat: Sequence[float]) -> dict:
    """The BENCH_BATCH.json column shape: p50/p99/mean in ms."""
    import numpy as np

    a = np.asarray(lat) * 1e3
    return {"p50_ms": round(float(np.percentile(a, 50)), 3),
            "p99_ms": round(float(np.percentile(a, 99)), 3),
            "mean_ms": round(float(a.mean()), 3)}


def latency_summary(lat: Sequence[float]) -> dict:
    """The full distribution dgbench reports per op class / outcome:
    percentiles() plus the tail (p90/p999/max) and the count."""
    import numpy as np

    if not len(lat):
        return {"count": 0}
    a = np.asarray(lat) * 1e3
    out = percentiles(lat)
    out.update({
        "count": int(len(a)),
        "p90_ms": round(float(np.percentile(a, 90)), 3),
        "p999_ms": round(float(np.percentile(a, 99.9)), 3),
        "max_ms": round(float(a.max()), 3),
    })
    return out


def occupancy(total_requests: int, dispatches: float) -> float:
    """Mean batch occupancy from a request count and a dispatch
    counter delta (the micro-batcher's efficiency summary)."""
    return round(total_requests / max(dispatches, 1), 2)
