"""Spawn/teardown of a real multi-group, multi-process cluster.

The reference load-tests against docker-compose topologies (compose/
compose.go emits N zeros x G groups x R replicas); this module is that
topology as subprocesses of the EXISTING CLI — every node is a real
`python -m dgraph_tpu node` process on real sockets, nothing shares a
GIL with the load generator. Used by tools/dgbench.py and the
tools/check.sh load smoke; tests spawn the same shape ad hoc
(tests/test_multigroup.py) and can migrate here.

Each node gets a --debug-port (the read-only observability listener,
server/debug_http.py) so collectors scrape HTTP; data traffic flows
over the cluster wire via the returned RoutedCluster.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class ProcessCluster:
    """`zeros` zero nodes (one Raft quorum) + `groups` alpha groups of
    `replicas` each, spawned via the CLI. `log_dir` captures each
    node's stderr (the run report's per-node logs); `max_pending`
    turns on wire-surface admission control on every alpha."""

    def __init__(self, groups: int = 2, replicas: int = 1,
                 zeros: int = 1, max_pending: int = 0,
                 log_dir: Optional[str] = None,
                 tick_ms: int = 30, election_ticks: int = 8,
                 env_extra: Optional[dict] = None):
        self.groups_n = groups
        self.replicas = replicas
        self.procs: dict[str, subprocess.Popen] = {}
        self.debug_urls: dict[str, str] = {}
        self.zero_addrs: dict[int, tuple[str, int]] = {}
        self.group_addrs: dict[int, dict[int, tuple[str, int]]] = {}
        self._logs: list = []
        self._env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"), PYTHONPATH=_REPO)
        if env_extra:
            self._env.update(env_extra)
        self._tick = ["--tick-ms", str(tick_ms),
                      "--election-ticks", str(election_ticks)]
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)

        # zero quorum
        zports = free_ports(3 * zeros)
        zraft = {i + 1: ("127.0.0.1", zports[3 * i])
                 for i in range(zeros)}
        zpeers = ",".join(f"{i}={h}:{p}" for i, (h, p) in zraft.items())
        for i in range(1, zeros + 1):
            cport, dport = zports[3 * (i - 1) + 1], zports[3 * (i - 1) + 2]
            self.zero_addrs[i] = ("127.0.0.1", cport)
            self._spawn(f"zero-n{i}", [
                "--kind", "zero", "--id", str(i),
                "--raft-peers", zpeers,
                "--client-addr", f"127.0.0.1:{cport}",
                "--debug-port", str(dport)])
        zero_spec = ",".join(f"{i}={h}:{p}"
                             for i, (h, p) in self.zero_addrs.items())

        # alpha groups
        for g in range(1, groups + 1):
            ports = free_ports(3 * replicas)
            graft = {i + 1: ("127.0.0.1", ports[3 * i])
                     for i in range(replicas)}
            gpeers = ",".join(f"{i}={h}:{p}"
                              for i, (h, p) in graft.items())
            self.group_addrs[g] = {}
            for i in range(1, replicas + 1):
                cport = ports[3 * (i - 1) + 1]
                dport = ports[3 * (i - 1) + 2]
                self.group_addrs[g][i] = ("127.0.0.1", cport)
                args = ["--kind", "alpha", "--id", str(i),
                        "--group", str(g),
                        "--raft-peers", gpeers,
                        "--client-addr", f"127.0.0.1:{cport}",
                        "--zero", zero_spec,
                        "--debug-port", str(dport)]
                if max_pending:
                    args += ["--max-pending", str(max_pending)]
                self._spawn(f"alpha-g{g}-n{i}", args)

    def _spawn(self, name: str, args: list[str]):
        if self.log_dir:
            log = open(os.path.join(self.log_dir, name + ".log"), "w")
            self._logs.append(log)
        else:
            log = subprocess.DEVNULL
        dport = args[args.index("--debug-port") + 1]
        self.debug_urls[name] = f"http://127.0.0.1:{dport}"
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", "dgraph_tpu", "node"]
            + args + self._tick,
            env=self._env, cwd=_REPO,
            stdout=subprocess.DEVNULL, stderr=log)

    # ------------------------------------------------------------ clients

    def routed(self, timeout: float = 30.0):
        """A fresh RoutedCluster over this topology (caller closes)."""
        from dgraph_tpu.cluster.client import ClusterClient
        from dgraph_tpu.cluster.topology import RoutedCluster
        zero = ClusterClient(self.zero_addrs, timeout=timeout)
        groups = {g: ClusterClient(addrs, timeout=timeout)
                  for g, addrs in self.group_addrs.items()}
        return RoutedCluster(zero, groups)

    def node_clients(self, timeout: float = 30.0) -> dict:
        """One single-address ClusterClient per NODE (not per group):
        the collector path — stats/traces/pprof ops hit a specific
        process, not whoever the leader is."""
        from dgraph_tpu.cluster.client import ClusterClient
        out = {}
        for i, addr in self.zero_addrs.items():
            out[f"zero-n{i}"] = ClusterClient({1: addr},
                                              timeout=timeout)
        for g, members in self.group_addrs.items():
            for i, addr in members.items():
                out[f"alpha-g{g}-n{i}"] = ClusterClient(
                    {1: addr}, timeout=timeout)
        return out

    # ------------------------------------------------------------- health

    def wait_ready(self, timeout_s: float = 60.0):
        """Every raft quorum (zero + each group) has a leader."""
        from dgraph_tpu.cluster.client import ClusterClient
        pending = {"zero": ClusterClient(self.zero_addrs, timeout=5.0)}
        for g, addrs in self.group_addrs.items():
            pending[f"g{g}"] = ClusterClient(addrs, timeout=5.0)
        try:
            end = time.monotonic() + timeout_s
            ready: set[str] = set()
            while time.monotonic() < end and len(ready) < len(pending):
                for name, cl in pending.items():
                    if name in ready:
                        continue
                    for node in list(cl.addrs):
                        try:
                            if cl.status(node).get("role") == "leader":
                                ready.add(name)
                                break
                        except (ConnectionError, RuntimeError, KeyError):
                            continue
                if len(ready) < len(pending):
                    time.sleep(0.2)
            if len(ready) < len(pending):
                raise TimeoutError(
                    f"cluster not ready after {timeout_s}s: "
                    f"missing {sorted(set(pending) - ready)}")
        finally:
            for cl in pending.values():
                cl.close()

    def alive(self) -> list[str]:
        return [n for n, p in self.procs.items() if p.poll() is None]

    def teardown(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for log in self._logs:
            try:
                log.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.teardown()
