"""Spawn/teardown of a real multi-group, multi-process cluster.

The reference load-tests against docker-compose topologies (compose/
compose.go emits N zeros x G groups x R replicas); this module is that
topology as subprocesses of the EXISTING CLI — every node is a real
`python -m dgraph_tpu node` process on real sockets, nothing shares a
GIL with the load generator. Used by tools/dgbench.py and the
tools/check.sh load smoke; tests spawn the same shape ad hoc
(tests/test_multigroup.py) and can migrate here.

Each node gets a --debug-port (the read-only observability listener,
server/debug_http.py) so collectors scrape HTTP; data traffic flows
over the cluster wire via the returned RoutedCluster.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class ProcessCluster:
    """`zeros` zero nodes (one Raft quorum) + `groups` alpha groups of
    `replicas` each, spawned via the CLI. `log_dir` captures each
    node's stderr (the run report's per-node logs); `max_pending`
    turns on wire-surface admission control on every alpha."""

    def __init__(self, groups: int = 2, replicas: int = 1,
                 zeros: int = 1, max_pending: int = 0,
                 log_dir: Optional[str] = None,
                 data_dir: Optional[str] = None,
                 tick_ms: int = 30, election_ticks: int = 8,
                 env_extra: Optional[dict] = None,
                 snapshots: Optional[dict] = None,
                 zero_args: Optional[list] = None,
                 alpha_args: Optional[list] = None,
                 learners: int = 0,
                 cpus_per_group: int = 0):
        # zero_args: extra CLI flags for every zero node — how the
        # rebalance smoke / benches arm the heat-driven rebalancer
        # (--rebalance-interval, --split-heat, --move-throttle-mb-s)
        #
        # cpus_per_group > 0 pins each alpha GROUP's processes to its
        # own disjoint CPU set (Linux sched_setaffinity). On one box
        # every "group" otherwise shares the same cores, so tablet
        # placement cannot change capacity and a placement bench
        # measures only federation overhead; disjoint sets emulate
        # the real deployment where each group owns its machines.
        self.cpus_per_group = int(cpus_per_group)
        if self.cpus_per_group > 0:
            try:
                avail = len(os.sched_getaffinity(0))
            except AttributeError:
                avail = 0
            if avail < groups * self.cpus_per_group:
                # a short final slice would hand higher-numbered
                # groups less silicon BY CONSTRUCTION and the bench
                # would attribute that to tablet placement — refuse
                # to pin asymmetrically, loudly
                print(f"[spawn] cpus_per_group={self.cpus_per_group} x "
                      f"{groups} groups exceeds {avail} available "
                      "CPUs; affinity pinning DISABLED",
                      file=sys.stderr)
                self.cpus_per_group = 0
        # snapshots: {group -> p.snap path} boots each group's alphas
        # from a bulk/distributed-ingest output (`node --snapshot`);
        # every replica of a group must boot the same file
        self.snapshots = dict(snapshots or {})
        # alpha_args: extra CLI flags for every alpha node — how the
        # read scale-out smoke/bench arm the result cache and tenant
        # QoS (--result-cache, --tenant-rate, --tenant-burst)
        self.alpha_args = [str(a) for a in (alpha_args or ())]
        # learners: non-voting read replicas per group, spawned AFTER
        # the voters with ids above the voter range. Their raft peer
        # map holds only themselves (the voters' --raft-peers must
        # never list a learner as a voter); the learner discovers the
        # group's voters through zero and conf-joins as add_learner.
        self.learners = int(learners)
        self.groups_n = groups
        self.replicas = replicas
        self.procs: dict[str, subprocess.Popen] = {}
        self.debug_urls: dict[str, str] = {}
        self.zero_addrs: dict[int, tuple[str, int]] = {}
        self.group_addrs: dict[int, dict[int, tuple[str, int]]] = {}
        # per-node address book for the chaos plane: a nemesis that
        # partitions node A from node B needs EVERY listener B owns
        # (raft + client; the debug port stays reachable on purpose —
        # it's the out-of-band control/observation channel)
        self.node_addrs: dict[str, dict[str, tuple[str, int]]] = {}
        self._node_args: dict[str, list[str]] = {}
        self._node_env: dict[str, dict] = {}
        self._logs: dict[str, object] = {}
        self._env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"), PYTHONPATH=_REPO)
        if env_extra:
            self._env.update(env_extra)
        self._tick = ["--tick-ms", str(tick_ms),
                      "--election-ticks", str(election_ticks)]
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        # data_dir gives every node a persistent raft WAL/snapshot dir
        # (--wal -> cluster/raft.DiskStorage): the restart() nemesis
        # reboots a SIGKILLed node onto its existing state, so
        # acknowledged writes must survive the crash
        self.data_dir = data_dir
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)

        # zero quorum
        zports = free_ports(3 * zeros)
        zraft = {i + 1: ("127.0.0.1", zports[3 * i])
                 for i in range(zeros)}
        zpeers = ",".join(f"{i}={h}:{p}" for i, (h, p) in zraft.items())
        for i in range(1, zeros + 1):
            cport, dport = zports[3 * (i - 1) + 1], zports[3 * (i - 1) + 2]
            self.zero_addrs[i] = ("127.0.0.1", cport)
            self.node_addrs[f"zero-n{i}"] = {
                "raft": zraft[i], "client": ("127.0.0.1", cport),
                "debug": ("127.0.0.1", dport)}
            self._spawn(f"zero-n{i}", [
                "--kind", "zero", "--id", str(i),
                "--raft-peers", zpeers,
                "--client-addr", f"127.0.0.1:{cport}",
                "--debug-port", str(dport)]
                + [str(a) for a in (zero_args or ())])
        zero_spec = ",".join(f"{i}={h}:{p}"
                             for i, (h, p) in self.zero_addrs.items())

        # alpha groups
        for g in range(1, groups + 1):
            ports = free_ports(3 * replicas)
            graft = {i + 1: ("127.0.0.1", ports[3 * i])
                     for i in range(replicas)}
            gpeers = ",".join(f"{i}={h}:{p}"
                              for i, (h, p) in graft.items())
            self.group_addrs[g] = {}
            for i in range(1, replicas + 1):
                cport = ports[3 * (i - 1) + 1]
                dport = ports[3 * (i - 1) + 2]
                self.group_addrs[g][i] = ("127.0.0.1", cport)
                self.node_addrs[f"alpha-g{g}-n{i}"] = {
                    "raft": graft[i], "client": ("127.0.0.1", cport),
                    "debug": ("127.0.0.1", dport)}
                args = ["--kind", "alpha", "--id", str(i),
                        "--group", str(g),
                        "--raft-peers", gpeers,
                        "--client-addr", f"127.0.0.1:{cport}",
                        "--zero", zero_spec,
                        "--debug-port", str(dport)]
                if max_pending:
                    args += ["--max-pending", str(max_pending)]
                if g in self.snapshots:
                    args += ["--snapshot", self.snapshots[g]]
                self._spawn(f"alpha-g{g}-n{i}", args + self.alpha_args)

        # learner read replicas (ids above the voter range; voters'
        # peer maps stay voters-only — the learner conf-joins live)
        self.learner_addrs: dict[int, dict[int, tuple[str, int]]] = {}
        for g in range(1, groups + 1):
            self.learner_addrs[g] = {}
            for k in range(self.learners):
                i = replicas + 1 + k
                rport, cport, dport = free_ports(3)
                self.learner_addrs[g][i] = ("127.0.0.1", cport)
                self.node_addrs[f"alpha-g{g}-n{i}"] = {
                    "raft": ("127.0.0.1", rport),
                    "client": ("127.0.0.1", cport),
                    "debug": ("127.0.0.1", dport)}
                args = ["--kind", "alpha", "--id", str(i),
                        "--group", str(g), "--learner",
                        "--raft-peers", f"{i}=127.0.0.1:{rport}",
                        "--client-addr", f"127.0.0.1:{cport}",
                        "--zero", zero_spec,
                        "--debug-port", str(dport)]
                if max_pending:
                    args += ["--max-pending", str(max_pending)]
                if g in self.snapshots:
                    args += ["--snapshot", self.snapshots[g]]
                self._spawn(f"alpha-g{g}-n{i}", args + self.alpha_args)

    def _spawn(self, name: str, args: list[str]):
        if name not in self._node_args:
            if self.data_dir:
                args = args + ["--wal",
                               os.path.join(self.data_dir, name)]
            self._node_args[name] = list(args)
        env = self._env
        if name in self._node_env:
            env = dict(env, **self._node_env[name])
        if self.log_dir:
            # append mode: a restarted node's pre-crash log survives
            log = open(os.path.join(self.log_dir, name + ".log"), "a")
            old = self._logs.get(name)
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            self._logs[name] = log
        else:
            log = subprocess.DEVNULL
        dport = args[args.index("--debug-port") + 1]
        self.debug_urls[name] = f"http://127.0.0.1:{dport}"
        preexec = None
        if self.cpus_per_group > 0 and name.startswith("alpha-g") \
                and hasattr(os, "sched_setaffinity"):
            g = int(name.split("-")[1][1:])
            avail = sorted(os.sched_getaffinity(0))
            lo = (g - 1) * self.cpus_per_group
            cpuset = set(avail[lo:lo + self.cpus_per_group])
            if cpuset:
                def preexec(cs=cpuset):  # noqa: E731
                    os.sched_setaffinity(0, cs)
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", "dgraph_tpu", "node"]
            + self._node_args[name] + self._tick,
            env=env, cwd=_REPO, preexec_fn=preexec,
            stdout=subprocess.DEVNULL, stderr=log)

    # ------------------------------------------------------------ clients

    def routed(self, timeout: float = 30.0):
        """A fresh RoutedCluster over this topology (caller closes)."""
        from dgraph_tpu.cluster.client import ClusterClient
        from dgraph_tpu.cluster.topology import RoutedCluster
        zero = ClusterClient(self.zero_addrs, timeout=timeout)
        groups = {g: ClusterClient(addrs, timeout=timeout)
                  for g, addrs in self.group_addrs.items()}
        return RoutedCluster(zero, groups)

    def node_clients(self, timeout: float = 30.0) -> dict:
        """One single-address ClusterClient per NODE (not per group):
        the collector path — stats/traces/pprof ops hit a specific
        process, not whoever the leader is."""
        from dgraph_tpu.cluster.client import ClusterClient
        out = {}
        for i, addr in self.zero_addrs.items():
            out[f"zero-n{i}"] = ClusterClient({1: addr},
                                              timeout=timeout)
        for g, members in self.group_addrs.items():
            for i, addr in members.items():
                out[f"alpha-g{g}-n{i}"] = ClusterClient(
                    {1: addr}, timeout=timeout)
        return out

    # ------------------------------------------------------------- health

    def wait_ready(self, timeout_s: float = 60.0):
        """Every raft quorum (zero + each group) has a leader."""
        from dgraph_tpu.cluster.client import ClusterClient
        pending = {"zero": ClusterClient(self.zero_addrs, timeout=5.0)}
        for g, addrs in self.group_addrs.items():
            pending[f"g{g}"] = ClusterClient(addrs, timeout=5.0)
        try:
            end = time.monotonic() + timeout_s
            ready: set[str] = set()
            while time.monotonic() < end and len(ready) < len(pending):
                for name, cl in pending.items():
                    if name in ready:
                        continue
                    for node in list(cl.addrs):
                        try:
                            if cl.status(node).get("role") == "leader":
                                ready.add(name)
                                break
                        except (ConnectionError, RuntimeError, KeyError):
                            continue
                if len(ready) < len(pending):
                    time.sleep(0.2)
            if len(ready) < len(pending):
                raise TimeoutError(
                    f"cluster not ready after {timeout_s}s: "
                    f"missing {sorted(set(pending) - ready)}")
        finally:
            for cl in pending.values():
                cl.close()

    def wait_learners(self, timeout_s: float = 60.0):
        """Every learner has conf-joined its group (it sees a leader
        and applied the joining snapshot/log) — the edge after which
        follower reads stop returning wholesale StaleRead."""
        from dgraph_tpu.cluster.client import ClusterClient
        end = time.monotonic() + timeout_s
        for g, members in getattr(self, "learner_addrs", {}).items():
            for i, addr in members.items():
                cl = ClusterClient({1: addr}, timeout=5.0)
                try:
                    while True:
                        try:
                            st = cl.status(1)
                            if st.get("leader") is not None \
                                    and st.get("learner"):
                                break
                        except (ConnectionError, RuntimeError,
                                KeyError):
                            pass
                        if time.monotonic() > end:
                            raise TimeoutError(
                                f"learner alpha-g{g}-n{i} did not "
                                f"join within {timeout_s}s")
                        time.sleep(0.2)
                finally:
                    cl.close()

    def alive(self) -> list[str]:
        return [n for n, p in self.procs.items() if p.poll() is None]

    # ------------------------------------------------------- chaos plane
    # Per-node crash/restart controls for the nemesis harness
    # (tools/dgchaos.py): a node can be SIGKILLed under load and
    # rebooted onto its existing WAL/snapshot dirs (data_dir=).

    def kill(self, name: str, sig: int = signal.SIGKILL):
        """Send `sig` to one node. SIGKILL/SIGTERM reap the process
        (so restart() can re-bind its ports); SIGSTOP/SIGCONT pause
        and resume in place — the network-indistinguishable-partition
        nemesis."""
        p = self.procs[name]
        if p.poll() is not None:
            return
        p.send_signal(sig)
        if sig in (signal.SIGKILL, signal.SIGTERM):
            # never hang the harness on a wedged shutdown path (an
            # armed failpoint holding a lock, a stuck flush): escalate
            # to SIGKILL like teardown() does
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def restart(self, name: str,
                extra_env: Optional[dict] = None):
        """Reboot a dead node with its ORIGINAL args — same ports,
        same --wal dir. Without data_dir the node comes back empty and
        relies on the raft snapshot transfer from its peers; with it,
        DiskStorage replays the persisted log + snapshot first.

        `extra_env` overlays the node's environment for THIS and
        every later restart — the rolling-upgrade nemesis reboots
        each node with a bumped DGRAPH_TPU_BUILD_VERSION to simulate
        a new binary (the version surfaces on hello/debug stats;
        format and protocol stay min()-negotiated)."""
        p = self.procs.get(name)
        if p is not None and p.poll() is None:
            raise RuntimeError(f"{name} is still running; kill() first")
        if extra_env:
            self._node_env.setdefault(name, {}).update(extra_env)
        self._spawn(name, self._node_args[name])

    def _quorum_of(self, name: str) -> dict[int, tuple[str, int]]:
        """The client addrs of the raft quorum `name` belongs to."""
        if name.startswith("zero"):
            return dict(self.zero_addrs)
        g = int(name.split("-")[1][1:])
        return dict(self.group_addrs[g])

    def leader_of(self, quorum: str,
                  timeout_s: float = 30.0) -> str:
        """Current leader of a quorum ('zero' or 'g<N>') as a node
        name — the kill-leader nemesis target."""
        from dgraph_tpu.cluster.client import ClusterClient
        addrs = dict(self.zero_addrs) if quorum == "zero" \
            else dict(self.group_addrs[int(quorum[1:])])
        cl = ClusterClient(addrs, timeout=5.0)
        try:
            end = time.monotonic() + timeout_s
            while time.monotonic() < end:
                for node in list(addrs):
                    try:
                        if cl.status(node).get("role") == "leader":
                            return f"zero-n{node}" \
                                if quorum == "zero" \
                                else f"alpha-{quorum}-n{node}"
                    except (ConnectionError, RuntimeError, KeyError):
                        continue
                time.sleep(0.2)
            raise TimeoutError(f"no {quorum} leader in {timeout_s}s")
        finally:
            cl.close()

    def wait_caught_up(self, name: str, timeout_s: float = 60.0):
        """Block until a (re)started node rejoined its quorum AND
        applied at least everything its peers had applied when this
        call began — the 'recovery is complete' edge the chaos
        report's restart nemeses measure against. Returns the node's
        final status dict."""
        from dgraph_tpu.cluster.client import ClusterClient
        addrs = self._quorum_of(name)
        nid = int(name.rsplit("n", 1)[1])
        cl = ClusterClient(addrs, timeout=5.0)
        try:
            end = time.monotonic() + timeout_s
            # the catch-up goal: the max applied index any PEER holds
            # now (a single-replica quorum has no peers — the node
            # only has to come back up and re-elect itself)
            goal = 0
            peers = [n for n in addrs if n != nid]
            while peers and time.monotonic() < end:
                seen = []
                for node in peers:
                    try:
                        seen.append(int(
                            cl.status(node).get("applied", 0)))
                    except (ConnectionError, RuntimeError, KeyError):
                        continue
                if seen:
                    goal = max(seen)
                    break
                time.sleep(0.2)
            while time.monotonic() < end:
                try:
                    st = cl.status(nid)
                except (ConnectionError, RuntimeError, KeyError):
                    time.sleep(0.2)
                    continue
                # `leader is not None` matters: a freshly rebooted
                # node reports follower/applied=0 BEFORE any election
                # — only once a leader exists has the new term's noop
                # committed and the persisted log replayed (§5.4.2)
                if st.get("leader") is not None \
                        and st.get("role") in ("leader", "follower") \
                        and int(st.get("applied", 0)) >= goal:
                    return st
                time.sleep(0.2)
            raise TimeoutError(
                f"{name} not caught up to applied>={goal} "
                f"within {timeout_s}s")
        finally:
            cl.close()

    def teardown(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for log in self._logs.values():
            try:
                log.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.teardown()
