"""Shared benchmark plumbing.

The repo's benchmark entry points (bench_queries.py --concurrency,
tools/dgbench.py, the tools/check.sh load smoke) all drive the same
two primitives:

  openloop   the open-loop arrival scheduler + latency/percentile
             summarizers (latency = finish - SCHEDULED arrival, so
             queueing counts — the property closed-loop harnesses
             can't measure)
  workload   the seeded LDBC-SNB-style social-graph generator and
             deterministic mixed read/write op stream

Keeping them here (inside the package, importable from any entry
point) is what lets a regression gate and a capacity probe agree on
what "offered load" and "p99" mean.
"""
