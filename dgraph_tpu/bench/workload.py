"""Seeded LDBC-SNB-style social-graph workload generator.

The reference validates its clusters with docker-compose topologies
under traffic tools (`dgraph counter`, SURVEY §4.5/§4.7); LDBC's
Social Network Benchmark is the community-standard graph workload
shape: a person/knows/post graph queried by short point reads,
2–3-hop friend traversals, and aggregations, interleaved with a
write stream. This module is that shape for dgraph-tpu, as two pure
functions of a seed:

  Workload(cfg).schema() / .quads()   the generated social graph
  Workload(cfg).ops(n)                the mixed read/write op stream

Determinism is a hard contract (tests/test_workload.py): the same
config produces BYTE-IDENTICAL schema, quads and op stream in any
process — random.Random(seed) only, no hash-order iteration, no wall
clock — so two harness runs (or a run and its CI re-check) replay the
exact same traffic.

Read/write disjointness, for the under-load parity oracle: every read
op touches only the seeded person.*/knows/post.* predicates, every
mutation touches only fresh blank nodes under churn.* predicates.
Reads are therefore time-invariant while the write stream churns, and
"responses under concurrent load" must byte-match "the same queries
replayed sequentially after quiescing" — an exact differential check
tools/dgbench.py runs on a sampled subset of every run.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass

FIRST = ("Alice", "Bruno", "Chen", "Devi", "Emeka", "Farah", "Goran",
         "Hana", "Ivan", "Jun", "Kaia", "Liam", "Mina", "Noor",
         "Otto", "Priya")
LAST = ("Abe", "Brandt", "Cruz", "Diaz", "Endo", "Fox", "Gupta",
        "Haas", "Ito", "Jha", "Kim", "Lund", "Mora", "Ngo", "Okafor",
        "Park")
CITIES = ("amsterdam", "bengaluru", "cairo", "denver", "edinburgh",
          "fukuoka", "geneva", "hanoi", "istanbul", "jakarta",
          "kyoto", "lagos")
TOPICS = ("ai", "bikes", "chess", "dgraph", "espresso", "fjords",
          "gardens", "hiking", "indie", "jazz", "kernels", "lasers",
          "maps", "noodles", "opera", "pottery")

SCHEMA = """\
person.name: string @index(exact, term) .
person.city: string @index(exact) .
person.age: int @index(int) .
person.embedding: float32vector @index(vector) .
knows: [uid] @reverse @count .
post.author: [uid] @reverse .
post.topic: string @index(exact) .
post.score: int @index(int) .
churn.note: string .
churn.ref: [uid] .
"""

# op kinds and their default mix weights: the LDBC-interactive-style
# split — short reads dominate, traversals and analytics ride along,
# ~20% writes (half single-edge, half fan-out)
DEFAULT_MIX = (
    ("short_read", 0.40),
    ("traverse2", 0.14),
    ("traverse3", 0.06),
    ("similar", 0.07),
    ("agg_count", 0.13),
    ("mut_edge", 0.12),
    ("mut_fanout", 0.08),
)

# read-only zipfian mix for the read scale-out bench (learner
# replicas + result cache): person popularity follows a zipf(s)
# distribution — a hot head the cache can serve, a long tail that
# keeps missing — so the measured QPS curve reflects what a cache +
# read replicas actually buy under skewed real-world traffic
ZIPF_READ_MIX = (
    ("zipf_short", 0.55),
    ("zipf_traverse", 0.25),
    ("zipf_agg", 0.20),
)

# --mix name -> weights table (tools/dgbench.py, scale-out bench)
MIXES = {"default": DEFAULT_MIX, "zipf-read": ZIPF_READ_MIX}

ZIPF_S = 1.1  # the exponent: ~YCSB's scrambled-zipfian skew


@dataclass(frozen=True)
class WorkloadConfig:
    seed: int = 20260803
    persons: int = 400
    posts_per_person: int = 2
    knows_out: int = 8          # out-degree of the knows graph
    embed_dim: int = 16
    fanout_edges: int = 8       # triples per fan-out mutation
    mix: tuple = DEFAULT_MIX


@dataclass(frozen=True)
class Op:
    """One workload operation. Reads carry `query`; writes carry
    `set_nquads` (all writes are inserts of fresh churn entities —
    see the module docstring's disjointness contract)."""
    kind: str
    write: bool
    query: str = ""
    set_nquads: str = ""

    def to_line(self) -> str:
        """Canonical one-line JSON — the byte-identity unit the
        determinism tests (and cross-process hashes) compare."""
        return json.dumps(
            {"kind": self.kind, "write": self.write,
             "query": self.query, "set_nquads": self.set_nquads},
            sort_keys=True, separators=(",", ":"))


def _person_name(i: int) -> str:
    return (f"{FIRST[i % len(FIRST)]} "
            f"{LAST[(i // len(FIRST)) % len(LAST)]} {i}")


def _zipf_cdf(n: int, s: float = ZIPF_S) -> list[float]:
    """Normalized cumulative weights of zipf(s) over ranks 1..n."""
    acc, out = 0.0, []
    for rank in range(1, n + 1):
        acc += 1.0 / rank ** s
        out.append(acc)
    return [c / acc for c in out]


def _zipf_draw(cdf: list[float], rng: random.Random) -> int:
    """Inverse-CDF zipfian index draw (0-based, 0 = hottest)."""
    import bisect

    return min(bisect.bisect_left(cdf, rng.random()), len(cdf) - 1)


def _vec_literal(vals: list[float]) -> str:
    return "[" + ", ".join(f"{v:.4f}" for v in vals) + "]"


class Workload:
    """The generated graph + op stream for one config. Every method
    is deterministic in `cfg` alone; `ops()` takes an extra stream
    seed so phases of one run can draw non-overlapping traffic from
    the same graph."""

    def __init__(self, cfg: WorkloadConfig = WorkloadConfig()):
        self.cfg = cfg
        rng = random.Random(cfg.seed)
        n = cfg.persons
        self._names = [_person_name(i) for i in range(n)]
        self._cities = [CITIES[rng.randrange(len(CITIES))]
                        for _ in range(n)]
        self._ages = [rng.randrange(18, 81) for _ in range(n)]
        self._vecs = [[rng.uniform(-1, 1) for _ in range(cfg.embed_dim)]
                      for _ in range(n)]
        # knows: fixed out-degree, no self loops; duplicates fine
        # (posting lists dedupe) but keep them rare for real fan-out
        self._knows = []
        for i in range(n):
            peers = set()
            while len(peers) < min(cfg.knows_out, n - 1):
                j = rng.randrange(n)
                if j != i:
                    peers.add(j)
            self._knows.append(sorted(peers))
        self._posts = []
        for i in range(n):
            for p in range(cfg.posts_per_person):
                self._posts.append(
                    (i, TOPICS[rng.randrange(len(TOPICS))],
                     rng.randrange(101)))
        # zipfian popularity CDFs for the zipf-read mix: person i has
        # rank i+1 (person 0 is the head), weight 1/rank^ZIPF_S;
        # sampling is inverse-CDF over rng.random() so the stream
        # stays a pure function of the seed (bisect, no rejection)
        self._zipf_cdf = _zipf_cdf(n)
        self._zipf_topic_cdf = _zipf_cdf(len(TOPICS))

    # ------------------------------------------------------------ graph

    def schema(self) -> str:
        return SCHEMA

    def quads(self) -> list[str]:
        """The seeded graph as RDF N-Quad lines (blank-node subjects;
        uid assignment happens at load time and no read op depends on
        it — everything is addressed by indexed values)."""
        out = []
        for i, name in enumerate(self._names):
            s = f"_:p{i}"
            out.append(f'{s} <person.name> "{name}" .')
            out.append(f'{s} <person.city> "{self._cities[i]}" .')
            out.append(f'{s} <person.age> "{self._ages[i]}"^^<xs:int> .')
            out.append(f'{s} <person.embedding> '
                       f'"{_vec_literal(self._vecs[i])}"'
                       f'^^<xs:float32vector> .')
            for j in self._knows[i]:
                out.append(f"{s} <knows> _:p{j} .")
        for k, (author, topic, score) in enumerate(self._posts):
            s = f"_:o{k}"
            out.append(f"{s} <post.author> _:p{author} .")
            out.append(f'{s} <post.topic> "{topic}" .')
            out.append(f'{s} <post.score> "{score}"^^<xs:int> .')
        return out

    def read_predicates(self) -> tuple:
        """The seeded (read-side) predicates, in a deterministic
        order — dgbench touches one of each early so tablet claiming
        spreads them across groups before the timed run."""
        return ("person.name", "person.city", "person.age",
                "person.embedding", "knows", "post.author",
                "post.topic", "post.score")

    # -------------------------------------------------------------- ops

    def ops(self, n: int, stream_seed: int = 0) -> list[Op]:
        """`n` mixed ops drawn with a stream-local RNG. Same (cfg,
        n, stream_seed) => byte-identical list in any process."""
        # string seed: version-2 seeding hashes the bytes with sha512
        # (stable across processes and Python versions; tuple seeds
        # are deprecated)
        rng = random.Random(f"{self.cfg.seed}:{stream_seed}:{n}")
        kinds = [k for k, _ in self.cfg.mix]
        weights = [w for _, w in self.cfg.mix]
        out = []
        for i in range(n):
            kind = rng.choices(kinds, weights=weights)[0]
            out.append(self._one(kind, i, rng))
        return out

    def _one(self, kind: str, i: int, rng: random.Random) -> Op:
        name = self._names[rng.randrange(len(self._names))]
        if kind == "short_read":
            return Op(kind, False, query=(
                '{ q(func: eq(person.name, "%s")) '
                '{ person.name person.age person.city } }' % name))
        if kind == "traverse2":
            return Op(kind, False, query=(
                '{ q(func: eq(person.name, "%s")) { person.name '
                'knows { person.name knows { person.name } } } }'
                % name))
        if kind == "traverse3":
            return Op(kind, False, query=(
                '{ q(func: eq(person.name, "%s")) { person.name '
                'knows { knows { knows { person.name } } } } }'
                % name))
        if kind == "similar":
            probe = [v + rng.uniform(-0.05, 0.05)
                     for v in self._vecs[rng.randrange(
                         len(self._vecs))]]
            return Op(kind, False, query=(
                '{ q(func: similar_to(person.embedding, 5, "%s")) '
                '{ person.name } }' % _vec_literal(probe)))
        if kind == "agg_count":
            topic = TOPICS[rng.randrange(len(TOPICS))]
            return Op(kind, False, query=(
                '{ q(func: eq(post.topic, "%s")) { count(uid) } }'
                % topic))
        if kind == "zipf_short":
            hot = self._names[_zipf_draw(self._zipf_cdf, rng)]
            return Op(kind, False, query=(
                '{ q(func: eq(person.name, "%s")) '
                '{ person.name person.age person.city } }' % hot))
        if kind == "zipf_traverse":
            hot = self._names[_zipf_draw(self._zipf_cdf, rng)]
            return Op(kind, False, query=(
                '{ q(func: eq(person.name, "%s")) { person.name '
                'knows { person.name } } }' % hot))
        if kind == "zipf_agg":
            topic = TOPICS[_zipf_draw(self._zipf_topic_cdf, rng)]
            return Op(kind, False, query=(
                '{ q(func: eq(post.topic, "%s")) { count(uid) } }'
                % topic))
        if kind == "mut_edge":
            return Op(kind, True, set_nquads=(
                f'_:c <churn.note> "edge-{i}-{rng.randrange(1 << 30)}" .'))
        if kind == "mut_fanout":
            sub = f"_:f{i}"
            tag = rng.randrange(1 << 30)
            lines = [f'{sub} <churn.note> "fan-{i}-{tag}" .']
            for e in range(self.cfg.fanout_edges):
                lines.append(f"{sub} <churn.ref> _:r{i}x{e} .")
                lines.append(
                    f'_:r{i}x{e} <churn.note> "ref-{i}-{e}-{tag}" .')
            return Op(kind, True, set_nquads="\n".join(lines))
        raise ValueError(f"unknown op kind {kind!r}")


def stream_digest(ops_list: list[Op]) -> str:
    """SHA-256 over the canonical op lines — what the cross-process
    determinism test compares."""
    import hashlib

    h = hashlib.sha256()
    for op in ops_list:
        h.update(op.to_line().encode())
        h.update(b"\n")
    return h.hexdigest()
