"""Cost-based adaptive planner: the stats plane drives tier choice.

Three PRs built evidence nobody consumed: per-predicate tablet
statistics with row-estimate bases (storage/tabstats.py, PR 7), an
observed per-stage cost store keyed (stage, tier, plan skeleton, size
bucket) (utils/coststore.py, PR 7), and a compressed posting tier
(PR 9) — yet tier routing stayed the static
`GraphDB(device_min_edges=1024, prefer_columnar, prefer_compressed)`
flags. This module closes the loop, the "Self-Driving DBMS"
(PAPERS.md) shape: per compiled-plan stage, pick
postings / columnar / compressed / device from

    estimated rows  (tabstats row estimates — EXPLAIN's four-basis
                     error contract — sharpened by the per-token
                     posting-length histogram, overridden by LEARNED
                     actuals after an estimate violation)
  x observed cost   (coststore EWMA per (stage, tier, bucket), falling
                     back to the documented static priors below when a
                     cell is cold)

and cache the decision on the `Plan` via its memo machinery
(`Plan.decide`), so a warm request pays ONE dict probe per stage.

Self-correction — the planner the reference never had:

  * estimate violation: the executed stage's actual rows land ≥ 3
    size buckets (8x) away from the estimate, or break the basis
    contract (`index`: actual <= estMax). The actual is LEARNED
    (EWMA per stage key) and the cached decision invalidated, so the
    next request re-decides against reality instead of repeating the
    mis-estimate.
  * cost drift: the coststore's fast/slow EWMA ratio for the chosen
    tier leaves [1/DRIFT, DRIFT] — the tier's cost moved (cache
    pressure, a rollup changed the data shape) — sampled every
    OUTCOME_SAMPLE outcomes, invalidating on trip.

  Re-planning is BOUNDED per stage key (token bucket: REPLAN_BURST,
  one token per REPLAN_REFILL_S) and counter-tracked
  (`planner_reoptimized_total{reason=}`,
  `planner_estimate_violations_total`,
  `planner_replans_suppressed_total`) so a flapping estimate cannot
  melt the plan cache.

Plan-level decisions on the same foundation:

  * probe-vs-scan pivot (`probe_or_scan`): an eq filter over a small
    candidate set scans the candidates' values instead of probing a
    token index whose estimated postings dwarf them ("index-probe vs
    columnar-scan", ref algo/uidlist.go:151's size-ratio strategy
    pick lifted to the index/candidate boundary).
  * k-way intersection galloping ratio (`gallop_ratio`): "SIMD
    Compression and the Intersection of Sorted Integers" (PAPERS.md)
    shows the gallop-vs-merge choice is a DENSITY decision, not a
    fixed size ratio — sparse expected intersections gallop earlier,
    dense ones merge longer.

COLD BEHAVIOR IS THE STATIC LADDER. The priors are ordering priors:
their magnitudes anchor to the round-5 measured host constants
(executor `_HOST_PER_*`), but their ordering is chosen so a cold cell
reproduces exactly what the static flags did (compressed ≥ columnar ≥
postings; device only past the measured dispatch RTT). Adaptivity is
therefore pure upside: with no evidence the engine routes as before,
and every deviation is backed by an observed cell or a learned actual.
The flags demote to overrides — `prefer_columnar=False` (the parity
oracle) removes the columnar+compressed tiers from every decision,
`prefer_device=False` the device tier, `device_min_edges <= 1` still
force-routes device — so pinned-tier debugging and the differential
parity suites keep their meaning.

Parity is structural: every tier is byte-identical by construction
(the differential suites prove it), so the planner chooses only among
answers that are already proven equal — it can never trade
correctness for speed.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Optional

from dgraph_tpu.utils import coststore, metrics

TIERS = ("postings", "columnar", "compressed", "device")

# -- documented static priors: (fixed_us, per_row_us) per (stage,
# tier). docs/deployment.md publishes this table; re-measure against
# `bench_micro.py --planner-overhead` + the round-5 constants when the
# data plane changes. ORDERING invariant (checked by
# tests/test_planner.py): for every stage and every row count,
# compressed <= columnar <= postings, so cold decisions reproduce the
# static tier ladder.
STATIC_PRIORS: dict[tuple[str, str], tuple[float, float]] = {
    # eq/terms token-index algebra: pack block-skip vs dense CSR probe
    # vs per-token index_uids walk + per-posting verify (~0.5 µs/row,
    # the round-5 python-loop constant)
    ("eq", "compressed"): (4.0, 0.010),
    ("eq", "columnar"): (6.0, 0.020),
    ("eq", "postings"): (8.0, 0.500),
    ("setops", "compressed"): (4.0, 0.010),
    ("setops", "columnar"): (6.0, 0.020),
    ("setops", "postings"): (8.0, 0.500),
    # ineq: device range kernel vs cached sort-key-array mask
    # (~5e-9 s/value measured) vs per-uid dict walk
    ("ineq", "device"): (5.0, 0.002),
    ("ineq", "columnar"): (6.0, 0.005),
    ("ineq", "postings"): (8.0, 0.500),
    # sort: device multisort vs presorted-permutation walk (cost
    # scales with the COLUMN, see rows_by_tier at the call site) vs
    # host key-gather + lexsort (~2e-7 s/key, round-5)
    ("sort", "device"): (5.0, 0.002),
    ("sort", "columnar"): (6.0, 0.010),
    ("sort", "postings"): (8.0, 0.050),
    # similar_to: quantized IVF probe (per SCANNED row — the caller
    # passes rows_by_tier with n*nprobe/nlist, so the row count
    # carries the probe's selectivity; per-row covers the int8
    # convert+gemm) vs MXU exact top-k vs host brute-force MIPS.
    # postings per-row is the MEASURED float64 host constant
    # (~180 ms / 100k x 128 single query, BENCH_VECTORS
    # host_exact_qps) — an optimistic figure here makes observed
    # quantized/device evidence "lose" to a fantasy host tier and
    # mis-routes similar_to onto a path that is orders slower
    ("similar_to", "quantized"): (6.0, 0.010),
    ("similar_to", "device"): (5.0, 0.002),
    ("similar_to", "postings"): (8.0, 1.800),
}

# estimate-violation threshold: actual rows >= this many size buckets
# (log2) away from the estimate invalidates the decision
VIOLATION_BUCKETS = 3
# drift threshold on the coststore's fast/slow EWMA ratio
DRIFT = 2.0
# drift/rival checks run on EVERY outcome for a decision's first
# EARLY_SAMPLES (each check is a couple of locked dict probes, and a
# fresh decision is exactly when contrary evidence should bite
# fastest — convergence within a handful of requests per stage key),
# then back off to every OUTCOME_SAMPLE-th (the EWMAs move slowly)
EARLY_SAMPLES = 8
OUTCOME_SAMPLE = 8
# rival margin: a warm ALTERNATIVE tier whose observed cost undercuts
# the chosen tier's by this factor invalidates the decision (the
# other half of cost drift: your tier didn't move, a better one
# appeared — e.g. another arm/pin/workload populated its cells).
# The margin is the anti-flap hysteresis over the interpolated
# histogram medians.
RIVAL_MARGIN = 1.5
# re-plan token bucket per stage key: burst + refill
REPLAN_BURST = 4
REPLAN_REFILL_S = 10.0
# -- exploration: budgeted probing of UNOBSERVED tiers ----------------
# The self-correction loop above only re-judges tiers that have
# evidence; a tier nobody ever served stays cold forever (the rival
# check needs a warm cell to rival with). Exploration closes that gap:
# once a stage key has real evidence for SOME tier, a cold tier whose
# modeled cost is within EXPLORE_MARGIN of the chosen tier's may be
# probed — served once, its stage span lands a cost cell, and the
# decision immediately re-judges with the new evidence
# (record_outcome invalidates an explored decision after its first
# outcome). Budgeted by its own token bucket per stage key so a hot
# path never pays more than EXPLORE_BURST probes per refill window,
# and NEVER fires cold-cold: with no evidence at all the static
# ladder stays authoritative (the documented cold contract).
EXPLORE_MARGIN = 4.0
EXPLORE_BURST = 2
EXPLORE_REFILL_S = 30.0
# learned-actual EWMA weight (fast: a violation should dominate the
# stale estimate within a couple of observations)
LEARN_ALPHA = 0.5
# bound on the learned-rows / versions / token tables
MAX_KEYS = 4096


def _bucket(n: int) -> int:
    n = int(n)
    return n.bit_length() if n > 0 else 0


def token_quantile(token_index: dict, q: float = 0.75) -> float:
    """Per-token posting-length quantile from the tabstats histogram
    (log2 buckets; bucket b covers lengths with bit_length b). The
    center of the bucket holding the q-th token is the estimate — a
    REAL per-token basis instead of the tablet-wide mean, so a
    Zipfian index's hot tokens stop being estimated at `avg`."""
    hist = token_index.get("hist")
    if not hist:
        return float(token_index.get("avgPostings", 0.0) or 0.0)
    total = sum(hist)
    if not total:
        return float(token_index.get("avgPostings", 0.0) or 0.0)
    want = q * total
    seen = 0
    for b, c in enumerate(hist):
        seen += c
        if seen >= want:
            # bucket b holds lengths in (2^(b-1), 2^b]: use the
            # midpoint (0 bucket = empty lists)
            return 0.75 * (1 << b) if b else 0.0
    return float(token_index.get("maxPostings", 0) or 0)


class Decision:
    """One cached per-stage tier decision plus everything EXPLAIN
    needs to say WHY (decision inputs, estimate basis, cost model per
    tier, re-optimization generation)."""

    __slots__ = ("stage", "pred", "tier", "basis", "est_rows",
                 "est_basis", "bucket", "costs", "version", "why",
                 "skeleton", "outcomes", "rows_buckets")

    def __init__(self, stage: str, pred: str, tier: str, basis: str,
                 est_rows: int, est_basis: str, bucket: int,
                 costs: dict[str, float], version: int, why: str,
                 skeleton: str,
                 rows_buckets: Optional[dict[str, int]] = None):
        self.stage = stage
        self.pred = pred
        self.tier = tier
        self.basis = basis          # "observed" | "prior" | "mixed"
        self.est_rows = est_rows
        self.est_basis = est_basis  # the row estimate's basis
        self.bucket = bucket
        self.costs = costs          # per-tier modeled cost (µs)
        self.version = version      # re-optimization generation
        self.why = why
        self.skeleton = skeleton
        self.outcomes = 0           # outcomes recorded against this
        # per-tier row-bucket overrides the decision was costed with
        # (the similar_to seam: the quantized tier scans
        # ~n*nprobe/nlist rows and its cost cells key on THAT bucket;
        # outcome-time drift/rival probes must look there too)
        self.rows_buckets = rows_buckets

    def describe(self) -> dict:
        return {"stage": self.stage, "pred": self.pred,
                "tier": self.tier, "basis": self.basis,
                "estRows": self.est_rows,
                "estBasis": self.est_basis,
                "sizeBucket": self.bucket,
                "costUs": {t: round(c, 3)
                           for t, c in self.costs.items()},
                "version": self.version,
                "reoptimized": self.version > 0,
                "why": self.why}


class AdaptivePlanner:
    """Per-engine decision maker over the process-global coststore.
    Thread-safe; every mutable table is bounded."""

    def __init__(self, db):
        self.db = db
        self._lock = threading.Lock()
        # dglint: guarded-by=_versions:atomic,_consults:atomic
        # (the warm-path version() probe is a bare GIL-atomic dict
        # read on purpose — writes serialize under _lock; _consults
        # is a stats-grade counter, a lost increment is acceptable)
        # (skeleton, stage, pred) -> re-optimization generation
        self._versions: dict[tuple, int] = {}
        # (skeleton, stage, pred) -> learned actual-rows EWMA
        self._learned: dict[tuple, float] = {}
        # (skeleton, stage, pred) -> (tokens, last_refill_mono)
        self._replan_tokens: dict[tuple, list] = {}
        # (skeleton, stage, pred) -> (tokens, last_refill_mono) for
        # cold-tier exploration (separate budget: a replan storm must
        # not eat the exploration allowance and vice versa)
        self._explore_tokens: dict[tuple, list] = {}
        self._explored = 0
        # decision mix for /debug/stats + the dgtop PLANNER panel
        self._mix: dict[tuple[str, str], int] = {}
        self._built = 0
        self._consults = 0  # every choose() call incl. cache hits
        # warm serves: decisions handed out by the executor's
        # plan-routing layer WITHOUT consulting choose() (incremented
        # by Executor._routed; plain int, stats-grade) — the
        # planner-overhead gate multiplies these by the measured
        # warm-path cost, so the gate stays meaningful in the steady
        # state where consults are zero
        self._warm_serves = 0
        self._violations = 0
        self._reoptimized = 0
        self._suppressed = 0

    # -- decision ------------------------------------------------------

    def version(self, skeleton: str, stage: str, pred: str) -> int:
        # lock-free: a dict probe is GIL-atomic and the value is an
        # int — this sits on the warm-request validity check
        return self._versions.get((skeleton, stage, pred), 0)

    def learned_rows(self, skeleton: str, stage: str,
                     pred: str) -> Optional[float]:
        with self._lock:
            return self._learned.get((skeleton, stage, pred))

    def choose(self, plan, stage: str, pred: str, est: dict,
               avail: tuple[str, ...],
               rows_by_tier: Optional[dict[str, int]] = None
               ) -> Optional[Decision]:
        """The per-stage entry: the current decision for
        (plan, stage, pred) — served from the plan's decision cache,
        built on first use or after an invalidation bumped the
        version. `est` is an EXPLAIN-shaped row estimate
        ({estRows, estRowsMax, basis, source}); `rows_by_tier`
        overrides the row count the cost model multiplies for
        specific tiers (the sort seam: the presorted-permutation walk
        scales with the COLUMN, not the candidate set)."""
        if plan is None or not avail:
            return None
        self._consults += 1  # plain int: stats-grade, GIL-atomic
        skeleton = plan.skeleton_hex
        k = (skeleton, stage, pred)
        with self._lock:
            version = self._versions.get(k, 0)
            learned = self._learned.get(k)
        est_rows = max(0, int(est.get("estRows", -1)))
        est_basis = str(est.get("basis", "unknown"))
        if learned is not None:
            est_rows = int(learned)
            est_basis = "learned"
        bucket = _bucket(est_rows)
        # per-tier row drivers quantize to log2 buckets BEFORE keying:
        # raw counts would mint a fresh cache entry per candidate-set
        # size and turn every sort into a decision rebuild
        rb = {t: _bucket(n) for t, n in rows_by_tier.items()} \
            if rows_by_tier else None
        key = ("tier", stage, pred, bucket,
               tuple(sorted(rb.items())) if rb else ())
        return plan.decide(key, version, lambda: self._build(
            plan, stage, pred, est_rows, est_basis, bucket, avail,
            version, skeleton, rb))

    @staticmethod
    def _rows_of_bucket(b: int) -> int:
        return int(0.75 * (1 << b)) if b else 0

    def _build(self, plan, stage: str, pred: str, est_rows: int,
               est_basis: str, bucket: int, avail: tuple[str, ...],
               version: int, skeleton: str,
               rows_buckets: Optional[dict[str, int]]) -> Decision:
        costs: dict[str, float] = {}
        cells: dict[str, Optional[dict]] = {}
        rtt_us = self.db.device_dispatch_seconds() * 1e6
        for tier in avail:
            rows = self._rows_of_bucket(rows_buckets[tier]) \
                if rows_buckets and tier in rows_buckets else est_rows
            cell = coststore.estimate(stage, tier, _bucket(rows),
                                      skeleton)
            cells[tier] = cell
            if cell is not None and cell["warm"]:
                # histogram median, not EWMA: robust to the tier's
                # first-observation cache-build spike. Observed device
                # cells already CONTAIN the dispatch round-trip (stage
                # spans wrap the whole device call) — adding the RTT
                # again would double-count it and mis-route warm
                # device stages to slower host tiers.
                costs[tier] = cell["p50_us"]
            else:
                fixed, per_row = STATIC_PRIORS.get(
                    (stage, tier), (8.0, 0.5))
                costs[tier] = fixed + per_row * rows
                if tier == "device":
                    # cold prior: model the measured dispatch
                    # round-trip the priors' compute figures exclude
                    costs[tier] += rtt_us
        warm = [t for t in avail if cells[t] is not None
                and cells[t]["warm"]]
        if len(warm) >= 2:
            # at least two tiers have real evidence: trust the
            # observed costs outright
            tier = min(warm, key=lambda t: costs[t])
            basis = "observed"
            why = "observed EWMA over " + ",".join(sorted(warm))
        elif len(warm) == 1 and warm[0] != min(
                avail, key=lambda t: costs[t]) \
                and costs[warm[0]] > min(costs.values()):
            # one observed tier that LOSES to a prior: deviating from
            # the static ladder on one-sided evidence is safe only
            # away from the margin (2x), else priors keep the ladder
            best_prior = min(avail, key=lambda t: costs[t])
            if costs[warm[0]] > 2.0 * costs[best_prior]:
                tier, basis = best_prior, "mixed"
                why = (f"observed {warm[0]} "
                       f"{costs[warm[0]]:.0f}us > 2x prior "
                       f"{best_prior}")
            else:
                tier, basis = warm[0], "observed"
                why = "single observed tier within margin"
        else:
            tier = min(avail, key=lambda t: costs[t])
            basis = "prior" if not warm else "observed"
            why = "static priors (cold cells)" if not warm \
                else "observed EWMA"
        probe = self._maybe_explore(skeleton, stage, pred, avail,
                                    warm, costs, tier)
        if probe is not None:
            basis = "explored"
            why = (f"probing cold tier {probe} "
                   f"({costs[probe]:.0f}us model) vs chosen {tier} "
                   f"({costs[tier]:.0f}us)")
            tier = probe
        dec = Decision(stage, pred, tier, basis, est_rows, est_basis,
                       bucket, costs, version, why, skeleton,
                       rows_buckets=rows_buckets)
        metrics.inc_counter("planner_decisions_total",
                            labels={"tier": tier})
        with self._lock:
            self._built += 1
            k = (stage, tier)
            self._mix[k] = self._mix.get(k, 0) + 1
        return dec

    def _maybe_explore(self, skeleton: str, stage: str, pred: str,
                       avail: tuple[str, ...], warm: list,
                       costs: dict[str, float],
                       chosen: str) -> Optional[str]:
        """The cheapest UNOBSERVED tier worth one budgeted probe, or
        None. Fires only with real evidence present (never cold-cold —
        the static ladder stays the cold contract), only within
        EXPLORE_MARGIN of the chosen tier's modeled cost, and only
        while the stage key's exploration token bucket has budget."""
        if not getattr(self.db, "planner_explore", True) or not warm:
            return None
        cold = [t for t in avail if t not in warm and t != chosen]
        if not cold:
            return None
        best = min(cold, key=lambda t: costs[t])
        if costs[best] > EXPLORE_MARGIN * costs[chosen]:
            return None
        now = _time.monotonic()
        k = (skeleton, stage, pred)
        with self._lock:
            tb = self._explore_tokens.get(k)
            if tb is None:
                if len(self._explore_tokens) >= MAX_KEYS:
                    self._explore_tokens.clear()
                tb = [float(EXPLORE_BURST), now]
                self._explore_tokens[k] = tb
            tb[0] = min(float(EXPLORE_BURST),
                        tb[0] + (now - tb[1]) / EXPLORE_REFILL_S)
            tb[1] = now
            if tb[0] < 1.0:
                return None
            tb[0] -= 1.0
            self._explored += 1
        metrics.inc_counter("planner_explored_total",
                            labels={"tier": best})
        return best

    # -- outcome / re-optimization -------------------------------------

    def record_outcome(self, dec: Optional[Decision],
                       actual_rows: int) -> None:
        """Feed one executed stage's observed result size back.
        Estimate violations learn the actual and invalidate; cost
        drift (sampled) invalidates. Both are rate-limited per stage
        key — EXPLAIN ANALYZE + the planner counters surface every
        event."""
        if dec is None:
            return
        dec.outcomes += 1
        actual_rows = max(0, int(actual_rows))
        ab = _bucket(actual_rows)
        key = (dec.skeleton, dec.stage, dec.pred)
        if dec.basis == "explored":
            # the probe served: its stage span just landed the cold
            # tier's first cost cell. Re-judge immediately instead of
            # serving the probe tier until drift/rival notices — one
            # exploration buys exactly one observation
            self._invalidate(key, "explored")
            return
        if abs(ab - dec.bucket) >= VIOLATION_BUCKETS:
            with self._lock:
                self._violations += 1
                if len(self._learned) >= MAX_KEYS:
                    self._learned.clear()
                old = self._learned.get(key)
                self._learned[key] = actual_rows if old is None \
                    else old + LEARN_ALPHA * (actual_rows - old)
            metrics.inc_counter("planner_estimate_violations_total")
            self._invalidate(key, "violation")
            return
        if dec.outcomes <= EARLY_SAMPLES \
                or dec.outcomes % OUTCOME_SAMPLE == 0:
            # probe at the ACTUAL size bucket `ab`, not the estimate
            # bucket: cost cells are recorded under the span's real
            # result size, and a sub-violation estimate error (1-2
            # buckets) would otherwise make every probe miss — both
            # self-correction paths would silently never fire. A
            # tier costed with a rows_buckets override records its
            # spans under THAT bucket (the quantized tier's scanned
            # rows), so its probes follow the override, not `ab`.
            rb = dec.rows_buckets or {}
            ratio = coststore.drift(dec.stage, dec.tier,
                                    rb.get(dec.tier, ab),
                                    dec.skeleton)
            if ratio >= DRIFT or ratio <= 1.0 / DRIFT:
                self._invalidate(key, "drift")
                return
            # rival check: cost drift's other direction — a warm
            # alternative's observed cost now undercuts the chosen
            # tier's. Without this a cold-prior choice never gets
            # revisited (nothing violates, its own EWMA is steady),
            # even as evidence piles up that another tier is faster.
            # exact_only: this runs per sampled OUTCOME — two dict
            # probes per tier, never the estimate() table scan (that
            # is decision-build territory).
            cur = coststore.estimate(dec.stage, dec.tier,
                                     rb.get(dec.tier, ab),
                                     dec.skeleton, exact_only=True)
            if cur is None or not cur["warm"]:
                return
            for tier in dec.costs:
                if tier == dec.tier or tier == "device":
                    # device rivalry needs the RTT added in; only a
                    # full rebuild models it — skip (conservative)
                    continue
                alt = coststore.estimate(dec.stage, tier,
                                         rb.get(tier, ab),
                                         dec.skeleton,
                                         exact_only=True)
                if alt is not None and alt["warm"] \
                        and alt["p50_us"] * RIVAL_MARGIN \
                        < cur["p50_us"]:
                    self._invalidate(key, "drift")
                    return

    def _invalidate(self, key: tuple, reason: str) -> None:
        """Bump the stage key's generation (the decision cache keys on
        it, so the stale decision becomes unreachable) under the
        re-plan token bucket."""
        now = _time.monotonic()
        with self._lock:
            tb = self._replan_tokens.get(key)
            if tb is None:
                if len(self._replan_tokens) >= MAX_KEYS:
                    self._replan_tokens.clear()
                tb = [float(REPLAN_BURST), now]
                self._replan_tokens[key] = tb
            tb[0] = min(float(REPLAN_BURST),
                        tb[0] + (now - tb[1]) / REPLAN_REFILL_S)
            tb[1] = now
            if tb[0] < 1.0:
                self._suppressed += 1
                suppressed = True
            else:
                tb[0] -= 1.0
                if len(self._versions) >= MAX_KEYS:
                    self._versions.clear()
                self._versions[key] = self._versions.get(key, 0) + 1
                self._reoptimized += 1
                suppressed = False
        if suppressed:
            metrics.inc_counter("planner_replans_suppressed_total")
        else:
            metrics.inc_counter("planner_reoptimized_total",
                                labels={"reason": reason})

    # -- plan-level decisions ------------------------------------------

    def probe_or_scan(self, stage: str, est_probe_rows: int,
                      n_candidates: int,
                      probe_tier: str = "compressed") -> str:
        """Index-probe vs candidate-scan pivot for a filter-context
        token function: probing costs ~per_row(probe_tier) x estimated
        postings; scanning verifies each candidate's value
        (~per_row(postings)). `probe_tier` is the tier the probe would
        ACTUALLY serve from (the stage's decided tier) — pricing a
        postings walk with the compressed prior would under-cost it
        ~50x and pick "probe" exactly where scanning wins biggest.
        Returns "probe" or "scan"."""
        fixed_s, per_scan = STATIC_PRIORS.get(
            (stage, "postings"), (8.0, 0.5))
        fixed_p, per_probe = STATIC_PRIORS.get(
            (stage, probe_tier), (4.0, 0.01))
        scan_us = fixed_s + per_scan * n_candidates
        probe_us = fixed_p + per_probe * max(0, est_probe_rows)
        return "scan" if scan_us < probe_us else "probe"

    @staticmethod
    def gallop_ratio(smallest: int, largest: int) -> int:
        """Density-driven gallop-vs-merge pivot for k-way
        intersection (SIMD-intersection paper, PAPERS.md): expected
        intersection density ~ |smallest|/|largest|. Sparse probes
        (ratio < 1/256) gallop already from 4x size skew — almost no
        probe will land, so the vectorized searchsorted beats the
        concat+sort merge even at modest skew (measured: gallop at
        9-13x skew runs ~1.3x faster than the 16x-default merge).
        Denser inputs keep the measured 16x default; holding the
        merge LONGER than 16x measured 3.5-4.5x slower at 18x skew
        on the numpy kernels, so there is deliberately no
        merge-favoring branch."""
        if largest <= 0 or smallest <= 0:
            return 16
        if smallest / largest < 1.0 / 256.0:
            return 4
        return 16

    @classmethod
    def intersect_schedule(cls, lens) -> Optional[tuple[int, ...]]:
        """Per-FOLD gallop ratios for a k-way intersection over parts
        of the given lengths — the intersection-ORDER decision beyond
        the single smallest-vs-largest pivot. The fold order is
        ascending length (commutative: parity-free); what changes per
        fold is the accumulator DENSITY: under the independent-draw
        model |A∩B| ≈ |A|·|B|/U (universe proxied by the largest
        part), the accumulator shrinks as folds proceed, so late
        folds against large parts are far sparser than the global
        smallest/largest ratio suggests and should gallop earlier.
        Returns len(lens)-1 ratios aligned with setops.intersect_many's
        ascending fold order, or None for trivial inputs (callers keep
        the flat-ratio path)."""
        lens = sorted(int(n) for n in lens)
        if len(lens) < 3:
            return None  # single fold: the flat ratio IS the schedule
        universe = float(max(lens[-1], 1))
        acc = float(lens[0])
        ratios = []
        for n in lens[1:]:
            # max(.,1): an expected-empty accumulator should gallop
            # (sparse), not trip gallop_ratio's degenerate-input guard
            ratios.append(cls.gallop_ratio(max(int(acc), 1), n))
            # expected accumulator after this fold (never grows)
            acc = max(0.0, min(acc, acc * n / universe))
        return tuple(ratios)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            mix: dict[str, dict[str, int]] = {}
            for (stage, tier), n in sorted(self._mix.items()):
                mix.setdefault(stage, {})[tier] = n
            return {"mode": "adaptive",
                    "decisions": self._built,
                    "consults": self._consults,
                    "warmServes": self._warm_serves,
                    "mix": mix,
                    "estimateViolations": self._violations,
                    "explored": self._explored,
                    "reoptimized": self._reoptimized,
                    "replansSuppressed": self._suppressed,
                    "learnedKeys": len(self._learned),
                    "versionedKeys": len(self._versions)}
