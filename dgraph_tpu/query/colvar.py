"""Columnar value variables: (sorted uids, numpy values) instead of
dict[int, Val].

The reference flows value variables between blocks as Go maps of typed
values (query/query.go valueVarAggregation, aggregator.go:435,
math.go:213).  Round 3 bound vars columnarly but every CONSUMER still
materialized a python dict and walked it per uid — q020-style
aggregation at the 21M regime spent seconds in those walks.  A ColVar
keeps the two parallel arrays end-to-end; math, aggregation,
`eq/le/ge(val(v), …)` filters and val() order keys all consume the
arrays directly.  Legacy consumers (mixed-type vars, facet vars,
string vars) still see a Mapping: iteration/len/contains are answered
from the uid array, and only __getitem__/items/values materialize the
dict — so the slow path is paid exactly where the dict path was the
status quo.

Value semantics mirror the dict path bit-for-bit:
  * math runs in float64 (the dict path converts every leaf with
    float(), so this is not a new rounding surface);
  * aggregation sums sequentially over the python list of the gathered
    column, matching the committed goldens' left-fold rounding;
  * materialization converts integral math results back to INT per
    element exactly like _eval_math's tail did.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Optional

import numpy as np

from dgraph_tpu.models.types import TypeID, Val

_NUMERIC = (TypeID.INT, TypeID.FLOAT, TypeID.BOOL)


class ColVar(Mapping):
    """A value variable as parallel arrays.

    uids:  uint64, sorted ascending, unique
    vals:  int64 (INT), float64 (FLOAT / math results), uint8 (BOOL)
    tid:   the Val type materialized entries carry
    frac:  math-result flag — materialize per-element INT-if-integral
           (matches _eval_math's historical output typing)
    isbool: math comparison result — materialize as BOOL
    """

    __slots__ = ("uids", "vals", "tid", "frac", "isbool", "objs",
                 "_d")

    def __init__(self, uids: np.ndarray, vals: np.ndarray, tid: TypeID,
                 frac: bool = False, isbool: bool = False, objs=None):
        self.uids = uids
        self.vals = vals
        self.tid = tid
        self.frac = frac
        self.isbool = isbool
        # DATETIME vars: vals carry float epoch seconds (the domain
        # math works in, aggregator.go applySince semantics) while
        # objs holds the EXACT datetime objects for materialization —
        # reconstruction from floats would lose precision and tz
        self.objs = objs
        self._d: Optional[dict] = None

    # -- Mapping protocol: cheap paths never materialize ---------------

    def __len__(self) -> int:
        return len(self.uids)

    def __iter__(self):
        return iter(self.uids.tolist())

    def __contains__(self, u) -> bool:
        i = np.searchsorted(self.uids, np.uint64(u))
        return i < len(self.uids) and int(self.uids[i]) == int(u)

    def __getitem__(self, u) -> Val:
        return self.dict()[u]

    def get(self, u, default=None):
        return self.dict().get(u, default)

    def items(self):
        return self.dict().items()

    def values(self):
        return self.dict().values()

    def keys(self):
        return self.dict().keys()

    # -- columnar API --------------------------------------------------

    def gather(self, uids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(present_uids, their values) for a sorted uid array — one
        searchsorted instead of per-uid dict probes; a gather over the
        var's own domain (filters/aggregates on the binding block's
        uids, the q020 shape) short-circuits to the arrays as-is."""
        if not len(uids) or not len(self.uids):
            return uids[:0], self.vals[:0]
        if len(uids) == len(self.uids) and (uids is self.uids or (
                uids[0] == self.uids[0] and uids[-1] == self.uids[-1]
                and np.array_equal(uids, self.uids))):
            # endpoint probes reject length-equal misses before the
            # full O(n) compare (array_equal does not short-circuit)
            return self.uids, self.vals
        pos = np.searchsorted(self.uids, uids)
        pos = np.minimum(pos, len(self.uids) - 1)
        hit = self.uids[pos] == uids
        return uids[hit], self.vals[pos[hit]]

    def to_val(self, x) -> Val:
        """One element → Val, with math-result typing rules."""
        if self.isbool:
            return Val(TypeID.BOOL, bool(x))
        if self.frac:
            f = float(x)
            if f.is_integer() and abs(f) < 2 ** 53:
                return Val(TypeID.INT, int(f))
            return Val(TypeID.FLOAT, f)
        if self.tid == TypeID.BOOL:
            return Val(TypeID.BOOL, bool(x))
        if self.tid == TypeID.INT:
            return Val(TypeID.INT, int(x))
        if self.tid == TypeID.FLOAT:
            return Val(TypeID.FLOAT, float(x))
        return Val(self.tid, x)

    def dict(self) -> dict:
        if self._d is None:
            if self.objs is not None:
                self._d = {u: Val(self.tid, o) for u, o in
                           zip(self.uids.tolist(), self.objs.tolist())}
            else:
                self._d = {u: self.to_val(v) for u, v in
                           zip(self.uids.tolist(), self.vals.tolist())}
        return self._d

    def floats(self) -> np.ndarray:
        """Values as float64 — the domain _eval_math works in."""
        return self.vals.astype(np.float64, copy=False)

    def take(self, uids: np.ndarray) -> "ColVar":
        """Subset ColVar for a sorted uid array, preserving the exact
        object column when present."""
        if not len(uids) or not len(self.uids):
            return ColVar(uids[:0], self.vals[:0], self.tid, self.frac,
                          self.isbool,
                          None if self.objs is None else self.objs[:0])
        if len(uids) == len(self.uids) and (uids is self.uids or (
                uids[0] == self.uids[0] and uids[-1] == self.uids[-1]
                and np.array_equal(uids, self.uids))):
            return self
        pos = np.searchsorted(self.uids, uids)
        pos = np.minimum(pos, len(self.uids) - 1)
        hit = self.uids[pos] == uids
        sel = pos[hit]
        return ColVar(uids[hit], self.vals[sel], self.tid, self.frac,
                      self.isbool,
                      None if self.objs is None else self.objs[sel])

    def sort_keys(self) -> np.ndarray:
        """Order-preserving int64 keys, vectorizing models.types.sort_key
        for the numeric types a ColVar carries."""
        if self.tid == TypeID.DATETIME and self.objs is not None:
            from dgraph_tpu.models.types import sort_key
            return np.fromiter(
                (sort_key(Val(TypeID.DATETIME, o))
                 for o in self.objs.tolist()),
                np.int64, len(self.objs))
        if self.isbool or self.tid == TypeID.BOOL:
            return self.vals.astype(np.int64)
        if self.frac:
            # math results: INT-if-integral typing doesn't change the
            # ORDER, and float keys order identically to int keys for
            # integral values — use the float key uniformly
            return _float_sort_keys(self.floats())
        if self.tid == TypeID.INT:
            return self.vals.astype(np.int64, copy=False)
        if self.tid == TypeID.FLOAT:
            return _float_sort_keys(self.vals)
        raise ValueError("unsortable colvar")


def _float_sort_keys(a: np.ndarray) -> np.ndarray:
    """IEEE754 total-order trick, elementwise (types.sort_key)."""
    bits = a.astype(np.float64).view(np.int64)
    u = np.where(bits < 0, ~bits.view(np.uint64),
                 bits.view(np.uint64) | np.uint64(1 << 63))
    return (u - np.uint64(1 << 63)).view(np.int64)


def make_colvar(uids: np.ndarray, vals: np.ndarray,
                tid: TypeID) -> Optional[ColVar]:
    """ColVar for a numeric column; None for types the columnar
    pipeline doesn't carry (strings/datetimes keep the dict path)."""
    if tid not in _NUMERIC:
        return None
    if tid == TypeID.INT:
        vals = vals.astype(np.int64, copy=False)
    elif tid == TypeID.FLOAT:
        vals = vals.astype(np.float64, copy=False)
    else:
        vals = vals.astype(np.uint8, copy=False)
    return ColVar(uids, vals, tid)
