"""Query processing: planner/executor (SubGraph-equivalent) and JSON
encoding. Re-provides the reference's query/ package semantics
(query/query.go ProcessGraph, outputnode.go ToJson) with level-batched
device calls in place of goroutine fan-out."""
