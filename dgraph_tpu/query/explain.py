"""EXPLAIN / EXPLAIN ANALYZE: the compiled plan tree, annotated.

A request that carries `@explain` (or HTTP `?explain=`) gets back
`extensions.explain`: the plan the engine actually used — per-block
stage chains from the compiled skeleton (query/plan.py), the plan
cache outcome, the tier configuration — annotated with row estimates
from the per-predicate tablet statistics (storage/tabstats.py). With
`analyze`, the same tree additionally carries what execution actually
did: resolved root/result row counts per block, the stage spans of
this request's trace with their durations, and the metrics-counter
delta of execution (tier hits, fallbacks, cache movement).

EXPLAIN never changes execution: both modes run the query normally and
the `data` payload is byte-identical with or without the flag (tier-1
proves it differentially). That is the reference's `debug=true`
philosophy extended to plan shape: annotate the real request, never a
simulation of it.

Row-estimate bases and their DOCUMENTED error bounds — these are the
contract tests/test_explain.py enforces over the full 75-query golden
workload and docs/deployment.md publishes:

  exact    est == actual. Literal-uid roots and roots over absent
           tablets: the estimator can count them without statistics.
  index    actual <= est <= estMax. The estimate counts a candidate
           SUPERSET the stage then verifies (has() key cardinality,
           similar_to's k): exact on clean tablets up to verification,
           never an undercount.
  stats    actual <= estMax (est itself is the statistical guess —
           token-index fanout, selectivity heuristics — with no
           per-query guarantee). estMax is the hard cap: the tablet's
           key cardinality plus its dirty-overlay op count.
  unknown  no claim. Var-dependent roots, count-at-root device
           shortcuts, shortest paths: plan-time statistics cannot see
           their inputs.

`estMax` everywhere includes `dirtyOps` slack: un-folded overlay ops
may introduce uids the base statistics have never seen; a rollup folds
them and the slack returns to zero.
"""

from __future__ import annotations

from typing import Any, Optional

from dgraph_tpu.gql.ast import VALUE_VAR, Function, GraphQuery
from dgraph_tpu.utils import metrics, tracing

# root functions whose index/scan candidates come from the predicate's
# own key set: actual rows can never exceed keys + dirty slack
_TABLET_BOUND_FNS = frozenset((
    "eq", "le", "lt", "ge", "gt", "between", "anyofterms", "allofterms",
    "anyoftext", "alloftext", "anyof", "allof", "regexp", "match",
    "near", "within", "contains", "intersects", "checkpwd",
))

_BASIS_RANK = {"exact": 0, "index": 1, "stats": 2, "unknown": 3}

# stage spans ANALYZE surfaces from the request's trace, in recorded
# order (subset of coststore.STAGES: the per-request ones)
_ANALYZE_SPANS = frozenset((
    "parse", "plan.compile", "block", "eq", "ineq", "setops", "expand",
    "sort", "match", "similar_to", "device.tile_load", "encode",
    "batch.wait",
))


def _worse(a: str, b: str) -> str:
    return a if _BASIS_RANK[a] >= _BASIS_RANK[b] else b


def _tab_stats(db, pred: str) -> Optional[dict]:
    """Cached per-tablet statistics (never bumps the touch counter —
    the estimator is not a query-path read)."""
    tab = db.tablets.get(pred)
    if tab is None or not hasattr(tab, "base_ts"):
        # federated RemoteTablet proxies carry no stats surface: the
        # coordinator estimates nothing rather than crash a query that
        # executed fine ("unknown" basis downstream)
        return None
    from dgraph_tpu.storage.tabstats import tablet_stats
    return tablet_stats(tab)


def _est(rows: int, cap: int, basis: str, source: str) -> dict:
    rows = max(0, int(rows))
    return {"estRows": min(rows, cap) if cap >= 0 else rows,
            "estRowsMax": int(cap), "basis": basis, "source": source}


def _unknown(source: str) -> dict:
    return {"estRows": -1, "estRowsMax": -1, "basis": "unknown",
            "source": source}


def _fn_estimate(db, fn: Function) -> dict:
    """Estimated result rows of one root function, from the tablet
    statistics alone (no data access beyond the cached aggregate)."""
    name = fn.name
    if name == "uid":
        if fn.needs_var:
            return _unknown("uid(var) domain is runtime state")
        n = len(set(fn.uids))
        return _est(n, n, "exact", "literal uid list")
    if fn.needs_var or fn.is_value_var or fn.is_len_var:
        return _unknown("value-var function")
    if fn.is_count:
        # le(count(p), 0) matches uids WITHOUT the predicate — no
        # tablet statistic bounds that set
        return _unknown("count() root")
    pred = fn.attr or ""
    reverse = pred.startswith("~")
    base = pred[1:] if reverse else pred
    st = _tab_stats(db, base)
    if st is None:
        if name == "type":
            st = _tab_stats(db, "dgraph.type")
            if st is None:
                if db.tablets.get("dgraph.type") is not None:
                    return _unknown("tablet without statistics surface")
                return _est(0, 0, "exact", "no dgraph.type tablet")
            cap = st["nSrc"] + _dirty(st)
            return _est(st["tokenIndex"]["avgPostings"], cap, "stats",
                        "dgraph.type token index")
        # "exact 0" is only a valid claim when the tablet truly does
        # not exist; a present-but-opaque tablet (RemoteTablet) makes
        # no claim at all
        if db.tablets.get(base) is not None:
            return _unknown("tablet without statistics surface")
        return _est(0, 0, "exact", "no tablet for predicate")
    dirty = _dirty(st)
    cap = st["nSrc"] + dirty
    # the superset ("index") claim — actual <= est — only holds when
    # the base statistics saw every op: a dirty overlay may hold uids
    # the base never had, so key-count estimates demote to "stats"
    # (estMax keeps the bound: it carries the dirty slack)
    key_basis = "stats" if dirty else "index"
    if name == "has":
        if reverse:
            n_dst = st["nDst"]
            if n_dst >= 0:
                return _est(n_dst, st["edges"] + dirty, key_basis,
                            "reverse-index key count")
            return _est(st["edges"], st["edges"] + dirty, "stats",
                        "edge count (nDst unknown)")
        return _est(st["nSrc"], cap, key_basis, "tablet key count")
    if name == "similar_to":
        try:
            k = int(float(fn.args[1].value))
        except (IndexError, ValueError, TypeError):
            return _unknown("similar_to without literal k")
        return _est(min(k, st["nSrc"]), min(k, cap), "index",
                    "top-k bound")
    if name == "eq":
        n_vals = max(1, len(fn.args))
        avg = st["tokenIndex"]["avgPostings"]
        return _est(int(round(n_vals * avg)) if avg else min(1, cap),
                    cap, "stats", "token-index fanout")
    if name in ("anyofterms", "anyoftext", "anyof"):
        n_terms = sum(len(str(a.value).split()) for a in fn.args) or 1
        avg = st["tokenIndex"]["avgPostings"]
        return _est(int(round(n_terms * avg)), cap, "stats",
                    "token-index fanout (union)")
    if name in ("allofterms", "alloftext", "allof"):
        avg = st["tokenIndex"]["avgPostings"]
        return _est(int(round(avg)), cap, "stats",
                    "token-index fanout (intersection)")
    if name in ("le", "lt", "ge", "gt"):
        return _est(st["nSrc"] // 2, cap, "stats",
                    "half-range heuristic")
    if name == "between":
        return _est(st["nSrc"] // 3, cap, "stats",
                    "range-fraction heuristic")
    if name in _TABLET_BOUND_FNS:
        return _est(st["nSrc"], cap, "stats", "tablet key count")
    return _unknown(f"no estimator for {name}()")


def _dirty(st: dict) -> int:
    return int(st.get("dirtyOps", 0))


def _root_estimate(db, gq: GraphQuery) -> dict:
    """Estimate for a block's resolved root set BEFORE filters and
    pagination — the number _run_block_inner measures as root_rows."""
    if gq.attr == "shortest":
        return _unknown("shortest-path block")
    parts: list[dict] = []
    if gq.uids:
        n = len(set(gq.uids))
        parts.append(_est(n, n, "exact", "literal uid list"))
    if any(vc.typ != VALUE_VAR for vc in gq.needs_var):
        parts.append(_unknown("uid-var root"))
    elif gq.needs_var and gq.func is not None and gq.func.name == "uid":
        parts.append(_unknown("uid(var) root"))
    if gq.func is not None and gq.func.name != "uid":
        parts.append(_fn_estimate(db, gq.func))
    # (func: uid(...) literals need no part of their own — the parser
    # copies them into gq.uids; uid(var) roots were flagged above)
    if not parts:
        if gq.is_empty:
            return _est(0, 0, "exact", "empty var block")
        return _unknown("no root source")
    basis = "exact"
    for p in parts:
        basis = _worse(basis, p["basis"])
    if basis == "unknown":
        return _unknown("; ".join(p["source"] for p in parts))
    # union of parts: each part's estimate/cap adds (overlap only
    # shrinks the actual, which every non-exact basis already allows)
    est = sum(p["estRows"] for p in parts)
    cap = sum(p["estRowsMax"] for p in parts)
    if len(parts) > 1:
        basis = _worse(basis, "index")  # union overlap: no longer exact
    src = parts[0]["source"] if len(parts) == 1 \
        else "union: " + "; ".join(p["source"] for p in parts)
    return _est(est, cap, basis, src)


def _child_estimate(db, gq: GraphQuery, parent_rows: int) -> dict:
    """Expansion-size estimate for one child predicate given the
    parent's (estimated) row count: uid edges multiply by the tablet's
    mean fan-out, scalars fill at most one row per parent."""
    pred = (gq.attr or "").lstrip("~")
    st = _tab_stats(db, pred)
    if st is None or parent_rows < 0:
        return _unknown("no tablet statistics")
    fan = st["fanout"].get("avg", 0.0) or 0.0
    if st["type"] == "uid":
        return _est(int(round(parent_rows * max(fan, 1.0))),
                    st["edges"] + _dirty(st), "stats",
                    "mean fan-out")
    return _est(min(parent_rows, st["nSrc"] + _dirty(st)),
                st["nPostings"] + _dirty(st), "stats",
                "scalar fill bound")


def _node_rows(node) -> int:
    """Observed result rows of one executed node: resolved uids, or
    bound scalar values when the node never materializes a uid set."""
    n = int(len(node.dest))
    if n == 0 and node.values:
        n = len(node.values)
    if n == 0 and node.col_vals:
        n = len(node.col_vals)
    return n


def _explain_node(db, gq: GraphQuery, node, mode: str,
                  parent_rows: int, depth: int = 0) -> dict:
    est = _root_estimate(db, gq) if depth == 0 \
        else _child_estimate(db, gq, parent_rows)
    out: dict[str, Any] = {
        "name": gq.alias or gq.attr,
        "attr": gq.attr,
        **est,
    }
    if depth == 0 and getattr(node, "fused", ""):
        # per-block fusion attribution: "fused" when the whole
        # filter+order+page chain ran as one device executable,
        # "staged:<reason>" when it fell back (query/fusion.py)
        out["fusion"] = node.fused
    if mode == "analyze":
        out["actualRows"] = _node_rows(node)
        if depth == 0:
            out["actualRootRows"] = int(node.root_rows)
    kids = []
    rows_in = est["estRows"]
    for ch in node.children:
        kids.append(_explain_node(db, ch.gq, ch, mode, rows_in,
                                  depth + 1))
    if kids:
        out["children"] = kids
    return out


def _stage_spans(trace_id: str) -> list[dict]:
    """This request's stage spans (recorded order) with durations and
    size attrs — the per-request slice of what the coststore
    aggregates globally."""
    out = []
    for rec in tracing.spans_for(trace_id):
        if rec["name"] not in _ANALYZE_SPANS:
            continue
        ent: dict[str, Any] = {"stage": rec["name"],
                               "durUs": round(rec.get("dur_us", 0.0), 1)}
        args = rec.get("args") or {}
        for k in ("pred", "fn", "alias", "rows", "n", "tier", "role"):
            if k in args:
                ent[k] = args[k]
        out.append(ent)
    return out


def build_explain(db, ex, done, expinfo: dict) -> dict:
    """Assemble extensions.explain for one finished execution.
    `ex`/`done` are the request's Executor and its executed blocks;
    `expinfo` carries the mode, this request's trace id, the
    pre-execution counter snapshot and the plan-cache outcome."""
    mode = expinfo["mode"]
    plan = ex.plan
    planner: dict[str, Any] = {
        "cached": plan is not None,
        "cacheHit": expinfo.get("cache", {}).get("hit"),
    }
    if plan is not None:
        planner.update(plan.describe())
        planner["memoEntries"] = len(plan._memo)
    else:
        planner["skeleton"] = None
        planner["epoch"] = getattr(db, "schema_epoch", 0)
    out: dict[str, Any] = {
        "mode": mode,
        "planner": planner,
        "tiers": {
            # adaptive: the prefer_* flags are OVERRIDES bounding
            # which tiers the cost-based planner may pick per stage;
            # static: they decide outright (pre-PR-13 heuristics)
            "planner": getattr(db, "planner", "static"),
            "columnar": bool(getattr(db, "prefer_columnar", True)),
            "compressed": bool(getattr(db, "prefer_columnar", True))
            and bool(getattr(db, "prefer_compressed", True)),
            "device": bool(getattr(db, "prefer_device", False)),
            "deviceMinEdges": int(getattr(db, "device_min_edges", 0)),
            # whole-plan fusion (query/fusion.py): a compiled-plan
            # tier — per-block served/fell-back attribution rides on
            # each block node as `fusion`
            "fused": bool(getattr(db, "prefer_fused", True)),
            "fusedMinRows": int(getattr(db, "fused_min_rows", 0)),
            "quantized": bool(getattr(db, "vec_quantized", False)),
            # per-stage vector-tier decisions, one per similar_to
            # evaluation this request ran: the tier that actually
            # scored (exact / two_stage / quantized / sharded*) and,
            # for the quantized tier, its recall budget (nprobe,
            # rerank depth, calibrated sample recall)
            "vector": list(getattr(ex, "vector_decisions", ())),
        },
        # per-stage chosen tier + estimate basis + decision inputs
        # (query/planner.py Decision.describe): every tier decision
        # this request consulted, in consult order — `reoptimized`
        # marks a decision rebuilt after an estimate violation or
        # cost-drift invalidation (version = its generation)
        "tierDecisions": [d.describe()
                          for d in getattr(ex, "tier_decisions", ())],
        "blocks": [_explain_node(db, gq, node, mode, -1)
                   for gq, node in done],
    }
    if mode == "analyze":
        out["traceId"] = expinfo.get("trace_id", "")
        # execution-side counter movement (post-parse: the plan-cache
        # counters land in planner.cacheHit instead)
        out["counters"] = metrics.counters_delta(
            expinfo["counters_before"])
        out["stages"] = _stage_spans(expinfo.get("trace_id", ""))
    return out
