"""Whole-plan device fusion: one executable per (skeleton, shapes, mesh).

The plan cache (query/plan.py) compiles per-stage kernels via
`jit_stage`, but a block's pipeline still hopped host<->device per
stage: filter set algebra, then the multisort, then the page slice —
each its own dispatch, each paying the tunnel round-trip and the
host-side interpreter glue between them. This module lowers a compiled
skeleton's whole post-probe chain

    root candidates -> filter set algebra -> multi-key order
                    -> after/offset/first page

into ONE jitted executable (ops/graph.fused_rank_page), keyed
through the sanctioned `jit_stage` seam by the block's STATIC shape —
filter combinator + leaf negations, order key count + directions, page
window — plus the engine's mesh layout. Literal values (eq arguments,
cursors, offsets) are runtime operands: a param-only change re-binds
and re-dispatches with ZERO recompiles (tools/fusion_smoke.py and
tests/test_fusion.py assert the executable count stays flat).

Index probes stay on host BY DESIGN: a token probe is a memoized dict
lookup (microseconds, value-dependent), and routing it through the
planner keeps the tier machinery — compressed block-skip vs CSR vs
postings — live under fusion. What fusion removes is everything
DOWNSTREAM of the probes: the per-stage set-algebra dispatches, the
separate sort dispatch, the pagination round-trip, and the host glue
between them.

Sharding is declared, not hand-placed: FUSION_RULES is an ordered
(regex, PartitionSpec) table resolved per operand name via
parallel/mesh.match_partition_rules (the pjit partition-rule pattern).
On a mesh-less engine the rules are inert; on a mesh the executable
pins every uid-vector operand before tracing the kernel.

Filter leaves lower in one of two forms:

  RANK leaves — eq/ineq over non-list, non-lang predicates whose sort
    key is injective (int / float / bool / datetime): the leaf becomes
    a [lo, hi) range test over the predicate's DeviceValues rank
    column, computed host-side from two binary searches of the view's
    sorted distinct keys. No index probe, no per-query upload, and the
    bounds are TRACED operands — a threshold change re-binds scalars.
  SET leaves — everything else the parity theorem covers (string eq,
    has, lang/list predicates): host root-context evaluation uploads a
    sorted uid vector and the kernel applies a membership mask.

ELIGIBILITY is two-layered, and the staged path is the permanent
byte-parity oracle (tests/test_columnar_parity.py runs the fused arm
against it across clean / dirty-overlay / rollup-boundary states):

  structural (recomputed per request — the verdict carries the
    request's literal-bearing filter Functions, so it must never be
    cached on the literal-blind shared plan):
    plain block (no shortest/recurse/groupby/similar_to), a non-empty
    order of plain sortable predicates, a bounded `first`, and a
    filter that is absent or a flat AND/OR of (optionally NOT-wrapped)
    eq / has / inequality leaves over indexed predicates — exactly
    the leaf set whose root-context evaluation is proven pointwise
    (C intersect f(None) == f(C)), so leaf probes run once with no
    candidate set and the fused kernel applies them as membership
    masks (rank leaves skip even that probe).
  runtime (per request, silent fallback to staged):
    device views resident for every order key and every rank leaf
    (clean tablets — a dirty overlay falls back, the same MVCC rule
    as every device tier; a missing leaf view demotes that leaf to
    set form), 32-bit uid space, no after-cursor, page bounds within
    the kernel's selection cap, a root at least `db.fused_min_rows`
    wide, and a boundary tie mass within FUSED_SEL_CAP (the kernel
    reports overflow and the executor re-runs the staged chain).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec

from dgraph_tpu.gql.ast import FilterTree, Function, GraphQuery
from dgraph_tpu.query.plan import jit_stage

# filter leaves whose root-context evaluation is pointwise-equal to
# their candidate-context evaluation (the fusion parity precondition)
_LEAF_FNS = frozenset(("eq", "has", "le", "lt", "ge", "gt", "between"))
_INEQ_FNS = frozenset(("le", "lt", "ge", "gt", "between"))

# sortable index types (mirrors executor._has_sortable_index: root
# inequalities demand one, and fused leaf probes run in root context)
_SORTABLE = frozenset(("exact", "int", "float", "datetime"))

# value types whose models.types.sort_key is INJECTIVE: equal keys
# imply equal values, so a [lo, hi) rank range over the DeviceValues
# view is byte-equal to the staged eq/ineq set. Strings are excluded —
# their key is an 8-byte prefix and ties are broken host-side.
_RANK_EXACT_TYPES = frozenset(("INT", "FLOAT", "BOOL", "DATETIME"))

# the per-plan sharding declaration (pjit partition-rule pattern,
# SNIPPETS.md): uid-vector operands ride the mesh's `uid` axis, rank
# columns follow their aligned uid vectors, scalars replicate. On a
# 1-chip mesh (or none) every rule degrades to replication.
FUSION_RULES = (
    (r"^cand$", PartitionSpec("uid")),
    (r"^fpart\d+$", PartitionSpec("uid")),
    (r"^rk_(uids|ranks)\d+$", PartitionSpec("uid")),
    (r"^dv_(uids|ranks)\d+$", PartitionSpec("uid")),
)


def _has_sortable_index(ps) -> bool:
    toks = getattr(ps, "tokenizers", ()) or ()
    return any(t in _SORTABLE for t in toks)


def _leaf_ok(fn: Optional[Function], schema) -> Optional[str]:
    """None when `fn` may serve as a fused filter leaf, else the
    reason it can't (attribution string)."""
    if fn is None:
        return "leaf:empty"
    if fn.name not in _LEAF_FNS:
        return f"leaf:{fn.name}"
    if fn.is_count or fn.needs_var or fn.is_value_var or fn.is_len_var:
        return "leaf:var-or-count"
    if not fn.attr or fn.attr == "uid":
        return "leaf:attr"
    if fn.name == "has":
        return None  # key-set membership: no index involved
    ps = schema.get(fn.attr.lstrip("~"))
    if ps is None or not getattr(ps, "indexed", False):
        # root-context evaluation of an unindexed eq/ineq raises;
        # the staged filter path legally scans instead
        return "leaf:not-indexed"
    if fn.name in _INEQ_FNS and not _has_sortable_index(ps):
        return "leaf:not-sortable"
    return None


def leaf_kind(fn: Function, schema) -> str:
    """"rank" when the leaf can evaluate as a traced rank-range test
    over the predicate's DeviceValues view with byte-exact staged
    semantics, else "set" (host eval + membership upload). Structural:
    schema + call shape only."""
    if fn.name == "has" or fn.lang:
        return "set"
    want = 2 if fn.name == "between" else 1
    if len(fn.args) != want:
        return "set"  # eq(p, [a, b]) list form: multiple token probes
    ps = schema.get(fn.attr.lstrip("~"))
    if ps is None or getattr(ps, "list_", False) \
            or getattr(ps, "lang", False):
        return "set"
    vt = getattr(ps, "value_type", None)
    if vt is None or vt.name not in _RANK_EXACT_TYPES:
        return "set"
    return "rank"


def filter_spec(ft: Optional[FilterTree], schema):
    """(fop, leaves) for a fusable filter tree, or a reason string.

    Accepted shapes: no filter; a single leaf; NOT(leaf); one flat
    AND/OR whose children are leaves or NOT(leaf). `leaves` is a list
    of (Function, negated, kind) in tree order, kind from leaf_kind."""
    if ft is None:
        return "none", []
    if ft.func is not None:
        why = _leaf_ok(ft.func, schema)
        return ("and", [(ft.func, False, leaf_kind(ft.func, schema))]) \
            if why is None else why
    if ft.op == "not" and len(ft.children) == 1 \
            and ft.children[0].func is not None:
        fn = ft.children[0].func
        why = _leaf_ok(fn, schema)
        return ("and", [(fn, True, leaf_kind(fn, schema))]) \
            if why is None else why
    if ft.op not in ("and", "or"):
        return f"filter:{ft.op}"
    leaves = []
    for c in ft.children:
        if c.func is not None:
            fn, neg = c.func, False
        elif c.op == "not" and len(c.children) == 1 \
                and c.children[0].func is not None:
            fn, neg = c.children[0].func, True
        else:
            return "filter:nested"
        why = _leaf_ok(fn, schema)
        if why is not None:
            return why
        leaves.append((fn, neg, leaf_kind(fn, schema)))
    if not leaves:
        return "filter:empty"
    return ft.op, leaves


def block_eligible(gq: GraphQuery, schema):
    """Structural fusion verdict for one block: ("ok", (fop, leaves))
    or ("<reason>", None). Cheap enough to run per request — and it
    MUST: `leaves` holds this request's Function objects (literals
    included), which a plan-scoped cache would freeze at their
    first-request values (tools/fusion_smoke.py case 2)."""
    if gq.attr == "shortest":
        return "shortest", None
    if gq.recurse is not None:
        return "recurse", None
    if gq.is_groupby:
        return "groupby", None
    if not gq.order:
        return "no-order", None
    if gq.first is None:
        return "no-first", None
    fn = gq.func
    if fn is not None and fn.name == "similar_to":
        return "similar-root", None
    for o in gq.order:
        if o.attr == "uid" or o.attr.startswith(("val(", "facet:")):
            return "order-attr", None
        if o.lang in (".", "*"):
            return "order-lang", None
        ops = schema.get(o.attr.lstrip("~"))
        if ops is None:
            return "order-unknown", None  # staged raises the GQLError
        if getattr(ops, "list_", False):
            return "order-list", None
        if getattr(ops, "value_type", None) is not None \
                and ops.value_type.name == "BOOL":
            return "order-bool", None
    spec = filter_spec(gq.filter, schema)
    if isinstance(spec, str):
        return spec, None
    return "ok", spec


def fused_executable(mesh, mesh_key, fop: str, rank_negs: tuple,
                     set_negs: tuple, set_aligned: bool, descs: tuple,
                     window: int, shift: int, rank_luts: tuple,
                     ord_luts: tuple):
    """The ONE jitted whole-block executable for this static shape,
    served from the process-wide `jit_stage` registry — the sanctioned
    dynamic-jit seam (dglint DG02 checks this file compiles through
    it and nowhere else). jax's trace cache keys on operand shapes
    below this; callers bucket every vector to powers of two
    (ops/uidvec.pad_to), so executables stay bounded per (fop, leaf
    negations, descs, window, bucket shift, view forms, shape-bucket,
    mesh layout). Rank bounds, the desc recenter and the page offset
    are traced operands: parameter changes NEVER recompile.

    `rank_luts`/`ord_luts` are the STATIC dv_view form flags (True =
    dense rank LUT, False = sorted uid/rank planes): they change which
    gather the trace emits, so they key the registry. LUT payloads are
    uid-indexed (not uid-partitioned) and replicate across the mesh;
    search payloads shard on the uid axis via FUSION_RULES."""
    import jax

    from dgraph_tpu.parallel.mesh import shard_by_rules

    def build():
        from dgraph_tpu.ops.graph import fused_rank_page

        def run(cand, rank_views, rank_los, rank_his, fparts,
                ord_views, base0, offset):
            if mesh is not None:
                def _names(prefix, views, luts):
                    out = {}
                    for i, ((a, b), is_lut) in enumerate(
                            zip(views, luts)):
                        if is_lut:  # replicated: no rule matches
                            out[f"{prefix}_lut{i}"] = a
                            out[f"{prefix}_base{i}"] = b
                        else:
                            out[f"{prefix}_uids{i}"] = a
                            out[f"{prefix}_ranks{i}"] = b
                    return out

                def _views(named, prefix, luts):
                    return tuple(
                        (named[f"{prefix}_lut{i}"],
                         named[f"{prefix}_base{i}"]) if is_lut else
                        (named[f"{prefix}_uids{i}"],
                         named[f"{prefix}_ranks{i}"])
                        for i, is_lut in enumerate(luts))

                named = {"cand": cand}
                named.update(_names("rk", rank_views, rank_luts))
                named.update(_names("dv", ord_views, ord_luts))
                named.update(
                    {f"fpart{i}": p for i, p in enumerate(fparts)})
                named = shard_by_rules(mesh, FUSION_RULES, named)
                cand = named["cand"]
                rank_views = _views(named, "rk", rank_luts)
                ord_views = _views(named, "dv", ord_luts)
                fparts = tuple(named[f"fpart{i}"]
                               for i in range(len(fparts)))
            return fused_rank_page(
                cand, rank_views, rank_luts, rank_los, rank_his,
                rank_negs, fparts, set_negs, set_aligned, fop,
                ord_views, ord_luts, descs, base0, shift, window,
                offset)

        return jax.jit(run)

    return jit_stage("fusion.block_page", build,
                     static=(fop, rank_negs, set_negs, set_aligned,
                             descs, window, shift, rank_luts, ord_luts,
                             mesh_key))


def collect_preds(parsed) -> list[str]:
    """Every predicate a parsed query MAY touch (root functions,
    filters, order keys, child expansion, recurse/groupby) — the
    prefetch working set the executor hands engine/prefetch.py before
    block execution, so store-backed tablets decode while earlier
    blocks compute."""
    preds: list[str] = []
    seen: set[str] = set()

    def _add(attr: Optional[str]):
        if not attr:
            return
        p = attr.lstrip("~")
        if p and p != "uid" and not p.startswith(("val(", "facet:")) \
                and p not in seen:
            seen.add(p)
            preds.append(p)

    def _fn(fn: Optional[Function]):
        if fn is not None:
            _add(fn.attr)

    def _ft(ft: Optional[FilterTree]):
        if ft is None:
            return
        _fn(ft.func)
        for c in ft.children:
            _ft(c)

    def _gq(gq: GraphQuery):
        _add(gq.attr if gq.attr not in ("shortest",) else None)
        _fn(gq.func)
        _ft(gq.filter)
        for o in gq.order:
            _add(o.attr)
        for g in gq.groupby:
            _add(g.attr)
        if gq.shortest is not None:
            _fn(gq.shortest.from_)
            _fn(gq.shortest.to)
        for c in gq.children:
            _gq(c)

    for gq in getattr(parsed, "queries", ()):
        _gq(gq)
    return preds
