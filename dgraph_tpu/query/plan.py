"""Compiled query plans: skeleton canonicalization + LRU'd executables.

The executor interprets a parsed GQL query in host Python: every
request re-parses its text, re-walks the AST to schedule blocks,
re-derives per-stage constants (index tokens, compiled regexes, tier
choices) and — on the device tier — re-dispatches eager jnp ops per
stage. Under a high-concurrency request mix that per-request
interpreter overhead dominates small-query latency, and dynamic
`jax.jit` wrapping anywhere in the request path is a standing
recompile hazard (dglint DG02's whole reason for existing).

This module is the planner seam that removes both:

- `skeleton()` canonicalizes a ParsedResult into a structure hash with
  literals hoisted to parameters, so `eq(name, "alice")` and
  `eq(name, "bob")` share ONE plan.
- `PlanCache` holds an LRU of compiled `Plan`s keyed by
  `(skeleton, schema epoch, mesh layout)` plus a parse-LRU keyed by
  `(query text, variables)` — a warm request binds parameters and
  dispatches without re-parsing or re-deriving stage constants.
  Schema `alter` bumps the engine's epoch, making every stale plan
  unreachable (it ages out of the LRU).
- `Plan.memo()` caches parameter-derived stage artifacts (index token
  batches, compiled regex programs) keyed by the parameter VALUES, so
  the cache never serves one request's literals to another.
- `jit_stage()` is THE sanctioned home for dynamic `jax.jit`
  wrapping: a bounded process-global registry of jitted executables.
  Device inputs are padded to power-of-two shape buckets
  (`ops/uidvec.pad_to` — the repo-wide masked-tail convention), so
  each executable compiles once per bucket instead of once per length.
  dglint DG02 flags per-call jit wrapping that bypasses this seam.

MVCC semantics are untouched: a plan caches structure- and
schema-derived state only, never data. Dirty tablets and overlay
reads fall back to the existing exact paths stage by stage, exactly
as the interpreted executor does.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from dgraph_tpu.gql.ast import (
    FilterTree, Function, GraphQuery, MathTree, ParsedResult,
)
from dgraph_tpu.utils import metrics
from dgraph_tpu.utils.tracing import span as _span

# literal placeholder in skeleton structure tuples; the hoisted value
# lands in the params list at the matching walk position
_P = "?"


# ----------------------------------------------------------------------
# skeleton canonicalization
# ----------------------------------------------------------------------


def _fn_skel(fn: Optional[Function], params: list) -> tuple:
    if fn is None:
        return ("fn", None)
    args = []
    for a in fn.args:
        params.append(a.value)
        args.append((_P, bool(a.is_value_var), bool(a.is_graphql_var)))
    params.append(tuple(fn.uids))
    return ("fn", fn.name, fn.attr, fn.lang, tuple(args),
            _P if fn.uids else (),
            tuple((vc.name, vc.typ) for vc in fn.needs_var),
            fn.is_count, fn.is_value_var, fn.is_len_var)


def _ft_skel(ft: Optional[FilterTree], params: list) -> tuple:
    if ft is None:
        return ("ft", None)
    return ("ft", ft.op, _fn_skel(ft.func, params),
            tuple(_ft_skel(c, params) for c in ft.children))


def _math_skel(mt: Optional[MathTree], params: list) -> tuple:
    if mt is None:
        return ("math", None)
    if mt.const is not None:
        params.append(mt.const)
    return ("math", mt.fn, _P if mt.const is not None else None, mt.var,
            tuple(_math_skel(c, params) for c in mt.children))


def _gq_skel(gq: GraphQuery, params: list) -> tuple:
    # names, aliases, flags and child shape are STRUCTURE (they decide
    # stage selection and the emitted JSON's keys); literal values —
    # uid lists, pagination numbers, function args, the checkpwd
    # plaintext — are parameters
    params.append(tuple(gq.uids))
    params.append((gq.first, gq.offset, gq.after))
    shortest = None
    if gq.shortest is not None:
        params.append((gq.shortest.numpaths, gq.shortest.depth,
                       gq.shortest.minweight, gq.shortest.maxweight))
        shortest = (_fn_skel(gq.shortest.from_, params),
                    _fn_skel(gq.shortest.to, params), _P)
    if gq.checkpwd_pwd is not None:
        params.append(gq.checkpwd_pwd)
    return (
        "gq", gq.attr, gq.alias, tuple(gq.langs),
        _P if gq.uids else (),
        _fn_skel(gq.func, params),
        _ft_skel(gq.filter, params),
        tuple((o.attr, o.desc, o.lang) for o in gq.order),
        (_P, gq.first is None),
        tuple(_gq_skel(c, params) for c in gq.children),
        gq.is_count, gq.is_internal, gq.var,
        tuple((vc.name, vc.typ) for vc in gq.needs_var),
        gq.expand,
        (gq.recurse.depth, gq.recurse.allow_loop)
        if gq.recurse is not None else None,
        shortest,
        gq.cascade, gq.normalize, gq.ignore_reflex,
        tuple((g.attr, g.alias, g.lang) for g in gq.groupby),
        gq.is_groupby,
        _math_skel(gq.math, params),
        gq.agg_func, gq.agg_pred,
        (gq.facets.all_keys, tuple(gq.facets.keys))
        if gq.facets is not None else None,
        _ft_skel(gq.facets_filter, params),
        tuple(sorted(gq.facet_var.items())),
        gq.checkpwd_pwd is not None,
        gq.is_empty,
    )


def skeleton(parsed: ParsedResult) -> tuple[tuple, tuple]:
    """Canonicalize a parsed query into (structure, params): the
    structure tuple is hashable and identical for any two queries that
    differ only in literal values; params is the hoisted literal
    vector in deterministic walk order."""
    params: list = []
    struct = ("q",
              tuple(_gq_skel(gq, params) for gq in parsed.queries),
              tuple(parsed.query_vars),
              tuple(sorted((k, tuple(v) if isinstance(v, list) else v)
                           for k, v in
                           (parsed.schema_request or {}).items()))
              if parsed.schema_request is not None else None)
    return struct, tuple(params)


# ----------------------------------------------------------------------
# plan IR
# ----------------------------------------------------------------------


_STAGE_NAMES = (
    ("recurse", lambda gq: gq.recurse is not None),
    ("shortest", lambda gq: gq.shortest is not None),
    ("groupby", lambda gq: gq.is_groupby),
)


def _block_stages(gq: GraphQuery) -> list[str]:
    """Human-readable stage chain for one block — the lowered IR
    `Plan.describe()` prints (tests assert on it; operators read it in
    debug output). Mirrors _run_block_inner's actual stage order."""
    stages = ["root:" + (gq.func.name if gq.func is not None
                         else ("uid" if gq.uids else "empty"))]
    for name, pred in _STAGE_NAMES:
        if pred(gq):
            stages.append(name)
    if gq.filter is not None:
        stages.append("filter")
    if gq.order:
        stages.append("sort:" + ",".join(o.attr for o in gq.order))
    if gq.first is not None or gq.offset or gq.after:
        stages.append("paginate")
    if gq.children:
        stages.append(f"expand[{len(gq.children)}]")
    if gq.cascade:
        stages.append("cascade")
    stages.append("emit")
    return stages


class Plan:
    """One compiled skeleton: the lowered stage IR plus every cached
    executable and parameter-memoized stage artifact that requests
    sharing this skeleton reuse. Immutable after compile except for
    the bounded memo/jit dicts (value-keyed, write-once entries)."""

    __slots__ = ("skeleton_hash", "skeleton_hex", "structure",
                 "stages", "epoch", "mesh_key", "_memo", "_memo_lock",
                 "compiled_ns", "_decisions", "_routing")

    MEMO_MAX = 256  # per-plan bound on param-derived artifacts

    def __init__(self, structure: tuple, skeleton_hash: int,
                 epoch: int, mesh_key: Any):
        self.skeleton_hash = skeleton_hash
        # pre-formatted: the planner/coststore join key, read per
        # stage consult on the query hot path
        self.skeleton_hex = f"{skeleton_hash:016x}"
        self.structure = structure
        self.epoch = epoch
        self.mesh_key = mesh_key
        self.stages: list[list[str]] = []
        # dglint: guarded-by=_memo:atomic,_decisions:atomic
        # (the hot read is a bare GIL-atomic dict probe by design;
        # writes are idempotent and serialize under _memo_lock)
        self._memo: dict = {}
        self._memo_lock = threading.Lock()
        self.compiled_ns = 0
        # planner tier decisions (query/planner.py), keyed per stage
        # with a re-optimization generation: kept APART from _memo so
        # param-churn memo clears never wipe tier choices, and so
        # EXPLAIN / /debug can enumerate the plan's current routing
        self._decisions: dict = {}
        # the executor's warm-request routing layer: its stage memo
        # key -> the live Decision, validated per request against the
        # planner's re-optimization generation with one dict probe —
        # so a warm request skips the estimate build AND the consult
        # (the adaptive planner's whole steady-state cost)
        self._routing: dict = {}

    def memo(self, key: tuple, build: Callable[[], Any]) -> Any:
        """Parameter-derived stage artifact cache (index token batches,
        compiled regexes). `key` MUST include every parameter value the
        artifact depends on — the plan is shared across requests whose
        literals differ. Unhashable keys fall through to build()."""
        try:
            got = self._memo.get(key, _MISS)
        except TypeError:
            return build()
        if got is not _MISS:
            return got
        val = build()
        with self._memo_lock:
            if len(self._memo) >= self.MEMO_MAX:
                self._memo.clear()  # rare: param-churn heavy skeleton
            self._memo.setdefault(key, val)
        return val

    def decide(self, key: tuple, version: int,
               build: Callable[[], Any]) -> Any:
        """Planner decision cache (same discipline as memo: bounded,
        write-racy-but-idempotent): ONE current decision per stage
        key. `version` is the planner's re-optimization generation —
        a bumped version makes the cached decision stale, so the next
        request rebuilds against fresh evidence; everything in
        between is served from the plan, which is what makes the
        adaptive planner's steady-state cost one dict probe."""
        got = self._decisions.get(key)
        if got is not None and got[0] == version:
            return got[1]
        val = build()
        with self._memo_lock:
            if len(self._decisions) >= self.MEMO_MAX:
                self._decisions.clear()  # rare: stage-key churn
            self._decisions[key] = (version, val)
        return val

    def decisions_snapshot(self) -> list:
        """Current tier decisions (EXPLAIN / /debug surface)."""
        with self._memo_lock:
            vals = [v for _ver, v in self._decisions.values()]
        return [v.describe() for v in vals
                if hasattr(v, "describe")]

    def describe(self) -> dict:
        return {"skeleton": f"{self.skeleton_hash:016x}",
                "epoch": self.epoch,
                "mesh": str(self.mesh_key),
                "blocks": [" -> ".join(s) for s in self.stages],
                "compile_us": self.compiled_ns // 1000}


_MISS = object()


# ----------------------------------------------------------------------
# the sanctioned dynamic-jit seam (dglint DG02)
# ----------------------------------------------------------------------

_JIT_LOCK = threading.Lock()
_JIT_MAX = 512
_JIT: "OrderedDict[tuple, Any]" = OrderedDict()


def jit_stage(name: str, build: Callable[[], Callable],
              static: tuple = ()) -> Callable:
    """Return the process-wide jitted executable for `(name, static)`,
    building (ONE `jax.jit` wrap) on first use. This is the one
    sanctioned home for dynamic jit wrapping outside module level:
    everything else retraces per call (dglint DG02). jax's own trace
    cache keys on argument shapes below this, so callers bucket their
    operands (`ops/uidvec.pad_to`) to bound compiled-shape count."""
    key = (name, static)
    with _JIT_LOCK:
        fn = _JIT.get(key)
        if fn is not None:
            _JIT.move_to_end(key)
            return fn
    fn = build()
    with _JIT_LOCK:
        got = _JIT.setdefault(key, fn)
        _JIT.move_to_end(key)
        while len(_JIT) > _JIT_MAX:
            _JIT.popitem(last=False)
    return got


def jit_stage_stats() -> dict:
    with _JIT_LOCK:
        return {"executables": len(_JIT)}


def shape_bucket(n: int) -> int:
    """Power-of-two shape bucket for a uid-vector/column length — the
    cache key component that keeps per-shape executables bounded.
    Delegates to the ops-plane convention (masked sentinel tails)."""
    from dgraph_tpu.ops.uidvec import pad_to
    return pad_to(int(n))


# ----------------------------------------------------------------------
# the cache
# ----------------------------------------------------------------------


def _mesh_key(db) -> Any:
    mesh = getattr(db, "mesh", None)
    if mesh is None:
        return None
    try:
        return tuple(sorted(mesh.shape.items()))
    except Exception:
        return str(mesh)


def _var_key(variables: Optional[dict]) -> tuple:
    if not variables:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in variables.items()))


class PlanCache:
    """Parse LRU (query text + variables -> ParsedResult + skeleton)
    over a plan LRU ((skeleton, schema epoch, mesh) -> Plan). Both
    bounded; thread-safe; counters feed /debug perf profiles:

      plan_cache_hits / plan_cache_misses / plan_cache_evictions
    """

    def __init__(self, size: int = 128, parse_size: Optional[int] = None):
        self.size = max(1, int(size))
        self.parse_size = parse_size if parse_size is not None \
            else self.size * 4
        self._lock = threading.Lock()
        self._parse: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._plans: "OrderedDict[tuple, Plan]" = OrderedDict()

    # -- parse tier ----------------------------------------------------

    def parse(self, q: str, variables: Optional[dict]
              ) -> tuple[ParsedResult, tuple, int]:
        """Cached gql parse. Returns (parsed, structure, skeleton hash).
        The cached ParsedResult is SHARED across requests and threads:
        the executor treats the AST as read-only (plans and ExecNodes
        carry all runtime state)."""
        from dgraph_tpu.gql import parse as gql_parse

        key = (q, _var_key(variables))
        with self._lock:
            got = self._parse.get(key)
            if got is not None:
                self._parse.move_to_end(key)
                return got
        parsed = gql_parse(q, variables)
        struct, _params = skeleton(parsed)
        entry = (parsed, struct, hash(struct) & 0xFFFFFFFFFFFFFFFF)
        with self._lock:
            self._parse.setdefault(key, entry)
            self._parse.move_to_end(key)
            while len(self._parse) > self.parse_size:
                self._parse.popitem(last=False)
        return entry

    # -- plan tier -----------------------------------------------------

    def lookup(self, db, q: str, variables: Optional[dict],
               info: Optional[dict] = None
               ) -> tuple[ParsedResult, Plan]:
        """The engine's per-request entry: cached parse, then the
        compiled plan for (skeleton, db.schema_epoch, mesh layout).
        `info`, when given, reports the cache outcome
        ({"hit": bool}) — EXPLAIN surfaces it per request."""
        parsed, struct, skel_hash = self.parse(q, variables)
        epoch = getattr(db, "schema_epoch", 0)
        key = (skel_hash, struct, epoch, _mesh_key(db))
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans.move_to_end(key)
                metrics.inc_counter("plan_cache_hits")
                if info is not None:
                    info["hit"] = True
                return parsed, plan
        metrics.inc_counter("plan_cache_misses")
        if info is not None:
            info["hit"] = False
        plan = self._compile(parsed, struct, skel_hash, epoch, key[3])
        with self._lock:
            plan = self._plans.setdefault(key, plan)
            self._plans.move_to_end(key)
            while len(self._plans) > self.size:
                self._plans.popitem(last=False)
                metrics.inc_counter("plan_cache_evictions")
        return parsed, plan

    def _compile(self, parsed: ParsedResult, struct: tuple,
                 skel_hash: int, epoch: int, mesh_key: Any) -> Plan:
        import time as _time

        with _span("plan.compile", skeleton=f"{skel_hash:016x}",
                   blocks=len(parsed.queries)):
            t0 = _time.perf_counter_ns()
            plan = Plan(struct, skel_hash, epoch, mesh_key)
            plan.stages = [_block_stages(gq) for gq in parsed.queries]
            plan.compiled_ns = _time.perf_counter_ns() - t0
        return plan

    def invalidate(self):
        """Drop everything (tests / operator escape hatch). Routine
        schema changes do NOT call this — the epoch key already makes
        stale plans unreachable and the LRU ages them out."""
        with self._lock:
            self._parse.clear()
            self._plans.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"plans": len(self._plans),
                    "parses": len(self._parse),
                    "size": self.size}
