"""Query executor.

Semantic port of the reference's query engine (query/query.go):
  - block scheduling with variable dataflow   (query.go:2537 ProcessQuery)
  - per-node execution                        (query.go:1902 ProcessGraph)
  - filter algebra                            (query.go:2078 and/or/not)
  - order + pagination                        (query.go:2231)
  - recurse                                   (query/recurse.go)
  - shortest paths                            (query/shortest.go)
  - aggregation/math/groupby                  (query/aggregator.go, math.go,
                                               groupby.go)

TPU-first structural change: the reference launches one goroutine per
child/filter and merges with heaps; here each traversal level is ONE
batched call — device kernels (ops/graph.py) over resident tablet tiles
when the tablet is clean, numpy overlay reads when MVCC deltas are live.
Both paths share the same set-algebra semantics and are property-tested
against each other.
"""

from __future__ import annotations

import re as _re
from collections.abc import Mapping
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from dgraph_tpu.gql.ast import (
    FilterTree, Function, GraphQuery, ParsedResult, UID_VAR, VALUE_VAR,
)
from dgraph_tpu.gql.lexer import GQLError
from dgraph_tpu.models.schema import PREDICATE_TYPE
from dgraph_tpu.models.tokenizer import get_tokenizer, tokens_for
from dgraph_tpu.models.types import (
    TypeID, Val, convert, sort_key, to_json_value, type_name,
)
from dgraph_tpu.cluster.coordinator import StaleSnapshot
from dgraph_tpu.ops import setops
from dgraph_tpu.query.colvar import ColVar, make_colvar
from dgraph_tpu.query.retrigram import compile_trigram_query
from dgraph_tpu.storage.tablet import Tablet
from dgraph_tpu.utils import failpoint
from dgraph_tpu.utils.keys import token_bytes
from dgraph_tpu.utils.metrics import inc_counter, set_gauge
from dgraph_tpu.utils.tracing import span as _span

_EMPTY = np.empty(0, dtype=np.uint64)
_MISS_CV = object()  # _colview memo sentinel (None is a valid verdict)

# value types the columnar JSON fast path serializes (DATETIME via its
# isoformat string); GEO/BINARY/PASSWORD keep the general emitter
_FLAT_TYPES = {TypeID.INT, TypeID.FLOAT, TypeID.BOOL, TypeID.STRING,
               TypeID.DEFAULT, TypeID.DATETIME}

# value variable a similar_to() root/filter binds its per-uid scores
# to, readable as val(similar_to_score) (see _eval_similar_to)
SIMILAR_SCORE_VAR = "similar_to_score"


def _member_of(uids: np.ndarray, sorted_set: np.ndarray) -> np.ndarray:
    """Bool mask: which of `uids` appear in the sorted-unique set
    (the hit-mask half of _col_positions)."""
    return _col_positions(sorted_set, uids)[1]


def _col_positions(srcs: np.ndarray, uids: np.ndarray):
    """Membership of `uids` in a sorted column: (pos, hit mask)."""
    n = len(srcs)
    if n and n == len(uids) and (srcs is uids or (
            srcs[0] == uids[0] and srcs[-1] == uids[-1]
            and np.array_equal(srcs, uids))):
        # a has()-root scan over the column's own domain (the q020
        # shape): identity gather, no O(n log n) searchsorted. The
        # endpoint probes reject almost every length-equal miss
        # before the full O(n) compare (array_equal does NOT
        # short-circuit)
        return np.arange(n), np.ones(n, bool)
    pos = np.searchsorted(srcs, uids)
    pos = np.clip(pos, 0, max(n - 1, 0))
    hit = (srcs[pos] == uids) if n else \
        np.zeros(len(uids), bool)
    return pos, hit


def _flat_column_vectorized(ex, ch, name: str, colview, n: int):
    """Pure-numpy column build over a clean tablet's columnar view —
    no per-row Python at all for numeric columns; strings pay one
    list-gather of pre-encoded payloads."""
    from dgraph_tpu import native as _native

    srcs, tid, data, enc = colview
    uids = ex._flat_uids
    pos, hit = _col_positions(srcs, uids)
    present = hit.astype(np.uint8)
    if tid == TypeID.INT:
        out = np.zeros(n, np.int64)
        out[hit] = data[pos[hit]]
        return (name, _native.JCOL_INT, out, None, present)
    if tid == TypeID.FLOAT:
        out = np.zeros(n, np.float64)
        out[hit] = data[pos[hit]]
        return (name, _native.JCOL_FLOAT, out, None, present)
    if tid == TypeID.BOOL:
        out = np.zeros(n, np.uint8)
        out[hit] = data[pos[hit]]
        return (name, _native.JCOL_BOOL, out, None, present)
    # strings (STRING/DEFAULT/DATETIME pre-encoded at cache build)
    sel = [enc[j] for j in pos[hit].tolist()]
    lens = np.zeros(n, np.int64)
    lens[hit] = [len(e) for e in sel]
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    blob = b"".join(sel)
    bdata = np.frombuffer(blob, np.uint8) if blob \
        else np.zeros(1, np.uint8)
    return (name, _native.JCOL_STR, bdata, offs, present)


def _flat_column(ex, ch, name: str, ulist: list, n: int):
    """Extract one scalar child's values into a typed column for
    native.json_rows. One pass selects each uid's first untagged
    posting (exactly _select_posting(ps, [])); the conversion is then
    BULK per column — mutations convert values to the schema type at
    stage time, so a typed tablet's stored tids are uniform and the
    per-cell _typed/to_json_value dispatch the dict path pays is
    skipped. Returns None when values are not uniformly one
    JSON-scalar type (mixed DEFAULT columns bail to the dict path)."""
    from dgraph_tpu import native as _native

    colview = ex._colview(ch.tablet)
    if colview is not None:
        col = _flat_column_vectorized(ex, ch, name, colview, n)
        if col is not None:
            return col
    ex._ensure_child_values(ch)
    vmap = ch.values
    present = np.zeros(n, np.uint8)
    idxs: list[int] = []
    sels: list = []
    get = vmap.get
    for i, u in enumerate(ulist):
        ps = get(u)
        if not ps:
            continue
        p0 = ps[0]
        if not p0.lang:
            present[i] = 1
            idxs.append(i)
            sels.append(p0.value)
        else:
            for p in ps[1:]:
                if not p.lang:
                    present[i] = 1
                    idxs.append(i)
                    sels.append(p.value)
                    break
    if not sels:
        return (name, _native.JCOL_INT, np.zeros(n, np.int64), None,
                present)
    tid = sels[0].tid
    if any(v.tid is not tid for v in sels):
        return None
    stype = ch.tablet.schema.value_type
    if stype != TypeID.DEFAULT and tid != stype:
        # stored tid predates a schema change: the dict path would
        # convert per cell (_typed), so the bulk path must not skip it
        return None
    if tid == TypeID.BOOL:
        data = np.zeros(n, np.uint8)
        data[idxs] = [1 if v.value else 0 for v in sels]
        return (name, _native.JCOL_BOOL, data, None, present)
    if tid == TypeID.INT:
        data = np.zeros(n, np.int64)
        try:
            data[idxs] = [v.value for v in sels]
        except (OverflowError, TypeError, ValueError):
            return None
        return (name, _native.JCOL_INT, data, None, present)
    if tid == TypeID.FLOAT:
        data = np.zeros(n, np.float64)
        try:
            data[idxs] = [v.value for v in sels]
        except (TypeError, ValueError):
            return None
        return (name, _native.JCOL_FLOAT, data, None, present)
    if tid in (TypeID.STRING, TypeID.DEFAULT, TypeID.DATETIME):
        try:
            if tid == TypeID.DATETIME:
                from dgraph_tpu.models.types import iso8601
                enc = [iso8601(v.value).encode("utf-8")
                       for v in sels]
            else:
                enc = [v.value.encode("utf-8") for v in sels]
        except (AttributeError, ValueError):
            # non-str payload in a DEFAULT column, or a lone-surrogate
            # string utf-8 refuses (UnicodeEncodeError is a
            # ValueError): keep the exact dict path
            return None
        lens = np.zeros(n, np.int64)
        lens[idxs] = [len(e) for e in enc]
        offs = np.zeros(n + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        blob = b"".join(enc)
        data = np.frombuffer(blob, np.uint8) if blob \
            else np.zeros(1, np.uint8)
        return (name, _native.JCOL_STR, data, offs, present)
    return None


def _lang_matches(posting_lang: str, query_lang: str) -> bool:
    """eq(pred@de, v) compares only the @de posting; eq(pred, v) only
    the untagged one; @. compares any (ref types/facets + worker
    valueForLang semantics: an explicit tag selects that tag, no tag
    selects the untagged value)."""
    if query_lang == ".":
        return True
    if not query_lang:
        return posting_lang == ""

    def base(t):
        return t.split("-")[0].split("_")[0].casefold()

    return bool(posting_lang) and base(posting_lang) == base(query_lang)


def _probe_langs(spec, lang: str) -> list[str]:
    """Analyzer languages to probe for an index lookup. Only fulltext is
    language-aware; `@.` (any language) probes every analyzer since the
    matching value may have been indexed under any of them."""
    if spec.name != "fulltext":
        return [""]
    if lang == ".":
        from dgraph_tpu.models.stemmer import STEMMERS
        return list(STEMMERS)
    return [lang]

_INEQ = {"le", "lt", "ge", "gt", "between"}


def _has_sortable_index(schema) -> bool:
    """Whether a root inequality can walk this predicate's index in
    value order (ref tok.Tokenizer IsSortable) — read from the
    tokenizer registry, the one place sortability is defined."""
    from dgraph_tpu.models.tokenizer import get_tokenizer

    for t in schema.tokenizers:
        try:
            if get_tokenizer(t).sortable:
                return True
        except KeyError:
            continue
    return False

# vectorized comparators for numpy count columns
_CMP_VEC = {
    "eq": lambda a, b: a == b,
    "le": lambda a, b: a <= b,
    "lt": lambda a, b: a < b,
    "ge": lambda a, b: a >= b,
    "gt": lambda a, b: a > b,
}
_TERM_FUNCS = {"anyofterms", "allofterms", "anyoftext", "alloftext"}


def _np_sorted(uids) -> np.ndarray:
    # np.unique = one C sort + adjacent-dedup; the python
    # sorted(set(...)) this replaces sat on every uid() root and var
    # union
    if isinstance(uids, np.ndarray):
        return np.unique(uids.astype(np.uint64, copy=False))
    arr = np.fromiter((int(u) for u in uids), dtype=np.uint64)
    return np.unique(arr)


def _var_domain(vmap) -> np.ndarray:
    """The sorted uid set a value var is defined on — columnar vars
    answer from their uid array without materializing Vals."""
    if isinstance(vmap, ColVar):
        return vmap.uids
    return _np_sorted(vmap.keys())


# pairwise set algebra now lives in ops/setops (one implementation for
# the executor, the k-way folds, and the microbench); inputs are sorted
# unique uid vectors (the repo-wide invariant)
_intersect = setops.intersect_pair
_union = setops.union_pair
_difference = setops.difference


@dataclass
class ExecNode:
    """Runtime state for one query node (the reference's SubGraph,
    query/query.go:222)."""

    gq: GraphQuery
    tablet: Optional[Tablet] = None
    reverse: bool = False
    src: np.ndarray = field(default_factory=lambda: _EMPTY)
    dest: np.ndarray = field(default_factory=lambda: _EMPTY)
    values: dict[int, list] = field(default_factory=dict)  # uid->Postings
    counts: dict[int, int] = field(default_factory=dict)
    children: list["ExecNode"] = field(default_factory=list)
    # recurse support: per-level (parent -> [children]) maps, and the
    # per-level resolved child list (expand() re-resolves per level)
    recurse_levels: list[dict[int, np.ndarray]] = field(default_factory=list)
    recurse_preds: list[list] = field(default_factory=list)
    emit_order: Optional[list[int]] = None  # path-var traversal order
    path_nodes: list[list[int]] = field(default_factory=list)  # shortest
    path_weights: list[float] = field(default_factory=list)
    block_idx: int = -1  # position in parsed.queries (plan memo key)
    # compiled flat blocks defer scalar-child value gathering to the
    # emitter (the columnar JSON emitter reads the column view
    # directly); _ensure_child_values materializes on demand for
    # every other consumer
    lazy_cols: bool = False
    # columnar emission fast path: uid -> ready json value for flat
    # scalar children (populated instead of `values` when eligible)
    col_vals: Optional[dict] = None
    # EXPLAIN ANALYZE observability: resolved root-set size BEFORE
    # filter/pagination (-1 = not measured, e.g. the device
    # count-at-root fast path never materializes the set)
    root_rows: int = -1
    # whole-plan fusion attribution (query/fusion.py): "fused" when
    # the block's filter+order+page chain ran as ONE device
    # executable, "staged:<reason>" when a structurally-eligible
    # block fell back at runtime, "" when fusion never applied
    fused: str = ""


class Executor:
    # dglint: guarded-by=*:single-thread (one Executor per request,
    # confined to the thread running that query; cross-request state
    # lives in GraphDB / Plan / AdaptivePlanner, never here)
    def __init__(self, db, read_ts: int, ctx=None, plan=None):
        self.db = db
        self.read_ts = read_ts
        # compiled plan (query/plan.py) for this request's skeleton,
        # or None on the interpreted path (plan cache disabled, upsert
        # queries). Carries parameter-memoized stage artifacts and the
        # skeleton identity; the AST stays the source of truth for
        # parameters, so a shared plan can never leak one request's
        # literals into another's
        self.plan = plan
        # RequestContext (utils/reqctx.py): deadline + cancellation,
        # consulted at block/level boundaries so deep traversals abort
        # mid-flight (the reference checks ctx.Err() in ProcessGraph)
        self.ctx = ctx
        self.parsed: Optional[ParsedResult] = None
        self.uid_vars: dict[str, np.ndarray] = {}
        self.value_vars: dict[str, dict[int, Val]] = {}
        self._path_var_order: dict[str, list[int]] = {}
        # score-descending uid order of the current block's similar_to
        # root, set by _eval_similar_to and consumed at pagination
        self._similar_order: Optional[list[int]] = None
        # per-request column-view memo (one snapshot, one verdict)
        self._cv_memo: dict = {}
        # adaptive-planner plumbing (query/planner.py): the tier
        # decisions this request consulted (EXPLAIN surfaces them) and
        # the tier the index machinery ACTUALLY served from (a decided
        # tier can still fall back — dirty tablet, missing export —
        # and cost attribution must follow the serving tier).
        # _adaptive gates every planner touch: static engines and the
        # interpreted path pay literally nothing; _dec_memo keeps a
        # request's REPEATED stage evaluations (a filter tree probing
        # one predicate dozens of times) at one est-build + consult
        self._adaptive = plan is not None \
            and getattr(db, "planner_impl", None) is not None
        self.tier_decisions: list = []
        self._dec_memo: dict = {}
        self._served_tier: Optional[str] = None
        # per-request vector-tier decisions (one per similar_to eval):
        # which tier actually scored (host/device exact, two_stage,
        # quantized, sharded) plus the quantized budget (nprobe,
        # rerank) — EXPLAIN surfaces them as tiers.vector
        self.vector_decisions: list[dict] = []

    def _checkpoint(self, where: str):
        """Block/level boundary: the `executor.level` failpoint (chaos
        tests slow traversals down here) and the request context's
        deadline/cancellation check."""
        failpoint.fire("executor.level")
        if self.ctx is not None:
            self.ctx.check(where)

    # ------------------------------------------------------------------
    # block scheduling (ref query.go:2596 dependency loop)
    # ------------------------------------------------------------------

    def run(self, parsed: ParsedResult) -> dict[str, Any]:
        return self.emit(self.execute(parsed))

    def execute(self, parsed: ParsedResult
                ) -> list[tuple[GraphQuery, ExecNode]]:
        """Process every block (var-dependency scheduled); emission is
        a separate phase so the engine can time it (Latency.encoding_ns
        — the reference ranks ToJson a top-5 hot loop) and pick the
        columnar fast path."""
        self.parsed = parsed
        pf = getattr(self.db, "prefetcher", None)
        if pf is not None:
            # announce the request's predicate working set before the
            # first block runs: cold-store blobs decode on the
            # prefetch pool while earlier blocks compute, and
            # TabletMap.get consumes them on arrival (the decode-stall
            # overlap BENCH_500M measures)
            from dgraph_tpu.query.fusion import collect_preds
            pf.schedule(self.db, collect_preds(parsed))
        if self.plan is None:
            self._check_similar_score_ambiguity(parsed)
        else:
            # structure-only validation: ran once at plan compile (a
            # rejected combination never produces a cached plan)
            self.plan.memo(("similar_check",),
                           lambda: self._check_similar_score_ambiguity(
                               parsed))
        blocks = list(parsed.queries)
        done: list[tuple[GraphQuery, ExecNode]] = []
        pending = list(enumerate(blocks))
        for _ in range(len(blocks) + 1):
            if not pending:
                break
            still = []
            for i, gq in pending:
                needs, own = self._block_vars_of(i, gq)
                if all(self._var_defined(n) or n in own for n in needs):
                    self._checkpoint(f"block {gq.alias or gq.attr}")
                    done.append((gq, self._run_block(gq, i)))
                else:
                    still.append((i, gq))
            if len(still) == len(pending):
                missing = sorted({n for i, gq in still
                                  for n in self._block_vars_of(i, gq)[0]
                                  if not self._var_defined(n)})
                raise GQLError(
                    f"circular or undefined variable dependency: {missing}")
            pending = still
        return done

    def _block_vars_of(self, i: int, gq: GraphQuery
                       ) -> tuple[tuple, frozenset]:
        """(consumed var names, provided var names) for block `i` —
        pure structure, so a warm plan binds it once per skeleton
        instead of re-walking the AST per request."""
        def build():
            return (tuple(vc.name for vc in self._all_needs(gq)),
                    frozenset(self._provides(gq)))
        if self.plan is not None:
            return self.plan.memo(("blockvars", i), build)
        return build()

    def _check_similar_score_ambiguity(self, parsed: ParsedResult):
        """`similar_to_score` is ONE binding per request; with several
        similar_to calls the last evaluation would clobber the others
        and any val(similar_to_score) reader would silently get the
        wrong call's scores. Reject the combination up front."""
        count = 0
        reads = False

        def walk_filter(ft):
            nonlocal count, reads
            if ft is None:
                return
            if ft.func is not None:
                if ft.func.name == "similar_to":
                    count += 1
                if any(vc.name == SIMILAR_SCORE_VAR
                       for vc in ft.func.needs_var):
                    reads = True
            for c in ft.children:
                walk_filter(c)

        def walk(gq):
            nonlocal count, reads
            if gq.func is not None:
                if gq.func.name == "similar_to":
                    count += 1
                if any(vc.name == SIMILAR_SCORE_VAR
                       for vc in gq.func.needs_var):
                    reads = True
            if any(vc.name == SIMILAR_SCORE_VAR
                   for vc in gq.needs_var):
                reads = True
            if any(o.attr == f"val({SIMILAR_SCORE_VAR})"
                   for o in gq.order):
                reads = True
            walk_filter(gq.filter)
            for c in gq.children:
                walk(c)

        for q in parsed.queries:
            walk(q)
        if count > 1 and reads:
            raise GQLError(
                f"val({SIMILAR_SCORE_VAR}) is ambiguous with "
                f"{count} similar_to calls in one request; split the "
                "query so each score reader has exactly one "
                "similar_to")

    def emit(self, done) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for gq, node in done:
            if gq.alias in ("var", "shortest") and gq.attr != "shortest":
                continue
            if gq.attr == "shortest":
                paths = self._emit_paths(node)
                if paths:
                    out["_path_"] = paths
                continue
            val = self._emit_block(node)
            if gq.is_groupby and not val:
                # empty root groupby omits its block key entirely
                # (ref query0:TestGroupByRootEmpty -> data {})
                continue
            out[gq.alias] = val
        return out

    def emit_json(self, done) -> str:
        """Emit the data payload as a JSON string: flat uid+scalar
        blocks go through the native columnar row serializer
        (native.json_rows — ref query/outputnode.go fastJsonNode);
        everything else falls back to dict building + json.dumps.
        Output is byte-identical to json.dumps(self.emit(done)) with
        compact separators."""
        import json as _json

        payloads: dict[str, str] = {}
        for gq, node in done:
            if gq.alias in ("var", "shortest") and gq.attr != "shortest":
                continue
            if gq.attr == "shortest":
                payloads["_path_"] = _json.dumps(
                    self._emit_paths(node), separators=(",", ":"))
                continue
            fast = self._emit_block_flat_json(node)
            if fast is None:
                val = self._emit_block(node)
                if gq.is_groupby and not val:
                    continue  # empty root groupby omits its key
                fast = _json.dumps(val, separators=(",", ":"))
            payloads[gq.alias] = fast
        return "{" + ",".join(
            _json.dumps(k) + ":" + v for k, v in payloads.items()) + "}"

    def _emit_block_flat_json(self, node: ExecNode) -> Optional[str]:
        """Columnar fast path for the overwhelmingly common result
        shape: a uid block whose children are plain scalar predicates
        (plus optional `uid`). Returns the serialized JSON array, or
        None when any feature needs the general emitter."""
        from dgraph_tpu import native as _native

        gq = node.gq
        if not node.children or node.emit_order is not None:
            # emit_order (path vars, similar_to score order) reorders
            # rows; the columnar emitter walks dest uid-ascending
            return None

        def eligible() -> Optional[list]:
            """Spec derivation is structure+schema-pure, so a warm
            plan binds it once per (skeleton, schema epoch): either
            None (this block shape keeps the general emitter — a
            predicate created on the fly after compile re-decides at
            the next epoch-keyed plan, costing only the fast path) or
            the (child index | uid marker, name) column list."""
            if (gq.recurse is not None or gq.is_groupby or gq.normalize
                    or gq.cascade or gq.ignore_reflex):
                return None
            sp = []  # (child idx, name); idx None marks the uid col
            for ci, ch in enumerate(node.children):
                cgq = ch.gq
                name = cgq.alias or cgq.attr
                if not all(32 <= ord(c) < 127 and c not in '"\\'
                           for c in name):
                    # the native emitter writes keys verbatim; names
                    # that need escaping (quotes, non-ASCII — legal in
                    # <iri> attrs and unicode identifiers) keep the
                    # dict path
                    return None
                if cgq.attr == "uid" and not cgq.is_count:
                    sp.append((None, "uid"))
                    continue
                tab = ch.tablet
                if (tab is None or cgq.is_count or cgq.agg_func
                        or cgq.attr == "math"
                        or cgq.attr.startswith("val(")
                        or cgq.langs or cgq.facets is not None
                        or cgq.facet_var or cgq.cascade or cgq.children
                        or ch.reverse or tab.schema.list_
                        or tab.schema.value_type not in _FLAT_TYPES):
                    return None
                sp.append((ci, name))
            return sp or None

        if self.plan is not None and node.block_idx >= 0 \
                and not any(c.expand for c in gq.children):
            # expand() resolves children from DATA (the src uids'
            # types), so its child list is not skeleton-stable: those
            # blocks re-derive per request
            idx_specs = self.plan.memo(
                ("flatspec", node.block_idx), eligible)
        else:
            idx_specs = eligible()
        if idx_specs is None:
            return None
        uids = node.dest
        n = len(uids)
        specs = [(None if ci is None else node.children[ci], name)
                 for ci, name in idx_specs]
        cols = []
        self._flat_uids = uids.astype(np.uint64)
        ulist = uids.tolist()
        for ch, name in specs:
            if ch is None:
                cols.append((name, _native.JCOL_UID,
                             uids.astype(np.uint64), None, None))
                continue
            col = _flat_column(self, ch, name, ulist, n)
            if col is None:
                return None
            cols.append(col)
        out = _native.json_rows(n, cols)
        if out is None:
            return None
        inc_counter("query_flat_json_total")
        return out.decode("utf-8")

    def _all_needs(self, gq: GraphQuery):
        yield from gq.needs_var
        if gq.func:
            yield from gq.func.needs_var
        if gq.filter:
            yield from self._filter_needs(gq.filter)
        for c in gq.children:
            yield from self._all_needs(c)

    def _filter_needs(self, ft: FilterTree):
        if ft.func:
            yield from ft.func.needs_var
        for c in ft.children:
            yield from self._filter_needs(c)

    def _var_defined(self, name: str) -> bool:
        return name in self.uid_vars or name in self.value_vars

    def _provides(self, gq: GraphQuery):
        """Vars a block's own subtree binds (uid vars, value vars,
        facet vars): consumers INSIDE the block must not make the
        scheduler wait for another block to provide them (ref
        query0_test.go level-based facet var tests: `path @facets(L1
        as weight) sumw: sum(val(L1))` in one block)."""
        if gq.var:
            yield gq.var
        if (gq.func is not None and gq.func.name == "similar_to") \
                or (gq.filter is not None
                    and self._filter_has_similar(gq.filter)):
            # running the block binds the score var — consumers inside
            # the block (or later blocks, via the retry rounds) see it
            yield SIMILAR_SCORE_VAR
        for varname in gq.facet_var.values():
            yield varname
        for c in gq.children:
            yield from self._provides(c)

    def _filter_has_similar(self, ft: FilterTree) -> bool:
        if ft.func is not None and ft.func.name == "similar_to":
            return True
        return any(self._filter_has_similar(c) for c in ft.children)

    # ------------------------------------------------------------------
    # one block
    # ------------------------------------------------------------------

    def _run_block(self, gq: GraphQuery, i: int = -1) -> ExecNode:
        with _span("block", alias=gq.alias or gq.attr):
            return self._run_block_inner(gq, i)

    def _run_block_inner(self, gq: GraphQuery, i: int = -1) -> ExecNode:
        self._block_root = gq
        self._block_vars = self._block_vars_of(i, gq)[1] \
            if self.plan is not None and i >= 0 \
            else set(self._provides(gq))
        # var-only blocks never reach emission, so their scalar
        # children may bind vars columnar-fast and skip posting walks
        self._block_emits = gq.alias != "var"
        node = ExecNode(gq, block_idx=i)
        if gq.attr == "shortest":
            self._run_shortest(node)
            return node
        self._similar_order = None
        root = self._device_root_count_page(gq)
        if root is None:
            fspec = self._fused_spec(gq, i)
            root = self._root_uids(gq)
            node.root_rows = int(len(root))
            paged = self._fused_block_page(gq, fspec, root, node) \
                if fspec is not None else None
            if paged is not None:
                root = paged
            else:
                if gq.filter is not None:
                    root = self._eval_filter(gq.filter, root)
                if self._similar_order is not None and not gq.order:
                    root = self._similar_paginate(gq, root, node)
                else:
                    root = self._order_paginate(gq, root)
        if not gq.order and gq.func is not None \
                and gq.func.name == "uid" and len(gq.func.needs_var) == 1:
            ordered = self._path_var_order.get(
                gq.func.needs_var[0].name)
            if ordered:
                # PATH vars emit in traversal order (ref query3_test.go
                # TestShortestPathRev) — but only the EMISSION reorders;
                # node.dest stays uid-sorted (searchsorted invariant of
                # every columnar consumer)
                inset = set(root.tolist())
                node.emit_order = [u for u in ordered if u in inset]
        node.dest = root
        if gq.var:
            self.uid_vars[gq.var] = root
        if gq.recurse is not None:
            self._run_recurse(node)
        elif gq.is_groupby:
            self._bind_groupby_vars(gq, root)
        else:
            if self.plan is not None and i >= 0 and self.plan.memo(
                    ("flatblock", i),
                    lambda: self._flat_block_eligible(i, gq)):
                # compiled dispatch: the plan proved (per skeleton +
                # schema epoch) this block is a var-free flat scalar
                # shape, so the per-child interpreter — dependency
                # scheduling, internal/uid-edge/facet branching — is
                # skipped wholesale
                self._expand_children_flat(node, gq.children, root)
            else:
                self._expand_children(node, gq.children, root)
            if gq.cascade and self._block_vars:
                # @cascade constrains the VARS the block binds, not
                # just its output rows (ref query3:TestUseVarsCascade:
                # `@cascade { L as friend { friend } }` binds L to
                # friends that themselves have friends). Var-free
                # cascade blocks skip this — emission applies their
                # cascade.
                self._cascade_rebind_vars(node)
        return node

    def _similar_paginate(self, gq: GraphQuery, root: np.ndarray,
                          node: ExecNode) -> np.ndarray:
        """similar_to roots emit nearest-first (score-descending, ties
        by uid — the order Dgraph's similar_to returns); pagination
        windows therefore cut in SCORE space. Only the emission
        reorders — node.dest stays uid-sorted, the searchsorted
        invariant of every columnar consumer (same split as path
        vars)."""
        inset = set(root.tolist())
        ordered = [u for u in self._similar_order if u in inset]
        if gq.after:
            try:
                ordered = ordered[ordered.index(gq.after) + 1:]
            except ValueError:
                pass
        if gq.offset:
            ordered = ordered[gq.offset:]
        if gq.first is not None:
            ordered = ordered[:gq.first] if gq.first >= 0 \
                else ordered[gq.first:]
        node.emit_order = ordered
        return _np_sorted(ordered)

    def _root_uids(self, gq: GraphQuery) -> np.ndarray:
        parts: list[np.ndarray] = []
        if gq.uids:
            parts.append(_np_sorted(gq.uids))
        func_args = {vc.name for vc in gq.func.needs_var} \
            if gq.func is not None else set()
        for vc in gq.needs_var:
            if vc.typ != VALUE_VAR and vc.name in self.uid_vars:
                parts.append(self.uid_vars[vc.name])
            elif vc.name in func_args and gq.func.name == "uid" \
                    and vc.name in self.value_vars \
                    and vc.name not in self.uid_vars:
                # uid(valueVar) roots at the uids the var is defined on
                # (ref query/query.go UidsFromVar)
                parts.append(_var_domain(self.value_vars[vc.name]))
        if gq.func is not None and gq.func.name != "uid":
            parts.append(self._eval_func(gq.func, None))
        return self._union_many(parts)

    # ------------------------------------------------------------------
    # root/filter functions (ref worker/task.go:1558 parseSrcFn +
    # processTask dispatch)
    # ------------------------------------------------------------------

    def _tablet(self, attr: str) -> Optional[Tablet]:
        tab = self.db.tablets.get(attr)
        if tab is not None:
            # stats plane: hottest-tablet signal (getattr: federated
            # RemoteTablet proxies have no stats fields)
            tab.touches = getattr(tab, "touches", 0) + 1
        if tab is not None \
                and getattr(tab, "base_ts", 0) > self.read_ts:
            # commits newer than this read's ts were already folded
            # into base state — the exact snapshot no longer exists.
            # Refuse (retryable) instead of serving silently-newer
            # data: the split-bank invariant broke exactly here when a
            # pinned cross-group read raced the rollup.
            raise StaleSnapshot(
                f"read at ts {self.read_ts} is below tablet "
                f"{attr!r}'s rollup watermark {tab.base_ts}; "
                f"retry at a fresh timestamp")
        return tab

    # -- columnar scan tier plumbing -----------------------------------

    def _columnar_on(self) -> bool:
        """db.prefer_columnar=False pins reads to the exact posting
        path — the differential parity suite's oracle."""
        return getattr(self.db, "prefer_columnar", True)

    def _colview(self, tab, lang: str | None = None):
        """THE chokepoint every columnar value read goes through: the
        tablet's cached column view (None on dirty/historical/mixed
        tablets or with the tier disabled), budgeted against the tile
        LRU and counted so BENCH_QUERIES can report tier routing.
        Memoized per request — one snapshot, one verdict — so a block
        that reads a column at eval AND emit time resolves, budgets
        and counts it once."""
        key = (id(tab), lang)
        got = self._cv_memo.get(key, _MISS_CV)
        if got is not _MISS_CV:
            return got
        cv = self._colview_inner(tab, lang)
        self._cv_memo[key] = cv
        return cv

    def _colview_inner(self, tab, lang: str | None = None):
        if not self._columnar_on() \
                or not hasattr(tab, "value_columns"):
            return None
        cv = tab.lang_value_columns(self.read_ts, lang) if lang \
            else tab.value_columns(self.read_ts)
        if cv is None:
            inc_counter("query_postings_fallback_total")
            return None
        from dgraph_tpu.engine.device_cache import host_column_tile
        host_column_tile(
            self.db, tab,
            f"_val_cols_lang@{lang}" if lang else "_val_cols", cv)
        inc_counter("query_colvar_hits_total")
        return cv

    def _index_sets(self, tab, toks: list[bytes],
                    tier: Optional[str] = None) -> list[np.ndarray]:
        """Posting sets for a token batch: one CSR probe per token on
        clean tablets (contiguous slices of one cached buffer, no
        per-token overlay generator), the exact index_uids walk
        otherwise. `tier` is the planner's pick: "postings" pins the
        exact walk; None/"columnar"/"compressed" keep the CSR."""
        csr = tab.token_index_csr(self.read_ts) \
            if tier != "postings" and self._columnar_on() \
            and hasattr(tab, "token_index_csr") \
            else None
        if csr is None:
            self._served_tier = "postings"
            return [tab.index_uids(t, self.read_ts) for t in toks]
        from dgraph_tpu.engine.device_cache import host_column_tile
        host_column_tile(self.db, tab, "_tok_csr", csr)
        inc_counter("query_index_csr_probe_total")
        self._served_tier = "columnar"
        return [csr.probe(t) for t in toks]

    # -- compressed posting tier ---------------------------------------

    def _compressed_on(self) -> bool:
        """The compressed tier rides the columnar tier's invalidation
        contract, so prefer_columnar=False (the parity oracle) pins
        BOTH off."""
        return self._columnar_on() \
            and getattr(self.db, "prefer_compressed", True)

    def _index_packs(self, tab):
        """The tablet's compressed token-index export, budgeted in the
        tile LRU by COMPRESSED size — None on dirty/historical
        tablets, unindexed predicates, or with the tier off (callers
        fall through to the dense CSR / exact index_uids chain)."""
        if not self._compressed_on() \
                or not hasattr(tab, "token_index_packs"):
            return None
        tix = tab.token_index_packs(self.read_ts)
        if tix is None:
            inc_counter("query_compressed_fallback_total")
            return None
        from dgraph_tpu.engine.device_cache import host_column_tile
        host_column_tile(self.db, tab, "_tok_packs", tix)
        return tix

    def _pack_scratch(self):
        sc = getattr(self.db, "decode_scratch", None)
        if sc is not None:
            set_gauge("codec_scratch_bytes", sc.high_water)
        return sc

    def _pack_device(self) -> bool:
        """Whether pack algebra may batch all-bitmap blocks into one
        device word-AND dispatch (setops.bitmap_and_device)."""
        return self.db.prefer_device and (
            self.db.device_min_edges <= 1
            or self.db.device_is_accelerator())

    # -- adaptive tier routing (query/planner.py) ----------------------

    def _tier_decision(self, stage: str, pred: str, est: dict,
                       avail: tuple, rows_by_tier=None):
        """Consult the adaptive planner for this stage's tier (None on
        the static/interpreted path — callers keep the flag
        heuristics). The decision is cached on the compiled plan;
        every consult lands in tier_decisions for EXPLAIN."""
        pl = getattr(self.db, "planner_impl", None)
        if pl is None or self.plan is None or not avail:
            return None
        dec = pl.choose(self.plan, stage, pred, est, avail,
                        rows_by_tier)
        if dec is not None:
            self.tier_decisions.append(dec)
        return dec

    def _record_outcome(self, dec, actual_rows: int) -> None:
        pl = getattr(self.db, "planner_impl", None)
        if pl is not None and dec is not None:
            pl.record_outcome(dec, actual_rows)

    def _routed(self, mkey: tuple, build):
        """Three-layer decision lookup: request memo -> the plan's
        routing cache (validated against the planner's
        re-optimization generation with one dict probe) -> full
        estimate + consult. The warm steady state — the plan cache
        serving every stage's decision — costs two dict reads per
        request per stage, which is what keeps the whole planner
        under the 1%% overhead gate on real (multi-stage) queries."""
        dec = self._dec_memo.get(mkey, _MISS_CV)
        if dec is not _MISS_CV:
            return dec
        pl = self.db.planner_impl
        dec = self.plan._routing.get(mkey)
        if dec is not None and pl.version(
                dec.skeleton, dec.stage, dec.pred) == dec.version:
            pl._warm_serves += 1
            self.tier_decisions.append(dec)
        else:
            dec = build()
            if dec is not None:
                routing = self.plan._routing
                if len(routing) >= self.plan.MEMO_MAX:
                    routing.clear()  # rare: stage-key churn
                routing[mkey] = dec
        self._dec_memo[mkey] = dec
        return dec

    def _index_tiers(self, tab) -> tuple:
        """Tiers the prefer_* overrides allow for a token-index stage
        on this tablet (availability, not choice — the planner picks
        within these)."""
        avail = ["postings"]
        if self._columnar_on() and hasattr(tab, "token_index_csr"):
            avail.append("columnar")
        if self._compressed_on() and hasattr(tab, "token_index_packs"):
            avail.append("compressed")
        return tuple(avail)

    def _tabstats(self, tab) -> Optional[dict]:
        """Cached BASE tablet statistics, or None for stat-less
        proxies (same guard as explain's estimator). The per-base_ts
        aggregate is computed once per rollup and shared with
        /debug/stats; the steady-state read on this query hot path is
        one tuple compare (tabstats.tablet_base_stats) — NOT the full
        tablet_stats(), whose live residency walk costs ~10 µs per
        call."""
        if tab is None or not hasattr(tab, "base_ts"):
            return None
        from dgraph_tpu.storage.tabstats import tablet_base_stats
        return tablet_base_stats(tab)

    def _dirty_slack(self, tab) -> int:
        from dgraph_tpu.storage.tabstats import dirty_ops
        return dirty_ops(tab)

    def _token_est(self, tab, n_tokens: int) -> dict:
        """EXPLAIN-shaped row estimate for an n-token index probe:
        per-token quantile from the tabstats posting-length histogram
        (the satellite basis), capped at keys + dirty slack. The
        quantile is cached on the tablet per base_ts — this sits on
        the eq/terms hot path."""
        st = self._tabstats(tab)
        if st is None:
            return {"estRows": -1, "estRowsMax": -1,
                    "basis": "unknown"}
        cached = getattr(tab, "_tokq_cache", None)
        if cached is not None and cached[0] == tab.base_ts:
            per = cached[1]
        else:
            from dgraph_tpu.query.planner import token_quantile
            per = token_quantile(st["tokenIndex"])
            tab._tokq_cache = (tab.base_ts, per)
        cap = st["nSrc"] + self._dirty_slack(tab)
        return {"estRows": min(int(round(n_tokens * per)), cap),
                "estRowsMax": cap, "basis": "stats",
                "source": "token-length histogram"}

    def _index_union(self, tab, toks: list[bytes],
                     tier: Optional[str] = None) -> np.ndarray:
        """k-token index union, staying on compressed blocks where
        they exist: the hybrid index hands back zero-copy dense
        slices for its small-list tail and packs for the long lists
        (setops.union_mixed merges the compressed side first).
        `tier` (the planner's pick) caps the ladder: "columnar" skips
        the packs, "postings" pins the exact walk; fallbacks on
        missing exports still cascade."""
        tix = self._index_packs(tab) \
            if tier in (None, "compressed") else None
        if tix is not None:
            ops = [o for o in (tix.probe_operand(t) for t in toks)
                   if o is not None]
            inc_counter("query_compressed_setops_total")
            self._served_tier = "compressed"
            return setops.union_mixed(ops,
                                      scratch=self._pack_scratch())
        return self._union_many(self._index_sets(tab, toks, tier))

    def _index_intersect(self, tab, toks: list[bytes],
                         tier: Optional[str] = None) -> np.ndarray:
        """k-token index intersection with block-descriptor skipping:
        dense operands intersect smallest-first, the survivor vector
        probes each pack in compressed form — blocks with no key
        overlap are NEVER decoded (all-pack inputs additionally batch
        bitmap blocks into one word-AND, device-routed when worth
        it). `tier` as in _index_union."""
        tix = self._index_packs(tab) \
            if tier in (None, "compressed") else None
        if tix is not None:
            ops = []
            for t in toks:
                o = tix.probe_operand(t)
                if o is None:
                    return _EMPTY  # a missing token empties the AND
                ops.append(o)
            inc_counter("query_compressed_setops_total")
            self._served_tier = "compressed"
            return setops.intersect_mixed(
                ops, scratch=self._pack_scratch(),
                device=self._pack_device())
        return self._intersect_many(self._index_sets(tab, toks, tier))

    def _trigram_tier(self, tab, kind: str, n_tokens: int):
        """Tier decision for a trigram-index probe batch (regexp /
        match) — stage "setops" like the other token set ops,
        memoized per request."""
        if not self._adaptive:
            return None
        return self._routed(
            ("setops", tab.pred, kind, n_tokens),
            lambda: self._tier_decision(
                "setops", tab.pred, self._token_est(tab, n_tokens),
                self._index_tiers(tab)))

    def _index_count_filter(self, tab, toks: list[bytes], need: int,
                            tier: Optional[str] = None) -> np.ndarray:
        """Uids in >= need of the tokens' posting lists (the match()
        q-gram bound): candidates come from the smallest operands
        (pigeonhole), the long packed lists answer by block-skipping
        membership probes without decoding. `tier` as in
        _index_union."""
        tix = self._index_packs(tab) \
            if tier in (None, "compressed") else None
        if tix is not None:
            ops = [o for o in (tix.probe_operand(t) for t in toks)
                   if o is not None]
            inc_counter("query_compressed_setops_total")
            self._served_tier = "compressed"
            return setops.count_filter_mixed(
                ops, need, scratch=self._pack_scratch())
        buckets = [b for b in self._index_sets(tab, toks, tier)
                   if len(b)]
        if not buckets:
            return _EMPTY
        from dgraph_tpu import native as _nat
        got = _nat.merge_count(buckets, need) if _nat.available() \
            else None
        return got if got is not None \
            else setops.count_filter(buckets, need)

    # np.unique cost per element of a k-way union — the fixed side of
    # the device-tier choice is the measured dispatch RTT
    _HOST_PER_SETOP_EL = 2e-8
    _DEVICE_RATIO_SETOP = 0.9  # device sort ≈ host sort at these sizes

    def _union_many(self, parts: list[np.ndarray]) -> np.ndarray:
        """k-way union; one device co-sort dispatch when the host cost
        clears the RTT (uidvec.merge_many), else concat + one sort."""
        if len(parts) >= 4 and self.db.prefer_device:
            total = sum(len(p) for p in parts)
            if total >= (1 << 17) and self._device_worth(
                    total * self._HOST_PER_SETOP_EL,
                    device_ratio=self._DEVICE_RATIO_SETOP):
                got = setops.union_many_device(parts)
                if got is not None:
                    inc_counter("query_device_setops_total")
                    return got
        return setops.union_many(parts)

    def _intersect_many(self, parts: list[np.ndarray]) -> np.ndarray:
        """k-way intersection, smallest set first. Under the adaptive
        planner the per-pair gallop-vs-merge pivot is density-derived
        (planner.gallop_ratio) instead of the fixed 16x skew."""
        if len(parts) >= 4 and self.db.prefer_device:
            total = sum(len(p) for p in parts)
            if total >= (1 << 17) and self._device_worth(
                    total * self._HOST_PER_SETOP_EL,
                    device_ratio=self._DEVICE_RATIO_SETOP):
                got = setops.intersect_many_device(parts)
                if got is not None:
                    inc_counter("query_device_setops_total")
                    return got
        pl = getattr(self.db, "planner_impl", None)
        if pl is not None and len(parts) >= 2:
            lens = [len(p) for p in parts]
            # per-fold schedule (>=3 parts: the accumulator-density
            # model has something to decay over), else the flat
            # density-derived ratio; both only pick strategies, the
            # intersection bytes are identical
            sched = pl.intersect_schedule(lens)
            if sched is not None:
                return setops.intersect_many(parts, gallop_ratio=sched)
            return setops.intersect_many(
                parts, gallop_ratio=pl.gallop_ratio(min(lens),
                                                    max(lens)))
        return setops.intersect_many(parts)

    def _eval_func(self, fn: Function, candidates: Optional[np.ndarray]
                   ) -> np.ndarray:
        name = fn.name
        if fn.attr == "uid" and name != "uid":
            # `uid` is a result field, never a predicate argument
            # (ref query1:TestUidAttr: 'Argument cannot be "uid"')
            raise GQLError('Argument cannot be "uid"')
        if name == "uid":
            parts = [_np_sorted(fn.uids)]
            for vc in fn.needs_var:
                if vc.name in self.uid_vars:
                    parts.append(self.uid_vars[vc.name])
                elif vc.name in self.value_vars:
                    # uid(valueVar): the uids the var is defined on
                    # (ref query/query.go UidsFromVar / outputnode uses)
                    parts.append(
                        _var_domain(self.value_vars[vc.name]))
            uids = self._union_many(parts)
            return uids if candidates is None \
                else _intersect(candidates, uids)
        if name == "type":
            return self._eval_eq_tokens(
                self._tablet(PREDICATE_TYPE),
                [Val(TypeID.STRING, fn.args[0].value)], candidates)
        if name == "has":
            if fn.attr.startswith("~"):
                # has(~pred): uids with at least one INCOMING edge
                # (ref worker/task.go reverse attr handling)
                tab = self._tablet(fn.attr[1:])
                if tab is None:
                    return _EMPTY
                if not tab.schema.reverse:
                    raise GQLError(
                        f"has(~{fn.attr[1:]}) needs @reverse on "
                        f"{fn.attr[1:]!r}")
                alluids = tab.dst_uids(self.read_ts)
            else:
                tab = self._tablet(fn.attr)
                if tab is None:
                    return _EMPTY
                alluids = tab.src_uids(self.read_ts)
            return alluids if candidates is None \
                else _intersect(candidates, alluids)
        if fn.is_count:
            return self._eval_count_fn(fn, candidates)
        if fn.is_value_var or fn.is_len_var:
            return self._eval_var_fn(fn, candidates)
        if name == "eq":
            tab = self._tablet(fn.attr)
            eqps = tab.schema if tab is not None \
                else self.db.schema.get(fn.attr)
            if candidates is None and eqps is not None \
                    and not eqps.indexed:
                # root eq needs an index to look tokens up in — a
                # schema property, data or not (ref query1:
                # TestNameNotIndexed; filters compare values per
                # candidate uid and stay legal without one)
                raise GQLError(
                    f"predicate {fn.attr!r} is not indexed")
            if fn.needs_var and not fn.is_value_var:
                # eq(pred, val(v)): each uid compares against ITS OWN
                # val(v) (ref query.go valueVarAggregation semantics)
                return self._eval_eq_own_val(tab, fn, candidates)
            vals = [Val(TypeID.DEFAULT, a.value) for a in fn.args]
            return self._eval_eq_tokens(tab, vals, candidates,
                                        fn.lang or "")
        if name in _INEQ:
            return self._eval_ineq(fn, candidates)
        if name in _TERM_FUNCS:
            return self._eval_terms(fn, candidates)
        if name in ("anyof", "allof"):
            return self._eval_anyof(fn, candidates)
        if name == "regexp":
            return self._eval_regexp(fn, candidates)
        if name == "match":
            return self._eval_match(fn, candidates)
        if name == "uid_in":
            return self._eval_uid_in(fn, candidates)
        if name == "checkpwd":
            return self._eval_checkpwd(fn, candidates)
        if name in ("near", "within", "contains", "intersects"):
            return self._eval_geo(fn, candidates)
        if name == "similar_to":
            return self._eval_similar_to(fn, candidates)
        raise GQLError(f"function {name!r} not supported")

    def _eval_similar_to(self, fn: Function, candidates) -> np.ndarray:
        with _span("similar_to", pred=fn.attr) as sp:
            return self._eval_similar_to_inner(fn, candidates, sp)

    def _eval_similar_to_inner(self, fn: Function, candidates,
                               sp: Optional[dict] = None) -> np.ndarray:
        """similar_to(embedding, k, $vec[, metric]): the k uids whose
        stored float32vector scores closest to the query vector
        (forward-port of modern Dgraph's similar_to onto the v1.1.x
        surface). Scoring is brute-force MIPS over the predicate's
        columnar vector block (ops/knn.py, TPU-KNN formulation):
        device tier with the two-stage approximate top-k when the
        block is resident-sized, mesh-sharded per-shard top-k + k-way
        merge above shard_min_edges, exact numpy otherwise. MVCC
        overlay rows are scored host-side and merged, so reads at any
        ts see exactly their snapshot. Scores land in the
        `similar_to_score` value variable (val(similar_to_score))."""
        from dgraph_tpu.models.types import parse_vector
        from dgraph_tpu.ops import knn as _knn

        tab = self._tablet(fn.attr)
        schema = tab.schema if tab is not None \
            else self.db.schema.get(fn.attr)
        if schema is None:
            raise GQLError(
                f"predicate {fn.attr!r} is not in the schema")
        if schema.value_type != TypeID.FLOAT32VECTOR:
            raise GQLError(
                f"similar_to requires a float32vector predicate; "
                f"{fn.attr!r} is {type_name(schema.value_type)}")
        if candidates is None and not (
                schema.indexed and "vector" in schema.tokenizers):
            # root similar_to needs @index(vector), a schema property
            # whether or not data exists (same contract as root eq)
            raise GQLError(
                f"predicate {fn.attr!r} needs @index(vector) for "
                "similar_to at the query root")
        if len(fn.args) < 2:
            raise GQLError(
                "similar_to(pred, k, vector) needs a k and a query "
                "vector")
        try:
            k = int(str(fn.args[0].value), 0)
        except ValueError:
            raise GQLError(
                f"similar_to k must be an integer, got "
                f"{fn.args[0].value!r}")
        if k < 1:
            raise GQLError("similar_to k must be >= 1")
        try:
            qvec = parse_vector(fn.args[1].value)
        except (ValueError, TypeError) as e:
            raise GQLError(f"bad similar_to query vector: {e}")
        metric = "cosine"
        if len(fn.args) > 2:
            metric = str(fn.args[2].value).lower()
            if metric not in _knn.METRICS:
                raise GQLError(
                    f"similar_to metric must be one of "
                    f"{'/'.join(_knn.METRICS)}, got {metric!r}")
        if tab is None:
            return _EMPTY
        if not hasattr(tab, "vector_view"):
            # federated RemoteTablet proxy: the embedding block lives
            # on another group and brute-force scoring must run where
            # the data is — keep the vector predicate co-located with
            # the querying group (clean error, not an AttributeError)
            raise GQLError(
                f"similar_to on {fn.attr!r} requires the vector "
                "predicate to be served by this group (cross-group "
                "vector search is not supported)")
        try:
            view = tab.vector_view(self.read_ts)
        except ValueError as e:
            raise GQLError(str(e))
        if view.dim and len(qvec) != view.dim:
            raise GQLError(
                f"similar_to query vector has dimension {len(qvec)}; "
                f"predicate {fn.attr!r} stores dimension {view.dim}")

        base_mask = view.base_keep
        ex_uids, ex_vecs = view.extra_uids, view.extra_vecs
        if candidates is not None:
            base_mask = base_mask & _member_of(view.base_uids,
                                               candidates)
            exm = _member_of(ex_uids, candidates)
            ex_uids, ex_vecs = ex_uids[exm], ex_vecs[exm]
        parts: list = []
        n = len(view.base_uids)
        if n and base_mask.any():
            qm = qvec[None, :]
            # quantized eligibility: a trained index for the CURRENT
            # base state, root context (a filter's candidate subset
            # can defeat the probe's recall budget — candidates keep
            # the exact tiers), and k within the calibrated regime.
            # vec_quantized=False is the exact-path parity oracle.
            ivf = tab.vector_ivf() \
                if hasattr(tab, "vector_ivf") else None
            quant_ok = (ivf is not None and self.db.vec_quantized
                        and candidates is None
                        and k <= self.db.vec_max_k)
            # tier arbitration: the planner weighs the measured
            # dispatch RTT / observed per-stage cost against the
            # per-tier scanned-row counts (the quantized tier scores
            # ~n*nprobe/nlist rows + the re-rank, not n); static mode
            # keeps the flag ladder. The mesh-sharded tier stays
            # first — capacity, not latency.
            dec = None
            force_device = self.db.prefer_device \
                and self.db.device_min_edges <= 1
            avail = ["postings"]
            if self.db.prefer_device and self.db.device_min_edges > 1:
                avail.append("device")
            if quant_ok:
                avail.append("quantized")
            if self._adaptive and len(avail) > 1 \
                    and not force_device and self.db.mesh is None:
                rows_by_tier = None
                if quant_ok:
                    rows_by_tier = {"quantized": ivf.scanned_rows(
                        self.db.vec_nprobe)}
                dec = self._tier_decision(
                    "similar_to", fn.attr,
                    {"estRows": n, "estRowsMax": n, "basis": "exact",
                     "source": "vector block rows"},
                    tuple(avail), rows_by_tier)
            if dec is not None:
                use_quant = dec.tier == "quantized"
                use_device = dec.tier == "device"
            else:
                # device_min_edges <= 1 force-routes device (the
                # pinned-tier debugging convention) ahead of the tier
                use_quant = quant_ok and not force_device
                use_device = not use_quant \
                    and self.db.prefer_device \
                    and n >= self.db.device_min_edges
            vdec = {"pred": fn.attr, "k": int(k), "n": int(n),
                    "metric": metric}
            if self.db.mesh is not None \
                    and n >= self.db.shard_min_edges:
                if quant_ok:
                    idx, sc = self._sharded_ivf_topk(
                        tab, ivf, view, qm, k, metric, base_mask)
                    vdec.update(tier="sharded_quantized",
                                **self._vec_budget(ivf, k))
                else:
                    idx, sc = self._sharded_vec_topk(
                        tab, view, qm, k, metric, base_mask)
                    vdec["tier"] = "sharded"
                if sp is not None:
                    # cost attribution follows the SERVING tier: the
                    # mesh-quantized span must not pollute the exact
                    # device tier's cost cells
                    sp["tier"] = vdec["tier"] \
                        if vdec["tier"] == "sharded_quantized" \
                        else "device"
            elif use_quant:
                from dgraph_tpu.ops import ivf as _ivf
                idx, sc = _ivf.search(
                    ivf, view.base_vecs, qm, k, metric,
                    keep=base_mask, nprobe=self.db.vec_nprobe,
                    rerank=self.db.vec_rerank)
                inc_counter("query_similar_quantized_total")
                budget = self._vec_budget(ivf, k)
                scanned = budget["scannedRows"]
                vdec.update(tier="quantized", **budget)
                if sp is not None:
                    sp["tier"] = "quantized"
                    # the span's size drives the coststore cell's
                    # bucket: record the SCANNED rows, the same size
                    # axis rows_by_tier gave the decision probe — a
                    # full-n bucket would park quantized observations
                    # where the planner never looks
                    sp["n"] = int(scanned)
            elif use_device:
                idx, sc = _knn.topk_device(
                    self._device_vec_block(tab, view), qm, k, metric,
                    mask=base_mask, n_real=n)
                inc_counter("query_similar_device_total")
                vdec["tier"] = "two_stage" \
                    if _knn.plan_two_stage(n, k) > 0 else "exact"
                if sp is not None:
                    sp["tier"] = "device"
                    sp["n"] = int(n)
            else:
                idx, sc = _knn.topk_host(view.base_vecs, qm, k,
                                         metric, mask=base_mask)
                vdec["tier"] = "exact"
                if sp is not None:
                    sp["tier"] = "postings"
                    sp["n"] = int(n)
            self.vector_decisions.append(vdec)
            self._record_outcome(dec, n)
            row, s = idx[0], sc[0]
            ok = np.isfinite(s) & (row < n) & (row >= 0)
            parts.append((view.base_uids[row[ok]], s[ok]))
        if len(ex_uids):
            idx, sc = _knn.topk_host(ex_vecs, qvec[None, :], k, metric)
            row, s = idx[0], sc[0]
            ok = np.isfinite(s)
            parts.append((ex_uids[row[ok]], s[ok]))
        uids, scores = _knn.merge_topk(parts, k)
        self.value_vars[SIMILAR_SCORE_VAR] = {
            int(u): Val(TypeID.FLOAT, float(s))
            for u, s in zip(uids.tolist(), scores.tolist())}
        if candidates is None:
            # root: the block emits nearest-first (_similar_paginate)
            self._similar_order = [int(u) for u in uids.tolist()]
        return np.sort(uids.astype(np.uint64))

    def _device_vec_block(self, tab, view):
        """The base vector block as a device array, cached per base_ts
        exactly like the adjacency tiles (_device_adj). Pre-padded to
        the bucket unit HOST-SIDE so topk_device never re-copies the
        block per query."""
        from dgraph_tpu.ops import knn as _knn

        cached = getattr(tab, "_device_vecs", None)
        if cached is not None and cached[0] == tab.base_ts:
            return cached[1]
        import jax.numpy as jnp

        arr = jnp.asarray(_knn.pad_rows(view.base_vecs))
        tab._device_vecs = (tab.base_ts, arr)
        return arr

    def _vec_rerank(self, k: int) -> int:
        """Effective exact re-rank depth for the quantized tier."""
        from dgraph_tpu.ops import ivf as _ivf
        return int(self.db.vec_rerank or _ivf.rerank_depth(k))

    def _vec_budget(self, ivf, k: int) -> dict:
        """The quantized tier's live budget as EXPLAIN reports it —
        ONE builder so the sharded and single-device tiers.vector
        entries can't drift apart. nprobe clamps to nlist exactly
        like ops/ivf.search does."""
        return {
            "nprobe": min(ivf.nlist,
                          int(self.db.vec_nprobe or ivf.nprobe)),
            "rerank": self._vec_rerank(k),
            "nlist": ivf.nlist,
            "scannedRows": ivf.scanned_rows(self.db.vec_nprobe),
            "sampleRecall": round(float(ivf.sample_recall), 4),
        }

    def _sharded_ivf_topk(self, tab, ivf, view, qm, k, metric,
                          base_mask):
        """Quantized scoring over a sharded corpus: per-shard
        candidate top-R + k-way merge + exact re-rank
        (parallel/dist_knn.sharded_ivf_topk)."""
        from dgraph_tpu.parallel.dist_knn import sharded_ivf_topk

        inc_counter("query_similar_sharded_total")
        return sharded_ivf_topk(
            self.db.mesh, ivf, view.base_vecs, qm, k, metric,
            keep=base_mask, nprobe=self.db.vec_nprobe,
            rerank=self.db.vec_rerank)

    def _sharded_vec_topk(self, tab, view, qm, k, metric, base_mask):
        """Mesh-sharded scoring: the block rides the `uid` axis, each
        shard computes a local top-k, one all_gather merges
        (parallel/dist_knn.py)."""
        from dgraph_tpu.parallel.dist_knn import (
            shard_corpus, sharded_topk,
        )

        mesh = self.db.mesh
        cached = getattr(tab, "_device_vecs_sharded", None)
        if cached is not None and cached[0] == tab.base_ts:
            block, n_real = cached[1], cached[2]
        else:
            block, n_real = shard_corpus(mesh, view.base_vecs)
            tab._device_vecs_sharded = (tab.base_ts, block, n_real)
        inc_counter("query_similar_sharded_total")
        return sharded_topk(mesh, block, qm, k, metric,
                            mask=base_mask, n_real=n_real)

    def _eval_geo(self, fn: Function, candidates) -> np.ndarray:
        """near/within/contains/intersects: geo-cell index prefilter +
        exact host verify (ref types/geofilter.go:65,222 +
        worker/task.go:1330 filterGeoFunction; s2index.go covers become
        the lon/lat grid in models/geo.py)."""
        from dgraph_tpu.models import geo as G

        tab = self._tablet(fn.attr)
        if tab is None:
            return _EMPTY
        if tab.schema.value_type != TypeID.GEO:
            raise GQLError(
                f"{fn.name} requires a geo predicate, "
                f"{fn.attr!r} is {tab.schema.value_type.name.lower()}")
        try:
            qgeom, dist = self._geo_args(fn)
        except (ValueError, KeyError, IndexError, TypeError) as e:
            raise GQLError(f"bad {fn.name} argument: {e}")

        # index prefilter: cells covering the query region, coarse->fine
        if fn.name == "near":
            bbox = G.expand_bbox_m(tuple(qgeom["coordinates"]), dist)
        else:
            bbox = G._bbox(qgeom)
        spec = get_tokenizer("geo")
        indexed = tab.schema.indexed and "geo" in tab.schema.tokenizers
        if indexed:
            scan = self._index_union(
                tab, [token_bytes(spec.ident, t)
                      for t in G.query_tokens(bbox)])
            if candidates is not None:
                scan = _intersect(candidates, scan)
        elif candidates is not None:
            scan = candidates
        else:
            raise GQLError(
                f"{fn.name} requires @index(geo) on {fn.attr!r} at the "
                "query root")

        keep = []
        for u in scan.tolist():
            for p in tab.get_postings(u, self.read_ts):
                try:
                    g = G.parse_geom(self._typed(tab, p).value)
                except ValueError:
                    continue
                if self._geo_match(fn.name, g, qgeom, dist):
                    keep.append(u)
                    break
        return np.asarray(keep, dtype=np.uint64)

    @staticmethod
    def _geo_args(fn: Function):
        """Parse [lon, lat] / polygon literal (+ distance for near)."""
        import json as _json

        from dgraph_tpu.models.geo import GeoError, parse_geom
        raw = fn.args[0].value
        obj = _json.loads(raw) if isinstance(raw, str) else raw
        if isinstance(obj, list):
            if obj and isinstance(obj[0], (int, float)):
                obj = {"type": "Point", "coordinates": obj}
            elif obj and isinstance(obj[0][0], (int, float)):
                obj = {"type": "Polygon", "coordinates": [obj]}
            else:
                obj = {"type": "Polygon", "coordinates": obj}
        qgeom = parse_geom(obj)
        dist = 0.0
        if fn.name == "near":
            if len(fn.args) < 2:
                raise GeoError("near needs a distance in meters")
            dist = float(fn.args[1].value)
            if qgeom["type"] != "Point":
                raise GeoError("near expects a point")
        return qgeom, dist

    @staticmethod
    def _geo_match(name: str, g: dict, q: dict, dist: float) -> bool:
        from dgraph_tpu.models import geo as G
        if name == "near":
            return G.min_distance_m(g, tuple(q["coordinates"])) <= dist
        if name == "within":
            return G.geom_within(g, q)
        if name == "contains":
            if q["type"] == "Point":
                return G.geom_contains_point(g, tuple(q["coordinates"]))
            return G.geom_within(q, g)
        return G.geom_intersects(g, q)

    def _eval_checkpwd(self, fn: Function, candidates) -> np.ndarray:
        """UIDs whose stored password hash verifies against the given
        plaintext (ref worker/task.go handleCheckPassword +
        types/password.go VerifyPassword)."""
        from dgraph_tpu.models.types import verify_password
        tab = self._tablet(fn.attr)
        if tab is None or not fn.args:
            return _EMPTY
        plain = str(fn.args[0].value)
        scan = candidates if candidates is not None \
            else tab.src_uids(self.read_ts)
        keep = [u for u in scan.tolist()
                if any(verify_password(plain, str(p.value.value))
                       for p in tab.get_postings(u, self.read_ts))]
        return np.asarray(keep, dtype=np.uint64)

    def _eval_eq_tokens(self, tab: Optional[Tablet], vals: list[Val],
                        candidates, lang: str = "") -> np.ndarray:
        if tab is None:
            return _EMPTY
        with _span("eq", pred=tab.pred) as sp:
            return self._eval_eq_tokens_inner(tab, vals, candidates,
                                              lang, sp)

    def _eval_eq_tokens_inner(self, tab: Tablet, vals: list[Val],
                              candidates, lang: str = "",
                              sp: Optional[dict] = None) -> np.ndarray:
        out = _EMPTY
        # pick a non-lossy tokenizer if indexed (ref worker/task.go
        # pickTokenizer); else scan candidates' values
        spec = None
        for tname in tab.schema.tokenizers:
            s = get_tokenizer(tname)
            if not s.lossy:
                spec = s
                break
        if spec is None and tab.schema.indexed:
            spec = get_tokenizer(tab.schema.tokenizers[0])
        if spec is not None:
            # the query value must be analyzed the same way the indexed
            # values were: `eq(pred@de, ...)` uses the German analyzer;
            # `@.` (any language) probes every analyzer's buckets.
            # Token probes batch into ONE index probe + ONE k-way
            # union instead of per-token incremental union re-sorts

            def _analyze() -> tuple[list[bytes], list[Val]]:
                langs = _probe_langs(spec, lang)
                ntv: list[Val] = []
                toks_all: list[bytes] = []
                for v in vals:
                    v_toks = 0
                    for lg in langs:
                        try:
                            toks = tokens_for(v, spec, lg)
                        except (ValueError, TypeError):
                            continue
                        v_toks += len(toks)
                        toks_all.extend(token_bytes(spec.ident, t)
                                        for t in toks)
                    if not v_toks:
                        # a value no tokenizer emits tokens for (e.g.
                        # "") is absent from the index — PER VALUE,
                        # scan it below and union (ref
                        # TestQueryEmptyRoomsWithTermIndex; eq(room,
                        # ["", "green"]) must match both)
                        ntv.append(v)
                return toks_all, ntv

            if self.plan is not None:
                # token analysis is (schema, lang, literal)-derived —
                # exactly what a compiled plan binds once per
                # parameter vector (keyed by the VALUES: a shared
                # skeleton never serves another request's tokens)
                all_toks, no_tok_vals = self.plan.memo(
                    ("eqtok", tab.pred, lang, spec.ident,
                     tuple((v.tid, v.value) for v in vals)),
                    _analyze)
            else:
                all_toks, no_tok_vals = _analyze()
            dec = None
            if all_toks and self._adaptive:
                dec = self._routed(
                    ("eq", tab.pred, len(all_toks)),
                    lambda: self._tier_decision(
                        "eq", tab.pred,
                        self._token_est(tab, len(all_toks)),
                        self._index_tiers(tab)))
                if dec is not None and candidates is not None \
                        and not no_tok_vals \
                        and self.db.planner_impl.probe_or_scan(
                            "eq", dec.est_rows, len(candidates),
                            probe_tier=dec.tier) == "scan":
                    # index-probe vs candidate-scan pivot: the
                    # estimated token postings dwarf the candidate
                    # set, so verify the candidates' values directly
                    # (the exact filter semantics — the unindexed
                    # branch below — chosen on cost, not necessity)
                    if sp is not None:
                        sp["tier"] = "postings"
                        sp["n"] = int(len(candidates))
                    return self._eq_scan(tab, candidates, vals, lang)
            if all_toks:
                self._served_tier = None
                out = self._index_union(tab, all_toks,
                                        tier=dec.tier
                                        if dec is not None else None)
                self._record_outcome(dec, len(out))
                if sp is not None:
                    sp["tier"] = self._served_tier or "postings"
                    sp["n"] = int(len(out))
            if len(no_tok_vals) < len(vals):
                if spec.lossy or tab.schema.lang:
                    # @lang predicates share index buckets across
                    # language tags (the token carries no lang), so
                    # the index hit must be verified against the
                    # posting the query's lang selector actually
                    # addresses: eq(name, "") must not match a value
                    # that is empty only in @hi (ref query0_test.go
                    # TestQueryEmptyDefaultNames)
                    out = self._verify_eq(tab, out, vals, lang)
                if no_tok_vals:
                    scan = candidates if candidates is not None \
                        else tab.src_uids(self.read_ts)
                    extra = self._eq_scan(tab, scan, no_tok_vals, lang)
                    out = _union(out, extra)
                return out if candidates is None \
                    else _intersect(candidates, out)
            # EVERY value was tokenless: plain scan below
        # unindexed: value scan over candidates (filter context) or all
        scan = candidates if candidates is not None \
            else tab.src_uids(self.read_ts)
        return self._eq_scan(tab, scan, vals, lang)

    def _eq_scan(self, tab, scan: np.ndarray, vals: list[Val],
                 lang: str = "") -> np.ndarray:
        """Equality scan over a sorted candidate vector: one vectorized
        column compare on clean tablets, per-uid postings otherwise."""
        got = self._eq_batch(tab, scan, vals, lang)
        if got is not None:
            return got
        return np.asarray(
            [u for u in scan.tolist()
             if self._value_matches_eq(tab, u, vals, lang)], np.uint64)

    def _eq_batch(self, tab, scan: np.ndarray, vals: list[Val],
                  lang: str = "") -> Optional[np.ndarray]:
        """Vectorized _value_matches_eq over the cached column view —
        the per-uid get_postings verify loop collapsed to one gather +
        one compare per query value. None keeps the exact path: dirty
        tablets, specific language tags (the untagged column can't
        answer them), datetime/geo columns, NUL-bearing payloads."""
        if lang not in ("", "."):
            return None
        colview = self._colview(tab)
        if colview is None:
            return None
        t = tab.schema.value_type
        if t == TypeID.DEFAULT:
            t = colview.tid if colview.tid != TypeID.DEFAULT \
                else TypeID.STRING
        if t not in (TypeID.STRING, TypeID.INT, TypeID.FLOAT,
                     TypeID.BOOL):
            return None
        if lang == ".":
            # '.' compares ANY posting: only string views track the
            # lang-tagged side (extra_*); a numeric tablet could carry
            # tagged postings the view never captured
            if t != TypeID.STRING or not colview.extra_ok:
                return None
        wants = []
        for v in vals:
            try:
                wants.append(convert(v, t).value)
            except ValueError:
                continue  # same skip as the per-posting loop
        pos, hit = _col_positions(colview.srcs, scan)
        sel = pos[hit]
        if t == TypeID.STRING:
            bc = colview.bytes_column()
            if bc is None:
                return None  # NUL-bearing payloads: exact path
            main_b, extra_b = bc
            col = main_b[sel]
            m = np.zeros(len(sel), bool)
            for w in wants:
                wb = str(w).encode("utf-8")
                if b"\x00" not in wb:  # a NUL-free column can't match
                    m |= col == wb
            parts = [scan[hit][m]]
            if lang == "." and len(colview.extra_srcs):
                em = np.isin(colview.extra_srcs, scan)
                ecol = extra_b[em]
                m2 = np.zeros(len(ecol), bool)
                for w in wants:
                    wb = str(w).encode("utf-8")
                    if b"\x00" not in wb:
                        m2 |= ecol == wb
                parts.append(np.unique(colview.extra_srcs[em][m2]))
            return setops.union_many(parts)
        col = colview.data[sel]
        m = np.zeros(len(sel), bool)
        for w in wants:
            try:
                m |= col == (int(w) if t == TypeID.BOOL else w)
            except (TypeError, OverflowError):
                continue
        return scan[hit][m]

    def _eval_eq_own_val(self, tab, fn: Function, candidates) -> np.ndarray:
        if tab is None:
            return _EMPTY
        vmap = {}
        for vc in fn.needs_var:
            vmap.update(self.value_vars.get(vc.name, {}))
        scan = candidates if candidates is not None \
            else _np_sorted(vmap.keys())
        keep = [u for u in scan.tolist()
                if u in vmap and self._value_matches_eq(tab, u, [vmap[u]])]
        return np.asarray(keep, dtype=np.uint64)

    def _verify_eq(self, tab, uids, vals, lang: str = "") -> np.ndarray:
        return self._eq_scan(tab, uids, vals, lang)

    def _value_matches_eq(self, tab: Tablet, uid: int,
                          vals: list[Val], lang: str = "") -> bool:
        for p in tab.get_postings(uid, self.read_ts):
            if not _lang_matches(p.lang, lang):
                continue
            for v in vals:
                try:
                    want = convert(v, self._cmp_type(tab, p))
                    have = convert(p.value, self._cmp_type(tab, p))
                except ValueError:
                    continue
                if have.value == want.value:
                    return True
        return False

    @staticmethod
    def _cmp_type(tab: Tablet, p) -> TypeID:
        t = tab.schema.value_type
        if t == TypeID.DEFAULT:
            t = p.value.tid if p.value.tid != TypeID.DEFAULT else TypeID.STRING
        return t

    def _eval_ineq(self, fn: Function, candidates) -> np.ndarray:
        with _span("ineq", fn=fn.name, pred=fn.attr) as sp:
            return self._eval_ineq_inner(fn, candidates, sp)

    def _ineq_est(self, tab, fname: str) -> dict:
        """EXPLAIN's range-fraction heuristic as the planner input
        (half the keys; a third for between), capped at keys + dirty
        slack."""
        st = self._tabstats(tab)
        if st is None:
            return {"estRows": -1, "estRowsMax": -1,
                    "basis": "unknown"}
        cap = st["nSrc"] + self._dirty_slack(tab)
        est = st["nSrc"] // (3 if fname == "between" else 2)
        return {"estRows": min(est, cap), "estRowsMax": cap,
                "basis": "stats", "source": "range-fraction heuristic"}

    def _eval_ineq_inner(self, fn: Function, candidates,
                         sp: Optional[dict] = None) -> np.ndarray:
        tab = self._tablet(fn.attr)
        ips = tab.schema if tab is not None \
            else self.db.schema.get(fn.attr)
        if candidates is None and ips is not None \
                and not fn.is_value_var \
                and ips.value_type != TypeID.BOOL \
                and not _has_sortable_index(ips):
            # schema-level check so declared-but-empty predicates
            # error like populated ones (ref worker/tokens.go
            # IsSortable requirement)
            raise GQLError(
                f"attribute {fn.attr!r} needs a sortable index "
                f"(exact/int/float/datetime) to serve {fn.name} "
                "at the query root")
        if tab is None:
            return _EMPTY
        tid = tab.schema.value_type
        if tid == TypeID.DEFAULT:
            tid = TypeID.STRING
        if fn.is_value_var:
            return self._eval_var_fn(fn, candidates)
        if tid == TypeID.BOOL:
            raise GQLError(
                f"attribute {fn.attr!r} is not sortable; only eq "
                "applies to bool values (ref TestBoolIndexgeRoot)")
        if fn.name != "between" and len(fn.args) > 1:
            # inequality against a value list is meaningless (ref
            # query1:TestMultipleGtError)
            raise GQLError(
                f"{fn.name}() expects a single value, "
                f"got {len(fn.args)}")
        def _bounds() -> tuple[int, int, bool, bool]:
            if fn.name == "between":
                return (sort_key(convert(
                            Val(TypeID.DEFAULT, fn.args[0].value), tid)),
                        sort_key(convert(
                            Val(TypeID.DEFAULT, fn.args[1].value), tid)),
                        False, False)
            bound = sort_key(
                convert(Val(TypeID.DEFAULT, fn.args[0].value), tid))
            b_lo, b_hi = -(1 << 63), (1 << 63) - 1
            b_lo_open = b_hi_open = False
            if fn.name == "le":
                b_hi = bound
            elif fn.name == "lt":
                b_hi, b_hi_open = bound, True
            elif fn.name == "ge":
                b_lo = bound
            else:
                b_lo, b_lo_open = bound, True
            return b_lo, b_hi, b_lo_open, b_hi_open

        try:
            if self.plan is not None:
                # bound parsing (datetime/float literal -> int64 sort
                # key) is (literal, type)-pure: bind once per params
                lo, hi, lo_open, hi_open = self.plan.memo(
                    ("ineq", fn.name, fn.attr, int(tid),
                     tuple(a.value for a in fn.args)),
                    _bounds)
            else:
                lo, hi, lo_open, hi_open = _bounds()
        except ValueError as e:
            raise GQLError(f"bad {fn.name} argument for {fn.attr}: {e}")
        # strings compare beyond the 8-byte key prefix: exact host compare
        if tid in (TypeID.STRING, TypeID.DEFAULT):
            return self._ineq_scan_strings(tab, fn, candidates)
        # tier choice: device range kernel / cached sort-key arrays /
        # exact per-uid walk. The planner decides from estimated rows
        # x observed cost; device_min_edges <= 1 (the force override)
        # and the static mode keep the measured-RTT gate.
        dec = tier = None
        if self._adaptive and self.db.device_min_edges > 1:
            def _build_ineq():
                avail = ["postings"]
                if self._columnar_on() \
                        and hasattr(tab, "sort_key_arrays"):
                    avail.append("columnar")
                if self.db.prefer_device \
                        and self.db.device_is_accelerator():
                    avail.append("device")
                return self._tier_decision(
                    "ineq", fn.attr, self._ineq_est(tab, fn.name),
                    tuple(avail))
            dec = self._routed(("ineq", fn.attr, fn.name), _build_ineq)
            tier = dec.tier if dec is not None else None
        if (tier == "device") if dec is not None else (
                self.db.prefer_device and self._device_worth(
                    len(getattr(tab, "values", ()))
                    * self._HOST_PER_RANGE_VAL,
                    device_ratio=self._DEVICE_RATIO_RANGE)):
            dev = self._device_range(tab, lo, hi, lo_open, hi_open)
            if dev is not None:
                self._record_outcome(dec, len(dev))
                if sp is not None:
                    sp["tier"] = "device"
                    sp["n"] = int(len(dev))
                return dev if candidates is None \
                    else _intersect(candidates, dev)
        if tier == "postings" \
                or not hasattr(tab, "sort_key_arrays") \
                or self.read_ts < tab.base_ts \
                or not self._columnar_on():
            served = "postings"
            pairs = self._sortkeys_for(tab)
            uids = np.fromiter(pairs.keys(), np.uint64, len(pairs))
            keys = np.fromiter(pairs.values(), np.int64, len(pairs))
            order = np.argsort(uids, kind="stable")
            uids, keys = uids[order], keys[order]
        elif tab.dirty():
            served = "columnar"
            uids, keys = self._sortkeys_dirty(tab)
        else:
            served = "columnar"
            uids, keys = tab.sort_key_arrays()
        if not len(uids):
            self._record_outcome(dec, 0)
            return _EMPTY

        def in_range(kk):
            return (kk > lo if lo_open else kk >= lo) & \
                (kk < hi if hi_open else kk <= hi)

        if candidates is not None \
                and len(uids) >= 2 * len(candidates):
            # filter context with a narrower candidate set: gather the
            # candidates' keys instead of masking the whole tablet
            # column and re-intersecting (the q003-at-21M shape)
            pos, hit = _col_positions(uids, candidates)
            kk = keys[pos[hit]]
            out = candidates[hit][in_range(kk)]
            self._record_outcome(dec, len(out))
            if sp is not None:
                sp["tier"] = served
                sp["n"] = int(len(out))
            return out
        out = np.sort(uids[in_range(keys)])
        self._record_outcome(dec, len(out))
        if sp is not None:
            sp["tier"] = served
            sp["n"] = int(len(out))
        return out if candidates is None else _intersect(candidates, out)

    def _sortkeys_dirty(self, tab) -> tuple[np.ndarray, np.ndarray]:
        """(uids, int64 sort keys) of a DIRTY tablet at read_ts: the
        cached base arrays answer every overlay-untouched row; touched
        rows re-read through the exact MVCC posting path and merge —
        the same immutable/mutable split the device tiles use (ref
        posting/mvcc.go). Replaces a full per-uid dict rebuild per
        query on bulk-mutated stores."""
        buids, bkeys = tab.sort_key_arrays()
        touched = tab.overlay_srcs(self.read_ts)
        if touched:
            tarr = np.fromiter(touched, np.uint64, len(touched))
            keep = ~np.isin(buids, tarr)
            buids, bkeys = buids[keep], bkeys[keep]
            ou: list[int] = []
            ok: list[int] = []
            for u in sorted(touched):
                for p in tab.get_postings(int(u), self.read_ts):
                    if p.lang:
                        continue
                    try:
                        ok.append(sort_key(convert(
                            p.value, tab.schema.value_type
                            if tab.schema.value_type != TypeID.DEFAULT
                            else p.value.tid)))
                        ou.append(int(u))
                    except ValueError:
                        pass
                    break
            if ou:
                buids = np.concatenate(
                    [buids, np.asarray(ou, np.uint64)])
                bkeys = np.concatenate(
                    [bkeys, np.asarray(ok, np.int64)])
                order = np.argsort(buids, kind="stable")
                buids, bkeys = buids[order], bkeys[order]
        return buids, bkeys

    def _device_range(self, tab, lo, hi, lo_open, hi_open
                      ) -> Optional[np.ndarray]:
        """le/lt/ge/gt/between root scan as one device mask + compact
        (ops/graph.range_select; ref worker/tokens.go:113)."""
        from dgraph_tpu.engine.device_cache import device_values
        from dgraph_tpu.ops.graph import range_select
        from dgraph_tpu.ops.uidvec import to_numpy

        dv = device_values(self.db, tab, self.read_ts)
        if dv is None:
            return None
        inc_counter("query_device_range_total")
        return to_numpy(range_select(dv, lo, hi, lo_open, hi_open)
                        ).astype(np.uint64)

    def _ineq_scan_strings(self, tab, fn, candidates) -> np.ndarray:
        want = str(fn.args[0].value)
        hi2 = str(fn.args[1].value) if fn.name == "between" else None
        op = fn.name
        keep = []
        scan = candidates if candidates is not None \
            else tab.src_uids(self.read_ts)
        batched = self._ineq_strings_batch(tab, scan, fn, want, hi2)
        if batched is not None:
            return batched
        for u in scan.tolist():
            for p in tab.get_postings(u, self.read_ts):
                if not _lang_matches(p.lang, fn.lang or ""):
                    # lt(name, v) compares the UNTAGGED value only;
                    # lt(name@de, v) the @de one (ref query0_test.go
                    # TestQueryNamesBeforeA: a value empty only in
                    # @hi must not satisfy lt(name, "A"))
                    continue
                s = str(p.value.value)
                ok = ((op == "le" and s <= want) or (op == "lt" and s < want)
                      or (op == "ge" and s >= want) or (op == "gt" and s > want)
                      or (op == "between" and want <= s <= hi2))
                if ok:
                    keep.append(u)
                    break
        return np.asarray(keep, dtype=np.uint64)

    _INEQ_VEC = {
        "le": lambda col, lo, hi: col <= lo,
        "lt": lambda col, lo, hi: col < lo,
        "ge": lambda col, lo, hi: col >= lo,
        "gt": lambda col, lo, hi: col > lo,
        "between": lambda col, lo, hi: (col >= lo) & (col <= hi),
    }

    def _ineq_strings_batch(self, tab, scan, fn, want: str,
                            hi2) -> Optional[np.ndarray]:
        """String inequality over the cached byte columns: UTF-8 byte
        order IS codepoint order, so fixed-width byte compares equal
        the host loop's str compares. Exact path stays for dirty
        tablets, specific language tags and NUL-bearing payloads."""
        lang = fn.lang or ""
        if lang not in ("", "."):
            return None
        colview = self._colview(tab)
        if colview is None \
                or colview.tid not in (TypeID.STRING, TypeID.DEFAULT):
            return None
        if lang == "." and not colview.extra_ok:
            return None
        bc = colview.bytes_column()
        if bc is None:
            return None
        wb = want.encode("utf-8")
        hb = hi2.encode("utf-8") if hi2 is not None else None
        cmp = self._INEQ_VEC[fn.name]
        main_b, extra_b = bc
        pos, hit = _col_positions(colview.srcs, scan)
        parts = [scan[hit][cmp(main_b[pos[hit]], wb, hb)]]
        if lang == "." and len(colview.extra_srcs):
            em = np.isin(colview.extra_srcs, scan)
            m2 = cmp(extra_b[em], wb, hb)
            parts.append(np.unique(colview.extra_srcs[em][m2]))
        return setops.union_many(parts)

    def _sortkeys_for(self, tab: Tablet) -> dict[int, int]:
        out = {}
        if tab.dirty():
            for u in tab.src_uids(self.read_ts).tolist():
                for p in tab.get_postings(u, self.read_ts):
                    if p.lang:
                        continue
                    try:
                        out[u] = sort_key(convert(
                            p.value, tab.schema.value_type
                            if tab.schema.value_type != TypeID.DEFAULT
                            else p.value.tid))
                    except ValueError:
                        pass
                    break
            return out
        return tab.sort_key_pairs()

    def _eval_terms(self, fn: Function, candidates) -> np.ndarray:
        with _span("setops", fn=fn.name, pred=fn.attr) as sp:
            return self._eval_terms_inner(fn, candidates, sp)

    def _eval_terms_inner(self, fn: Function, candidates,
                          sp: Optional[dict] = None) -> np.ndarray:
        tab = self._tablet(fn.attr)
        toker = "fulltext" if fn.name in ("anyoftext", "alloftext") else "term"
        ps = tab.schema if tab is not None \
            else self.db.schema.get(fn.attr)
        if ps is not None and toker not in ps.tokenizers:
            # the functions read the index buckets; without the
            # matching tokenizer there is nothing to read — a SCHEMA
            # property, checked whether or not data exists yet (ref
            # query4:TestDeleteAndReaddIndex "Attribute ... is not
            # indexed with type fulltext")
            raise GQLError(
                f"attribute {fn.attr!r} is not indexed with type "
                f"{toker} (required by {fn.name})")
        if tab is None:
            return _EMPTY
        spec = get_tokenizer(toker)
        text = " ".join(a.value for a in fn.args)
        # `pred@.` (any language): a value matches if it satisfies the
        # all/any condition under at least one language's analyzer —
        # per-analyzer evaluation, then union. Each analyzer's token
        # probe is one batched CSR slice + one k-way set op
        # (ops/setops) instead of a pairwise union/intersect fold
        dec = None
        if self._adaptive:
            n_terms = len(text.split()) or 1
            dec = self._routed(
                ("setops", fn.attr, fn.name, n_terms),
                lambda: self._tier_decision(
                    "setops", fn.attr,
                    self._token_est(tab, 1 if fn.name.startswith("all")
                                    else n_terms),
                    self._index_tiers(tab)))
        tier = dec.tier if dec is not None else None
        self._served_tier = None
        parts: list[np.ndarray] = []
        for lg in _probe_langs(spec, fn.lang or ""):
            if self.plan is not None:
                # term analysis is (analyzer, literal)-pure — a warm
                # plan binds the token batch once per parameter vector
                toks = self.plan.memo(
                    ("terms", toker, lg, text),
                    lambda: tokens_for(Val(TypeID.STRING, text),
                                       spec, lg))
            else:
                toks = tokens_for(Val(TypeID.STRING, text), spec, lg)
            if not toks:
                continue
            tbs = [token_bytes(spec.ident, t) for t in toks]
            if fn.name.startswith("all"):
                parts.append(self._index_intersect(tab, tbs, tier))
            else:
                parts.append(self._index_union(tab, tbs, tier))
        out = self._union_many(parts)
        self._record_outcome(dec, len(out))
        if sp is not None:
            sp["tier"] = self._served_tier or "postings"
            sp["n"] = int(len(out))
        return out if candidates is None else _intersect(candidates, out)

    def _eval_anyof(self, fn: Function, candidates) -> np.ndarray:
        with _span("setops", fn=fn.name, pred=fn.attr) as sp:
            return self._eval_anyof_inner(fn, candidates, sp)

    def _eval_anyof_inner(self, fn: Function, candidates,
                          sp: Optional[dict] = None) -> np.ndarray:
        """anyof/allof(pred, tokenizer, v...): generic token match with
        an explicitly named (usually custom plugin) tokenizer — the
        custom-tokenizer query surface (ref worker/task.go:260 anyof/
        allof cases; systest/plugin_test.go usage)."""
        tab = self._tablet(fn.attr)
        if tab is None:
            return _EMPTY
        if len(fn.args) < 2:
            raise GQLError(
                f"{fn.name} requires a tokenizer name and a value")
        tokname = str(fn.args[0].value)
        spec = get_tokenizer(tokname)
        if tokname not in (tab.schema.tokenizers or []):
            raise GQLError(
                f"attribute {fn.attr!r} is not indexed with "
                f"tokenizer {tokname!r}")
        toks: list = []
        for a in fn.args[1:]:
            toks.extend(tokens_for(
                Val(TypeID.STRING, str(a.value)), spec))
        if not toks:
            return _EMPTY
        tbs = [token_bytes(spec.ident, t) for t in toks]
        dec = None
        if self._adaptive:
            dec = self._routed(
                ("setops", fn.attr, fn.name, len(tbs)),
                lambda: self._tier_decision(
                    "setops", fn.attr,
                    self._token_est(tab, 1 if fn.name == "allof"
                                    else len(tbs)),
                    self._index_tiers(tab)))
        tier = dec.tier if dec is not None else None
        self._served_tier = None
        if fn.name == "allof":
            got = self._index_intersect(tab, tbs, tier)
        else:
            got = self._index_union(tab, tbs, tier)
        self._record_outcome(dec, len(got))
        if sp is not None:
            sp["tier"] = self._served_tier or "postings"
            sp["n"] = int(len(got))
        return got if candidates is None else _intersect(candidates, got)

    def _eval_regexp(self, fn: Function, candidates) -> np.ndarray:
        """Trigram-index prefilter + host regex verify
        (ref worker/trigram.go:35 + task.go:1001)."""
        tab = self._tablet(fn.attr)
        if tab is None:
            return _EMPTY
        pattern = fn.args[0].value
        flags = _re.IGNORECASE if (len(fn.args) > 1
                                   and "i" in fn.args[1].value) else 0
        if self.plan is not None:
            # regex + trigram-query compilation is pure in (pattern,
            # flags): a compiled plan binds it once per literal
            rx, triq = self.plan.memo(
                ("regexp", pattern, flags),
                lambda: (_re.compile(pattern, flags),
                         compile_trigram_query(pattern, flags)))
        else:
            rx = _re.compile(pattern, flags)
            triq = None
        indexed = tab.schema.indexed and "trigram" in tab.schema.tokenizers
        if indexed and candidates is None:
            # Compile the regex AST into an AND/OR trigram query — a
            # necessary condition per alternation branch — and walk the
            # index with it (ref worker/trigram.go:35 uidsForRegex via
            # cindex.RegexpQuery).  ALL ⇒ no index help ⇒ full scan.
            q = triq if triq is not None \
                else compile_trigram_query(pattern, flags)
            dec = self._trigram_tier(tab, "regexp", 3)
            # the trigram walk opens a setops span so every tier's
            # cost lands in the coststore — without cells the
            # planner's rival check has no evidence to correct a
            # cold-prior pick with
            with _span("setops", fn="regexp", pred=tab.pred) as tsp:
                self._served_tier = None
                cand = self._trigram_query_uids(
                    tab, q, dec.tier if dec is not None else None)
                if cand is not None:
                    self._record_outcome(dec, len(cand))
                    tsp["n"] = int(len(cand))
                tsp["tier"] = self._served_tier or "postings"
            scan = cand if cand is not None else tab.src_uids(self.read_ts)
        else:
            scan = candidates if candidates is not None \
                else tab.src_uids(self.read_ts)
        batched = self._regexp_batch(tab, scan, pattern, flags)
        if batched is not None:
            return batched
        keep = []
        for u in scan.tolist():
            for p in tab.get_postings(u, self.read_ts):
                if rx.search(str(p.value.value)):
                    keep.append(u)
                    break
        return np.asarray(keep, dtype=np.uint64)

    def _trigram_query_uids(self, tab, q,
                            tier: Optional[str] = None
                            ) -> Optional[np.ndarray]:
        """Evaluate a compiled TriQuery against `tab`'s trigram index.
        Returns None for an unconstrained (ALL) query — caller scans —
        so an ALL branch inside an OR correctly un-constrains the whole
        OR, as in the reference's trigram query algebra. `tier` (the
        planner's pick) routes every probe batch."""
        spec = get_tokenizer("trigram")

        def ev(node) -> Optional[np.ndarray]:
            if node.op == "all":
                return None
            if node.op == "none":
                return _EMPTY
            if node.op == "and":
                parts = []
                if node.trigrams:
                    # one compressed/batched k-token AND: block-
                    # descriptor skipping prunes non-overlapping
                    # posting blocks before any decode
                    first = self._index_intersect(
                        tab, [token_bytes(spec.ident, t)
                              for t in node.trigrams], tier)
                    if first.size == 0:
                        return first  # dead branch: skip the subs
                    parts = [first]
                for s in node.subs:
                    got = ev(s)
                    if got is not None:
                        parts.append(got)
                if not parts:
                    return None  # every child unconstrained
                return self._intersect_many(parts)
            # OR
            parts = [self._index_union(
                tab, [token_bytes(spec.ident, t)
                      for t in node.trigrams], tier)] \
                if node.trigrams else []
            for s in node.subs:
                got = ev(s)
                if got is None:
                    return None
                parts.append(got)
            return self._union_many(parts)

        return ev(q)

    def _regexp_batch(self, tab, scan, pattern: str,
                      flags) -> Optional[np.ndarray]:
        """Regex verify over the clean tablet's pre-encoded column
        payloads (bytes-level re for ASCII patterns — identical
        semantics, no get_postings walk per uid). Lang-tagged extras
        verify in the same pass, so mixed uids match like the host
        loop."""
        colview = self._colview(tab)
        if colview is None or colview.enc is None \
                or colview.tid not in (TypeID.STRING, TypeID.DEFAULT) \
                or not colview.extra_ok or not colview.ascii_only \
                or any(ord(c) > 127 for c in pattern):
            return None
        try:
            rxb = _re.compile(pattern.encode("ascii"), flags)
        except _re.error:
            return None
        srcs, _tid, _data, enc = colview
        pos, hit = _col_positions(srcs, scan)
        search = rxb.search
        keep = [np.asarray(
            [u for u, j in zip(scan[hit].tolist(), pos[hit].tolist())
             if search(enc[j])], np.uint64)]
        if len(colview.extra_srcs):
            em = np.isin(colview.extra_srcs, scan)
            keep.append(np.asarray(
                [u for u, j in zip(colview.extra_srcs[em].tolist(),
                                   np.nonzero(em)[0].tolist())
                 if search(colview.extra_enc[j])], np.uint64))
        inc_counter("query_regexp_batch_total")
        return np.unique(np.concatenate(keep))

    def _eval_match(self, fn: Function, candidates) -> np.ndarray:
        """Fuzzy match: trigram-index candidate narrowing + Levenshtein
        verify (ref worker/match.go uidsForMatch — the index UNION of
        the term's trigrams — then matchFuzzy; default max distance 8).
        Unindexed predicates fall back to a full scan, a superset of
        the reference (which rejects match() without @index(trigram))."""
        tab = self._tablet(fn.attr)
        if tab is None:
            return _EMPTY
        want = fn.args[0].value
        maxd = int(fn.args[1].value) if len(fn.args) > 1 else 8
        scan = candidates
        if scan is None:
            spec = get_tokenizer("trigram")
            if tab.schema.indexed and \
                    "trigram" in tab.schema.tokenizers:
                # candidates = UNION of the term's trigram buckets —
                # the reference's own candidate set (worker/match.go
                # uidsForMatch): values sharing no trigram with the
                # term are out, exactly like the reference. Terms too
                # short to produce a trigram keep the full scan.
                toks = tokens_for(Val(TypeID.STRING, want), spec)
                if toks:
                    # q-gram COUNT filter: a value within edit
                    # distance d of the term must share at least
                    # T - 3d of its T distinct trigrams (each edit
                    # destroys <= 3 windows) — at 21M this prunes the
                    # "shares any trigram" union from ~2M candidates
                    # to thousands. Compressed tier: posting blocks
                    # held by < need trigrams skip without decode.
                    need = max(1, len(toks) - 3 * maxd)
                    dec = self._trigram_tier(tab, "match", len(toks))
                    with _span("setops", fn="match",
                               pred=tab.pred) as tsp:
                        self._served_tier = None
                        scan = self._index_count_filter(
                            tab, [token_bytes(spec.ident, t)
                                  for t in toks], need,
                            dec.tier if dec is not None else None)
                        tsp["tier"] = self._served_tier or "postings"
                        tsp["n"] = int(len(scan))
                    self._record_outcome(dec, len(scan))
        if scan is None:
            scan = tab.src_uids(self.read_ts)
        batched = self._match_batch(tab, scan, want, maxd)
        if batched is not None:
            return batched
        return self._match_scan(tab, scan, want, maxd)

    def _match_scan(self, tab, scan, want: str, maxd: int) -> np.ndarray:
        # case-sensitive over code points, like the reference's
        # levenshteinDistance (worker/match.go:35 — no lowering)
        keep = []
        for u in scan.tolist():
            for p in tab.get_postings(u, self.read_ts):
                if _levenshtein(str(p.value.value), want,
                                maxd) <= maxd:
                    keep.append(u)
                    break
        return np.asarray(keep, dtype=np.uint64)

    def _match_batch(self, tab, scan, want: str,
                     maxd: int) -> Optional[np.ndarray]:
        with _span("match", pred=tab.pred, n=len(scan)):
            return self._match_batch_inner(tab, scan, want, maxd)

    def _match_batch_inner(self, tab, scan, want: str,
                           maxd: int) -> Optional[np.ndarray]:
        """Verify all candidates in ONE native call over the columnar
        string view (C loop + banded Levenshtein) instead of a per-uid
        get_postings round — 21M-regime q015 spends ~45s in the Python
        loop otherwise. Lang-tagged postings (absent from the untagged
        column) re-verify on the exact host path, so tagged-only and
        mixed uids match identically to _match_scan."""
        from dgraph_tpu import native as _native

        colview = self._colview(tab)
        if colview is None or colview.enc is None \
                or colview.tid not in (TypeID.STRING, TypeID.DEFAULT) \
                or not colview.extra_ok:
            return None
        if not _native.available():
            return self._match_batch_np(colview, scan, want, maxd)
        srcs, _tid, _data, enc = colview

        def masked(cand_srcs, payloads):
            offs = np.zeros(len(payloads) + 1, np.int64)
            np.cumsum([len(e) for e in payloads], out=offs[1:])
            blob = np.frombuffer(b"".join(payloads), np.uint8) \
                if payloads else np.zeros(1, np.uint8)
            m = _native.match_mask(want.encode("utf-8"), maxd, blob,
                                   offs)
            return None if m is None else cand_srcs[m == 1]

        pos, hit = _col_positions(srcs, scan)
        sel = pos[hit]
        blob, boffs = colview.payload_blob()
        m = _native.match_mask_idx(want.encode("utf-8"), maxd,
                                   blob, boffs, sel)
        if m is None:
            return None
        got = scan[hit][m == 1]
        keep = [got]
        if len(colview.extra_srcs):
            # lang-tagged payloads of candidate uids, same batch call
            em = np.isin(colview.extra_srcs, scan)
            egot = masked(colview.extra_srcs[em],
                          [colview.extra_enc[j]
                           for j in np.nonzero(em)[0].tolist()])
            if egot is None:
                return None
            keep.append(egot)
        inc_counter("query_match_batch_total")
        out = np.unique(np.concatenate(keep))
        return out

    def _match_batch_np(self, colview, scan, want: str,
                        maxd: int) -> Optional[np.ndarray]:
        """match() verify without the native extension: Myers
        bit-parallel edit distance (ops/editdist) over the cached byte
        matrix — every candidate in ~15 numpy ops per payload column
        instead of a per-uid python DP (the whole q015 budget when the
        C++ kernel isn't built). Byte scores equal codepoint distances
        only for ASCII rows; the kernel flags the rest (-1) and they
        re-verify on the exact path."""
        from dgraph_tpu.ops.editdist import levenshtein_scores

        if not want or not want.isascii() or len(want) > 63:
            return None  # outside the bit-parallel kernel's domain
        bc = colview.bytes_column()
        if bc is None:
            return None
        main_b, extra_b = bc

        m = len(want)

        def verify(cand_uids, barr, enc_list, idx):
            if not len(cand_uids):
                return cand_uids
            sub = np.ascontiguousarray(barr)
            mat = sub.view(np.uint8).reshape(
                len(sub), sub.dtype.itemsize)
            lens = np.char.str_len(sub)
            # length band: |len(b) - len(a)| > maxd means distance >
            # maxd. Byte length >= codepoint count, so the LOW side is
            # exact for every row; the high side is exact only for
            # ASCII rows — longer non-ASCII rows re-verify exactly
            low = lens < m - maxd
            up = lens > m + maxd
            run = ~(low | up)
            keep = np.zeros(len(cand_uids), bool)
            if run.any():
                ridx = np.nonzero(run)[0]
                scores = levenshtein_scores(want, mat[ridx],
                                            lens[ridx])
                if scores is None:
                    return None
                keep[ridx[(scores >= 0) & (scores <= maxd)]] = True
                for i in ridx[scores == -1].tolist():
                    s = enc_list[int(idx[i])].decode("utf-8")
                    if _levenshtein(s, want, maxd) <= maxd:
                        keep[i] = True
            if up.any():
                uidx = np.nonzero(up)[0]
                for i in uidx[(mat[uidx] >= 0x80).any(axis=1)].tolist():
                    s = enc_list[int(idx[i])].decode("utf-8")
                    if _levenshtein(s, want, maxd) <= maxd:
                        keep[i] = True
            return cand_uids[keep]

        pos, hit = _col_positions(colview.srcs, scan)
        sel = pos[hit]
        got = verify(scan[hit], main_b[sel], colview.enc, sel)
        if got is None:
            return None
        parts = [got]
        if len(colview.extra_srcs):
            em = np.isin(colview.extra_srcs, scan)
            eidx = np.nonzero(em)[0]
            egot = verify(colview.extra_srcs[em], extra_b[em],
                          colview.extra_enc, eidx)
            if egot is None:
                return None
            parts.append(np.unique(egot))
        inc_counter("query_match_batch_total")
        return setops.union_many(parts)

    def _eval_uid_in(self, fn: Function, candidates) -> np.ndarray:
        """uid_in(pred, uids) — also over reverse edges: uid_in(~pred, X)
        keeps uids that X points at via pred (ref worker/task.go
        handleUidPostings UidInFn; reverse attrs resolve like any
        predicate)."""
        if candidates is None:
            # filter-only, like the reference (query1:
            # TestUidInFunctionAtRoot rejects it at the root)
            raise GQLError(
                "the uid_in function is only valid in @filter")
        rev = fn.attr.startswith("~")
        tab = self._tablet(fn.attr[1:] if rev else fn.attr)
        if tab is None:
            return _EMPTY
        if rev and not tab.schema.reverse:
            raise GQLError(
                f"uid_in: no reverse index on {fn.attr[1:]!r} "
                f"(add @reverse to the schema)")
        targets = set(fn.uids)
        for vc in fn.needs_var:
            targets.update(self.uid_vars.get(vc.name, _EMPTY).tolist())
        # Flip the iteration: expand from the (few) TARGETS and
        # intersect with the candidate set instead of walking every
        # candidate's edge list — uid_in over 960k candidates at 21M
        # was ~0.8s of per-uid python. uid_in(~p, X) keeps uids X
        # points at via p (= dst(X)); uid_in(p, X) keeps uids pointing
        # AT some X (= reverse(X), when @reverse exists).
        flip = rev or tab.schema.reverse
        if flip and candidates is not None \
                and len(targets) > len(candidates):
            flip = False  # per-candidate walk is the cheaper direction
        if flip:
            expand = tab.get_dst_uids if rev else tab.get_reverse_uids
            parts = [expand(int(t), self.read_ts) for t in targets]
            parts = [p for p in parts if len(p)]
            if not parts:
                return _EMPTY
            valid = np.unique(np.concatenate(parts))
            # valid uids have a live edge by construction, so with no
            # candidate set they ARE the answer — don't materialize
            # the whole src/dst table just to intersect with a subset
            return valid if candidates is None \
                else _intersect(candidates, valid)
        scan = candidates if candidates is not None else (
            tab.dst_uids(self.read_ts) if rev
            else tab.src_uids(self.read_ts))
        getter = tab.get_reverse_uids if rev else tab.get_dst_uids
        keep = [u for u in scan.tolist()
                if targets & set(getter(u, self.read_ts).tolist())]
        return np.asarray(keep, dtype=np.uint64)

    def _eval_count_fn(self, fn: Function, candidates) -> np.ndarray:
        """gt(count(friend), 2) etc (ref task.go:1111 handleCompare +
        count index). Vectorized over the base count table; only
        overlay-touched uids fall back to per-uid MVCC counting.
        count(~pred) counts incoming edges (ref query2_test.go
        TestCountReverseFunc; needs @reverse)."""
        if fn.attr.startswith("~"):
            tab = self._tablet(fn.attr[1:])
            rps = tab.schema if tab is not None \
                else self.db.schema.get(fn.attr[1:])
            if candidates is None and rps is not None \
                    and not rps.count:
                raise GQLError(
                    f"need @count directive in schema for attribute "
                    f"{fn.attr[1:]!r} to serve count comparisons at "
                    "the root")
            if tab is None:
                return self._count_zero_case(fn, candidates)
            if not tab.schema.reverse:
                raise GQLError(
                    f"count(~{fn.attr[1:]}) needs @reverse on "
                    f"{fn.attr[1:]!r}")
            scan = candidates if candidates is not None else \
                tab.dst_uids(self.read_ts)

            def ok(n: int) -> bool:
                if fn.name == "between":
                    return int(fn.args[0].value) <= n <= \
                        int(fn.args[1].value)
                return _cmp(fn.name, n, int(fn.args[0].value))

            keep = np.asarray(
                [u for u in scan.tolist()
                 if ok(len(tab.get_reverse_uids(int(u),
                                                self.read_ts)))],
                dtype=np.uint64)
            keep.sort()
            return keep
        tab = self._tablet(fn.attr)
        ps = tab.schema if tab is not None \
            else self.db.schema.get(fn.attr)
        if candidates is None and ps is not None and not ps.count:
            # a root count comparison walks the count index: every
            # predicate — uid ones included — needs @count, and the
            # requirement is a SCHEMA property independent of whether
            # data exists yet (ref query4:TestDeleteAndReaddCount
            # "Need @count directive in schema for attr")
            raise GQLError(
                f"need @count directive in schema for attribute "
                f"{fn.attr!r} to serve count comparisons at the root")
        if tab is None:
            # every candidate has count 0: let the zero-case decide
            # whether 0 satisfies the comparison (ge(count(x), 0) does)
            return self._count_zero_case(fn, candidates)
        want = int(fn.args[0].value)
        cmp_name = fn.name
        if fn.name == "between":
            # between(count(p), lo, hi): vector range mask; the scalar
            # fallback closes over the same bounds
            lo, hi = want, int(fn.args[1].value)
            vec = lambda a, b: (a >= lo) & (a <= hi)  # noqa: E731
        elif fn.name in _CMP_VEC:
            vec = _CMP_VEC[fn.name]
        else:
            raise GQLError(f"bad count comparison {fn.name}")
        scan = candidates if candidates is not None else \
            tab.src_uids(self.read_ts)
        if not len(scan):
            return _EMPTY
        touched = tab.overlay_srcs(self.read_ts) if tab.dirty() \
            else set()
        srcs, counts = tab.count_table()
        if touched:
            tarr = np.fromiter(touched, np.uint64, len(touched))
            dirty_mask = np.isin(scan, tarr)
            clean = scan[~dirty_mask]
            dirty = scan[dirty_mask]
        else:
            clean, dirty = scan, scan[:0]
        # clean uids: one searchsorted lookup + one vector compare
        if len(srcs):
            idx = np.clip(np.searchsorted(srcs, clean), 0, len(srcs) - 1)
            hit = srcs[idx] == clean
            cnts = np.where(hit, counts[idx], 0)
        else:
            cnts = np.zeros(len(clean), np.int64)
        ok = vec(cnts, want)
        keep = [clean[ok]]
        # overlay-touched uids: exact per-uid MVCC count
        keep.append(np.asarray(
            [u for u in dirty.tolist()
             if vec(tab.count_of(u, self.read_ts), want)],
            dtype=np.uint64))
        out = np.concatenate(keep)
        out.sort()
        return out

    def _count_zero_case(self, fn, candidates):
        if candidates is None:
            return _EMPTY
        if fn.name == "between":
            lo, hi = int(fn.args[0].value), int(fn.args[1].value)
            return candidates if lo <= 0 <= hi else _EMPTY
        if _cmp(fn.name, 0, int(fn.args[0].value)):
            return candidates
        return _EMPTY

    def _eval_var_fn(self, fn: Function, candidates) -> np.ndarray:
        """eq/ineq over val(v) or len(v) (ref query.go shortest var
        filtering + parser IsValueVar)."""
        if fn.is_len_var:
            vc = fn.needs_var[0]
            n = len(self.uid_vars.get(vc.name, _EMPTY))
            if vc.name in self.value_vars:
                n = len(self.value_vars[vc.name])
            ok = _cmp(fn.name, n, int(fn.args[0].value))
            if candidates is None:
                return _EMPTY
            return candidates if ok else _EMPTY
        vc = fn.needs_var[0]
        vmap = self.value_vars.get(vc.name, {})
        want_raw = fn.args[0].value if fn.args else None
        scan = candidates if candidates is not None else _var_domain(vmap)
        if isinstance(vmap, ColVar) and not vmap.frac \
                and vmap.tid != TypeID.DATETIME \
                and fn.name in _CMP_VEC:
            # columnar filter: one gather + one vector compare (ref
            # query.go val-var filters; the dict walk remains only for
            # mixed-typed math results where per-uid tids differ)
            vtid = TypeID.BOOL if vmap.isbool else vmap.tid
            try:
                want = convert(Val(TypeID.DEFAULT, want_raw), vtid).value
            except ValueError:
                return _EMPTY
            uids, vals = vmap.gather(scan)
            if vtid == TypeID.BOOL:
                vals, want = vals.astype(bool), bool(want)
            ok = _CMP_VEC[fn.name](vals, want)
            return uids[ok]
        keep = []
        for u in scan.tolist():
            v = vmap.get(u)
            if v is None:
                continue
            try:
                want = convert(Val(TypeID.DEFAULT, want_raw), v.tid).value
            except ValueError:
                continue
            if _cmp(fn.name, v.value, want):
                keep.append(u)
        return np.asarray(keep, dtype=np.uint64)

    # ------------------------------------------------------------------
    # filters (ref query.go:2078)
    # ------------------------------------------------------------------

    def _eval_filter(self, ft: FilterTree, candidates: np.ndarray
                     ) -> np.ndarray:
        if ft.func is not None:
            return self._eval_func(ft.func, candidates)
        if ft.op == "and":
            out = candidates
            for c in ft.children:
                out = self._eval_filter(c, out)
            return out
        if ft.op == "or":
            # k-way: one merge over every branch instead of a pairwise
            # accumulator re-sort per child (ref algo.MergeSorted)
            return self._union_many(
                [self._eval_filter(c, candidates)
                 for c in ft.children])
        if ft.op == "not":
            sub = self._eval_filter(ft.children[0], candidates)
            return _difference(candidates, sub)
        raise GQLError(f"bad filter node {ft.op!r}")

    # ------------------------------------------------------------------
    # traversal (ref query.go:1902 ProcessGraph)
    # ------------------------------------------------------------------

    def _flat_block_eligible(self, i: int, gq: GraphQuery) -> bool:
        """Whether block `i` may take the compiled flat child
        expansion: no variables in or out, no block-level modifiers,
        and every child a plain scalar leaf (or bare `uid`). Pure
        structure + schema, so the plan binds the verdict once per
        (skeleton, epoch); anything this misses (a predicate created
        after compile stays on the interpreter until the next epoch)
        costs only the fast path, never correctness."""
        if (gq.alias == "var" or gq.cascade or gq.normalize
                or gq.ignore_reflex or gq.is_count or gq.is_empty
                or gq.var or gq.facet_var or gq.facets is not None
                or gq.facets_filter is not None):
            return False
        needs, provides = self._block_vars_of(i, gq)
        if needs or provides:
            return False
        if any(o.attr.startswith(("val(", "facet:")) for o in gq.order):
            return False
        if not gq.children:
            return False
        for c in gq.children:
            if (c.expand or c.children or c.var or c.facet_var
                    or c.facets is not None or c.facets_filter is not None
                    or c.filter is not None or c.order or c.is_count
                    or c.math is not None or c.agg_func or c.agg_pred
                    or c.is_internal or c.cascade or c.normalize
                    or c.langs or c.recurse is not None
                    or c.shortest is not None or c.is_groupby
                    or c.checkpwd_pwd is not None or c.is_empty):
                return False
            if c.attr == "uid":
                continue
            if c.attr.startswith(("~", "val(", "fragment/")) \
                    or c.attr == "math":
                return False
            ps = self.db.schema.get(c.attr)
            if ps is None or ps.list_ or ps.value_type == TypeID.UID:
                return False
        return True

    def _expand_children_flat(self, parent: ExecNode,
                              children: list[GraphQuery],
                              src: np.ndarray):
        """Straight-line child expansion for plan-proven flat blocks:
        semantically the scalar tail of _process_child (columnar
        gather, exact posting-walk fallback) with the generic
        dispatch, sibling scheduling and per-child span bookkeeping
        compiled away. The level checkpoint stays — deadlines and the
        chaos failpoint fire exactly like the interpreted path."""
        self._checkpoint(
            f"level {parent.gq.alias or parent.gq.attr}")
        for cgq in children:
            cn = ExecNode(cgq, src=src)
            if cgq.attr != "uid":
                cn.tablet = self._tablet(cgq.attr)
                if cn.tablet is not None:
                    cn.lazy_cols = True
            parent.children.append(cn)

    def _ensure_child_values(self, ch: ExecNode):
        """Materialize a lazily-deferred scalar child for consumers
        that need per-uid values (the dict emitters); the columnar
        JSON emitter never calls this on clean tablets. Reads the same
        read_ts snapshot the eager path would have — MVCC makes the
        deferral invisible."""
        if not ch.lazy_cols:
            return
        ch.lazy_cols = False
        tab, src = ch.tablet, ch.src
        cv = self._colvals_for_emit(tab, ch.gq, src)
        if cv is not None:
            ch.col_vals = cv
            return
        if hasattr(tab, "prefetch_postings"):
            tab.prefetch_postings(src)
        get = tab.get_postings
        for u in src.tolist():
            ps = get(u, self.read_ts)
            if ps:
                ch.values[u] = ps

    def _expand_children(self, parent: ExecNode,
                         children: list[GraphQuery], src: np.ndarray):
        with _span("expand", level=parent.gq.alias or parent.gq.attr,
                   n=len(src)):
            self._expand_children_inner(parent, children, src)

    def _expand_children_inner(self, parent: ExecNode,
                               children: list[GraphQuery],
                               src: np.ndarray):
        # one traversal level (incl. @cascade recursion into subtrees)
        self._checkpoint(f"level {parent.gq.alias or parent.gq.attr}")
        children = self._expand_expand(children, src)
        # dependency-ordered processing: a child consuming a var that a
        # SIBLING subtree binds (facet var, deeper value var) must run
        # after that sibling regardless of listing order — emission
        # keeps the listed order. Unresolvable needs fall back to the
        # listed order (outer blocks / genuinely-undefined vars).
        nodes: dict[int, ExecNode] = {}
        prev_sib = getattr(self, "_sibling_nodes", None)
        self._sibling_nodes = nodes
        try:
            pending = list(enumerate(children))
            while pending:
                progressed = False
                for i, cgq in list(pending):
                    unmet = [vc.name for vc in self._all_needs(cgq)
                             if not self._var_defined(vc.name)
                             and vc.name
                             in getattr(self, "_block_vars", ())]
                    if not unmet:
                        pending.remove((i, cgq))
                        nodes[i] = self._process_child(cgq, src)
                        progressed = True
                if not progressed:
                    for i, cgq in pending:
                        nodes[i] = self._process_child(cgq, src)
                    break
        finally:
            self._sibling_nodes = prev_sib
        for i in range(len(children)):
            parent.children.append(nodes[i])

    def _expand_ownership_guard(self, pname: str) -> None:
        """Ownership check at expansion time: a predicate reached only
        via expand() never appears in the query text, so the server's
        _misroute_guard_query screen cannot see it — without this
        hook, a stale-routed expand racing a tablet cutover silently
        under-reports the moved predicate's edges for the one
        in-flight query (the router's next map fetch routes
        correctly). Same typed failure as the server guard:
        TabletMisrouted carries the forwarding hint. Zero-cost until
        this engine has actually moved a tablet out or holds a split
        hash range."""
        moved = self.db.moved_out
        split = self.db.split_partial
        if not moved and not split:
            return
        if pname in moved and pname not in self.db.tablets:
            from dgraph_tpu.cluster.errors import TabletMisrouted
            raise TabletMisrouted(pname, moved[pname])
        if pname in split:
            from dgraph_tpu.cluster.errors import TabletMisrouted
            raise TabletMisrouted(
                pname, None,
                f"tablet {pname!r} is split across groups; refresh "
                "the tablet map and fan out per sub-tablet")

    def _expand_expand(self, children: list[GraphQuery],
                       src: np.ndarray,
                       keep_uid_leaves: bool = False
                       ) -> list[GraphQuery]:
        """expand(_all_) / expand(Type) (ref query.go:1812
        expandSubgraph). `keep_uid_leaves` is the @recurse mode: the
        recursion traverses expanded uid predicates itself, so they
        stay even without a nested block."""
        out = []
        for c in children:
            if not c.expand:
                out.append(c)
                continue
            preds: list[str] = []
            if c.expand == "_all_":
                type_tab = self._tablet(PREDICATE_TYPE)
                tnames = set()
                if type_tab is not None:
                    for u in src.tolist():
                        for p in type_tab.get_postings(u, self.read_ts):
                            tnames.add(str(p.value.value))
                for tn in sorted(tnames):
                    td = self.db.schema.get_type(tn)
                    if td:
                        preds.extend(td.fields)
                if not tnames:  # no type system in play: expand schema
                    preds = [p for p in self.db.schema.predicates()
                             if not p.startswith("dgraph.")]
            else:
                for tname in c.expand.split(","):
                    td = self.db.schema.get_type(tname)
                    if td:
                        preds.extend(td.fields)
            seen = set()
            for pname in preds:
                if pname in seen:
                    continue
                seen.add(pname)
                self._expand_ownership_guard(pname)
                sub = GraphQuery(attr=pname, children=list(c.children),
                                 filter=c.filter)
                tab = self.db.tablets.get(pname)
                if not c.children and not keep_uid_leaves \
                        and tab is not None \
                        and tab.schema.value_type == TypeID.UID:
                    # expand() without a nested block: expanded UID
                    # predicates emit nothing (ref query4:
                    # TestNestedExpandAll — the innermost expand
                    # yields only scalars; `expand(_all_) { uid }` is
                    # how the suite asks for edge targets)
                    continue
                if c.filter is not None and (
                        tab is None
                        or tab.schema.value_type != TypeID.UID):
                    # expand() @filter filters the expanded EDGES'
                    # targets; scalar predicates have none and drop
                    # out entirely (ref query4_test.go
                    # TestTypeFilterAtExpand: only `owner` survives)
                    continue
                if tab is not None and tab.schema.lang \
                        and tab.schema.value_type != TypeID.UID:
                    # expanded @lang preds emit every language under
                    # attr@lang keys (ref query4_test.go
                    # TestTypeExpandLang: model + model@jp)
                    sub.langs = ["*"]
                out.append(sub)
        return out

    def _process_child(self, gq: GraphQuery, src: np.ndarray) -> ExecNode:
        node = ExecNode(gq, src=src)
        attr = gq.attr
        if attr == "uid" and not gq.is_count:
            # bare `uid` / `x as uid`: binds/emits the enclosing uid set
            if gq.var:
                self.uid_vars[gq.var] = src
            return node
        if gq.is_internal or attr == "math" or gq.agg_func \
                or attr.startswith("val(") or attr.startswith("fragment/"):
            self._process_internal(node)
            return node
        node.reverse = attr.startswith("~")
        if node.reverse:
            attr = attr[1:]
        tab = self._tablet(attr)
        node.tablet = tab
        if tab is None:
            if gq.var:
                self.uid_vars[gq.var] = _EMPTY
            return node
        if node.reverse and not tab.schema.reverse:
            raise GQLError(
                f"reverse edges are not defined for predicate {attr!r} "
                f"(add @reverse to the schema)")
        if tab.schema.value_type == TypeID.UID and not node.reverse or \
                (node.reverse and tab.schema.reverse):
            if gq.is_count and gq.filter is None and not gq.var \
                    and gq.facets_filter is None and not gq.facet_var \
                    and not gq.children \
                    and not hasattr(tab, "prefetch_edges"):
                # count-only child on a LOCAL tablet: per-parent
                # degrees suffice — never materialize (or device-
                # expand) the destination union (ref worker/task.go
                # count tasks read the count index, not the posting
                # lists). Federated proxies keep the edge-prefetch
                # path: their counts ride the level's batched edge
                # cache with zero extra RPCs
                for u in src.tolist():
                    node.counts[u] = self._child_count(
                        tab, u, node.reverse)
                return node
            if hasattr(tab, "prefetch_edges"):
                # federated tablet: one batched task RPC warms every
                # per-parent edge read this block (and its emission)
                # will do (ref worker/task.go per-attr task batching)
                tab.prefetch_edges(src, node.reverse)
            if hasattr(tab, "prefetch_facets") and (
                    gq.facets_filter is not None or gq.facet_var
                    or (gq.facets is not None and not gq.first
                        and not gq.offset and not gq.after)
                    or any(o.attr.startswith("facet:")
                           for o in (gq.order or ()))):
                # federated: one facets RPC per (predicate, level) for
                # the consumers that must see EVERY edge's facets
                # (filters, facet vars, facet ordering) — edges are
                # already batch-cached above, so assembling the
                # level's pairs costs no extra round trips (ref
                # worker/task.go FacetParams on the per-attr task).
                # Plain @facets emission prefetches per parent at the
                # emit site instead, after pagination.
                pairs = []
                for u in src.tolist():
                    dsts = (tab.get_reverse_uids(u, self.read_ts)
                            if node.reverse
                            else tab.get_dst_uids(u, self.read_ts))
                    if node.reverse:
                        pairs.extend((int(d), int(u))
                                     for d in dsts.tolist())
                    else:
                        pairs.extend((int(u), int(d))
                                     for d in dsts.tolist())
                tab.prefetch_facets(pairs)
            # one per-parent edge pass serves both the dest union and
            # every facet-var binding (avoids re-walking high-fanout
            # edge lists once per facet key)
            edge_dsts: dict[int, np.ndarray] | None = None
            if gq.facets_filter is not None or gq.facet_var:
                edge_dsts = {}
                for u in src.tolist():
                    if gq.facets_filter is not None:
                        # @facets(eq(k, v)) drops EDGES, so the union
                        # must be built per-parent (ref worker/
                        # task.go:1806 applyFacetsTree, also edge-wise)
                        dsts = self._edge_dsts_facet_filtered(
                            tab, int(u), node.reverse, gq.facets_filter)
                    else:
                        dsts = (tab.get_reverse_uids(u, self.read_ts)
                                if node.reverse
                                else tab.get_dst_uids(u, self.read_ts))
                    edge_dsts[int(u)] = dsts
            if gq.facets_filter is not None:
                parts = [d for d in edge_dsts.values() if len(d)]
                dest = np.unique(np.concatenate(parts)) if parts \
                    else _EMPTY.copy()
            else:
                dest = self._expand_level(tab, src, node.reverse)
            if gq.filter is not None:
                dest = self._eval_filter(gq.filter, dest)
            node.dest = dest
            if gq.facet_var:
                self._bind_facet_vars(tab, src, node.reverse, gq,
                                      edge_dsts)
            if gq.var:
                if gq.first is not None or gq.offset or gq.after:
                    # `L as friend(first:2, orderasc: dob)`: the var
                    # holds the PAGINATED per-parent edge windows, not
                    # the full expansion (ref query0:
                    # TestUseVarsMultiOrder). Order alone never
                    # changes the union — only a cut window does.
                    parts = []
                    get = tab.get_reverse_uids if node.reverse \
                        else tab.get_dst_uids
                    facet_orders = [o for o in gq.order
                                    if o.attr.startswith("facet:")]
                    for u in src.tolist():
                        # facet-filtered edges were already computed;
                        # a raw re-read would resurrect excluded edges
                        dsts = edge_dsts[int(u)] \
                            if edge_dsts is not None \
                            else get(u, self.read_ts)
                        dsts = _intersect(dsts, dest) \
                            if len(dest) else _EMPTY
                        if not len(dsts):
                            continue
                        if facet_orders:
                            dsts = self._order_paginate_facets(
                                gq, tab, int(u), node.reverse, dsts,
                                facet_orders)
                        else:
                            dsts = self._order_paginate(gq, dsts)
                        if len(dsts):
                            parts.append(np.asarray(dsts,
                                                    dtype=np.uint64))
                    self.uid_vars[gq.var] = np.unique(
                        np.concatenate(parts)) if parts else _EMPTY
                else:
                    self.uid_vars[gq.var] = dest
            if gq.is_count:
                if gq.filter is not None:
                    # count(pred @filter(...)): per-parent size of the
                    # edge list INTERSECTED with the filtered union
                    # (ref TestQueryEmptyRoomsWithTermIndex)
                    get = tab.get_reverse_uids if node.reverse \
                        else tab.get_dst_uids
                    for u in src.tolist():
                        node.counts[u] = len(_intersect(
                            get(u, self.read_ts), dest))
                else:
                    if hasattr(tab, "prefetch_counts"):
                        tab.prefetch_counts(src, node.reverse)
                    for u in src.tolist():
                        node.counts[u] = self._child_count(
                            tab, u, node.reverse)
                if gq.var:
                    # `s as count(friend)` binds a per-parent value
                    # var, zero for parents with no edges (ref
                    # query0_test.go TestQueryVarValAggOrderDesc: the
                    # friendless uid still carries count 0)
                    self.value_vars[gq.var] = {
                        int(u): Val(TypeID.INT, node.counts.get(u, 0))
                        for u in src.tolist()}
            elif gq.is_groupby:
                # emission groups per parent; var assignment aggregates
                # over the whole block's edge set now so later blocks
                # can consume it
                self._bind_groupby_vars(gq, dest)
            else:
                self._expand_children(node, gq.children, dest)
        else:
            # scalar predicate: fetch values for src uids. A pure
            # var-binding block (var(func: ...) { v as pred }) never
            # emits, so the columnar fast path below can skip this
            # per-uid posting walk entirely — at the 21M regime this
            # loop dominates var-heavy aggregation queries (q020)
            if self._bind_var_columnar(node, gq, tab, src):
                return node
            if self._bind_var_emit_columnar(node, gq, tab, src):
                return node
            cv = self._colvals_for_emit(tab, gq, src)
            if cv is not None:
                # columnar emission: json-ready values gathered in one
                # pass — the per-uid get_postings walk below was the
                # bulk of flat-block emission at 21M (q003)
                node.col_vals = cv
                return node
            if hasattr(tab, "prefetch_postings"):
                tab.prefetch_postings(src)
            for u in src.tolist():
                ps = tab.get_postings(u, self.read_ts)
                if ps:
                    node.values[u] = ps
            if gq.is_count:
                for u in src.tolist():
                    node.counts[u] = len(node.values.get(u, ()))
            if gq.var:
                vmap = {}
                for u, ps in node.values.items():
                    sel = self._select_posting(ps, gq.langs)
                    if sel is not None:
                        vmap[u] = self._typed(tab, sel)
                self.value_vars[gq.var] = vmap
            if gq.facet_var:
                for key, varname in gq.facet_var.items():
                    vmap = {}
                    for u, ps in node.values.items():
                        sel = self._select_posting(ps, gq.langs)
                        if sel is not None and key in sel.facets:
                            vmap[u] = sel.facets[key]
                    self.value_vars[varname] = vmap
        return node

    def _colvals_for_emit(self, tab, gq, src: np.ndarray
                          ) -> Optional[dict]:
        """uid -> json-ready value for a FLAT scalar child (no langs,
        lists, facets, counts or var binding), gathered through the
        cached column view — replaces the per-uid posting walk both at
        process time and inside _emit_uid/_emit_value.  None keeps the
        exact path."""
        if gq.langs or gq.is_count or gq.var or gq.facet_var \
                or gq.facets is not None or gq.facets_filter is not None \
                or gq.children or tab.schema.list_:
            return None
        colview = self._colview(tab)
        if colview is None:
            return None
        srcs, tid, data, enc = colview
        pos, hit = _col_positions(srcs, src)
        sel = pos[hit]
        uids = src[hit].tolist()
        if data is not None:
            if tid == TypeID.BOOL:
                vals = [bool(v) for v in data[sel].tolist()]
            else:
                vals = data[sel].tolist()
        else:
            # STRING/DEFAULT/DATETIME columns carry the exact
            # to_json_value payload (isoformat for datetimes)
            dec = colview.decoded()
            vals = [dec[j] for j in sel.tolist()]
        return dict(zip(uids, vals))

    def _bind_var_columnar(self, node: ExecNode, gq, tab,
                           src: np.ndarray) -> bool:
        """Vectorized value-var binding over the clean tablet's column
        view: one searchsorted + array gather instead of a per-uid
        get_postings loop. Only for blocks whose values are consumed
        EXCLUSIVELY through the var (nothing emits, counts, or reads
        facets), with untagged single values — everything else keeps
        the exact posting path."""
        if not gq.var or gq.langs or gq.is_count or gq.facet_var \
                or gq.children or gq.facets is not None \
                or getattr(self, "_block_emits", True):
            return False
        colview = self._colview(tab)
        if colview is None or len(colview.extra_srcs) \
                or colview.tid == TypeID.DATETIME:
            # lang-tagged postings need _select_posting semantics; a
            # DATETIME column caches ISO strings but the var needs the
            # datetime value — both keep the per-posting walk
            return False
        srcs, tid, data, enc = colview
        pos, hit = _col_positions(srcs, src)
        sel = pos[hit]
        inc_counter("query_columnar_var_bind_total")
        if data is not None:
            # numeric var (data arrays exist only for INT/FLOAT/BOOL):
            # stays columnar END-TO-END — math, agg, val() filters and
            # order keys consume the arrays; a dict materializes only
            # if a legacy consumer asks
            self.value_vars[gq.var] = make_colvar(src[hit], data[sel],
                                                  tid)
        else:
            dec = colview.decoded()
            self.value_vars[gq.var] = {
                u: Val(tid, dec[j])
                for u, j in zip(src[hit].tolist(), sel.tolist())}
        return True

    def _bind_var_emit_columnar(self, node: ExecNode, gq, tab,
                                src: np.ndarray) -> bool:
        """Emitting block that ALSO binds a var (d as pred): serve the
        emission from the column view AND bind the var columnarly —
        datetime vars carry (float epoch seconds, exact objects) so
        math/since() stays vectorized (ref query/math.go:213,
        aggregator.go applySince) while materialization stays exact.
        The q046 shape walked 1M postings per query otherwise."""
        if not gq.var or gq.langs or gq.is_count or gq.facet_var \
                or gq.children or gq.facets is not None \
                or tab.schema.list_:
            return False
        colview = self._colview(tab)
        if colview is None or len(colview.extra_srcs):
            return False
        srcs, tid, data, enc = colview
        pos, hit = _col_positions(srcs, src)
        sel = pos[hit]
        bound = src[hit]
        if data is not None:
            vmap = make_colvar(bound, data[sel], tid)
            if vmap is None:
                return False
            if tid == TypeID.BOOL:
                vals = [bool(v) for v in data[sel].tolist()]
            else:
                vals = data[sel].tolist()
        elif tid == TypeID.DATETIME and colview.dt_secs is not None:
            vmap = ColVar(bound, colview.dt_secs[sel], TypeID.DATETIME,
                          objs=colview.dt_objs[sel])
            dec = colview.decoded()
            vals = [dec[j] for j in sel.tolist()]
        elif tid in (TypeID.STRING, TypeID.DEFAULT):
            dec = colview.decoded()
            vals = [dec[j] for j in sel.tolist()]
            vmap = {u: Val(tid, v)
                    for u, v in zip(bound.tolist(), vals)}
        else:
            return False
        inc_counter("query_columnar_var_bind_total")
        self.value_vars[gq.var] = vmap
        node.col_vals = dict(zip(bound.tolist(), vals))
        return True

    # -- facets (ref worker/task.go:1806 applyFacetsTree,
    #    types/facets/utils.go:129) --

    def _edge_dsts_facet_filtered(self, tab: Tablet, u: int,
                                  reverse: bool, ft) -> np.ndarray:
        dsts = (tab.get_reverse_uids(u, self.read_ts) if reverse
                else tab.get_dst_uids(u, self.read_ts))
        if not len(dsts):
            return dsts
        keep = []
        for d in dsts.tolist():
            fsrc, fdst = (int(d), u) if reverse else (u, int(d))
            if self._eval_facet_tree(
                    ft, tab.get_facets(fsrc, fdst, self.read_ts)):
                keep.append(d)
        return np.asarray(keep, dtype=np.uint64)

    def _eval_facet_tree(self, ft: FilterTree, facets: dict) -> bool:
        """Boolean facet filter over one edge's facet map."""
        if ft.func is not None:
            fn = ft.func
            fv = facets.get(fn.attr)
            if fv is None:
                return False
            if fn.name in ("allofterms", "anyofterms"):
                have = set(str(fv.value).lower().split())
                want = set(" ".join(str(a.value)
                                    for a in fn.args).lower().split())
                return want <= have if fn.name == "allofterms" \
                    else bool(want & have)
            want_raw = fn.args[0].value if fn.args else None
            try:
                want = convert(Val(TypeID.DEFAULT, want_raw), fv.tid).value
            except ValueError:
                return False
            try:
                return _cmp(fn.name, fv.value, want)
            except TypeError:
                return False
        if ft.op == "and":
            return all(self._eval_facet_tree(c, facets)
                       for c in ft.children)
        if ft.op == "or":
            return any(self._eval_facet_tree(c, facets)
                       for c in ft.children)
        if ft.op == "not":
            return not self._eval_facet_tree(ft.children[0], facets)
        raise GQLError(f"bad facet filter node {ft.op!r}")

    def _bind_facet_vars(self, tab: Tablet, src: np.ndarray,
                         reverse: bool, gq: GraphQuery,
                         edge_dsts: dict[int, np.ndarray]):
        """@facets(v as key): dst uid -> facet value; numeric values
        sum over multiple in-edges (ref query.go valueVarAggregation
        over facet vars). `edge_dsts` is the (already facet-filtered)
        per-parent edge map built by _process_child — one edge pass
        binds every key."""
        vmaps: dict[str, dict[int, Val]] = {k: {} for k in gq.facet_var}
        for u in src.tolist():
            for d in edge_dsts.get(int(u), _EMPTY).tolist():
                fsrc, fdst = (int(d), u) if reverse else (u, int(d))
                facets = tab.get_facets(fsrc, fdst, self.read_ts)
                for key in gq.facet_var:
                    fv = facets.get(key)
                    if fv is None:
                        continue
                    vmap = vmaps[key]
                    prev = vmap.get(int(d))
                    if prev is not None and isinstance(
                            fv.value, (int, float)) and isinstance(
                            prev.value, (int, float)) and not isinstance(
                            fv.value, bool):
                        vmap[int(d)] = Val(fv.tid, prev.value + fv.value)
                    else:
                        vmap[int(d)] = fv
        for key, varname in gq.facet_var.items():
            self.value_vars[varname] = vmaps[key]

    def _child_count(self, tab: Tablet, uid: int, reverse: bool) -> int:
        # count_of serves both directions so a federated proxy answers
        # from its batch-prefetched count cache instead of shipping
        # whole reverse edge lists (ref worker/task.go count tasks)
        return tab.count_of(uid, self.read_ts, reverse=reverse)

    def _typed(self, tab: Tablet, p) -> Val:
        t = tab.schema.value_type
        if t == TypeID.DEFAULT:
            return p.value
        try:
            return convert(p.value, t)
        except ValueError:
            return p.value

    def _select_posting(self, ps, langs: list[str]):
        """Language preference list (ref types/valForLang semantics):
        first matching lang wins; '.' means any; no langs -> untagged
        first, else any."""
        if langs:
            for lg in langs:
                if lg == ".":
                    return ps[0]
                if lg == "*":
                    # multi-key expansion happens in the emit paths;
                    # single-posting consumers (var binding, sort
                    # keys) fall back to any-language
                    return ps[0]
                for p in ps:
                    if p.lang == lg:
                        return p
            return None
        for p in ps:
            if not p.lang:
                return p
        return None

    # -- the hot loop: one level of expansion --

    def _expand_level(self, tab: Tablet, src: np.ndarray,
                      reverse: bool) -> np.ndarray:
        dev = None
        if self.db.prefer_device:
            dev = self._device_expand(tab, src, reverse)
        if dev is not None:
            return dev
        return tab.expand_frontier(src, self.read_ts, reverse)

    # host-side cost constants for the device/host tier choice (coarse
    # per-element figures for the vectorized numpy paths; the fixed
    # side of the comparison is the MEASURED dispatch RTT, so only the
    # order of magnitude matters here)
    _HOST_PER_FRONTIER_UID = 2e-7     # prefetched posting fetch per
    #                                   parent (round-5 measured: the
    #                                   q049/q067 host expansions run
    #                                   ~7.5x faster than the old
    #                                   1.5e-6 estimate)
    _HOST_PER_EDGE = 4e-8             # np.unique share per edge
    # measured device-compute/host-compute ratios per dispatch family
    # (round-5 21M run; see _device_worth) — re-measure HERE, the call
    # sites only reference these
    _DEVICE_RATIO_ORDER = 0.9         # multisort/count-page ~parity
    _DEVICE_RATIO_RANGE = 0.5         # range-scan mask
    _DEVICE_RATIO_EXPAND = 0.5        # one-shot expand incl. transfer
    _HOST_PER_ORDER_KEY = 2e-7        # columnar key gather + lexsort
    #                                   share per uid (clean tablets
    #                                   read cached sort-key arrays)
    _HOST_PER_RANGE_VAL = 5e-9        # cached-array mask per value

    def _device_worth(self, est_host_seconds: float,
                      device_ratio: float = 0.0) -> bool:
        """Use the device only when the estimated host cost clears the
        measured dispatch round-trip PLUS the device's own compute
        (ref algo/uidlist.go:151's size-ratio strategy pick, applied
        to the host/accelerator boundary). `device_ratio` is the
        measured device-compute/host-compute ratio for the family:
        0 models a device that answers instantly (batched traversal —
        the digest BFS runs 11-14x host), while the round-5 21M run
        measured ~0.95 for the 1M-row multisort/count-page family
        (device_ms - RTT ≈ host_ms) — dispatching those buys nothing
        but the round-trip, so their sites pass ~0.9 and stay host
        until the host estimate dwarfs the RTT. `device_min_edges
        <= 1` forces the tier — the tests' and operators' explicit
        override."""
        if self.db.device_min_edges <= 1:
            return True
        if not self.db.device_is_accelerator():
            # a CPU 'device' backend shares the host's silicon: XLA-CPU
            # dispatches can only lose to the numpy columnar tier
            return False
        margin = est_host_seconds * (1.0 - device_ratio)
        return margin > self.db.device_dispatch_seconds() * 1.25

    def _device_expand(self, tab: Tablet, src: np.ndarray,
                       reverse: bool = False) -> Optional[np.ndarray]:
        from dgraph_tpu.engine.device_cache import (
            device_adjacency, device_radjacency,
            device_sharded_adjacency, expand_np,
        )

        if len(src) == 0:
            return None
        if self.db.mesh is not None:
            # uid-range-sharded tier first: a predicate too big for one
            # chip expands via shard_map over the mesh (SURVEY §5.7).
            # Capacity, not latency: the cost gate below never blocks
            # this tier — the single-chip/host choice is moot for a
            # tablet that exceeds one chip.
            sadj = device_sharded_adjacency(self.db, tab, self.read_ts,
                                            reverse)
            if sadj is not None:
                from dgraph_tpu.parallel.dist_graph import \
                    expand_sharded_np
                inc_counter("query_sharded_expand_total",
                            labels={"dir": "rev" if reverse else "fwd"})
                return expand_sharded_np(self.db.mesh, sadj, src)
        store = tab.reverse if reverse else tab.edges
        deg = tab.edge_count(reverse) / max(1, len(store))
        if not self._device_worth(
                len(src) * (self._HOST_PER_FRONTIER_UID
                            + deg * self._HOST_PER_EDGE),
                # the one-shot expand ships src + result across the
                # dispatch boundary; round-5 21M run: q049's lone
                # gated expand paid the RTT for no compute win
                device_ratio=self._DEVICE_RATIO_EXPAND):
            return None
        adj = (device_radjacency if reverse else device_adjacency)(
            self.db, tab, self.read_ts, allow_dirty=True)
        if adj is None:
            return None
        if tab.dirty():
            # overlay-on-device (ref posting/mvcc.go immutable+mutable
            # layer split): the tile answers rows the overlay never
            # touched; overlay-touched frontier uids take the exact
            # host MVCC path, results union
            touched = tab.overlay_srcs(self.read_ts, reverse=reverse)
            if touched:
                mask = np.isin(src, np.fromiter(
                    touched, dtype=np.uint64, count=len(touched)))
                clean, dirty = src[~mask], src[mask]
                parts = []
                if len(clean):
                    parts.append(expand_np(adj, clean))
                if len(dirty):
                    parts.append(tab.expand_frontier(
                        dirty, self.read_ts, reverse))
                inc_counter("query_device_overlay_expand_total",
                            labels={"dir": "rev" if reverse else "fwd"})
                if not parts:
                    return _EMPTY.copy()
                return np.unique(np.concatenate(parts)) \
                    if len(parts) > 1 else parts[0]
        inc_counter("query_device_expand_total",
                    labels={"dir": "rev" if reverse else "fwd"})
        return expand_np(adj, src)

    # ------------------------------------------------------------------
    # internal nodes: uid/count(uid)/val()/aggregations/math
    # ------------------------------------------------------------------

    def _process_internal(self, node: ExecNode):
        gq = node.gq
        if gq.agg_func:
            if not gq.needs_var:
                # max(pred): only valid inside @groupby (ref
                # groupby.go aggregateGroup; elsewhere the reference
                # rejects it)
                raise GQLError(
                    f"aggregation {gq.agg_func}({gq.agg_pred}) is "
                    "only allowed inside @groupby; use "
                    f"{gq.agg_func}(val(var)) here")
            vc = gq.needs_var[0]
            vmap = self.value_vars.get(vc.name, {})
            src = node.src
            if len(src) \
                    and self._agg_per_parent(node, vc.name, vmap):
                # `min(val(x))` (bare or `n as ...`) with x bound in a
                # SIBLING subtree: one aggregate PER PARENT over that
                # parent's reachable x values (ref query.go
                # valueVarAggregation — TestQueryVarValAggNestedFunc*,
                # TestMinMulti, TestMultiLevelAgg shapes). Vars bound
                # elsewhere keep the whole-block scalar below.
                return
            whole = vc.name in getattr(self, "_block_vars", ()) \
                or not len(src)
            # bound by this block's own subtree (facet var, deeper
            # value var, same-level scalar var): the map's domain
            # is already scoped by where it was bound — aggregate
            # it whole, dgraph's flat-variable semantics (ref
            # TestLevelBasedFacetVarAggSum; a same-level var's
            # keys equal this level's src so whole == restricted);
            # an outer-block var restricts to this level's uids
            if isinstance(vmap, ColVar) \
                    and vmap.tid != TypeID.DATETIME:
                arr = vmap.vals if whole else vmap.gather(src)[1]
                agg = _aggregate_col(gq.agg_func, arr, vmap)
            else:
                vals = list(vmap.values()) if whole \
                    else [vmap[u] for u in src.tolist() if u in vmap]
                agg = _aggregate(gq.agg_func, vals)
            if agg is None and gq.agg_func == "sum" and not len(src):
                # sum over an empty var emits 0 in a row-less block
                # (ref query1:TestAggregateRoot5 "sum(val(m))":0.000000)
                agg = Val(TypeID.FLOAT, 0.0)
            node.values[0] = [Agg(gq.agg_func, agg)]
            if gq.var:
                # `minVal as min(val(a))` in an empty block binds a
                # GLOBAL var: key 0, matching the reference's
                # aggregated-var map (query.go empty-block aggregation;
                # TestAggregateRoot4/TestAggregateEmpty1). An empty
                # aggregate still DEFINES the var so downstream blocks
                # schedule (TestAggregateRoot6 expects [], not an
                # undefined-variable error).
                self.value_vars[gq.var] = \
                    {} if agg is None else {0: agg}
        elif gq.math is not None:
            root = getattr(self, "_block_root", None)
            if root is not None and root.func is None \
                    and not root.uids and not root.needs_var:
                # empty blocks (`me()`) may only do math over
                # aggregated (global, key-0) vars (ref edgraph:
                # "Only aggregated variables allowed within empty
                # block." — query1:TestAggregateRootError)
                for vn in _math_tree_vars(gq.math):
                    vmap0 = self.value_vars.get(vn, {})
                    keys = vmap0.uids if isinstance(vmap0, ColVar) \
                        else vmap0.keys()
                    if any(int(k) != 0 for k in keys):
                        raise GQLError(
                            "Only aggregated variables allowed "
                            "within empty block.")
            vmap = _eval_math(gq.math, self.value_vars, node.src)
            if gq.var:
                self.value_vars[gq.var] = vmap
            node.values = _internal_values(vmap, node.src, "math")
        elif gq.attr.startswith("val("):
            vc = gq.needs_var[0]
            vmap = self.value_vars.get(vc.name, {})
            node.values = _internal_values(vmap, node.src, "val")
        elif gq.checkpwd_pwd is not None:
            # checkpwd(pred, "plain") per row (ref query3:
            # TestCheckPassword; worker/task.go handleCheckPassword)
            from dgraph_tpu.models.types import verify_password

            tab = self._tablet(gq.attr)
            for u in node.src.tolist():
                ok = tab is not None and any(
                    verify_password(gq.checkpwd_pwd,
                                    str(p.value.value))
                    for p in tab.get_postings(int(u), self.read_ts))
                node.values[int(u)] = [
                    Agg("checkpwd", Val(TypeID.BOOL, ok))]

    def _agg_per_parent(self, node: ExecNode, name: str,
                        vmap) -> bool:
        """Level-based aggregation (ref query.go valueVarAggregation):
        when the aggregated var is bound inside a sibling subtree of
        the same block, each PARENT uid aggregates over the x values
        reachable through that sibling's edges. Binds the result var
        and per-parent node.values; returns False when no sibling
        chain provides the var (caller keeps whole-block semantics)."""
        sibs = getattr(self, "_sibling_nodes", None)
        if not sibs:
            return False
        chain = None
        for e in sibs.values():
            if e is node:
                continue
            if e.gq.var == name:
                chain = []  # bound on the parent level itself
                break
            if e.tablet is not None \
                    and (e.tablet.schema.value_type == TypeID.UID
                         or e.reverse):
                sub = self._chain_to(e, name)
                if sub is not None:
                    chain = sub
                    break
        if chain is None:
            return False
        gq = node.gq
        out: dict[int, Val] = {}
        for p in node.src.tolist():
            frontier = [int(p)]
            for e in chain:
                nxt: list[int] = []
                get = e.tablet.get_reverse_uids if e.reverse \
                    else e.tablet.get_dst_uids
                dest = e.dest
                for u in frontier:
                    ds = get(u, self.read_ts)
                    if len(dest):
                        ds = _intersect(ds, dest)
                    nxt.extend(int(d) for d in ds.tolist())
                frontier = sorted(set(nxt))
            vals = [vmap[u] for u in frontier if u in vmap]
            agg = _aggregate(gq.agg_func, vals)
            if agg is not None:
                out[int(p)] = agg
                node.values[int(p)] = [Agg(gq.agg_func, agg)]
        if gq.var:
            self.value_vars[gq.var] = out
        return True

    def _chain_to(self, e: ExecNode, name: str):
        """Edge-node path from sibling `e` down to the subtree level
        that binds `name` (scalar var or facet var), or None."""
        if name in e.gq.facet_var.values():
            return [e]
        for c in e.children:
            if c.gq.var == name:
                return [e]
        for c in e.children:
            if c.tablet is not None \
                    and (c.tablet.schema.value_type == TypeID.UID
                         or c.reverse):
                sub = self._chain_to(c, name)
                if sub is not None:
                    return [e] + sub
        return None

    # ------------------------------------------------------------------
    # order + pagination (ref query.go:2231 applyOrderAndPagination)
    # ------------------------------------------------------------------

    def _order_paginate(self, gq: GraphQuery, uids: np.ndarray
                        ) -> np.ndarray:
        if gq.order:
            for o in gq.order:
                if o.attr.startswith("val("):
                    vn = o.attr[4:-1]
                    if vn not in self.value_vars \
                            and vn not in self.uid_vars:
                        # bound later in this same block: the
                        # reference rejects rather than ordering by
                        # a not-yet-computed var (query1:
                        # TestUseVariableBeforeDefinitionError)
                        raise GQLError(
                            f"Variable: [{vn}] used before "
                            "definition.")
                    # ordering by val(v) keeps ONLY uids v is bound
                    # for (ref query0_test.go
                    # TestQueryVarValOrderDescMissing -> empty)
                    vmap = self.value_vars.get(vn, {})
                    uids = _intersect(uids, _var_domain(vmap))
                elif o.attr != "uid" \
                        and not o.attr.startswith("facet:"):
                    oattr = o.attr.lstrip("~")
                    otab = self._tablet(oattr)
                    if otab is None and not self.db.schema.has(oattr):
                        # ref query2:TestToFastJSONOrderNameError —
                        # ordering by a predicate the schema has
                        # never seen is a typo, not an empty sort
                        raise GQLError(
                            f"cannot order by unknown attribute "
                            f"{oattr!r}")
                    if otab is not None and otab.schema.list_:
                        # ref query1:TestMultipleValueSortError
                        raise GQLError(
                            f"Sorting not supported on attr: "
                            f"{o.attr} of type: [scalar]")
                    if otab is not None and \
                            otab.schema.value_type == TypeID.BOOL:
                        # ref query1:TestBoolSort (types.Sort has no
                        # bool ordering)
                        raise GQLError(
                            f"Sorting not supported on attr: "
                            f"{o.attr} of type: bool")
            paged = self._device_order_page(gq, uids)
            if paged is not None:
                return paged
            uids = self._apply_order(gq.order, uids)
        if gq.after:
            if gq.order:
                pos = np.nonzero(uids == gq.after)[0]
                uids = uids[int(pos[0]) + 1:] if len(pos) else uids
            else:
                uids = uids[uids > gq.after]
        off = gq.offset or 0
        if off:
            uids = uids[off:]
        if gq.first is not None:
            if gq.first >= 0:
                uids = uids[: gq.first]
            else:
                uids = uids[gq.first:]
        return uids

    def _apply_order(self, orders, uids: np.ndarray) -> np.ndarray:
        with _span("sort", n=len(uids), keys=len(orders)) as sp:
            return self._apply_order_inner(orders, uids, sp)

    def _apply_order_inner(self, orders, uids: np.ndarray,
                           sp: Optional[dict] = None) -> np.ndarray:
        """Multi-key value sort; stable, missing-value uids last
        (ref types/sort.go:118 + worker/sort.go)."""
        # device_min_edges <= 1 is the explicit force-device override
        # (tests, operators): it outranks the presorted host shortcut
        forced = self.db.prefer_device and self.db.device_min_edges <= 1
        # tier choice: presorted-permutation walk ("columnar") /
        # device multisort / host key-gather + lexsort ("postings").
        # rows_by_tier carries each tier's REAL cost driver — the
        # permutation walk streams the whole column, the lexsort
        # scales with candidates x keys — replacing the static 8x
        # candidate-fraction rule with the cost model.
        dec = tier = None
        info = None
        if not forced and len(uids) and self._adaptive:
            info = self._presorted_info(orders)

            def _build_sort():
                avail = ["postings"]
                rows = {"postings": len(uids) * max(1, len(orders))}
                if info is not None:
                    avail.append("columnar")
                    rows["columnar"] = len(info[1])
                if self.db.prefer_device and len(uids) >= 8 \
                        and self.db.device_is_accelerator():
                    avail.append("device")
                    rows["device"] = len(uids)
                return self._tier_decision(
                    "sort", orders[0].attr,
                    {"estRows": len(uids), "estRowsMax": len(uids),
                     "basis": "exact", "source": "candidate set"},
                    tuple(avail), rows_by_tier=rows)
            dec = self._routed(
                ("sort", orders[0].attr, len(orders),
                 len(uids).bit_length(), info is not None),
                _build_sort)
            tier = dec.tier if dec is not None else None
        if not forced:
            if dec is None:
                fast = self._apply_order_presorted(orders, uids, info)
                if fast is not None:
                    # static path serves the permutation tier too:
                    # stamp it so its cost cells land under "columnar"
                    # (the tier name the planner reads), not the
                    # observer's default "host"
                    if sp is not None:
                        sp["tier"] = "columnar"
                    return fast
            elif tier == "columnar":
                # the decision already weighed candidate-vs-column
                # size: skip the static 8x fraction rule
                fast = self._apply_order_presorted(
                    orders, uids, info, ignore_size_rule=True)
                if fast is not None:
                    self._record_outcome(dec, len(uids))
                    if sp is not None:
                        sp["tier"] = "columnar"
                    return fast
        if (tier == "device") if dec is not None else (
                self.db.prefer_device and len(uids) >= 8
                and self._device_worth(
                    len(uids) * len(orders) * self._HOST_PER_ORDER_KEY,
                    device_ratio=self._DEVICE_RATIO_ORDER)):
            dev = self._device_apply_order(orders, uids)
            if dev is not None:
                self._record_outcome(dec, len(uids))
                if sp is not None:
                    sp["tier"] = "device"
                return dev
        if forced:
            fast = self._apply_order_presorted(orders, uids)
            if fast is not None:
                if sp is not None:
                    sp["tier"] = "columnar"
                return fast
        self._record_outcome(dec, len(uids))
        if sp is not None:
            sp["tier"] = "postings"
        keyrows = [self._order_key_cols(o, uids) for o in orders]
        # lexsort: last key is primary
        cols = []
        for col, sub in reversed(keyrows):
            cols.append(sub)
            cols.append(col)  # missing flag dominates its key
        cols.insert(0, uids)  # final tiebreak: uid asc
        order = np.lexsort(tuple(cols))
        return uids[order]

    def _presorted_info(self, orders):
        """(tablet, sorted-column uids) when the presorted-permutation
        sort tier is structurally available for this order spec —
        single key, columnar on, clean tablet with a cached
        permutation — else None. Shared by the static fast path and
        the planner's availability probe so the two can never
        diverge."""
        if len(orders) != 1 or not self._columnar_on():
            return None
        o = orders[0]
        if o.attr == "uid" or o.attr.startswith(("val(", "facet:")) \
                or o.lang in (".", "*"):
            return None
        tab = self._tablet(o.attr)
        if tab is None or not hasattr(tab, "sorted_by_key_uids") \
                or tab.dirty() or self.read_ts < tab.base_ts:
            return None
        suids, _skeys = tab.sort_key_arrays(o.lang or "")
        if not len(suids):
            return None
        return tab, suids

    def _apply_order_presorted(self, orders, uids: np.ndarray,
                               info=None, ignore_size_rule: bool = False
                               ) -> Optional[np.ndarray]:
        """Single-key order-by through the tablet's CACHED
        (key, uid)-sorted permutation: one membership gather over the
        pre-sorted column replaces the per-query key gather + lexsort
        — worker/sort.go walks the value-ordered index the same way.
        Only when the candidate set is a sizable fraction of the
        column (streaming a 1M-row permutation to order 50 uids would
        lose) unless the planner's cost model already decided
        (ignore_size_rule); missing-key uids append uid-ascending,
        identical to the lexsort's missing-flag column."""
        if info is None:
            info = self._presorted_info(orders)
        if info is None:
            return None
        tab, suids = info
        o = orders[0]
        if not ignore_size_rule and len(uids) * 8 < len(suids):
            return None
        op, attr = tab.sorted_by_key_uids(o.lang or "", bool(o.desc))
        from dgraph_tpu.engine.device_cache import host_column_tile
        host_column_tile(self.db, tab, attr, op)
        full, perm = op.uids, op.perm
        inc_counter("query_order_presorted_total")
        # probe in the SMALLER direction (candidates into the sorted
        # column), then re-order the hit mask through the permutation
        pos, hit = _col_positions(suids, uids)
        mask = np.zeros(len(suids), bool)
        mask[pos[hit]] = True
        ordered = full[mask[perm]]
        if len(ordered) == len(uids):
            return ordered
        rest = uids[~hit]  # no sort key: appended uid-ascending
        return np.concatenate([ordered, rest])

    def _order_device_views(self, orders) -> Optional[list]:
        """DeviceValues views for every order key, or None when any
        key has no device view (val()/facet orders, dirty/small
        tablets)."""
        from dgraph_tpu.engine.device_cache import device_values

        dvs = []
        for o in orders:
            if o.attr.startswith("val(") or o.attr.startswith("facet:"):
                return None
            tab = self._tablet(o.attr)
            if tab is None or not hasattr(tab, "sort_key_pairs"):
                return None
            dv = device_values(self.db, tab, self.read_ts, o.lang)
            if dv is None:
                return None
            dvs.append(dv)
        return dvs

    def _device_apply_order(self, orders, uids: np.ndarray
                            ) -> Optional[np.ndarray]:
        """Whole multi-key (and lang-tagged) order-by on device: one
        multisort call over per-attr DeviceValues rank columns (ref
        worker/sort.go:300 multiSort). Falls back to the host lexsort
        whenever any order key has no device view (val() orders,
        dirty/small tablets, >32-bit uids)."""
        from dgraph_tpu.ops.graph import multisort
        from dgraph_tpu.ops.uidvec import SENTINEL, pad_to, to_numpy

        if np.any(uids > 0xFFFFFFFE):
            return None
        dvs = self._order_device_views(orders)
        if dvs is None:
            return None
        import jax.numpy as jnp
        cand = np.full(pad_to(len(uids)), SENTINEL, np.uint32)
        cand[: len(uids)] = np.sort(uids).astype(np.uint32)
        inc_counter("query_device_multisort_total")
        out = multisort(jnp.asarray(cand),
                        tuple(dv.uids for dv in dvs),
                        tuple(dv.ranks for dv in dvs),
                        tuple(bool(o.desc) for o in orders))
        res = to_numpy(out)
        return res[: len(uids)].astype(np.uint64)

    _PAGE_MAX_FIRST = 2048

    def _page_window(self, first: int) -> int:
        w = 8
        while w < first:
            w <<= 1
        return w

    def _device_resident_root(self, gq: GraphQuery, uids: np.ndarray,
                              allow_filter: bool = False):
        """The device-resident uid vector of an unfiltered clean
        has(attr) root, or None. When the root candidate set IS the
        tablet's own device view, the sort page kernel reads it in
        place — no 4MB-per-query upload over the tunnel.
        `allow_filter` is the fused-path relaxation: fusion calls this
        with the PRE-filter root (its kernel applies the filter as
        membership masks), so a filter's presence no longer disproves
        uids == the tablet's key set."""
        from dgraph_tpu.engine.device_cache import (
            device_adjacency, device_values,
        )

        fn = gq.func
        if fn is None or fn.name != "has" or fn.attr.startswith("~") \
                or (gq.filter is not None and not allow_filter) \
                or gq.uids or gq.needs_var:
            return None
        tab = self.db.tablets.get(fn.attr)
        if tab is None or not hasattr(tab, "schema"):
            return None
        if getattr(tab, "is_uid", False):
            adj = device_adjacency(self.db, tab, self.read_ts)
            if adj is not None and adj.n_src == len(uids):
                return adj.src_uids
            return None
        dv = device_values(self.db, tab, self.read_ts)
        if dv is not None and dv.n == len(uids):
            return dv.uids
        return None

    def _device_order_page(self, gq: GraphQuery, uids: np.ndarray
                           ) -> Optional[np.ndarray]:
        """order + after + offset + first fused into ONE device
        dispatch returning only the page (ref worker/sort.go:177
        processSort applies offset+count inside the sort). The full
        multisort path transfers the whole candidate vector both ways
        (~8MB at the 21M regime); this moves a few KB."""
        first = gq.first
        if first is None or first <= 0 or first > self._PAGE_MAX_FIRST:
            return None
        if not 0 <= (gq.offset or 0) <= 2**30 \
                or (gq.after or 0) > 0xFFFFFFFE:
            # the kernels compute start in int32: an absurd offset
            # must take the host path, not wrap the slice start
            return None
        if not self.db.prefer_device or len(uids) < 8:
            return None
        if not self._device_worth(
                len(uids) * len(gq.order) * self._HOST_PER_ORDER_KEY,
                device_ratio=self._DEVICE_RATIO_ORDER):
            return None
        if np.any(uids > 0xFFFFFFFE):
            return None
        dvs = self._order_device_views(gq.order)
        if dvs is None:
            return None
        from dgraph_tpu.ops.graph import multisort_page
        from dgraph_tpu.ops.uidvec import SENTINEL, pad_to, to_numpy
        import jax.numpy as jnp

        cand = self._device_resident_root(gq, uids)
        if cand is None:
            buf = np.full(pad_to(len(uids)), SENTINEL, np.uint32)
            buf[: len(uids)] = np.sort(uids).astype(np.uint32)
            cand = jnp.asarray(buf)
        inc_counter("query_device_sort_page_total")
        out = multisort_page(
            cand,
            tuple(dv.uids for dv in dvs),
            tuple(dv.ranks for dv in dvs),
            tuple(bool(o.desc) for o in gq.order),
            self._page_window(first),
            jnp.uint32(gq.after or 0),
            jnp.int32(gq.offset or 0))
        res = to_numpy(out)
        start = int(np.int32(res[-1]))
        valid = max(0, min(first, len(uids) - start))
        return res[:valid].astype(np.uint64)

    def _fused_spec(self, gq: GraphQuery, i: int):
        """Structural whole-plan-fusion verdict for block `i`,
        recomputed per request — deliberately NOT memoized on the
        plan: the verdict carries this request's filter Function
        objects, and the plan is shared across requests whose literals
        differ (a cached leaf would replay the FIRST request's
        literals into every later mask — wrong bytes, not just wrong
        speed). The walk is a handful of attribute checks and schema
        probes, noise next to one device dispatch. None on the
        interpreted path — fusion is a compiled-plan tier."""
        if self.plan is None or i < 0:
            return None
        from dgraph_tpu.query import fusion
        return fusion.block_eligible(gq, self.db.schema)

    def _fused_block_page(self, gq: GraphQuery, fspec, root: np.ndarray,
                          node: ExecNode) -> Optional[np.ndarray]:
        """Whole-block chain — filter set algebra + multi-key order +
        after/offset/first — as ONE fused device dispatch
        (query/fusion.py), or None to run the staged pipeline.
        `root` is the staged `_root_uids` result: the index probes
        stay on host (planner/tier machinery intact) and fusion
        collapses everything downstream of them. Every fallback stamps
        its reason on the node ("staged:<why>") so EXPLAIN attributes
        the block either way; byte-parity with the staged path is the
        structural contract (tests/test_columnar_parity.py)."""
        why, fs = fspec
        if why != "ok":
            node.fused = "staged:" + why
            return None

        def _stage(reason: str) -> None:
            node.fused = "staged:" + reason
            return None

        if not getattr(self.db, "prefer_fused", True):
            return _stage("disabled")
        first = gq.first
        if first is None or first <= 0 or first > self._PAGE_MAX_FIRST:
            return _stage("first-range")
        if gq.after:
            # the selection kernel can't bound how deep an arbitrary
            # cursor uid sits in the ordering
            return _stage("after-cursor")
        window = self._page_window(first)
        offset = gq.offset or 0
        from dgraph_tpu.ops.graph import FUSED_SEL_CAP
        if not 0 <= offset or offset + window > FUSED_SEL_CAP:
            # the page must fit inside the kernel's static survivor cap
            return _stage("deep-offset")
        if len(root) < max(8, getattr(self.db, "fused_min_rows", 1024)):
            # tiny roots: one dispatch still costs a round-trip the
            # host pipeline finishes first
            return _stage("small-root")
        if np.any(root > 0xFFFFFFFE):
            return _stage("uids-64bit")
        dvs = self._order_device_views(gq.order)
        if dvs is None:
            # dirty/small/unexported order tablets: the same MVCC rule
            # as every device tier
            return _stage("no-device-views")

        from dgraph_tpu.engine.device_cache import device_values
        from dgraph_tpu.ops.uidvec import SENTINEL, pad_to, to_numpy
        from dgraph_tpu.query import fusion
        import jax.numpy as jnp

        from dgraph_tpu.ops.graph import dv_view

        # root fingerprint: the snapshot ts plus cheap positional
        # invariants of the root set. Memo keys below pair it with the
        # full leaf/func signature, so a hit requires the same literals
        # against the same snapshot — the conditions under which the
        # staged chain would reproduce the same bytes.
        rfp = (self.read_ts, len(root),
               int(root[0]) if len(root) else 0,
               int(root[-1]) if len(root) else 0,
               int(root[::257].sum()) if len(root) else 0)
        cand = self._device_resident_root(gq, root, allow_filter=True)
        host_root = None
        if cand is None:
            def _root_upload():
                hr = np.sort(root).astype(np.uint32)
                buf = np.full(pad_to(len(root)), SENTINEL, np.uint32)
                buf[: len(hr)] = hr
                return hr, jnp.asarray(buf)

            host_root, cand = self.plan.memo(
                ("fused-root", self._fn_sig(gq.func), rfp),
                _root_upload)

        fop, leaves = fs
        rank_views, rank_luts, rank_los, rank_his, rank_negs = \
            [], [], [], [], []
        fparts, set_negs = [], []
        for fn, neg, kind in leaves:
            bounds = None
            if kind == "rank":
                tab = self._tablet(fn.attr)
                dv = device_values(self.db, tab, self.read_ts) \
                    if tab is not None else None
                if dv is not None:
                    bounds = self._rank_leaf_bounds(dv, tab.schema, fn)
            if bounds is not None:
                view, is_lut = dv_view(dv)
                rank_views.append(view)
                rank_luts.append(is_lut)
                rank_los.append(jnp.int32(bounds[0]))
                rank_his.append(jnp.int32(bounds[1]))
                rank_negs.append(bool(neg))
                continue
            # set form — host root-context probe (pointwise-equal to
            # the staged candidate-context eval, the parity
            # precondition block_eligible enforces), and the demotion
            # target when a rank leaf's view is missing (dirty/small
            # tablet) or its literal doesn't convert (the staged eval
            # then raises the identical GQLError)
            sig = self._fn_sig(fn)

            def _leaf(fn=fn):
                return self._eval_func(fn, None)

            if host_root is not None:
                # host-known candidates: fold the membership test into
                # ONE host searchsorted and ship a cand-ALIGNED bool
                # mask — the kernel sees a pure vector operand instead
                # of a device-side binary search per candidate
                def _mask(fn=fn, sig=sig, cand=cand, hr=host_root):
                    part = self.plan.memo(
                        ("fused-leaf", sig, self.read_ts), _leaf) \
                        if sig is not None else _leaf()
                    mask = np.zeros(int(cand.shape[0]), bool)
                    if len(part) and len(hr):
                        pi = np.minimum(np.searchsorted(part, hr),
                                        len(part) - 1)
                        mask[: len(hr)] = part[pi] == hr
                    return jnp.asarray(mask)

                fparts.append(
                    self.plan.memo(("fused-mask", sig, rfp), _mask)
                    if sig is not None else _mask())
            else:
                part = self.plan.memo(
                    ("fused-leaf", sig, self.read_ts), _leaf) \
                    if sig is not None else _leaf()
                if np.any(part > 0xFFFFFFFE):
                    return _stage("filter-64bit")

                def _part_upload(part=part):
                    buf = np.full(pad_to(len(part)), SENTINEL,
                                  np.uint32)
                    buf[: len(part)] = part.astype(np.uint32)
                    return jnp.asarray(buf)

                fparts.append(
                    self.plan.memo(("fused-part", sig, self.read_ts),
                                   _part_upload)
                    if sig is not None else _part_upload())
            set_negs.append(bool(neg))
        # primary-rank bucket geometry: static shift (recompiles only
        # when the key domain crosses a power of two), traced recenter
        domain = max(1, len(dvs[0].host_keys))
        shift = max(0, (domain - 1).bit_length() - 12)
        base0 = -(domain - 1) if gq.order[0].desc else 0
        ord_pairs = [dv_view(dv) for dv in dvs]
        run = fusion.fused_executable(
            self.db.mesh, self.plan.mesh_key, fop,
            tuple(rank_negs), tuple(set_negs), host_root is not None,
            tuple(bool(o.desc) for o in gq.order), window, shift,
            tuple(rank_luts), tuple(is_lut for _, is_lut in ord_pairs))
        inc_counter("query_fused_dispatch_total")
        out = run(cand, tuple(rank_views),
                  tuple(rank_los), tuple(rank_his), tuple(fparts),
                  tuple(view for view, _ in ord_pairs),
                  jnp.int32(base0), jnp.int32(offset))
        res = to_numpy(out)
        sel_count = int(res[-2])
        n_kept = int(res[-1])
        if sel_count > FUSED_SEL_CAP:
            # boundary tie mass overflowed the survivor cap (e.g. a
            # few-distinct-values primary order): page unprovable on
            # device, the staged chain is the answer
            return _stage("tie-overflow")
        valid = max(0, min(first, n_kept - offset))
        node.fused = "fused"
        return res[:valid].astype(np.uint64)

    @staticmethod
    def _fn_sig(fn) -> Optional[tuple]:
        """Hashable full-literal signature of a root/filter function,
        or None when the call depends on request-scoped state (value
        variables) that a cross-request memo key cannot capture."""
        if fn is None or fn.needs_var or fn.is_value_var \
                or fn.is_len_var:
            return None
        return (fn.name, fn.attr, fn.lang, fn.is_count,
                tuple((a.value, a.is_value_var, a.is_graphql_var)
                      for a in fn.args),
                tuple(fn.uids))

    @staticmethod
    def _rank_leaf_bounds(dv, ps, fn: Function
                          ) -> Optional[tuple[int, int]]:
        """[lo, hi) rank bounds over dv.host_keys for a rank-form
        filter leaf, or None to demote it to set form. Conversion
        mirrors the staged eq/ineq literal handling (Val DEFAULT ->
        predicate type); sort-key injectivity on the rank-exact types
        makes the range byte-equal to the staged leaf set."""
        from dgraph_tpu.models.types import Val, convert, sort_key

        def key(raw) -> int:
            return sort_key(convert(Val(TypeID.DEFAULT, raw),
                                    ps.value_type))

        hk = dv.host_keys
        try:
            if fn.name == "between":
                return (int(np.searchsorted(hk, key(fn.args[0].value),
                                            "left")),
                        int(np.searchsorted(hk, key(fn.args[1].value),
                                            "right")))
            k = key(fn.args[0].value)
        except (ValueError, TypeError, OverflowError,
                AttributeError):
            return None
        lo, hi = 0, len(hk)
        if fn.name == "eq":
            lo = int(np.searchsorted(hk, k, "left"))
            hi = int(np.searchsorted(hk, k, "right"))
        elif fn.name == "ge":
            lo = int(np.searchsorted(hk, k, "left"))
        elif fn.name == "gt":
            lo = int(np.searchsorted(hk, k, "right"))
        elif fn.name == "le":
            hi = int(np.searchsorted(hk, k, "right"))
        elif fn.name == "lt":
            hi = int(np.searchsorted(hk, k, "left"))
        else:
            return None
        return lo, hi

    @staticmethod
    def _count_cmp_bounds(fn: Function) -> Optional[tuple[int, int]]:
        """count-cmp -> inclusive [lo, hi] degree bounds over has()
        candidates (every candidate has degree >= 1)."""
        hi_max = 2**31 - 1
        try:
            v = int(fn.args[0].value)
        except (ValueError, IndexError):
            return None
        if fn.name == "ge":
            return max(v, 1), hi_max
        if fn.name == "gt":
            return max(v + 1, 1), hi_max
        if fn.name == "le":
            return 1, v
        if fn.name == "lt":
            return 1, v - 1
        if fn.name == "eq":
            return max(v, 1), v
        if fn.name == "between":
            try:
                hi = int(fn.args[1].value)
            except (ValueError, IndexError):
                return None
            return max(v, 1), hi
        return None

    def _device_root_count_page(self, gq: GraphQuery
                                ) -> Optional[np.ndarray]:
        """has(A) root + count(A) filter + order + paginate in ONE
        dispatch over A's resident adjacency (candidates = its src
        vector, degrees aligned): nothing uploaded, only the page
        downloaded (ref worker/task.go:1111 handleCompare over the
        count index + sort.go:177). Engages only for the exact shape
        q010 has; anything else falls back to the general path."""
        ft = gq.filter
        fn = gq.func
        if (ft is None or ft.op or ft.children or ft.func is None
                or fn is None or fn.name != "has"
                or fn.attr.startswith("~") or gq.uids or gq.needs_var
                or not gq.order):
            return None
        cfn = ft.func
        if (not cfn.is_count or cfn.attr != fn.attr
                or cfn.needs_var or cfn.attr.startswith("~")):
            return None
        bounds = self._count_cmp_bounds(cfn)
        if bounds is None:
            return None
        first = gq.first
        if first is None or first <= 0 or first > self._PAGE_MAX_FIRST:
            return None
        if not 0 <= (gq.offset or 0) <= 2**30 \
                or (gq.after or 0) > 0xFFFFFFFE:
            return None
        if not self.db.prefer_device:
            return None
        tab = self.db.tablets.get(fn.attr)
        if tab is None or not getattr(tab, "is_uid", False) \
                or not hasattr(tab, "sort_key_pairs"):
            return None
        from dgraph_tpu.engine.device_cache import device_adjacency
        adj = device_adjacency(self.db, tab, self.read_ts)
        if adj is None:
            return None
        if not self._device_worth(
                adj.n_src * (len(gq.order) + 1)
                * self._HOST_PER_ORDER_KEY,
                device_ratio=self._DEVICE_RATIO_ORDER):
            return None
        dvs = self._order_device_views(gq.order)
        if dvs is None:
            return None
        from dgraph_tpu.ops.graph import count_filter_sort_page
        import jax.numpy as jnp
        from dgraph_tpu.ops.uidvec import to_numpy

        inc_counter("query_device_count_page_total")
        out = count_filter_sort_page(
            adj.src_uids, adj.degrees,
            jnp.int32(min(bounds[0], 2**31 - 1)),
            jnp.int32(min(bounds[1], 2**31 - 1)),
            tuple(dv.uids for dv in dvs),
            tuple(dv.ranks for dv in dvs),
            tuple(bool(o.desc) for o in gq.order),
            self._page_window(first),
            jnp.uint32(gq.after or 0),
            jnp.int32(gq.offset or 0))
        res = to_numpy(out)
        start = int(np.int32(res[-2]))
        n_kept = int(res[-1])
        valid = max(0, min(first, n_kept - start))
        return res[:valid].astype(np.uint64)

    def _order_key_cols(self, o, uids: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """(missing_flag, key) int64 columns for one order attr over
        `uids` — the cached (uids, keys) sort arrays answer clean
        untagged/lang-selected predicates in two numpy gathers, so a
        1M-row host order-by stops walking a python dict per uid
        (q006 host path: 3.1s -> columnar). Falls back to the exact
        per-uid dict path for val()/facet keys and dirty tablets."""
        attr = o.attr
        if attr == "uid":
            # order by uid: the key IS the uid (this keeps q070's
            # orderasc:uid off the per-uid dict walk). Sign-bit XOR
            # maps uint64 to int64 order-preservingly so uids >= 2^63
            # sort correctly; uid 0 never exists, so desc negation
            # cannot hit INT64_MIN.
            arr = np.ascontiguousarray(uids, dtype=np.uint64)
            sub = (arr ^ np.uint64(1 << 63)).view(np.int64)
            col = np.zeros(len(arr), np.int64)
            return col, (-sub if o.desc else sub)
        if not attr.startswith(("val(", "facet:")) \
                and o.lang not in (".", "*") and self._columnar_on():
            # '.' / '*' tags resolve "any language" via
            # _select_posting; sort_key_pairs matches tags exactly, so
            # those keep the per-uid path
            tab = self._tablet(attr)
            if tab is not None and hasattr(tab, "sort_key_arrays") \
                    and not tab.dirty() and self.read_ts >= tab.base_ts:
                suids, skeys = tab.sort_key_arrays(o.lang or "")
                arr = np.ascontiguousarray(uids, dtype=np.uint64)
                if len(suids):
                    pos = np.clip(np.searchsorted(suids, arr), 0,
                                  len(suids) - 1)
                    hit = suids[pos] == arr
                    sub = np.where(hit, skeys[pos], 0)
                else:
                    hit = np.zeros(len(arr), bool)
                    sub = np.zeros(len(arr), np.int64)
                col = np.where(hit, 0, 1).astype(np.int64)
                return col, (-sub if o.desc else sub)
        vmap = self._order_keys(attr, o.lang, uids)
        col = np.asarray(
            [vmap.get(int(u), (1, 0))[0] for u in uids], dtype=np.int64)
        sub = np.asarray(
            [vmap.get(int(u), (1, 0))[1] for u in uids], dtype=np.int64)
        return col, (-sub if o.desc else sub)

    def _order_keys(self, attr: str, lang: str, uids) -> dict:
        """uid -> (missing_flag, int64 key)."""
        out = {}
        if attr.startswith("val("):
            vmap = self.value_vars.get(attr[4:-1], {})
            if isinstance(vmap, ColVar):
                sub = vmap.take(np.asarray(uids, np.uint64))
                return {int(u): (0, int(k)) for u, k in
                        zip(sub.uids.tolist(),
                            sub.sort_keys().tolist())}
            for u in uids.tolist():
                v = vmap.get(u)
                if v is not None:
                    try:
                        out[u] = (0, sort_key(v))
                    except ValueError:
                        pass
            return out
        tab = self._tablet(attr)
        if tab is None:
            return out
        if self.db.prefer_device and len(uids) >= 8 \
                and self._device_worth(
                    len(uids) * self._HOST_PER_ORDER_KEY,
                    device_ratio=self._DEVICE_RATIO_ORDER):
            dev = self._device_order_keys(tab, uids, lang)
            if dev is not None:
                return dev
        if hasattr(tab, "prefetch_postings"):
            tab.prefetch_postings(uids)
        for u in uids.tolist():
            ps = tab.get_postings(u, self.read_ts)
            sel = self._select_posting(ps, [lang] if lang else [])
            if sel is None and lang and ps:
                # sorting falls back tag -> untagged -> first (ref
                # posting.List.ValueFor; TestToFastJSONOrderLang)
                sel = self._select_posting(ps, []) or ps[0]
            if sel is not None:
                try:
                    # strict schema-type conversion, matching
                    # sort_key_pairs: an unconvertible value has NO
                    # sort key (missing, sorts last) on every path —
                    # _typed would silently sort the raw value here
                    out[u] = (0, sort_key(tab._converted(sel)))
                except ValueError:
                    pass
        return out

    def _device_order_keys(self, tab: Tablet, uids,
                           lang: str = "") -> Optional[dict]:
        """Sort keys for a uid batch in ONE device gather instead of a
        get_postings loop (SURVEY §2a item 4; ref worker/sort.go:177).
        Parity: device_values indexes each uid's first posting in
        `lang` ("" = untagged), exactly what _select_posting picks on
        the host path. The gather input is pow2-padded so repeated
        sorts share compiled code instead of one XLA program per
        candidate count."""
        from dgraph_tpu.engine.device_cache import device_values
        from dgraph_tpu.ops.graph import RANK_MISSING, key_gather
        from dgraph_tpu.ops.uidvec import SENTINEL, pad_to

        dv = device_values(self.db, tab, self.read_ts, lang)
        if dv is None:
            return None
        import jax.numpy as jnp
        u32 = uids[uids <= 0xFFFFFFFE].astype(np.uint32)
        if not len(u32):
            return {}
        inc_counter("query_device_orderkeys_total")
        cand = np.full(pad_to(len(u32)), SENTINEL, np.uint32)
        cand[: len(u32)] = np.sort(u32)
        ranks = np.asarray(key_gather(dv, jnp.asarray(cand)))
        out = {}
        for u, r in zip(cand[: len(u32)].tolist(),
                        ranks[: len(u32)].tolist()):
            if r != RANK_MISSING:
                out[u] = (0, int(r))
        return out

    # ------------------------------------------------------------------
    # recurse (ref query/recurse.go:29)
    # ------------------------------------------------------------------

    def _run_recurse(self, node: ExecNode):
        gq = node.gq
        # depth counts LEVELS including the root: depth 2 expands one
        # edge hop (ref query3_test.go TestRecurseQueryLimitDepth1)
        depth = (gq.recurse.depth or 64) - 1
        allow_loop = gq.recurse.allow_loop
        frontier = node.dest
        visited = frontier.copy()
        # uid vars bound inside @recurse accumulate every uid reached
        # via that predicate across ALL levels (ref query3_test.go
        # TestRecurseVariable); seeded empty so a recursion that
        # reaches nothing still DEFINES the var (a consumer block must
        # get [], not an undefined-variable error)
        var_accum: dict[str, np.ndarray] = {
            c.var: _EMPTY for c in gq.children
            if not c.is_internal and c.var}
        for _ in range(depth):
            if not len(frontier):
                break
            self._checkpoint(f"recurse {gq.alias or gq.attr}")
            # expand(_all_)/expand(Type) re-resolves per level against
            # the CURRENT frontier's types (ref TestRecurseExpand)
            preds = [c for c in
                     self._expand_expand(gq.children, frontier,
                                         keep_uid_leaves=True)
                     if not c.is_internal]
            node.recurse_preds.append(preds)
            level: dict[str, dict[int, np.ndarray]] = {}
            nxt = _EMPTY
            for cgq in preds:
                attr = cgq.attr
                rev = attr.startswith("~")
                tab = self._tablet(attr[1:] if rev else attr)
                if tab is None or tab.schema.value_type != TypeID.UID:
                    continue
                if rev and not tab.schema.reverse:
                    raise GQLError(
                        f"reverse edges are not defined for predicate "
                        f"{attr[1:]!r} (add @reverse to the schema)")
                # filtered recurse: ONE batched expansion per level
                # (device-capable) and one filter evaluation on the
                # level's union instead of once per parent (ref
                # recurse.go:29 — its per-level subgraph exec batches
                # over SrcUIDs the same way). Unfiltered recurse skips
                # the union pass: per-parent edge lists are needed for
                # the nested output regardless, and their concat IS the
                # union.
                union = None
                if cgq.filter is not None:
                    union = self._expand_level(tab, frontier, rev)
                    if len(union):
                        union = self._eval_filter(cgq.filter, union)
                    if not len(union):
                        level[attr] = {}
                        continue
                per_parent: dict[int, np.ndarray] = {}
                parts = []
                for u in frontier.tolist():
                    dst = (tab.get_reverse_uids(u, self.read_ts) if rev
                           else tab.get_dst_uids(u, self.read_ts))
                    if union is not None:
                        dst = _intersect(dst, union)
                    if len(dst):
                        per_parent[u] = dst
                        parts.append(dst)
                level[attr] = per_parent
                reached = union if union is not None else (
                    np.unique(np.concatenate(parts)) if parts else _EMPTY)
                if cgq.var and len(reached):
                    var_accum[cgq.var] = _union(
                        var_accum.get(cgq.var, _EMPTY), reached)
                if len(reached):
                    nxt = _union(nxt, reached)
            node.recurse_levels.append(level)
            if not allow_loop:
                nxt = _difference(nxt, visited)
                visited = _union(visited, nxt)
            else:
                visited = _union(visited, nxt)
            frontier = nxt
        for cgq in gq.children:
            if cgq.var and cgq.attr == "uid" and not cgq.is_count:
                # `a as uid` inside @recurse: every visited uid
                # (ref query3:TestRecurseVariableUid)
                var_accum[cgq.var] = _union(
                    var_accum.get(cgq.var, _EMPTY), visited)
        for name, uids in var_accum.items():
            self.uid_vars[name] = uids
        node.recurse_frontiers = None  # levels carry everything

    # ------------------------------------------------------------------
    # shortest path (ref query/shortest.go:451 Dijkstra / :287 k-paths)
    # ------------------------------------------------------------------

    def _run_shortest(self, node: ExecNode):
        """shortest(from, to, numpaths, depth, minweight, maxweight)
        with optional @facets(<key>) edge weights on the predicate
        children. Ref: query/shortest.go:451 route() (Dijkstra),
        :287 runKShortestPaths, gql/parser.go:2501 args."""
        gq = node.gq
        sa = gq.shortest
        if sa is None or sa.from_ is None or sa.to is None:
            raise GQLError("shortest requires from: and to:")
        src = self._fn_single_uid(sa.from_)
        dst = self._fn_single_uid(sa.to)
        pred_specs = self._shortest_preds(gq)
        maxdepth = sa.depth or 64
        weighted = any(w for _, _, _, w in pred_specs)
        simple = (sa.numpaths <= 1 and not weighted
                  and sa.minweight == float("-inf")
                  and sa.maxweight == float("inf"))
        if self.db.prefer_device and simple and len(pred_specs) == 1:
            path = self._device_shortest(pred_specs[0][0], src, dst,
                                         maxdepth)
            if path is not None:
                # [] is the unreachable sentinel, None means not
                # device-resident (fall through to host)
                self._finish_shortest(
                    node,
                    [(path, float(len(path) - 1))] if path else [],
                    pred_specs)
                return
        paths = self._k_shortest(pred_specs, src, dst, maxdepth,
                                 max(1, sa.numpaths),
                                 sa.minweight, sa.maxweight)
        self._finish_shortest(node, paths, pred_specs)

    def _shortest_preds(self, gq) -> list[tuple]:
        """[(attr, tablet, reverse, weight_facet_key)] for the block's
        predicate children."""
        out = []
        for c in gq.children:
            if c.is_internal:
                continue
            pname = c.attr
            rev = pname.startswith("~")
            tab = self._tablet(pname[1:] if rev else pname)
            if tab is None:
                continue
            if rev and not tab.schema.reverse:
                raise GQLError(
                    f"reverse edges are not defined for predicate "
                    f"{pname[1:]!r} (add @reverse to the schema)")
            wkey = ""
            if c.facets is not None and c.facets.keys:
                wkey = c.facets.keys[0][0]
            out.append((pname, tab, rev, wkey))
        return out

    def _shortest_neighbors(self, pred_specs, u: int
                            ) -> list[tuple[int, float]]:
        """(neighbor, edge weight) pairs; facet weight when requested,
        else 1 per hop (ref shortest.go expandOut)."""
        out = []
        for pname, tab, rev, wkey in pred_specs:
            dsts = (tab.get_reverse_uids(u, self.read_ts) if rev
                    else tab.get_dst_uids(u, self.read_ts))
            for d in dsts.tolist():
                w = 1.0
                if wkey:
                    # facets live on the forward edge; an edge MISSING
                    # the weight facet is unusable in weighted mode
                    # (ref query3_test.go TestKShortestPathWeighted:
                    # only the fully-faceted route exists)
                    fsrc, fdst = (d, u) if rev else (u, d)
                    fv = tab.get_facets(fsrc, fdst, self.read_ts).get(wkey)
                    if fv is None:
                        continue
                    try:
                        w = float(fv.value)
                    except (TypeError, ValueError):
                        continue
                out.append((int(d), w))
        return out

    def _k_shortest(self, pred_specs, src: int, dst: int, maxdepth: int,
                    k: int, minw: float = float("-inf"),
                    maxw: float = float("inf")
                    ) -> list[tuple[list[int], float]]:
        """Yen's algorithm over hop-labeled Dijkstra: loopless shortest
        paths in nondecreasing weight until k of them fall inside the
        [minweight, maxweight] window (ref shortest.go:287
        runKShortestPaths — the weight bounds are search constraints,
        not a post-filter)."""
        import heapq

        nbr_memo: dict[int, list[tuple[int, float]]] = {}

        def neighbors(u: int):
            out = nbr_memo.get(u)
            if out is None:
                out = nbr_memo[u] = self._shortest_neighbors(
                    pred_specs, u)
            return out

        def dijkstra(banned_edges, banned_nodes, start, depth_budget):
            self._checkpoint("shortest")
            # labels are (node, hops): a cheap-but-deep route must not
            # shadow a shallower one that still has hop budget left
            dist = {(start, 0): 0.0}
            prev: dict[tuple[int, int], tuple[int, int]] = {}
            pq = [(0.0, 0, start)]
            best_dst = None
            while pq:
                if self.ctx is not None and (len(dist) & 0xFF) == 0:
                    self.ctx.check("shortest")
                d, hops, u = heapq.heappop(pq)
                if u == dst:
                    best_dst = (u, hops)
                    break
                if d > dist.get((u, hops), float("inf")) \
                        or hops >= depth_budget:
                    continue
                for v, w in neighbors(u):
                    if v in banned_nodes or (u, v) in banned_edges:
                        continue
                    nd = d + w
                    if nd < dist.get((v, hops + 1), float("inf")):
                        dist[(v, hops + 1)] = nd
                        prev[(v, hops + 1)] = (u, hops)
                        heapq.heappush(pq, (nd, hops + 1, v))
            if best_dst is None:
                return None
            path = [best_dst[0]]
            label = best_dst
            while label[0] != start or label[1] != 0:
                label = prev[label]
                path.append(label[0])
            path.reverse()
            return path, dist[best_dst]

        def in_window(w):
            return minw <= w <= maxw

        if src == dst:
            return [([src], 0.0)] if in_window(0.0) else []
        first = dijkstra(set(), set(), src, maxdepth)
        if first is None:
            return []
        found = [first]
        cand: list[tuple[float, list[int]]] = []
        seen = {tuple(first[0])}
        max_rounds = max(64, 8 * k)  # window search safety valve
        while sum(1 for _, w in found if in_window(w)) < k \
                and len(found) < max_rounds:
            base_path, base_w = found[-1]
            # prefix weights of the base path, one edge-lookup pass
            prefix_w = [0.0]
            for a, b in zip(base_path, base_path[1:]):
                ws = [w for v, w in neighbors(a) if v == b]
                prefix_w.append(prefix_w[-1] + (min(ws) if ws else 1.0))
            for i in range(len(base_path) - 1):
                spur = base_path[i]
                root = base_path[: i + 1]
                banned_edges = {(p[i], p[i + 1]) for p, _ in found
                                if len(p) > i + 1 and p[: i + 1] == root}
                banned_nodes = set(root[:-1])
                rest = dijkstra(banned_edges, banned_nodes, spur,
                                maxdepth - i)
                if rest is None:
                    continue
                total = root[:-1] + rest[0]
                key = tuple(total)
                if key not in seen:
                    seen.add(key)
                    heapq.heappush(cand, (prefix_w[i] + rest[1], total))
            if not cand:
                break
            w, p = heapq.heappop(cand)
            if w > maxw:
                break  # nondecreasing weights: nothing ahead can fit
            found.append((p, w))
        return [(p, w) for p, w in found if in_window(w)][:k]

    def _finish_shortest(self, node: ExecNode, paths, pred_specs=None):
        node.path_nodes = [p for p, _ in paths]
        node.path_weights = [w for _, w in paths]
        node.path_specs = pred_specs or []
        gq = node.gq
        if gq.var:
            # the uid var holds the FIRST (best) path, ref shortest.go
            if paths:
                self.uid_vars[gq.var] = _np_sorted(paths[0][0])
                # consumers of a PATH var emit in traversal order, not
                # uid order (ref query3_test.go TestShortestPathRev)
                self._path_var_order[gq.var] = list(paths[0][0])
            else:
                self.uid_vars[gq.var] = _EMPTY

    def _device_shortest(self, pred: str, src: int, dst: int,
                         maxdepth: int) -> Optional[list[int]]:
        """Hop-count shortest path via the device SSSP kernel.

        Distances-to-target come from one dense Bellman-Ford over the
        traversal graph's transpose (ops/bitgraph.make_sssp_bits, the
        TPU translation of query/shortest.go:451's priority queue);
        the path itself is reconstructed on host by walking forward
        from `src`, at each hop picking the smallest-uid neighbor one
        step closer. Returns None when the tablet isn't device-resident
        (caller falls back to host BFS), [] when unreachable."""
        from dgraph_tpu.engine.device_cache import device_bitadjacency

        rev = pred.startswith("~")
        tab = self._tablet(pred[1:] if rev else pred)
        if tab is None or tab.schema.value_type != TypeID.UID:
            return None
        if rev and not tab.schema.reverse:
            raise GQLError(
                f"reverse edges are not defined for predicate "
                f"{pred[1:]!r} (add @reverse to the schema)")
        if src > 0xFFFFFFFE or dst > 0xFFFFFFFE:
            return None
        # walking ~pred backwards follows pred forwards, so the
        # distance-to-target pass uses the untransposed adjacency
        badj_t = device_bitadjacency(self.db, tab, self.read_ts,
                                     transpose=not rev)
        if badj_t is None:
            return None
        from dgraph_tpu.ops.bitgraph import sssp_dist
        inc_counter("query_device_sssp_total")
        if src == dst:
            return [src]
        dist_to = sssp_dist(badj_t, np.asarray([dst], np.uint32),
                            max_iters=maxdepth)
        d0 = dist_to.get(src)
        if d0 is None or d0 > maxdepth:
            return []
        path = [src]
        u = src
        while u != dst:
            want = dist_to[u] - 1
            nbrs = (tab.get_reverse_uids(u, self.read_ts) if rev
                    else tab.get_dst_uids(u, self.read_ts))
            nxt = None
            for v in nbrs.tolist():
                if dist_to.get(int(v)) == want:
                    nxt = int(v)
                    break
            if nxt is None:  # overlay changed under us — fall back
                return None
            path.append(nxt)
            u = nxt
        return path

    def _fn_single_uid(self, fn: Function) -> int:
        if fn.uids:
            return fn.uids[0]
        for vc in fn.needs_var:
            arr = self.uid_vars.get(vc.name, _EMPTY)
            if len(arr):
                return int(arr[0])
        raise GQLError("shortest from/to resolved to no uid")

    # ------------------------------------------------------------------
    # output (ref query/outputnode.go:653 preTraverse)
    # ------------------------------------------------------------------

    def _cascade_rebind_vars(self, node: ExecNode):
        """Prune every var bound inside a @cascade block the way the
        reference's applyCascade does BEFORE var population (ref
        query.go applyCascade; query3:TestUseVarsCascade): two passes —
        bottom-up per-uid subtree satisfaction (_cascade_keep), then
        top-down parent reachability, so a uid bound through a parent
        the cascade dropped (e.g. for a missing sibling scalar) is
        unbound too."""
        memo: dict[int, np.ndarray] = {}
        self._cascade_edge_cache: dict[tuple, np.ndarray] = {}
        alive = self._cascade_keep(node, memo)
        if node.gq.var:
            self.uid_vars[node.gq.var] = alive
        self._cascade_descend(node, alive, memo)
        self._cascade_edge_cache = {}

    def _cascade_edges(self, c: ExecNode, u: int) -> np.ndarray:
        """Per-(child, parent) edge list, cached across the keep and
        descend passes so each tablet edge list is read once."""
        key = (id(c), u)
        got = self._cascade_edge_cache.get(key)
        if got is None:
            get = c.tablet.get_reverse_uids if c.reverse \
                else c.tablet.get_dst_uids
            got = get(u, self.read_ts)
            self._cascade_edge_cache[key] = got
        return got

    def _cascade_table(self, c: ExecNode):
        """Flat (parent_keys sorted, child_uids) columnar edge table in
        the child's direction for a CLEAN tablet — the same
        searchsorted join surface _join_codes consumes — or None
        (dirty tablets keep the exact per-uid MVCC loop). Reverse
        children pay one lexsort to flip the forward table; cached for
        the cascade pass like the per-parent edge lists."""
        key = ("table", id(c))
        got = self._cascade_edge_cache.get(key, False)
        if got is not False:
            return got
        et = c.tablet.edge_table(self.read_ts) \
            if self._columnar_on() and hasattr(c.tablet, "edge_table") \
            else None
        out = None
        if et is not None:
            srcs, dsts = et
            if c.reverse:
                order = np.argsort(dsts, kind="stable")
                out = (dsts[order], srcs[order])
            else:
                out = (srcs, dsts)
        self._cascade_edge_cache[key] = out
        return out

    def _cascade_descend(self, node: ExecNode, alive: np.ndarray,
                         memo: dict):
        for c in node.children:
            if c.gq.attr == "uid" and c.gq.var and not c.gq.is_count:
                # `x as uid` binds the SURVIVING parents
                self.uid_vars[c.gq.var] = alive
                continue
            if c.tablet is None or c.gq.is_count:
                continue
            if c.tablet.schema.value_type == TypeID.UID or c.reverse:
                table = self._cascade_table(c)
                if table is not None and len(alive):
                    # columnar: gather every edge of the surviving
                    # parents with ONE searchsorted join (_join_codes)
                    # instead of a per-parent edge-fetch loop
                    got = _join_codes(table[0], table[1], alive)
                    reach = np.unique(got[1]) if got is not None \
                        else _EMPTY
                else:
                    parts = [self._cascade_edges(c, int(p))
                             for p in alive.tolist()]
                    parts = [p for p in parts if len(p)]
                    reach = np.unique(np.concatenate(parts)) if parts \
                        else _EMPTY
                alive_c = _intersect(
                    _intersect(reach, c.dest),
                    self._cascade_keep(c, memo))
                if c.gq.var:
                    self.uid_vars[c.gq.var] = alive_c
                self._cascade_descend(c, alive_c, memo)
            elif c.gq.var:
                # scalar value var: restrict its domain to surviving
                # parents
                vm = self.value_vars.get(c.gq.var)
                if isinstance(vm, dict):
                    keep = set(alive.tolist())
                    self.value_vars[c.gq.var] = {
                        u: v for u, v in vm.items() if u in keep}
                elif isinstance(vm, ColVar):
                    self.value_vars[c.gq.var] = vm.take(alive)

    def _cascade_keep(self, node: ExecNode, memo: dict) -> np.ndarray:
        """dest uids satisfying node's OWN subtree constraints,
        bottom-up (an edge child's targets must themselves satisfy
        theirs). Parent reachability is _cascade_descend's job."""
        key = id(node)
        if key in memo:
            return memo[key]
        keep = node.dest
        for c in node.children:
            if c.tablet is None or c.gq.is_count or not len(keep):
                continue
            if c.tablet.schema.value_type == TypeID.UID or c.reverse:
                sub = self._cascade_keep(c, memo) if c.children \
                    else c.dest
                table = self._cascade_table(c)
                if table is not None:
                    # columnar: one searchsorted join gathers every
                    # parent's edges, one membership test against
                    # `sub` keeps parents with >= 1 surviving edge —
                    # no per-(child, parent) Python loop
                    got = _join_codes(table[0], table[1], keep)
                    ok = np.zeros(len(keep), bool)
                    if got is not None and len(sub):
                        rep, gathered = got
                        hit = _member_of(gathered, sub)
                        ok[rep[hit]] = True
                    keep = keep[ok]
                else:
                    keep = np.asarray(
                        [u for u in keep.tolist()
                         if len(_intersect(
                             self._cascade_edges(c, int(u)), sub))],
                        dtype=np.uint64)
            else:
                keep = np.asarray(
                    [u for u in keep.tolist()
                     if self._cascade_scalar_present(c, int(u))],
                    dtype=np.uint64)
        memo[key] = keep
        return keep

    def _cascade_scalar_present(self, c: ExecNode, u: int) -> bool:
        """Same presence predicate the emission-time cascade applies:
        col_vals is authoritative when built; otherwise the posting
        list filtered through the child's language selectors (a var
        block skips scalar materialization, so fall through to the
        tablet)."""
        if c.col_vals is not None:
            return c.col_vals.get(u) is not None
        ps = c.values.get(u)
        if not ps:
            ps = c.tablet.get_postings(u, self.read_ts)
        if ps and c.gq.facets_filter is not None:
            # same value-facet filter the emission applies (ref
            # facets:TestFacetsFilterAtValueBasic)
            ps = [p for p in ps
                  if self._eval_facet_tree(c.gq.facets_filter,
                                           p.facets)]
        if not ps:
            return False
        if c.gq.langs == ["*"]:
            return True
        return self._select_posting(ps, c.gq.langs or []) is not None

    def _emit_block(self, node: ExecNode) -> list:
        gq = node.gq
        if gq.recurse is not None:
            self._recurse_colvals = self._recurse_scalar_cache(node)
            try:
                return [r for r in
                        (self._emit_recurse_node(node, int(u), 0)
                         for u in node.dest.tolist()) if r]
            finally:
                self._recurse_colvals = {}
        if gq.is_groupby:
            # root-level @groupby groups the block's matched uids (ref
            # query0_test.go TestGroupByRoot:
            # {"me":[{"@groupby":[...]}]}); ZERO groups omit the
            # whole block key (TestGroupByRootEmpty -> {})
            fake = ExecNode(gq)
            grp = self._emit_groupby(fake, node.dest)
            return [grp] if grp.get("@groupby") else []
        if not node.children:
            # empty selection: rows emit nothing (ref query0:
            # TestMultiEmptyBlocks -> "you": [])
            return []
        for ch in node.children:
            self._ensure_child_values(ch)
        fast = self._emit_block_flat(node)
        if fast is not None:
            return fast
        out = []
        # count(uid) at block level: one summed object
        # (ref outputnode.go uid count emission)
        n_counts = 0
        for ch in node.children:
            if ch.gq.attr == "uid" and ch.gq.is_count:
                out.append({ch.gq.alias or "count": len(node.dest)})
                n_counts += 1
        if n_counts and n_counts == len(node.children):
            # count-only block: the per-uid walk below would emit (and
            # drop) an empty object per row — 0.5s of the 21M q009
            return out
        order = node.emit_order if node.emit_order is not None \
            else node.dest.tolist()
        for u in order:
            # @ignorereflex: track the result path so children never
            # re-emit an ancestor (ref query.go:164 removeCycles)
            path = frozenset({int(u)}) if gq.ignore_reflex else None
            obj = self._emit_uid(node, int(u), path,
                                 normalize=gq.normalize)
            if obj:  # empty objects are dropped (ref outputnode.go)
                out.append(obj)
        # row-less blocks (q() { min(val(a)) }) emit aggregations as
        # standalone objects; blocks WITH rows attach them per row in
        # _emit_uid (ref preTraverse)
        if not len(node.dest):
            for ch in node.children:
                if ch.gq.agg_func and 0 in ch.values:
                    agg = ch.values[0][0]
                    if agg.value is not None:
                        name = ch.gq.alias or ch.gq.attr
                        out.append({name: to_json_value(agg.value)})
                elif ch.gq.math is not None and 0 in ch.values:
                    # math over aggregated (global) vars in a row-less
                    # block (ref query1:TestAggregateRoot4 `Sum:
                    # math(minVal + maxVal)`); same naming convention
                    # as the per-row path: `v as math(...)` emits
                    # under "val(v)"
                    agg = ch.values[0][0]
                    if agg.value is not None:
                        name = ch.gq.alias or (
                            f"val({ch.gq.var})" if ch.gq.var
                            else "math")
                        out.append({name: to_json_value(agg.value)})
        if gq.normalize:
            out = [row for o in out if o
                   for row in self._normalize(o)]
            out = [o for o in out if o]
        return out

    def _emit_block_flat(self, node: ExecNode) -> Optional[list]:
        """Dict-output twin of _emit_block_flat_json: a uid block whose
        children are all `uid` fields or columnar scalars (col_vals
        built) emits via one tight gather loop — the general _emit_uid
        walk re-decides langs/facets/cascade per row and dominated
        flat-block profiles (q003). None keeps the exact emitter."""
        gq = node.gq
        if gq.normalize or gq.cascade or gq.ignore_reflex:
            return None
        specs = []
        for ch in node.children:
            cgq = ch.gq
            if cgq.attr == "uid" and not cgq.is_count:
                specs.append((cgq.alias or "uid", None))
            elif ch.col_vals is not None and not cgq.is_count:
                specs.append((cgq.alias or cgq.attr, ch.col_vals))
            else:
                return None
        order = node.emit_order if node.emit_order is not None \
            else node.dest.tolist()
        out = []
        for u in order:
            obj = {}
            for name, cv in specs:
                if cv is None:
                    obj[name] = hex(u)
                else:
                    v = cv.get(u)
                    if v is not None:
                        obj[name] = v
            if obj:  # empty objects drop (ref outputnode.go)
                out.append(obj)
        return out

    def _emit_uid(self, node: ExecNode, uid: int,
                  path: Optional[frozenset] = None,
                  cascade: bool = False,
                  normalize: bool = False) -> Optional[dict]:
        obj: dict[str, Any] = {}
        gq = node.gq
        # @cascade and @normalize apply to the WHOLE subtree under the
        # block that declares them (ref query.go applyCascade;
        # @normalize keeps ONLY aliased attributes —
        # query2_test.go TestNormalizeDirective drops bare `gender`)
        cascade = cascade or gq.cascade
        normalize = normalize or gq.normalize
        have: set[str] = set()  # names satisfied but normalize-hidden
        children = node.children
        if not children:
            obj["uid"] = hex(uid)
            return obj
        for ch in children:
            cgq = ch.gq
            name = cgq.alias or cgq.attr
            if normalize and not cgq.alias and ch.tablet is not None \
                    and ch.tablet.schema.value_type != TypeID.UID \
                    and not (cgq.is_count or ch.reverse):
                # @normalize: bare scalars don't emit — but @cascade's
                # presence check still counts a value that EXISTS
                if (ch.col_vals or {}).get(uid) is not None \
                        or ch.values.get(uid):
                    have.add(name)
                continue
            if normalize and not cgq.alias and cgq.attr == "uid" \
                    and not cgq.is_count:
                continue
            if cgq.langs and not cgq.alias:
                name = f"{cgq.attr}@{':'.join(cgq.langs)}"
            if cgq.attr == "uid":
                if cgq.is_count:
                    continue  # count(uid) handled at parent level
                obj[cgq.alias or "uid"] = hex(uid)
                continue
            if normalize and not cgq.alias \
                    and (cgq.agg_func or cgq.attr == "math"
                         or cgq.attr.startswith("val(")
                         or cgq.is_count):
                continue
            if cgq.agg_func:
                # aggregations attach INSIDE each parent row (ref
                # outputnode.go preTraverse: the agg subgraph hangs
                # under its parent node — TestLevelBasedFacetVarAggSum
                # shape); per-parent (level-based) aggregates emit the
                # parent's own value under the VAR name; row-less
                # blocks emit them standalone in _emit_block instead
                vs = ch.values.get(uid)
                if vs is not None and cgq.var:
                    name = cgq.alias or cgq.var
                if vs is None:
                    vs = ch.values.get(0)
                if vs is not None and vs[0].value is not None:
                    obj[name] = to_json_value(vs[0].value)
                continue
            if cgq.attr == "math" or cgq.attr.startswith("val("):
                if cgq.attr == "math" and cgq.var and not cgq.alias:
                    # `sum as math(...)` emits under "val(sum)" (ref
                    # TestQueryVarValAggOrderDesc expected shape)
                    name = f"val({cgq.var})"
                vs = ch.values.get(uid)
                if vs:
                    obj[name] = to_json_value(vs[0].value)
                continue
            if cgq.checkpwd_pwd is not None:
                vs = ch.values.get(uid)
                if vs is not None:
                    obj[cgq.alias or f"checkpwd({cgq.attr})"] = \
                        to_json_value(vs[0].value)
                continue
            if ch.tablet is None:
                continue
            if cgq.is_count:
                cname = cgq.alias or f"count({cgq.attr})"
                obj[cname] = ch.counts.get(uid, 0)
                continue
            tab = ch.tablet
            if tab.schema.value_type == TypeID.UID and not ch.reverse \
                    or (ch.reverse and tab.schema.reverse):
                if cgq.facets_filter is not None:
                    dsts = self._edge_dsts_facet_filtered(
                        tab, uid, ch.reverse, cgq.facets_filter)
                else:
                    dsts = (tab.get_reverse_uids(uid, self.read_ts)
                            if ch.reverse
                            else tab.get_dst_uids(uid, self.read_ts))
                dsts = _intersect(dsts, ch.dest) if len(ch.dest) else \
                    (dsts if not ch.gq.filter else _EMPTY)
                if path is not None and len(dsts):
                    dsts = _difference(dsts, _np_sorted(path))
                if cgq.is_groupby:
                    # the reference emits child groupby as a one-
                    # element array (query0_test.go TestGroupBy shape);
                    # a repeated attr merges into one key in child
                    # order (TestGroupBy_RepeatAttr); ZERO groups
                    # emit nothing so a member-less parent row drops
                    # (TestGroupByAgeMultiParents skips uids 99999/8)
                    grp = self._emit_groupby(ch, dsts)
                    if grp.get("@groupby"):
                        _merge_list_key(obj, name, [grp])
                    continue
                facet_orders = [o for o in cgq.order
                                if o.attr.startswith("facet:")]
                if facet_orders:
                    dsts = self._order_paginate_facets(
                        cgq, tab, uid, ch.reverse, dsts, facet_orders)
                else:
                    dsts = self._order_paginate(cgq, dsts)
                counts = [c for c in cgq.children
                          if c.attr == "uid" and c.is_count]
                if counts and all(c.attr == "uid" and c.is_count
                                  for c in cgq.children):
                    obj[name] = [{counts[0].alias or "count": len(dsts)}]
                    continue
                if cgq.facets is not None \
                        and hasattr(tab, "prefetch_facets"):
                    # federated: one facets RPC per parent, over the
                    # PAGINATED edge list only (the level-wide
                    # prefetch would ship every edge's facets on
                    # first: N queries)
                    tab.prefetch_facets(
                        [((int(d), uid) if ch.reverse
                          else (uid, int(d))) for d in dsts.tolist()])
                items = []
                for d in dsts.tolist():
                    sub = self._emit_uid(
                        ch, int(d),
                        path | {int(d)} if path is not None else None,
                        cascade or cgq.cascade,
                        normalize or cgq.normalize)
                    if sub is None:
                        continue
                    if cgq.facets is not None:
                        fsrc, fdst = (int(d), uid) if ch.reverse \
                            else (uid, int(d))
                        fc = tab.get_facets(fsrc, fdst, self.read_ts)
                        self._attach_facets(sub, cgq.facets, fc, name)
                    if sub:
                        items.append(sub)
                if counts and len(dsts):
                    # count(uid) alongside siblings: the count rides
                    # as an extra row object even when every sibling
                    # row came up empty — but an empty EDGE LIST emits
                    # no key at all (ref query1_test.go
                    # TestCountAtRoot3: Daryl has count(friend):0 and
                    # NO friend key)
                    items.append({counts[0].alias or "count":
                                  len(dsts)})
                if items:
                    # a non-list uid predicate emits its single target
                    # as an OBJECT (ref query0_test.go
                    # TestGetNonListUidPredicate); reverse edges and
                    # count-carrying lists stay list-shaped
                    if not tab.schema.list_ and not ch.reverse \
                            and not counts and name not in obj:
                        obj[name] = items[0]
                    else:
                        _merge_list_key(obj, name, items)
                elif cascade:
                    # only an INHERITED cascade scope drops the
                    # parent; @cascade declared ON this child governs
                    # the child's own subtree — the parent just emits
                    # without the field (ref query4:TestCascadeSubQuery1)
                    return None
            else:
                if ch.col_vals is not None:
                    v = ch.col_vals.get(uid)
                    if v is not None:
                        obj[name] = v
                        continue
                    if cascade:
                        return None
                    continue
                ps = ch.values.get(uid)
                if ps and cgq.facets_filter is not None:
                    # @facets(eq(k, v)) on a VALUE predicate keeps
                    # only postings whose facets match (ref facets:
                    # TestFacetsFilterAtValueBasic — rows whose value
                    # fails the filter emit nothing)
                    ps = [p for p in ps
                          if self._eval_facet_tree(
                              cgq.facets_filter, p.facets)]
                if ps and cgq.langs == ["*"]:
                    # name@* : every language as its own key, the
                    # untagged value under the bare attr (ref
                    # query0_test.go TestQueryAllLanguages)
                    emitted = False
                    for p in ps:
                        key = f"{cgq.attr}@{p.lang}" if p.lang \
                            else cgq.attr
                        # canonical per-language keys; an alias can't
                        # name several keys, so it is ignored here
                        obj[key] = to_json_value(
                            self._typed(ch.tablet, p))
                        emitted = True
                    if emitted:
                        continue
                elif ps:
                    v = self._emit_value(ch, ps)
                    if v is not None:
                        obj[name] = v
                        if cgq.facets is not None:
                            self._attach_value_facets(obj, ch, ps, name)
                        continue
                if cascade:
                    return None
        if cascade:
            want = [c for c in children
                    if c.tablet is not None and not c.gq.is_count]
            for c in want:
                nm = c.gq.alias or c.gq.attr
                if nm not in obj and nm not in have:
                    return None
        return obj

    def _emit_value(self, ch: ExecNode, ps) -> Any:
        cgq = ch.gq
        tab = ch.tablet
        if tab.schema.value_type == TypeID.PASSWORD:
            # password hashes are never fetchable — only checkpwd()
            # reads them (ref query3:TestQueryPassword)
            return None
        if tab.schema.list_:
            vals = [to_json_value(self._typed(tab, p)) for p in ps
                    if not p.lang]
            return vals or None
        if cgq.langs:
            sel = self._select_posting(ps, cgq.langs)
            return to_json_value(self._typed(tab, sel)) if sel else None
        sel = self._select_posting(ps, [])
        return to_json_value(self._typed(tab, sel)) if sel else None

    def _order_paginate_facets(self, gq: GraphQuery, tab: Tablet,
                               parent: int, reverse: bool,
                               dsts: np.ndarray, orders) -> np.ndarray:
        """@facets(orderasc: k): sort a parent's edge list by facet
        value, missing-facet edges last (ref query.go sortWithFacet)."""
        def keys_for(d):
            row = []
            for o in orders:
                key = o.attr[len("facet:"):]
                fsrc, fdst = (int(d), parent) if reverse \
                    else (parent, int(d))
                fv = tab.get_facets(fsrc, fdst, self.read_ts).get(key)
                if fv is None:
                    row.append((1, 0))
                else:
                    try:
                        k = sort_key(fv)
                    except ValueError:
                        k = 0
                    row.append((0, -k if o.desc else k))
            row.append((0, int(d)))
            return tuple(row)

        ordered = np.asarray(sorted(dsts.tolist(), key=keys_for),
                             dtype=np.uint64)
        # pagination still applies after the facet sort
        stripped = GraphQuery(attr=gq.attr, first=gq.first,
                              offset=gq.offset, after=gq.after)
        return self._order_paginate(stripped, ordered)

    def _attach_value_facets(self, obj: dict, ch: ExecNode, ps,
                             name: str):
        """name|key facets of value postings; list predicates emit a
        position-indexed map (ref outputnode.go facetsNode handling)."""
        cgq = ch.gq
        fp = cgq.facets
        tab = ch.tablet
        if tab.schema.list_:
            plist = [p for p in ps if not p.lang]
            by_key: dict[str, dict[str, Any]] = {}
            for i, p in enumerate(plist):
                sel = p.facets if fp.all_keys else {
                    k: p.facets[k] for k, _ in fp.keys if k in p.facets}
                for k, v in sel.items():
                    by_key.setdefault(k, {})[str(i)] = to_json_value(v)
            alias = {} if fp.all_keys else \
                {k: a for k, a in fp.keys if a}
            for k, m in by_key.items():
                obj[alias.get(k) or f"{name}|{k}"] = m
            return
        sel = self._select_posting(ps, cgq.langs)
        if sel is not None and sel.facets:
            self._attach_facets(obj, fp, sel.facets, name)

    def _attach_facets(self, item: dict, fp, facets: dict, edge: str):
        if not facets:
            return
        sel = facets if fp.all_keys else {
            k: facets[k] for k, _ in fp.keys if k in facets}
        alias = {} if fp.all_keys else \
            {k: a for k, a in fp.keys if a}
        for k, v in sel.items():
            # an ALIASED facet emits under the bare alias; unaliased
            # ones keep the edge|key form (ref facets:TestFacetsAlias:
            # `tagalias: tag` -> "tagalias", bare `family` ->
            # "friend|family")
            key = alias.get(k) or f"{edge}|{k}"
            item[key] = to_json_value(v)

    def _groupby_groups(self, gq: GraphQuery, dsts: np.ndarray
                        ) -> dict[tuple, list[int]]:
        """Group member uids by the tuple of their @groupby attr values
        (ref query/groupby.go:371 processGroupBy). Multi-valued attrs
        fan a member into every combination; members missing any
        grouped attr are dropped (the reference's dedupMap only sees
        uids that produced a value for each predicate)."""
        from itertools import product

        fast = self._groupby_groups_vec(gq.groupby, dsts)
        if fast is not None:
            return fast
        groups: dict[tuple, list[int]] = {}
        for d in dsts.tolist():
            per_attr: list[list] = []
            for ga in gq.groupby:
                tab = self._tablet(ga.attr)
                vals: list = []
                if tab is not None:
                    if tab.schema.value_type == TypeID.UID:
                        vals = [hex(t) for t in tab.get_dst_uids(
                            d, self.read_ts).tolist()]
                    else:
                        # list-valued scalars fan into every value's
                        # group; ga.lang selects that language's
                        # postings, default the untagged ones
                        ps = tab.get_postings(d, self.read_ts)
                        want = ga.lang or ""
                        seen = set()
                        for p in ps:
                            if p.lang != want:
                                continue
                            v = to_json_value(self._typed(tab, p))
                            k = v if isinstance(v, (str, int, float,
                                                    bool)) else str(v)
                            if k not in seen:
                                seen.add(k)
                                vals.append(v)
                if not vals:
                    per_attr = []
                    break
                per_attr.append(vals)
            if not per_attr:
                continue
            for combo in product(*per_attr):
                groups.setdefault(tuple(combo), []).append(int(d))
        return groups

    def _groupby_attr_codes(self, ga):
        """One @groupby attr as a vectorized key column:
        (uids sorted u64, codes int64 aligned, decode) where decode
        maps a code back to the output key value. uid predicates fan
        out via their flat edge table (need_pairs marks them); scalar
        predicates contribute one (uid, code) per valued member.
        Returns None -> caller keeps the exact per-uid path."""
        tab = self._tablet(ga.attr)
        if tab is None or not self._columnar_on():
            return None
        if tab.schema.value_type == TypeID.UID:
            if ga.lang or not hasattr(tab, "edge_table"):
                return None
            et = tab.edge_table(self.read_ts)
            if et is None:
                return None
            srcs, dsts = et
            # dst uids ARE the codes — kept uint64 (an int64 cast
            # would render uids >= 2^63 as negative hex)
            return srcs, dsts, lambda c: hex(int(c))
        col = self._colview(tab, lang=ga.lang or None)
        if col is None:
            return None
        srcs, tid, data, enc = col
        if data is not None:
            if tid == TypeID.BOOL:
                return srcs, data.astype(np.int64), \
                    lambda c: bool(c)
            if tid == TypeID.FLOAT:
                if np.isnan(data).any():
                    return None  # nan keys keep dict semantics
                # float keys: code through the unique table to stay
                # integral for the lexsort/boundary pass
                uk = np.unique(data)
                return srcs, np.searchsorted(uk, data), \
                    lambda c, _uk=uk: float(_uk[int(c)])
            return srcs, data.astype(np.int64), lambda c: int(c)
        got = col.enc_codes()
        if got is None:
            return None
        codes, table = got

        def dec(c, _t=table):
            return _t[int(c)].decode("utf-8")

        # count-fast extras: bulk decode (no per-element dispatch)
        # and, when byte order == output order, permission to skip
        # the per-group python sort altogether
        dec.bulk = lambda cs, _t=table: \
            [_t[c].decode("utf-8") for c in cs]
        dec.byte_ordered = col.enc_sort_safe() \
            if hasattr(col, "enc_sort_safe") else False
        return srcs, codes, dec

    def _groupby_groups_vec(self, gattrs, dsts: np.ndarray
                            ) -> Optional[dict[tuple, list[int]]]:
        """Vectorized grouping for ANY @groupby attr list (ref
        query/groupby.go:371 processGroupBy): each attr's keys come
        from columnar views (cached integer codes for strings, flat
        edge tables for uid fan-out), members join against them with
        searchsorted ranges, and the combined key tuples group via one
        lexsort + boundary scan — no per-uid posting walks. Returns
        None (exact path) when any attr lacks a clean columnar view."""
        cols = []
        for ga in gattrs:
            got = self._groupby_attr_codes(ga)
            if got is None:
                return None
            cols.append(got)
        rows = np.ascontiguousarray(dsts, dtype=np.uint64)
        code_cols: list[np.ndarray] = []
        for (u_sorted, codes, _dec) in cols:
            got = _join_codes(u_sorted, codes, rows)
            if got is None:
                return {}
            rep, gathered = got
            code_cols = [c[rep] for c in code_cols]
            code_cols.append(gathered)
            rows = rows[rep]
        if not len(rows):
            return {}
        order = np.lexsort(tuple(reversed(code_cols)))
        sorted_cols = [c[order] for c in code_cols]
        rows_s = rows[order]
        change = np.zeros(len(rows_s), bool)
        change[0] = True
        for c in sorted_cols:
            change[1:] |= c[1:] != c[:-1]
        bidx = np.nonzero(change)[0]
        bounds = np.append(bidx, len(rows_s)).tolist()
        inc_counter("query_groupby_fast_total")
        groups: dict[tuple, list[int]] = {}
        members = rows_s.tolist()
        for g in range(len(bidx)):
            s, e = bounds[g], bounds[g + 1]
            key = tuple(cols[k][2](sorted_cols[k][s])
                        for k in range(len(cols)))
            groups[key] = members[s:e]
        return groups

    def _groupby_entry(self, gq: GraphQuery, key: tuple,
                       members: list[int]) -> dict:
        """One output group: keys + count(uid) + aggregations over
        value vars (ref groupby.go aggregateGroup)."""
        ent: dict[str, Any] = {}
        for ga, kv in zip(gq.groupby, key):
            ent[ga.alias or ga.attr] = kv
        for cgq in gq.children:
            if cgq.attr == "uid" and cgq.is_count:
                ent[cgq.alias or "count"] = len(members)
            elif cgq.agg_func and cgq.needs_var:
                vmap = self.value_vars.get(cgq.needs_var[0].name, {})
                agg = _agg_members(cgq.agg_func, vmap, members)
                if agg is not None:
                    name = cgq.alias or \
                        f"{cgq.agg_func}(val({cgq.needs_var[0].name}))"
                    ent[name] = to_json_value(agg)
            elif cgq.agg_func and cgq.agg_pred:
                # max(name): aggregate a PREDICATE over the group's
                # members (ref query0_test.go TestGroupByAgg)
                agg = self._agg_pred_members(cgq, members)
                if agg is not None:
                    name = cgq.alias or \
                        f"{cgq.agg_func}({cgq.agg_pred})"
                    ent[name] = to_json_value(agg)
        return ent

    def _agg_pred_members(self, cgq, members) -> Optional[Val]:
        tab = self._tablet(cgq.agg_pred)
        if tab is None:
            return None
        if not cgq.langs:
            colview = self._colview(tab)
            if colview is not None and colview.data is not None \
                    and colview.tid in (TypeID.INT, TypeID.FLOAT):
                # max(name)-style predicate aggregation over a group:
                # one gather in MEMBER order (float-sum rounding equals
                # the posting walk's left fold) instead of a
                # get_postings round per member. Untagged selection ==
                # the column's own selection; tagged postings are never
                # picked by an empty lang list, so extras don't matter
                marr = np.asarray(members, np.uint64)
                pos, hit = _col_positions(colview.srcs, marr)
                arr = colview.data[pos[hit]]
                if not len(arr):
                    return None
                tid = colview.tid
                fn = cgq.agg_func
                if fn == "min":
                    return Val(tid, arr[int(np.argmin(arr))].item())
                if fn == "max":
                    return Val(tid, arr[int(np.argmax(arr))].item())
                if fn in ("sum", "avg"):
                    s = sum(arr.tolist())
                    if fn == "avg":
                        return Val(TypeID.FLOAT, s / len(arr))
                    return Val(TypeID.INT if isinstance(s, int)
                               else TypeID.FLOAT, s)
                return None
        vals = []
        for u in members:
            ps = tab.get_postings(int(u), self.read_ts)
            sel = self._select_posting(ps, cgq.langs or [])
            if sel is not None:
                vals.append(self._typed(tab, sel))
        return _aggregate(cgq.agg_func, vals)

    def _emit_groupby(self, ch: ExecNode, dsts: np.ndarray) -> dict:
        """@groupby(attrs...) { count(uid) aggs... }
        (ref query/groupby.go:371)."""
        fast = self._emit_groupby_count_fast(ch.gq, dsts)
        if fast is not None:
            return fast
        groups = self._groupby_groups(ch.gq, dsts)
        return {"@groupby": [
            self._groupby_entry(ch.gq, key, members)
            for key, members in sorted(groups.items(),
                                       key=lambda kv: str(kv[0]))]}

    def _emit_groupby_count_fast(self, gq: GraphQuery,
                                 dsts: np.ndarray) -> Optional[dict]:
        """Single-attr @groupby whose only child is count(uid): group
        counts come from one np.unique over the gathered key codes —
        no member lists, no per-group entry builder. This is the root
        groupby shape (q052/ref query0:TestGroupByRoot) where the
        general path's per-group Python dominated at 21M."""
        if len(gq.groupby) != 1 or len(gq.children) != 1:
            return None
        cgq = gq.children[0]
        if cgq.attr != "uid" or not cgq.is_count or cgq.var:
            return None
        got = self._groupby_attr_codes(gq.groupby[0])
        if got is None:
            return None
        u_sorted, codes, dec = got
        rows = np.ascontiguousarray(dsts, dtype=np.uint64)
        joined = _join_codes(u_sorted, codes, rows)
        if joined is None:
            return {"@groupby": []}
        uniq, counts = np.unique(joined[1], return_counts=True)
        inc_counter("query_groupby_fast_total")
        ga = gq.groupby[0]
        keyname = ga.alias or ga.attr
        cname = cgq.alias or "count"
        bulk = getattr(dec, "bulk", None)
        ucodes = uniq.tolist()
        vals = bulk(ucodes) if bulk else [dec(c) for c in ucodes]
        ents = [{keyname: v, cname: n}
                for v, n in zip(vals, counts.tolist())]
        # identical ordering contract to the general path: sort by
        # the str() of the 1-key tuple — skipped when np.unique's
        # byte order already IS that order (safe-ASCII payloads)
        if not getattr(dec, "byte_ordered", False):
            ents.sort(key=lambda e: str((e[keyname],)))
        return {"@groupby": ents}

    def _bind_groupby_vars(self, gq: GraphQuery, dest: np.ndarray):
        """`a as count(uid)` / `m as max(val(x))` inside a groupby block
        binds a value var keyed by the group's uid — only legal when
        grouping by exactly one uid predicate (ref groupby.go:118
        "can only use UID predicate with groupby" for var assignment).
        Aggregated across every parent's edge set (dest union), like
        the reference's var groupby over the whole block."""
        var_children = [c for c in gq.children if c.var]
        if not var_children:
            return
        tab0 = self._tablet(gq.groupby[0].attr) if gq.groupby else None
        if len(gq.groupby) != 1 or tab0 is None or \
                tab0.schema.value_type != TypeID.UID:
            raise GQLError(
                "assigning a groupby result to a variable needs exactly "
                "one uid predicate in @groupby")
        groups = self._groupby_groups(gq, dest)
        for cgq in var_children:
            vmap: dict[int, Val] = {}
            for key, members in groups.items():
                guid = int(key[0], 0)
                if cgq.attr == "uid" and cgq.is_count:
                    vmap[guid] = Val(TypeID.INT, len(members))
                elif cgq.agg_func and cgq.needs_var:
                    src = self.value_vars.get(cgq.needs_var[0].name, {})
                    agg = _agg_members(cgq.agg_func, src, members)
                    if agg is not None:
                        vmap[guid] = agg
                elif cgq.agg_func and cgq.agg_pred:
                    agg = self._agg_pred_members(cgq, members)
                    if agg is not None:
                        vmap[guid] = agg
            self.value_vars[cgq.var] = vmap

    def _recurse_scalar_cache(self, node: ExecNode) -> dict:
        """uid -> json value maps for every flat scalar child of a
        @recurse block, gathered columnarly over the WHOLE visited uid
        set once — the per-node get_postings walk dominated the q067
        profile (one posting fetch per node per scalar pred across
        ~10k visited nodes). Keys = (attr, langs); ineligible children
        (lang fans, lists, vars, facets) stay on the exact path."""
        parts = [node.dest]
        for lv in node.recurse_levels:
            for per_parent in lv.values():
                parts.extend(per_parent.values())
        parts = [p for p in parts if len(p)]
        if not parts:
            return {}
        allu = np.unique(np.concatenate(parts))
        cache: dict = {}
        seen: set = set()
        levels = node.recurse_preds or [node.gq.children]
        for preds in levels:
            for cgq in preds:
                tab = self._tablet(cgq.attr.lstrip("~"))
                if tab is None \
                        or tab.schema.value_type == TypeID.UID:
                    continue
                key = (cgq.attr, tuple(cgq.langs or ()))
                if key in seen:
                    continue
                seen.add(key)
                cm = self._colvals_for_emit(tab, cgq, allu)
                if cm is not None:
                    cache[key] = cm
        return cache

    def _emit_recurse_node(self, node: ExecNode, uid: int, level: int
                           ) -> dict:
        # uid appears only when the block asks for it (ref
        # query3_test.go TestRecurseQuery vs TestRecurseQueryLimitDepth2)
        obj: dict[str, Any] = {}
        if any(c.attr == "uid" and not c.is_count
               for c in node.gq.children):
            obj["uid"] = hex(uid)
        # per-level resolved children (expand() differs by level); the
        # deepest nodes reuse the last level's resolution for scalars
        if node.recurse_preds:
            children = node.recurse_preds[
                min(level, len(node.recurse_preds) - 1)]
        else:
            children = node.gq.children
        # value/scalar children at every level
        for cgq in children:
            tab = self._tablet(cgq.attr.lstrip("~"))
            if tab is None:
                continue
            name = cgq.alias or cgq.attr
            if tab.schema.value_type != TypeID.UID:
                cm = getattr(self, "_recurse_colvals", {}).get(
                    (cgq.attr, tuple(cgq.langs or ())))
                if cm is not None:
                    v = cm.get(uid)
                    if v is not None:
                        obj[name] = v
                    continue
                ps = tab.get_postings(uid, self.read_ts)
                if cgq.langs == ["*"]:
                    for p in ps:
                        key = f"{cgq.attr}@{p.lang}" if p.lang \
                            else cgq.attr
                        obj[key] = to_json_value(self._typed(tab, p))
                    continue
                sel = self._select_posting(ps, cgq.langs)
                if sel is not None:
                    obj[name] = to_json_value(self._typed(tab, sel))
        if level < len(node.recurse_levels):
            lv = node.recurse_levels[level]
            for cgq in children:
                attr = cgq.attr
                per_parent = lv.get(attr)
                if not per_parent or uid not in per_parent:
                    continue
                name = cgq.alias or attr
                kids = [k for k in
                        (self._emit_recurse_node(node, int(d),
                                                 level + 1)
                         for d in self._order_paginate(
                             cgq, per_parent[uid]).tolist())
                        if k]  # empty nodes drop (TestRecurseQuery:
                #                the nameless friend never appears)
                if kids:
                    obj[name] = kids
        return obj

    def _emit_paths(self, node: ExecNode) -> list:
        """_path_ emission: the NESTED chain keyed by each hop's
        traversed predicate, facet weight as `pred|key` on the hop's
        child object (ref query/outputnode.go shortest-path subgraph +
        query3_test.go TestKShortestPathWeighted shape)."""
        out = []
        weights = node.path_weights or [None] * len(node.path_nodes)
        specs = getattr(node, "path_specs", None) or []
        for path, w in zip(node.path_nodes, weights):
            if not path:
                continue
            tree: dict[str, Any] = {"uid": hex(path[0])}
            if w is not None:
                # the reference renders weights %f-style (6 places), so
                # an accumulated 0.30000000000000004 reads back as 0.3
                tree["_weight_"] = float(f"{w:.6f}")
            cur = tree
            for u, v in zip(path, path[1:]):
                hop = None
                for attr, tab, rev, wkey in specs:
                    get = tab.get_reverse_uids if rev \
                        else tab.get_dst_uids
                    ds = get(int(u), self.read_ts)
                    if np.any(ds == v):
                        hop = (attr, tab, rev, wkey)
                        break
                child: dict[str, Any] = {"uid": hex(int(v))}
                if hop is None:
                    cur["path"] = child
                else:
                    attr, tab, rev, wkey = hop
                    cur[attr] = child
                    if wkey:
                        fsrc, fdst = (int(v), int(u)) if rev \
                            else (int(u), int(v))
                        fv = tab.get_facets(
                            fsrc, fdst, self.read_ts).get(wkey)
                        if fv is not None:
                            child[f"{attr}|{wkey}"] = to_json_value(fv)
                cur = child
            out.append(tree)
        return out

    def _normalize(self, obj: dict) -> list[dict]:
        """@normalize: flatten nesting into one row per LEAF PATH —
        the cartesian merge of each child list's flattened rows with
        the parent's scalars (ref outputnode.go:325 normalize's
        parentSlice x childSlice merge). A parent with two friends
        yields two flat rows, never one merged-overwritten object."""
        rows: list[dict] = [{k: v for k, v in obj.items()
                             if k != "uid" and not isinstance(v, dict)
                             and not (isinstance(v, list) and v
                                      and isinstance(v[0], dict))}]
        for k, v in obj.items():
            if isinstance(v, dict):
                child_rows = self._normalize(v)
            elif isinstance(v, list) and v and isinstance(v[0], dict):
                child_rows = [r for item in v
                              for r in self._normalize(item)]
            else:
                continue
            if child_rows:
                rows = [{**r, **c} for r in rows for c in child_rows]
        return rows


class Agg:
    __slots__ = ("kind", "value")

    def __init__(self, kind, value):
        self.kind = kind
        self.value = value


def _cmp(op: str, a, b) -> bool:
    # one comparator table for scalar and vector paths (_CMP_VEC) —
    # they had drifted once already (review finding)
    fn = _CMP_VEC.get(op)
    if fn is None:
        raise GQLError(f"bad comparison {op}")
    return fn(a, b)


def _agg_members(fn: str, vmap, members: list[int]) -> Optional[Val]:
    """Aggregate a value var over one group's member uids — columnar
    vars use one searchsorted gather in member order (the dict path's
    iteration order, so float-sum rounding is unchanged)."""
    if isinstance(vmap, ColVar):
        m = np.asarray(members, dtype=np.uint64)
        _u, vals = vmap.gather(m)
        return _aggregate_col(fn, vals, vmap)
    vals = [vmap[u] for u in members if u in vmap]
    return _aggregate(fn, vals)


def _internal_values(vmap, src: np.ndarray, kind: str) -> dict:
    """node.values for a val()/math node.  Emission only ever reads the
    block's own uids, so a columnar var materializes Vals for src
    alone — not its whole (possibly 21M-scale) domain."""
    if isinstance(vmap, ColVar) and src is not None and len(src):
        # materialize per ROW at emission, not per domain here: the
        # block may paginate 1M var rows down to a handful (q046).
        # src arrives in EMISSION order (post-sort) — the lazy map's
        # lookups need an ascending domain
        return _ColAggVals(vmap.take(np.sort(src)), kind)
    return {u: [Agg(kind, v)] for u, v in vmap.items()}


class _ColAggVals(Mapping):
    """node.values view over a ColVar subset: each emitted row
    materializes its [Agg(Val)] on demand; exact object columns
    (datetime vars) bypass the lossy float domain."""

    __slots__ = ("sub", "kind")

    def __init__(self, sub: ColVar, kind: str):
        self.sub = sub
        self.kind = kind

    def __len__(self):
        return len(self.sub.uids)

    def __iter__(self):
        return iter(self.sub.uids.tolist())

    def __contains__(self, u):
        arr = self.sub.uids
        i = int(np.searchsorted(arr, np.uint64(u)))
        return i < len(arr) and int(arr[i]) == int(u)

    def get(self, u, default=None):
        arr = self.sub.uids
        i = int(np.searchsorted(arr, np.uint64(u)))
        if i >= len(arr) or int(arr[i]) != int(u):
            return default
        if self.sub.objs is not None:
            v = Val(self.sub.tid, self.sub.objs[i])
        else:
            v = self.sub.to_val(self.sub.vals[i])
        return [Agg(self.kind, v)]

    def __getitem__(self, u):
        got = self.get(u)
        if got is None:
            raise KeyError(u)
        return got


def _aggregate_col(fn: str, arr: np.ndarray, cv: ColVar) -> Optional[Val]:
    """_aggregate over a gathered ColVar column — no Val materialization.
    Sum stays a sequential left fold over the python list (ints exact,
    float rounding identical to the dict path's committed goldens).
    Math-result vars (frac/isbool) keep per-element typing quirks by
    falling back to the Val path."""
    if not len(arr):
        return None
    if cv.frac or cv.isbool:
        return _aggregate(fn, [cv.to_val(x) for x in arr.tolist()])
    if cv.tid == TypeID.BOOL:
        if fn == "min":
            return Val(TypeID.BOOL, bool(arr.min()))
        if fn == "max":
            return Val(TypeID.BOOL, bool(arr.max()))
        return None  # sum/avg over bools: not numeric (dict-path parity)
    if fn == "min":
        return cv.to_val(arr[int(np.argmin(arr))])
    if fn == "max":
        return cv.to_val(arr[int(np.argmax(arr))])
    if fn == "sum":
        s = sum(arr.tolist())
        return Val(TypeID.INT if isinstance(s, int) else TypeID.FLOAT, s)
    if fn == "avg":
        return Val(TypeID.FLOAT, sum(arr.tolist()) / len(arr))
    return None


def _aggregate(fn: str, vals: list[Val]) -> Optional[Val]:
    # uniform numeric fast path: one numpy reduction instead of a
    # per-element sort_key() python loop (q020 at the 21M regime spends
    # ~half its time here otherwise; ref query/aggregator.go works on
    # typed scalars the same way)
    if vals:
        t0 = vals[0].tid
        if t0 in (TypeID.INT, TypeID.FLOAT) \
                and all(v.tid is t0 for v in vals):
            try:
                arr = np.asarray(
                    [v.value for v in vals],
                    np.int64 if t0 == TypeID.INT else np.float64)
            except (TypeError, ValueError, OverflowError):
                arr = None
            if arr is not None:
                if fn == "min":
                    return vals[int(np.argmin(arr))]
                if fn == "max":
                    return vals[int(np.argmax(arr))]
                if fn == "sum":
                    # sequential sum over the C-level list, NOT
                    # np.sum: ints must not wrap at int64, and
                    # numpy's pairwise float summation rounds
                    # differently than the committed goldens
                    return Val(t0, sum(arr.tolist()))
                if fn == "avg":
                    return Val(TypeID.FLOAT,
                               sum(arr.tolist()) / len(arr))
    nums = []
    for v in vals:
        if v.tid in (TypeID.INT, TypeID.FLOAT):
            nums.append(v.value)
        elif v.tid == TypeID.DATETIME:
            nums.append(v)
    if not vals:
        return None
    if fn in ("min", "max"):
        try:
            pick = (min if fn == "min" else max)(
                vals, key=lambda v: sort_key(v))
            return pick
        except ValueError:
            return None
    if not nums:
        return None
    plain = [n for n in nums if not isinstance(n, Val)]
    if not plain:
        return None
    if fn == "sum":
        s = sum(plain)
        return Val(TypeID.INT if isinstance(s, int) else TypeID.FLOAT, s)
    if fn == "avg":
        return Val(TypeID.FLOAT, sum(plain) / len(plain))
    return None


class _VecFallback(Exception):
    """Raised inside _eval_math_vec when a leaf or op needs the dict
    path (non-columnar var, datetime, exotic result)."""


def _eval_math_vec(tree, value_vars):
    """Columnar _eval_math: every var leaf is a ColVar, every op is a
    vector op over float64 — the same domain the dict path works in
    (its leaves go through float()).  N-ary ops align operands by
    intersecting uid arrays; per-element failure semantics (div by
    zero, sqrt of negative, log of nonpositive drop the uid) are
    reproduced with masks or per-element maps.  Returns a ColVar, or
    None for an all-constant tree (dict-path parity: no per-uid map)."""
    import math as _m
    import time as _time

    # Array nodes are (uids, float64 vals, isbool).  Bool-ness is a
    # FLAG, never a dtype: the dict path's python bools act as 0/1
    # ints inside arithmetic (True+True == 2) but materialize as BOOL
    # when they survive to the top — numpy bool arrays would instead
    # do logical arithmetic (True+True == True), so comparisons store
    # 0.0/1.0 and carry the flag.

    # float64 is the working domain — bail to the exact dict path
    # whenever int semantics are observable: int columns beyond 2^53,
    # or an int/int division/mod (integral + truncating in the
    # reference's int64 arm, math.go applyArith; float division would
    # both misdivide and misround)
    def _int_exactness_check(t) -> tuple[bool, float]:
        """(is_int, max-abs bound) for subtree t; raises _VecFallback
        when int RESULTS could leave float64's exact range (not just
        inputs — f*f of two in-range ints overflows 2^53) or an
        int/int division needs the exact truncating arm."""
        if t.const is not None:
            isint = isinstance(t.const, int)
            if isint and abs(t.const) >= 2 ** 53:
                raise _VecFallback
            return isint, float(abs(t.const))
        if t.var:
            cv = value_vars.get(t.var)
            if isinstance(cv, ColVar) and cv.tid == TypeID.INT:
                b = float(np.abs(cv.vals).max()) if len(cv.vals) \
                    else 0.0
                if b >= 2.0 ** 53:
                    raise _VecFallback
                return True, b
            return False, 0.0
        subs = [_int_exactness_check(c) for c in t.children]
        if t.fn == "cond":
            # the RESULT is one of the branches — the boolean
            # condition child never contributes int-ness or bounds
            subs = subs[1:]
        ints = bool(subs) and all(i for i, _ in subs)
        bounds = [b for _, b in subs]
        if t.fn in ("/", "%") and ints:
            raise _VecFallback
        if not ints:
            return False, 0.0
        if t.fn in ("+", "-"):
            b = sum(bounds)
        elif t.fn == "*":
            b = 1.0
            for x in bounds:
                b *= max(x, 1.0)
        elif t.fn in ("min", "max", "cond"):
            b = max(bounds) if bounds else 0.0
        else:
            return False, 0.0
        if b >= 2.0 ** 53:
            raise _VecFallback
        return True, b

    _int_exactness_check(tree)

    def align(args):
        """Align array-arg uid domains; broadcast consts. Mismatched
        domains need the dict path's union-with-zero semantics
        (ref query/math.go:73) — bail rather than intersect."""
        arrs = [a for a in args if not isinstance(a, float)]
        uids = arrs[0][0]
        for a in arrs[1:]:
            if len(a[0]) != len(uids) \
                    or not np.array_equal(a[0], uids):
                raise _VecFallback
        out = []
        for a in args:
            if isinstance(a, float):
                out.append(np.full(len(uids), a))
            else:
                pos = np.searchsorted(a[0], uids)
                out.append(a[1][pos])
        return uids, out

    def map1(fn, uids, x):
        ou, ov = [], []
        for u, xv in zip(uids.tolist(), x.tolist()):
            try:
                ov.append(float(fn(xv)))
            except (ZeroDivisionError, ValueError):
                continue
            ou.append(u)
        return (np.asarray(ou, np.uint64),
                np.asarray(ov, np.float64), False)

    def eval_node(t):
        if t.const is not None:
            return float(t.const)
        if t.var:
            cv = value_vars.get(t.var)
            if cv is None:
                return (np.asarray([], np.uint64),
                        np.asarray([], np.float64), False)
            if not isinstance(cv, ColVar):
                raise _VecFallback
            return (cv.uids, cv.floats(), False)
        args = [eval_node(c) for c in t.children]
        if all(isinstance(a, float) for a in args):
            raise _VecFallback  # constant subtree feeding per-uid ops:
            # keep the dict path's scalar folding exactly
        flags = [a[2] if not isinstance(a, float) else False
                 for a in args]
        uids, asarr = align(args)
        fn = t.fn
        if fn == "+":
            return uids, asarr[0] + asarr[1], False
        if fn == "-":
            return (uids, asarr[0] - asarr[1], False) \
                if len(asarr) == 2 else (uids, -asarr[0], False)
        if fn == "*":
            return uids, asarr[0] * asarr[1], False
        if fn in ("/", "%"):
            keep = asarr[1] != 0.0
            u2, a, b = uids[keep], asarr[0][keep], asarr[1][keep]
            return u2, (a / b if fn == "/" else np.mod(a, b)), False
        if fn in ("<", ">", "<=", ">=", "==", "!="):
            r = {"<": np.less, ">": np.greater, "<=": np.less_equal,
                 ">=": np.greater_equal, "==": np.equal,
                 "!=": np.not_equal}[fn](asarr[0], asarr[1])
            return uids, r.astype(np.float64), True
        if fn == "cond":
            # the result is one of the BRANCHES, so only their flags
            # matter; mixed bool/number branches would need a
            # per-element flag — dict path handles those
            bflags = flags[1:]
            if any(bflags) and not all(bflags):
                raise _VecFallback
            r = np.where(asarr[0] != 0, asarr[1], asarr[2])
            return uids, r, all(bflags)
        if fn in ("min", "max"):
            # python min/max RETURN one operand, so a bool operand can
            # surface element-wise; only uniform flags are
            # representable with one flag
            if any(flags) and not all(flags):
                raise _VecFallback
            r = asarr[0]
            red = np.minimum if fn == "min" else np.maximum
            for x in asarr[1:]:
                r = red(r, x)
            return uids, r, all(flags)
        if fn == "floor":
            return uids, np.floor(asarr[0]), False
        if fn == "ceil":
            return uids, np.ceil(asarr[0]), False
        if fn == "sqrt":
            # math.sqrt raises only for NEGATIVE args; NaN passes
            # through as NaN and keeps its uid
            keep = ~(asarr[0] < 0.0)
            return uids[keep], np.sqrt(asarr[0][keep]), False
        # transcendental / two-arg host funcs: per-element math.* calls
        # for bit-parity with the dict path (numpy's vectorized exp/log
        # can differ in the last ulp)
        if fn == "exp":
            return map1(_m.exp, uids, asarr[0])
        if fn == "ln":
            return map1(_m.log, uids, asarr[0])
        if fn == "sigmoid":
            return map1(lambda x: 1.0 / (1.0 + _m.exp(-x)),
                        uids, asarr[0])
        if fn == "since":
            # wall clock by SEMANTICS: since() measures from an
            # epoch-seconds datetime value (ref applySince)
            now = _time.time()  # dglint: disable=DG06
            return uids, now - asarr[0], False
        if fn in ("pow", "logbase"):
            xs, ys = asarr[0].tolist(), asarr[1].tolist()
            ou, ov = [], []
            op = (lambda x, y: x ** y) if fn == "pow" else _m.log
            for u, xv, yv in zip(uids.tolist(), xs, ys):
                try:
                    # complex pow results raise TypeError at float()
                    # and must propagate to the dict-path fallback,
                    # which keeps the uid (historical behavior)
                    ov.append(float(op(xv, yv)))
                except (ZeroDivisionError, ValueError):
                    continue
                ou.append(u)
            return (np.asarray(ou, np.uint64),
                    np.asarray(ov, np.float64), False)
        raise _VecFallback  # op the vector path doesn't cover

    res = eval_node(tree)
    if isinstance(res, float):
        return None
    uids, vals, isbool = res
    if isbool:
        return ColVar(uids, vals.astype(np.uint8), TypeID.FLOAT,
                      isbool=True)
    return ColVar(uids, vals.astype(np.float64), TypeID.FLOAT,
                  frac=True)


def _merge_list_key(obj: dict, name: str, items: list):
    """Repeated child attrs share one output key, merged in child
    order (ref query0:TestGroupBy_RepeatAttr: a @groupby friend and a
    plain friend both land under \"friend\"); a prior single-object
    occupant joins the list rather than being dropped."""
    prev = obj.get(name)
    if isinstance(prev, list):
        obj[name] = prev + items
    elif name in obj:
        obj[name] = [prev] + items
    else:
        obj[name] = items


def _join_codes(u_sorted: np.ndarray, codes: np.ndarray,
                rows: np.ndarray
                ) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Join group members against one key column: for every row uid,
    gather EVERY aligned code (multi-valued attrs fan out). Returns
    (rep, gathered) where rep repeats each row index once per matched
    code and gathered holds the codes; None when nothing matches."""
    starts = np.searchsorted(u_sorted, rows, "left")
    ends = np.searchsorted(u_sorted, rows, "right")
    cnt = (ends - starts).astype(np.int64)
    total = int(cnt.sum())
    if total == 0:
        return None
    rep = np.repeat(np.arange(len(rows)), cnt)
    # gathered indices = starts[row] + position-within-row
    base = np.repeat(starts, cnt)
    csum = np.concatenate(([0], np.cumsum(cnt)[:-1]))
    inner = np.arange(total) - np.repeat(csum, cnt)
    return rep, codes[base + inner]


def _math_tree_vars(tree):
    """Every var name a math tree reads."""
    if tree.var:
        yield tree.var
    for c in tree.children:
        yield from _math_tree_vars(c)


def _eval_math(tree, value_vars, src=None) -> "dict[int, Val] | ColVar":
    """Per-uid math over value vars (ref query/math.go:213 processBinary).
    Tries the columnar path first; falls back to the per-uid dict walk
    when a var isn't columnar or an op needs scalar semantics. An
    ALL-CONSTANT expression broadcasts over the enclosing block's uids
    (ref query0_test.go TestQueryConstMathVal: `a as math(24/8 * 3)`
    binds 9 for every root uid)."""
    import math as _m

    def const_map(x):
        if src is None or not len(src):
            return {}
        if isinstance(x, int) and not isinstance(x, bool):
            v = Val(TypeID.INT, x)  # exact at any magnitude
        elif float(x).is_integer() and abs(x) < 2**53:
            v = Val(TypeID.INT, int(x))
        else:
            v = Val(TypeID.FLOAT, float(x))
        return {int(u): v for u in src.tolist()}

    try:
        cv = _eval_math_vec(tree, value_vars)
        if cv is not None:
            return cv
        # None = all-constant tree: fall through so the dict path
        # folds the scalar and broadcasts it
    except _VecFallback:
        pass
    except (TypeError, OverflowError):
        # exotic per-element results (complex pow, overflow) — let the
        # dict path produce its exact historical behavior
        pass

    def eval_node(t) -> dict[int, float] | float:
        if t.const is not None:
            # int literals stay ints (exact arithmetic + the int/int
            # division arm); everything else is float64
            return t.const if isinstance(t.const, int) \
                else float(t.const)
        if t.var:
            vmap = value_vars.get(t.var, {})
            # datetimes flow as epoch-seconds floats so since() and
            # date comparisons work (ref aggregator.go applySince
            # converts datetime -> float seconds); INT values stay
            # python ints — the int/int arithmetic arm must be exact
            # beyond 2^53 and divide integrally (ref math.go int64
            # arm; query4:TestBigMathValue/TestFloatConverstion)
            return {u: (v.value.timestamp()
                        if v.tid == TypeID.DATETIME
                        else int(v.value) if v.tid == TypeID.INT
                        else float(v.value))
                    for u, v in vmap.items()
                    if v.tid in (TypeID.INT, TypeID.FLOAT, TypeID.BOOL,
                                 TypeID.DATETIME)}
        args = [eval_node(c) for c in t.children]
        fn = t.fn
        dicts = [a for a in args if isinstance(a, dict)]
        if not dicts:
            # all-constant expression
            return _apply_math(fn, list(args), _m)
        out = {}
        if fn in ("<", ">", "<=", ">=", "==", "!="):
            # comparisons iterate the LEFT operand's domain; a uid the
            # right map misses compares against zero (ref
            # query/math.go:147 processBinaryBoolean srcMap loop)
            left, right = args[0], args[1]
            if not isinstance(left, dict):
                return {}
            for u, lv in left.items():
                rv = right.get(u, 0.0) if isinstance(right, dict) \
                    else right
                try:
                    out[u] = _apply_math(fn, [lv, rv], _m)
                except (ZeroDivisionError, ValueError):
                    continue
            return out
        if fn == "cond":
            cond = args[0]
            if not isinstance(cond, dict):
                return {}
            for u, cv in cond.items():
                branch = args[1] if cv else args[2]
                out[u] = branch.get(u, 0.0) \
                    if isinstance(branch, dict) else branch
            return out
        # arithmetic / min / max / unary: the UNION of the operand
        # domains, zero-filling a side that misses the uid (ref
        # query/math.go:73 processBinary iterating mpr then mpl)
        uids = set()
        for a in dicts:
            uids |= set(a)
        for u in uids:
            vals = [a.get(u, 0.0) if isinstance(a, dict) else a
                    for a in args]
            try:
                out[u] = _apply_math(fn, vals, _m)
            except (ZeroDivisionError, ValueError):
                continue
        return out

    res = eval_node(tree)
    if not isinstance(res, dict):
        if isinstance(res, (int, float)) and not isinstance(res, bool):
            return const_map(res)
        return {}
    out = {}
    for u, x in res.items():
        if isinstance(x, bool):
            out[u] = Val(TypeID.BOOL, x)
        elif isinstance(x, int):
            # exact int arithmetic result (any magnitude)
            out[u] = Val(TypeID.INT, x)
        elif isinstance(x, float) and x.is_integer() and abs(x) < 2**53:
            out[u] = Val(TypeID.INT, int(x))
        else:
            out[u] = Val(TypeID.FLOAT, x)
    return out


def _trunc_div(a: int, b: int) -> int:
    """Go's int64 division truncates toward zero; python's // floors."""
    q = a // b
    if q < 0 and q * b != a:
        q += 1
    return q


def _apply_math(fn: str, v: list, _m):
    both_int = len(v) == 2 \
        and isinstance(v[0], int) and not isinstance(v[0], bool) \
        and isinstance(v[1], int) and not isinstance(v[1], bool)
    if fn == "+":
        return v[0] + v[1]
    if fn == "-":
        return v[0] - v[1] if len(v) == 2 else -v[0]
    if fn == "*":
        return v[0] * v[1]
    if fn == "/":
        if both_int:
            # int/int divides INTEGRALLY and exactly (ref math.go
            # applyArith int64 arm; query4:TestBigMathValue)
            return _trunc_div(v[0], v[1])
        return v[0] / v[1]
    if fn == "%":
        if both_int:
            return v[0] - _trunc_div(v[0], v[1]) * v[1]
        return v[0] % v[1]
    if fn == "<":
        return v[0] < v[1]
    if fn == ">":
        return v[0] > v[1]
    if fn == "<=":
        return v[0] <= v[1]
    if fn == ">=":
        return v[0] >= v[1]
    if fn == "==":
        return v[0] == v[1]
    if fn == "!=":
        return v[0] != v[1]
    if fn == "min":
        return min(v)
    if fn == "max":
        return max(v)
    if fn == "exp":
        return _m.exp(v[0])
    if fn == "ln":
        return _m.log(v[0])
    if fn == "sqrt":
        return _m.sqrt(v[0])
    if fn == "floor":
        return float(_m.floor(v[0]))
    if fn == "ceil":
        return float(_m.ceil(v[0]))
    if fn == "pow":
        # float domain like the reference's math.Pow — exact bigint
        # pow would happily materialize petabyte integers; overflow
        # drops the uid like the other per-element failures
        try:
            return float(v[0]) ** float(v[1])
        except OverflowError:
            raise ValueError("math: pow overflow")
    if fn == "logbase":
        return _m.log(v[0], v[1])
    if fn == "sigmoid":
        return 1.0 / (1.0 + _m.exp(-v[0]))
    if fn == "cond":
        return v[1] if v[0] else v[2]
    if fn == "since":
        # ref query/aggregator.go:353 applySince: seconds elapsed since
        # the datetime (datetimes reach math as epoch-seconds floats)
        import time as _time
        # wall clock by SEMANTICS (epoch-seconds argument)
        return _time.time() - v[0]  # dglint: disable=DG06
    raise GQLError(f"math op {fn!r} not supported")


def _levenshtein(a: str, b: str, cap: int) -> int:
    """Banded edit distance (ref worker/match.go levenshtein).
    Dispatches to the native C++ kernel (native/native.cc
    dgt_levenshtein) when built."""
    from dgraph_tpu import native
    if native.available():
        return native.levenshtein(a, b, cap)
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        lo = cap + 1
        for j, cb in enumerate(b, 1):
            c = min(prev[j] + 1, cur[j - 1] + 1,
                    prev[j - 1] + (ca != cb))
            cur.append(c)
            lo = min(lo, c)
        if lo > cap:
            return cap + 1
        prev = cur
    return prev[-1]
