"""Regex → trigram AND/OR query compilation.

The reference answers ``regexp()`` by compiling the regex AST into a
boolean query over trigrams — a NECESSARY condition for any match —
walking the trigram index with it, then regex-verifying the survivors
(ref worker/trigram.go:35 uidsForRegex → cindex.RegexpQuery, which
handles alternation, optionality and anchors).  Round 3 approximated
this with literal-fragment extraction and an unconditional intersect,
which wrongly ANDs trigram sets across alternation branches
(``/foo|bar/`` demanded both).  This module is the real compiler.

Design (simplified from codesearch's RegexpQuery):
  * Walk CPython's own ``re`` parse tree (``re._parser``) — the ground
    truth for what the verify pass will accept, so the filter can never
    be stricter than the verifier along a path we constrain.
  * For each subexpression compute either its small EXACT string set
    (alternations/optionals/char-classes multiply sets, bounded) or a
    trigram query that any containing string must satisfy.
  * Concatenation ANDs, alternation ORs, ``x{0,n}`` widens to the empty
    string, ``x{1,}`` keeps one copy's constraint, anchors/lookarounds
    contribute nothing (necessity is preserved by ignoring them).
  * Unconstrainable nodes (``.``, negated classes, backrefs) become ALL;
    an ALL branch of an OR makes the whole OR unconstrained, exactly as
    in the reference's query algebra.

The output query is evaluated against the index by the executor
(`_trigram_query_uids`); ALL means "no index help — full scan".
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from itertools import product
from typing import Optional

try:  # Python 3.11+ moved the sre internals under re.*
    from re import _constants as _sc
    from re import _parser as _sre
except ImportError:  # Python <= 3.10: the public top-level names
    import sre_constants as _sc
    import sre_parse as _sre

# opcodes added in 3.11 (atomic groups / possessive quantifiers): a
# 3.10 parser never emits them, so distinct sentinels keep the `is`
# dispatch below falsy instead of AttributeError-ing into the
# degrade-to-ALL path on every pattern
_OPC_ATOMIC_GROUP = getattr(_sc, "ATOMIC_GROUP", object())
_OPC_POSSESSIVE_REPEAT = getattr(_sc, "POSSESSIVE_REPEAT", object())

# Bounds on the exact-set tracking: past these we degrade to trigram
# queries (still correct, just a weaker prefilter).  codesearch uses
# comparable small constants for the same reason — exact sets exist
# only to form trigrams across node boundaries like (foo|bar)baz.
_EXACT_SET_MAX = 64
_EXACT_LEN_MAX = 32
_CLASS_ENUM_MAX = 16

_OP_ALL = "all"
_OP_NONE = "none"
_OP_AND = "and"
_OP_OR = "or"


@dataclass(frozen=True)
class TriQuery:
    """AND/OR tree over trigram index lookups."""

    op: str
    trigrams: tuple = ()
    subs: tuple = ()

    def __repr__(self):  # compact, for test goldens / debugging
        if self.op in (_OP_ALL, _OP_NONE):
            return self.op.upper()
        parts = [repr(t) for t in self.trigrams] + [repr(s) for s in self.subs]
        return f"{self.op}({' '.join(parts)})"


ALL = TriQuery(_OP_ALL)
NONE = TriQuery(_OP_NONE)


def _and(parts: list) -> TriQuery:
    tris: list = []
    subs: list = []
    for p in parts:
        if p.op == _OP_NONE:
            return NONE
        if p.op == _OP_ALL:
            continue
        if p.op == _OP_AND:
            tris.extend(p.trigrams)
            subs.extend(p.subs)
        else:
            subs.append(p)
    if not tris and not subs:
        return ALL
    return TriQuery(_OP_AND, tuple(dict.fromkeys(tris)), tuple(subs))


def _or(parts: list) -> TriQuery:
    tris: list = []
    subs: list = []
    for p in parts:
        if p.op == _OP_ALL:
            return ALL
        if p.op == _OP_NONE:
            continue
        if p.op == _OP_OR:
            tris.extend(p.trigrams)
            subs.extend(p.subs)
        else:
            subs.append(p)
    if not tris and not subs:
        return NONE
    return TriQuery(_OP_OR, tuple(dict.fromkeys(tris)), tuple(subs))


class _Info:
    """Analysis result for one subexpression: either the exact set of
    strings it can match (small), or a necessary trigram query for any
    string containing a match of it."""

    __slots__ = ("exact", "match")

    def __init__(self, exact: Optional[frozenset] = None,
                 match: TriQuery = ALL):
        self.exact = exact
        self.match = match


_EMPTY_STR = _Info(exact=frozenset({""}))


try:  # sre's own table of extra case equivalents (ſ↔s, ı↔i, µ↔μ…)
    from re._casefix import _EXTRA_CASES
except ImportError:  # Python <= 3.10 keeps the same table as
    # codepoint tuples in sre_compile._equivalences
    try:
        from sre_compile import _equivalences
        _EXTRA_CASES = {i: [j for j in t if i != j]
                        for t in _equivalences for i in t}
    except ImportError:  # pragma: no cover
        _EXTRA_CASES = {}

# chr → every codepoint that sre's LITERAL_UNI_IGNORE accepts for it.
# sre matches X against literal c iff lower(X) == lower(c) or lower(X)
# is one of lower(c)'s extra cases, so completeness needs the INVERSE
# lower map (e.g. 'k' must admit KELVIN SIGN U+212A).  Built lazily on
# the first ignorecase compile and cached for the process.
_INV_LOWER: Optional[dict] = None
_VARIANTS_MAX = 32  # per-window cap: 3 variants/char (e.g. s/S/ſ) = 27


def _inv_lower_map() -> dict:
    global _INV_LOWER
    if _INV_LOWER is None:
        import numpy as np
        # One C-level lower() over the whole codepoint space, then a
        # vectorized diff: only the ~3k chars whose lowercase differs
        # need dict entries (identity is handled at lookup time).
        # U+0130 İ is excluded up front — its lowercase is two chars,
        # which would misalign the parallel arrays (and sre cannot
        # enumerate it either; _case_variants bails the same way).
        big = "".join(
            chr(cp) for cp in range(0x110000)
            if cp != 0x130 and not 0xD800 <= cp <= 0xDFFF)
        low = big.lower()
        assert len(low) == len(big), "unexpected multi-char lowercase"
        a = np.frombuffer(big.encode("utf-32-le"), dtype=np.uint32)
        b = np.frombuffer(low.encode("utf-32-le"), dtype=np.uint32)
        m: dict = {}
        for cp, lo in zip(a[a != b].tolist(), b[a != b].tolist()):
            m.setdefault(chr(lo), []).append(chr(cp))
        _INV_LOWER = m
    return _INV_LOWER


def _case_variants(ch: str) -> Optional[tuple]:
    """All characters the verifier's IGNORECASE literal `ch` matches,
    or None when the set can't be enumerated soundly (multi-char
    lowercase like İ → i̇)."""
    lo = ch.lower()
    if len(lo) != 1:
        return None
    inv = _inv_lower_map()
    out = set(inv.get(lo, ())) | {lo}
    for e in _EXTRA_CASES.get(ord(lo), ()):
        ec = chr(e)
        out |= set(inv.get(ec, ())) | {ec}
    return tuple(sorted(out))


def _trigram_query_for(s: str, ignorecase: bool) -> TriQuery:
    """Necessary condition for a string CONTAINING literal `s`."""
    if len(s) < 3:
        return ALL  # too short to pin a trigram
    parts: list = []
    for i in range(len(s) - 2):
        win = s[i:i + 3]
        if not ignorecase:
            parts.append(TriQuery(_OP_AND, (win,)))
            continue
        # Case-fold: the value may carry any case mix, so the necessary
        # condition per window is an OR over its full case-variant set.
        # An unenumerable or oversized set degrades that WINDOW to
        # unconstrained (skipped); other windows still filter.
        per_char = [_case_variants(c) for c in win]
        if any(v is None for v in per_char):
            continue
        n = 1
        for v in per_char:
            n *= len(v)
        if n > _VARIANTS_MAX:
            continue
        variants = ["".join(t) for t in product(*per_char)]
        if len(variants) == 1:
            parts.append(TriQuery(_OP_AND, (variants[0],)))
        else:
            parts.append(TriQuery(_OP_OR, tuple(variants)))
    return _and(parts)


def _matchq(info: _Info, ignorecase: bool) -> TriQuery:
    if info.exact is None:
        return info.match
    return _or([_trigram_query_for(s, ignorecase) for s in info.exact])


def _concat(a: _Info, b: _Info, ignorecase: bool) -> _Info:
    if a.exact is not None and b.exact is not None:
        prod = len(a.exact) * len(b.exact)
        if prod <= _EXACT_SET_MAX:
            joined = {x + y for x in a.exact for y in b.exact}
            if all(len(s) <= _EXACT_LEN_MAX for s in joined):
                return _Info(exact=frozenset(joined))
    return _Info(match=_and([_matchq(a, ignorecase),
                             _matchq(b, ignorecase)]))


def _an_class(items) -> _Info:
    """[...] character class: enumerate small positive classes."""
    chars: set = set()
    for it in items:
        op, av = it
        if op is _sc.LITERAL:
            chars.add(chr(av))
        elif op is _sc.RANGE:
            lo, hi = av
            if hi - lo + 1 > _CLASS_ENUM_MAX:
                return _Info(match=ALL)
            chars.update(chr(c) for c in range(lo, hi + 1))
        else:  # NEGATE, CATEGORY (\w, \d…) — unconstrainable
            return _Info(match=ALL)
        if len(chars) > _CLASS_ENUM_MAX:
            return _Info(match=ALL)
    if not chars:
        return _Info(match=ALL)
    return _Info(exact=frozenset(chars))


def _an_node(node, ic: bool) -> _Info:
    op, av = node
    if op is _sc.LITERAL:
        return _Info(exact=frozenset({chr(av)}))
    if op is _sc.IN:
        return _an_class(av)
    if op is _sc.AT:  # anchors: zero-width, ignore
        return _EMPTY_STR
    if op in (_sc.ASSERT, _sc.ASSERT_NOT):
        # Lookarounds only narrow the match; dropping them keeps the
        # query a necessary condition.
        return _EMPTY_STR
    if op is _sc.SUBPATTERN:
        _gid, add_flags, del_flags, seq = av
        ic2 = (ic or bool(add_flags & re.IGNORECASE)) \
            and not bool(del_flags & re.IGNORECASE)
        return _an_seq(seq, ic2)
    if op is _OPC_ATOMIC_GROUP:
        return _an_seq(av, ic)
    if op in (_sc.MAX_REPEAT, _sc.MIN_REPEAT, _OPC_POSSESSIVE_REPEAT):
        lo, hi, seq = av
        sub = _an_seq(seq, ic)
        if lo == 0:
            if hi == 0:
                return _EMPTY_STR
            if hi == 1 and sub.exact is not None \
                    and len(sub.exact) < _EXACT_SET_MAX:
                return _Info(exact=sub.exact | {""})  # x? → {"", x…}
            return _Info(match=ALL)  # x* — may be absent entirely
        # lo >= 1: at least one copy is present.
        if lo == hi and sub.exact is not None:
            acc = _Info(exact=frozenset({""}))
            for _ in range(lo):
                acc = _concat(acc, sub, ic)
                if acc.exact is None:
                    break
            if acc.exact is not None:
                return acc
        return _Info(match=_matchq(sub, ic))
    # ANY (.), NOT_LITERAL, GROUPREF, and anything unrecognised:
    # a match exists but we can say nothing about its text.
    return _Info(match=ALL)


def _an_seq(nodes, ic: bool) -> _Info:
    # Fold left, but keep the exact-string run alive ACROSS match-typed
    # nodes: "abc.*def" must yield and(abc-query, def-query), not lose
    # "def" to one-char-at-a-time concatenation below trigram length.
    pending: list = []
    cur = _EMPTY_STR

    def flush():
        nonlocal cur
        if cur.exact != _EMPTY_STR.exact:
            q = _matchq(cur, ic)
            if q is not ALL:
                pending.append(q)
        cur = _EMPTY_STR

    for node in nodes:
        if node[0] is _sc.BRANCH:
            _unused, branches = node[1]
            infos = [_an_seq(b, ic) for b in branches]
            if all(i.exact is not None for i in infos) \
                    and sum(len(i.exact) for i in infos) <= _EXACT_SET_MAX:
                info = _Info(exact=frozenset().union(
                    *[i.exact for i in infos]))
            else:
                info = _Info(match=_or([_matchq(i, ic) for i in infos]))
        else:
            info = _an_node(node, ic)
        if info.exact is None:
            flush()
            if info.match is not ALL:
                pending.append(info.match)
            continue
        if cur.exact is not None:
            joined = _concat(cur, info, ic)
            if joined.exact is not None:
                cur = joined
                continue
        flush()
        cur = info

    if not pending:
        return cur
    flush()
    return _Info(match=_and(pending))


def compile_trigram_query(pattern: str, flags: int = 0) -> TriQuery:
    """Compile `pattern` into a trigram AND/OR query that every string
    with an ``re.search`` match must satisfy.  Returns ALL (no index
    help) when the pattern cannot be constrained or fails to parse —
    the caller then falls back to a full scan + verify, matching the
    reference's behaviour for e.g. ``/.*/``."""
    try:
        tree = _sre.parse(pattern, flags)
    except Exception:
        return ALL
    # Inline global flags like (?i) land in the parse state, not in the
    # caller's flags — fold them in so the filter matches the verifier.
    eff = flags | getattr(getattr(tree, "state", None), "flags", 0)
    ic = bool(eff & re.IGNORECASE)
    try:
        info = _an_seq(list(tree), ic)
        return _matchq(info, ic)
    except Exception:
        return ALL
