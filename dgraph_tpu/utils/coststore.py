"""Observed-cost store: per-stage span durations -> streaming histograms.

PR 5 built the tracing plane; this module is its first always-on
consumer, and the statistics source the planned cost-based tier router
(ROADMAP item 5) reads instead of static thresholds like
`device_min_edges`. "Self-Driving Database Management Systems"
(PAPERS.md) is the template: keep cheap, always-on observations of
what each operator actually cost, keyed finely enough that a planner
can ask "what does an `eq` stage on this plan at this input size
usually take on this tier?".

Mechanics:

- a span observer (utils/tracing.add_span_observer) fires at every
  span close; stage spans (STAGES) aggregate into a bounded table
  keyed `(stage, tier, plan skeleton, size bucket)`:
    stage     the span name (eq/sort/expand/... plus the engine
              envelopes parse/execute/encode)
    tier      "host" unless the span carries a `tier` attr
              ("device" for device.tile_load)
    skeleton  the compiled plan's 16-hex skeleton hash — the engine
              binds it around execution (bind_plan), so every stage of
              a planned query lands under its plan; "" outside one
    bucket    power-of-two bucket of the span's row/edge count
- each key holds a log2 duration histogram (µs), count/sum, an EWMA
  summary, and the single slowest observation's (duration, trace_id) —
  the trace exemplar the Prometheus exporter attaches to its bucket.
- `save()`/`load()` persist the table as JSON; a store-backed GraphDB
  loads at boot and saves at checkpoint/close, so observations survive
  restarts (load MERGES, it never truncates live state). The table is
  process-global like the tracing plane it observes — spans carry no
  engine identity — so persistence assumes AT MOST ONE store-backed
  GraphDB per process at a time: two live engines with different
  store_dirs would fold each other's observations into both files.
- `render_prometheus()` emits the table aggregated per (stage, tier)
  as a `dgraph_stage_duration_us` histogram with an OpenMetrics-style
  trace exemplar on the bucket holding the slowest sample; it is
  registered with utils/metrics so /debug/prometheus_metrics carries
  it automatically.

The observer is ALWAYS ON once this module is imported (the engine
imports it). Budget: one frozenset probe for non-stage spans, a few
dict operations for stage spans — enforced by
`bench_micro.py --stats-overhead` (< 1% on the 21M-regime summary
queries) and the existing per-span budget in tests/test_tracing.py.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time as _time
from bisect import bisect_left
from typing import Any, Iterator, Optional

from dgraph_tpu.utils import metrics, tracing

# log2 duration buckets in µs: le 1, le 2, ..., le 2^19 (~0.5 s); one
# +Inf tail. Stage durations span ~1 µs (a memoized eq) to seconds (a
# cold 21M sort), so exponential buckets hold the whole range in 21
# counters per key.
N_BUCKETS = 20
BUCKETS_US = [float(1 << i) for i in range(N_BUCKETS)]
EWMA_ALPHA = 0.05
# fast companion EWMA: reacts in ~3 observations where the slow one
# takes ~20 — their RATIO is the drift signal the adaptive planner's
# re-optimization reads (a tier whose recent cost runs 2x its
# long-term average has drifted; see query/planner.py)
EWMA_FAST_ALPHA = 0.30
# below this many observations a cell's EWMAs are noise: estimate()
# reports the cell but flags it cold, and drift() stays neutral.
# 4 is deliberately low — each observation is a full stage execution,
# and the planner's margin rules (2x vs priors, 1.3x rival
# hysteresis) absorb the residual noise; a higher floor just delays
# adaptation by whole workload passes
MIN_WARM_COUNT = 4

# span names the observer aggregates — the executor's stage spans plus
# the engine/cluster envelopes. Everything else stays trace-only
# detail (names here must exist in tracing.SPAN_NAMES).
STAGES = frozenset((
    "batch.wait", "block", "commit", "device.tile_load", "encode",
    "eq", "execute", "expand", "ineq", "match", "mutate", "parse",
    "plan.compile", "query", "raft.apply", "rpc.recv", "rpc.send",
    "setops", "similar_to", "sort", "tablet.rollup", "wal.append",
))

# the active plan skeleton: the engine binds it around execution so
# stage spans key under their plan without threading an argument
# through every executor call
_PLAN_CV: contextvars.ContextVar[str] = contextvars.ContextVar(
    "dgraph_tpu_cost_plan", default="")


@contextlib.contextmanager
def bind_plan(skeleton: str) -> Iterator[None]:
    """Attribute stage spans closed inside the block to `skeleton`
    (the plan's 16-hex hash; "" for unplanned requests)."""
    tok = _PLAN_CV.set(str(skeleton))
    try:
        yield
    finally:
        _PLAN_CV.reset(tok)


def _size_bucket(args: dict) -> int:
    """Power-of-two size bucket from the span's own row/size attrs —
    bucket b covers counts in (2^(b-1), 2^b]; 0 = empty/unsized."""
    n = args.get("rows")
    if n is None:
        n = args.get("n")
    if n is None:
        n = args.get("edges")
    if type(n) is int:  # fast path: tracing sites emit plain ints
        return n.bit_length() if n > 0 else 0
    try:
        n = int(n)
    except (TypeError, ValueError):
        return 0
    return n.bit_length() if n > 0 else 0


class CostStore:
    """Bounded aggregation table. Entry value layout (list, mutated in
    place under the lock): [hist, count, sum_us, ewma_us, max_us,
    max_trace, last_mono, fast_ewma_us] where hist has N_BUCKETS+1
    slots (last = +Inf). `last_mono` is the monotonic stamp of the
    newest observation — /debug/stats reports each cell's age from it,
    so a cold/dead cell (a tier the planner stopped routing to, a
    skeleton that aged out) is distinguishable from a fresh one;
    `fast_ewma_us` is the quick-reacting EWMA whose ratio to the slow
    one is the drift signal."""

    MAX_KEYS = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: dict[tuple, list] = {}
        self._enabled = True
        # paths whose on-disk content is already folded into (or was
        # just written FROM) this store: load() skips them, so a
        # close-then-reopen cycle in one process cannot merge the same
        # observations twice
        self._synced_paths: set[str] = set()

    # -- recording -----------------------------------------------------

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    def enabled(self) -> bool:
        return self._enabled

    def record(self, stage: str, tier: str, skeleton: str,
               size_bucket: int, dur_us: float,
               trace_id: str = "") -> None:
        """Trusted-caller hot path (the span observer fires this for
        EVERY stage span): arguments arrive well-typed; the module
        level record() wrapper normalizes for external callers."""
        key = (stage, tier, skeleton, size_bucket)
        idx = bisect_left(BUCKETS_US, dur_us)
        now = _time.monotonic()
        with self._lock:
            e = self._data.get(key)
            if e is None:
                if len(self._data) >= self.MAX_KEYS:
                    # overflow: fold into the per-(stage, tier)
                    # aggregate key instead of growing unboundedly
                    # (skeleton churn is the only unbounded axis)
                    key = (key[0], key[1], "~", key[3])
                    e = self._data.get(key)
                if e is None:
                    e = [[0] * (N_BUCKETS + 1), 0, 0.0, dur_us, 0.0,
                         "", now, dur_us]
                    self._data[key] = e
            e[0][idx] += 1
            e[1] += 1
            e[2] += dur_us
            e[3] += EWMA_ALPHA * (dur_us - e[3])
            if dur_us >= e[4]:
                e[4] = dur_us
                e[5] = trace_id
            e[6] = now
            e[7] += EWMA_FAST_ALPHA * (dur_us - e[7])
    # (record stays under ~1 µs: one bisect over 20 floats + in-place
    # list updates under an uncontended lock)

    def observe_span(self, rec: dict) -> None:
        """The tracing observer: aggregate one finished span record.
        Runs on every stage span the process closes — bench_micro
        --stats-overhead holds the whole plane under 1% of the
        summary-query mix."""
        name = rec["name"]
        if name not in STAGES or not self._enabled:
            return
        args = rec["args"]
        tier = args.get("tier") or (
            "device" if name == "device.tile_load" else "host")
        self.record(name, str(tier), _PLAN_CV.get(), _size_bucket(args),
                    rec["dur_us"], rec.get("trace_id", ""))

    # -- reads ---------------------------------------------------------

    def summary(self, stage: Optional[str] = None,
                skeleton: Optional[str] = None) -> list[dict]:
        """Per-key summaries (optionally filtered), slowest-EWMA first
        — the `/debug/stats` "cost" payload and the per-plan query
        surface (`skeleton=` answers "what has THIS plan's stage mix
        been costing?")."""
        out = []
        now = _time.monotonic()
        with self._lock:
            items = list(self._data.items())
        for (st, tier, skel, bucket), e in items:
            if stage is not None and st != stage:
                continue
            if skeleton is not None and skel != skeleton:
                continue
            out.append({
                "stage": st, "tier": tier, "skeleton": skel,
                "size_bucket": bucket, "count": e[1],
                "sum_us": round(e[2], 3), "ewma_us": round(e[3], 3),
                "max_us": round(e[4], 3), "max_trace": e[5],
                # seconds since the newest observation landed in this
                # cell — the cold/dead-vs-fresh discriminator the
                # drift-invalidation signal needs (-1 = never stamped:
                # a pre-age persisted cell)
                "ageS": round(now - e[6], 3) if e[6] > 0 else -1,
                "fastEwmaUs": round(e[7], 3),
                "drift": round(e[7] / e[3], 3)
                if e[1] >= MIN_WARM_COUNT and e[3] > 0 else 1.0,
                "hist": list(e[0]),
            })
        out.sort(key=lambda r: -r["ewma_us"])
        return out

    def stats(self) -> dict:
        now = _time.monotonic()
        with self._lock:
            ages = [now - e[6] for e in self._data.values()
                    if e[6] > 0]
            return {"keys": len(self._data),
                    "observations": sum(e[1]
                                        for e in self._data.values()),
                    "freshestAgeS": round(min(ages), 3) if ages else -1,
                    "stalestAgeS": round(max(ages), 3) if ages else -1}

    # -- planner-facing estimate surface -------------------------------

    def estimate(self, stage: str, tier: str, size_bucket: int,
                 skeleton: str = "", exact_only: bool = False
                 ) -> Optional[dict]:
        """Observed-cost estimate for one (stage, tier) at an input
        size bucket — what the adaptive planner asks instead of
        trusting static priors. Fallback chain, most-specific first:

          exact     this plan's own (stage, tier, skeleton, bucket)
          overflow  the "~" aggregate the bounded table folds into
          scaled    the NEAREST populated bucket of the same
                    (stage, tier) under any skeleton, EWMA scaled
                    linearly in rows (2^Δbucket, clamped) — stage
                    costs are row-linear to first order

        Returns {ewma_us, fast_ewma_us, count, age_s, cell, warm} or
        None when the (stage, tier) has never been observed at all
        (the caller falls back to its documented static priors)."""
        now = _time.monotonic()

        def _p50(e: list) -> float:
            # histogram median, INTERPOLATED inside the bucket:
            # robust to the one-off spikes that poison a young EWMA —
            # a tier's FIRST observation is typically its cache build
            # (CSR export, pack materialization), and the slow EWMA
            # seeds on it, making the tier look expensive for ~20
            # observations. Interpolation matters: a raw
            # bucket-midpoint median moves in 2x steps, which no
            # reasonable rival-margin hysteresis can damp — two
            # near-equal tiers would flap on quantization noise. The
            # planner compares p50s; the EWMAs remain the drift
            # signal.
            half = e[1] / 2.0
            seen = 0
            for b, c in enumerate(e[0]):
                if not c:
                    continue
                if seen + c >= half:
                    if b >= N_BUCKETS:
                        return float(1 << N_BUCKETS)
                    lo = float(1 << (b - 1)) if b else 0.0
                    hi = float(1 << b)
                    return lo + (hi - lo) * (half - seen) / c
                seen += c
            return e[3]

        def _fmt(e: list, cell: str, scale: float = 1.0) -> dict:
            return {"ewma_us": e[3] * scale,
                    "fast_ewma_us": e[7] * scale,
                    "p50_us": _p50(e) * scale,
                    "count": e[1],
                    "age_s": (now - e[6]) if e[6] > 0 else -1.0,
                    "cell": cell,
                    "warm": e[1] >= MIN_WARM_COUNT}

        with self._lock:
            for skel, cell in ((skeleton, "exact"), ("~", "overflow")):
                e = self._data.get((stage, tier, skel, size_bucket))
                if e is not None and e[1]:
                    return _fmt(e, cell)
            if exact_only:
                # hot-path callers (the planner's per-outcome rival
                # check): two dict probes, NEVER the table scan below
                return None
            best = None  # (bucket distance, -count, bucket, entry)
            for (st, t, _sk, b), e in self._data.items():
                if st != stage or t != tier or not e[1]:
                    continue
                cand = (abs(b - size_bucket), -e[1], b, e)
                if best is None or cand[:2] < best[:2]:
                    best = cand
            if best is None:
                return None
            _d, _negc, b, e = best
            scale = min(64.0, max(1.0 / 64.0,
                                  2.0 ** (size_bucket - b)))
            return _fmt(e, "scaled", scale)

    def drift(self, stage: str, tier: str, size_bucket: int,
              skeleton: str = "") -> float:
        """fast-EWMA / slow-EWMA ratio of the most specific populated
        cell (1.0 = no drift / too cold to tell). > 1 means the tier
        got slower recently; < 1 faster — either way past the
        planner's threshold, a cached tier decision made against the
        old cost is stale."""
        with self._lock:
            for skel in (skeleton, "~"):
                e = self._data.get((stage, tier, skel, size_bucket))
                if e is not None and e[1] >= MIN_WARM_COUNT \
                        and e[3] > 0:
                    return e[7] / e[3]
        return 1.0

    def reset(self) -> None:
        with self._lock:
            self._data.clear()
            self._synced_paths.clear()

    # -- persistence ---------------------------------------------------

    def save(self, path: str) -> None:
        """Atomic JSON dump (tmp + rename): a crash mid-save must not
        leave a truncated store for the next boot's load()."""
        now = _time.monotonic()
        with self._lock:
            entries = [
                {"stage": k[0], "tier": k[1], "skeleton": k[2],
                 "bucket": k[3], "hist": list(e[0]), "count": e[1],
                 "sum_us": e[2], "ewma_us": e[3], "max_us": e[4],
                 "max_trace": e[5],
                 # age is persisted RELATIVE (monotonic clocks do not
                 # survive restarts); load() re-anchors it to the new
                 # process's clock
                 "age_s": round(now - e[6], 3) if e[6] > 0 else -1,
                 "fast_ewma_us": e[7]}
                for k, e in self._data.items()]
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 2, "entries": entries}, f)
        os.replace(tmp, path)
        with self._lock:
            # the file is now a subset of the live table; loading it
            # back in this process would double every observation
            self._synced_paths.add(os.path.abspath(path))

    def load(self, path: str) -> int:
        """Merge a saved table into the live one (histograms/counts
        add; EWMA blends by observation count; max keeps the larger).
        Returns the number of entries merged; missing/corrupt files
        merge nothing. A path this store already saved to (or loaded
        from) in this process merges nothing either — a close-then-
        reopen cycle on the same store_dir must not fold the same
        observations in twice."""
        apath = os.path.abspath(path)
        with self._lock:
            if apath in self._synced_paths:
                return 0
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc["entries"]
        except (OSError, ValueError, KeyError):
            return 0
        n = 0
        now = _time.monotonic()
        for ent in entries:
            try:
                key = (str(ent["stage"]), str(ent["tier"]),
                       str(ent["skeleton"]), int(ent["bucket"]))
                hist = [int(x) for x in ent["hist"]]
                if len(hist) != N_BUCKETS + 1:
                    continue
                cnt, s = int(ent["count"]), float(ent["sum_us"])
                ewma, mx = float(ent["ewma_us"]), float(ent["max_us"])
                trace = str(ent.get("max_trace", ""))
                # v1 files carry neither age nor the fast EWMA: an
                # unknown age re-anchors as "never stamped" (reported
                # -1 / maximally stale — exactly right for data of
                # unknown vintage), the fast EWMA seeds from the slow
                age = float(ent.get("age_s", -1))
                mono = (now - age) if age >= 0 else 0.0
                fast = float(ent.get("fast_ewma_us", ewma))
            except (KeyError, TypeError, ValueError):
                continue
            with self._lock:
                e = self._data.get(key)
                if e is None:
                    if len(self._data) >= self.MAX_KEYS:
                        continue
                    self._data[key] = [hist, cnt, s, ewma, mx, trace,
                                       mono, fast]
                else:
                    e[0] = [a + b for a, b in zip(e[0], hist)]
                    total = e[1] + cnt
                    if total:
                        e[3] = (e[3] * e[1] + ewma * cnt) / total
                        e[7] = (e[7] * e[1] + fast * cnt) / total
                    e[1] = total
                    e[2] += s
                    if mx > e[4]:
                        e[4], e[5] = mx, trace
                    e[6] = max(e[6], mono)
            n += 1
        with self._lock:
            self._synced_paths.add(apath)
        return n

    # -- Prometheus export ----------------------------------------------

    def render_prometheus(self) -> str:
        """`dgraph_stage_duration_us` histogram series aggregated per
        (stage, tier) — the skeleton/size axes stay in /debug/stats
        where cardinality is free — with an OpenMetrics-style trace
        exemplar (`# exemplar: {trace_id="..."} <µs>`) on its OWN
        comment line directly under the bucket holding the slowest
        observation, so a p99 cliff on a dashboard links straight to a
        pullable trace. The endpoint serves text format 0.0.4, whose
        grammar has no inline exemplar syntax — appending one to the
        sample line would abort a real Prometheus scrape of the WHOLE
        exposition; a line-leading comment is ignored by every 0.0.4
        parser and still adjacent for humans/dgtop. Empty store
        renders nothing."""
        agg: dict[tuple[str, str], list] = {}
        with self._lock:
            for (st, tier, _skel, _bucket), e in self._data.items():
                a = agg.get((st, tier))
                if a is None:
                    agg[(st, tier)] = [list(e[0]), e[1], e[2],
                                       e[4], e[5]]
                else:
                    a[0] = [x + y for x, y in zip(a[0], e[0])]
                    a[1] += e[1]
                    a[2] += e[2]
                    if e[4] > a[3]:
                        a[3], a[4] = e[4], e[5]
        if not agg:
            return ""
        name = "dgraph_stage_duration_us"
        lines = [f"# TYPE {name} histogram"]
        for (st, tier), (hist, count, sum_us, max_us, trace) in \
                sorted(agg.items()):
            lab = f'stage="{st}",tier="{tier}"'
            ex_idx = bisect_left(BUCKETS_US, max_us)
            cum = 0
            for i, b in enumerate(BUCKETS_US):
                cum += hist[i]
                lines.append(f'{name}_bucket{{{lab},le="{b:g}"}} {cum}')
                if trace and i == ex_idx:
                    lines.append(f'# exemplar: {{trace_id="{trace}"}} '
                                 f'{max_us:g}')
            cum += hist[-1]
            lines.append(f'{name}_bucket{{{lab},le="+Inf"}} {cum}')
            if trace and ex_idx >= N_BUCKETS:
                lines.append(f'# exemplar: {{trace_id="{trace}"}} '
                             f'{max_us:g}')
            lines.append(f'{name}_count{{{lab}}} {cum}')
            lines.append(f'{name}_sum{{{lab}}} {sum_us:g}')
        return "\n".join(lines) + "\n"


# ------------------------------------------------------- global store

_GLOBAL = CostStore()


def record(stage: str, tier: str = "host", skeleton: str = "",
           size_bucket: int = 0, dur_us: float = 0.0,
           trace_id: str = "") -> None:
    _GLOBAL.record(str(stage), str(tier) or "host", str(skeleton),
                   int(size_bucket), float(dur_us), str(trace_id))


def summary(stage: Optional[str] = None,
            skeleton: Optional[str] = None) -> list[dict]:
    return _GLOBAL.summary(stage=stage, skeleton=skeleton)


def stats() -> dict:
    return _GLOBAL.stats()


def estimate(stage: str, tier: str, size_bucket: int,
             skeleton: str = "",
             exact_only: bool = False) -> Optional[dict]:
    return _GLOBAL.estimate(stage, tier, size_bucket, skeleton,
                            exact_only)


def drift(stage: str, tier: str, size_bucket: int,
          skeleton: str = "") -> float:
    return _GLOBAL.drift(stage, tier, size_bucket, skeleton)


def reset() -> None:
    _GLOBAL.reset()


def set_enabled(on: bool) -> None:
    _GLOBAL.set_enabled(on)


def save(path: str) -> None:
    _GLOBAL.save(path)


def load(path: str) -> int:
    return _GLOBAL.load(path)


def render_prometheus() -> str:
    return _GLOBAL.render_prometheus()


def store() -> CostStore:
    return _GLOBAL


# always-on wiring: aggregate every stage span from import onward, and
# ride along /debug/prometheus_metrics
tracing.add_span_observer(_GLOBAL.observe_span)
metrics.register_renderer(_GLOBAL.render_prometheus)
