"""On-demand wall-clock sampling profiler (`/debug/pprof?seconds=N`).

The reference ships Go's net/http/pprof on every node (x/metrics.go
pprof mux); this is the Python analogue the runtime actually needs: a
sampling profiler an operator can point at a LOADED node without
restarting it or paying always-on instrumentation. `collect()` wakes
`hz` times a second, snapshots every thread's stack via
`sys._current_frames()`, and aggregates identical stacks; the result
renders as collapsed-stack text (flamegraph.pl / speedscope paste) or
speedscope's sampled-profile JSON (one profile per thread).

Wall-clock on purpose: a thread blocked on a lock, a socket or the
GIL is exactly what "where did my p99 go" needs to show — a CPU-only
profile of a Python server under IO hides the story.

Cost model (bench_micro.py --pprof-overhead gates it): each sample
holds the GIL for one frames() walk, so overhead ≈ hz x per-sample
walk time. At the default 100 Hz over a few dozen threads that is
well under the 2% budget; `seconds` and `hz` are clamped so a typo'd
request cannot turn the profiler into a DoS.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter

DEFAULT_HZ = 100
MAX_SECONDS = 120.0
MAX_HZ = 1000

_PROFILE_LOCK = threading.Lock()  # one collection at a time per process


class Profile:
    """Aggregated samples: {(thread_name, (frame, ...)): count} with
    frames root-first. Frame identity is (function, file, firstlineno)
    — the function, not the currently-executing line — so one hot
    function aggregates to one frame regardless of which bytecode its
    samples landed on (standard sampling-profiler aggregation)."""

    def __init__(self, stacks: Counter, samples: int, hz: int,
                 seconds: float, node: str = ""):
        self.stacks = stacks
        self.samples = samples
        self.hz = hz
        self.seconds = seconds
        self.node = node

    # ---------------------------------------------------------- renders

    def collapsed(self) -> str:
        """Brendan-Gregg collapsed-stack text: one line per distinct
        (thread, stack), `thread;frame;frame;... count`, sorted for a
        stable, diffable artifact."""
        lines = []
        for (tname, frames), n in sorted(self.stacks.items()):
            lines.append(";".join((tname,) + frames) + f" {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def speedscope(self) -> dict:
        """speedscope file-format JSON: one "sampled" profile per
        thread, weights in seconds (sample count / hz), shared frame
        table. Load at https://www.speedscope.app or `speedscope f`."""
        frame_ix: dict[str, int] = {}
        frames: list[dict] = []

        def ix(frame: str) -> int:
            got = frame_ix.get(frame)
            if got is None:
                got = frame_ix[frame] = len(frames)
                name, _, loc = frame.partition(" (")
                rec: dict = {"name": name}
                if loc.endswith(")"):
                    fname, _, line = loc[:-1].rpartition(":")
                    rec["file"] = fname
                    try:
                        rec["line"] = int(line)
                    except ValueError:
                        pass
                frames.append(rec)
            return got

        by_thread: dict[str, list[tuple[tuple, int]]] = {}
        for (tname, stack), n in sorted(self.stacks.items()):
            by_thread.setdefault(tname, []).append((stack, n))
        profiles = []
        for tname in sorted(by_thread):
            samples, weights = [], []
            total = 0.0
            for stack, n in by_thread[tname]:
                samples.append([ix(f) for f in stack])
                w = n / max(self.hz, 1)
                weights.append(w)
                total += w
            profiles.append({
                "type": "sampled", "name": tname, "unit": "seconds",
                "startValue": 0, "endValue": round(total, 6),
                "samples": samples, "weights": weights})
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": profiles,
            "activeProfileIndex": 0,
            "exporter": "dgraph-tpu-pprof",
            "name": (f"{self.node or 'node'} wall "
                     f"{self.seconds:g}s @ {self.hz}Hz"),
        }

    def to_payload(self, fmt: str = "speedscope") -> dict:
        """The /debug/pprof response body (HTTP and cluster wire):
        metadata + the requested render(s)."""
        out = {"samples": self.samples, "hz": self.hz,
               "seconds": self.seconds, "node": self.node,
               "threads": len({t for t, _ in self.stacks})}
        if fmt in ("collapsed", "both"):
            out["collapsed"] = self.collapsed()
        if fmt in ("speedscope", "both"):
            out["speedscope"] = self.speedscope()
        return out


# code object -> rendered frame id. The sampler's per-sample cost IS
# the profiler's overhead (each walk holds the GIL), and string
# formatting dominates a cold walk — memoizing by code object makes
# the steady-state walk a dict hit per frame. Code objects are
# immortal for the life of their module; the map stays small.
_FRAME_IDS: dict = {}


def _frame_id(code) -> str:
    got = _FRAME_IDS.get(code)
    if got is not None:
        return got
    fname = code.co_filename
    # trim to the package-relative tail: absolute prefixes differ per
    # deploy and would fragment cross-node aggregation
    for marker in ("/dgraph_tpu/", "/tools/", "/tests/"):
        at = fname.rfind(marker)
        if at >= 0:
            fname = fname[at + 1:]
            break
    else:
        fname = fname.rsplit("/", 1)[-1]
    got = f"{code.co_name} ({fname}:{code.co_firstlineno})"
    _FRAME_IDS[code] = got
    return got


def sample_once(skip_idents: frozenset,
                names: dict[int, str]) -> list[tuple[str, tuple]]:
    """One snapshot of every thread's stack (root-first), skipping the
    profiler's own thread(s). Split out so the overhead bench measures
    exactly the per-sample cost the collect loop pays."""
    out = []
    for ident, frame in sys._current_frames().items():
        if ident in skip_idents:
            continue
        stack = []
        f = frame
        while f is not None:
            stack.append(_frame_id(f.f_code))
            f = f.f_back
        out.append((names.get(ident, f"thread-{ident}"),
                    tuple(reversed(stack))))
    return out


def collect(seconds: float, hz: int = DEFAULT_HZ,
            node: str = "") -> Profile:
    """Sample every live thread for `seconds` at `hz`. Runs in the
    CALLING thread (the debug endpoint's request thread blocks for the
    duration — that is the /debug/pprof?seconds=N contract, same as Go
    pprof's ?seconds=). Serialized process-wide: two concurrent
    collections would double the sampling overhead and each blame the
    other's walk time."""
    seconds = max(0.1, min(float(seconds), MAX_SECONDS))
    hz = max(1, min(int(hz), MAX_HZ))
    interval = 1.0 / hz
    me = frozenset({threading.get_ident()})
    stacks: Counter = Counter()
    samples = 0
    with _PROFILE_LOCK:
        end = time.monotonic() + seconds
        next_at = time.monotonic()
        while time.monotonic() < end:
            names = {t.ident: t.name for t in threading.enumerate()
                     if t.ident is not None}
            for rec in sample_once(me, names):
                stacks[rec] += 1
            samples += 1
            next_at += interval
            delay = next_at - time.monotonic()
            if delay > 0:
                # the inter-sample pacing IS the critical section:
                # _PROFILE_LOCK exists to serialize whole collections
                # (overlapping samplers double overhead and blame each
                # other), so sleeping under it is the contract
                time.sleep(delay)  # dglint: disable=DG04
            else:
                next_at = time.monotonic()  # fell behind: don't burst
    return Profile(stacks, samples, hz, seconds, node=node)


def handle_params(params: dict, node: str = "",
                  default_seconds: float = 1.0) -> dict:
    """Shared /debug/pprof parameter handling for every surface (HTTP
    server, node debug listener, cluster wire op): seconds=, hz=,
    format=collapsed|speedscope|both."""
    seconds = float(params.get("seconds", default_seconds))
    hz = int(params.get("hz", DEFAULT_HZ))
    fmt = str(params.get("format", "speedscope"))
    if fmt not in ("collapsed", "speedscope", "both"):
        raise ValueError(
            f"format must be collapsed/speedscope/both, got {fmt!r}")
    return collect(seconds, hz=hz, node=node).to_payload(fmt)
