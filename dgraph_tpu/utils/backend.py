"""JAX backend selection helpers.

The ambient environment pre-imports jax via sitecustomize and pins
JAX_PLATFORMS=axon (a single-chip TPU tunnel). That has two consequences
for any code that wants the virtual-CPU path (tests, the multichip
dryrun, CI):

1. Setting the JAX_PLATFORMS env var after interpreter start does
   nothing — jax.config latched the ambient value at import time. The
   config must be updated in-process.
2. Even under jax_platforms=cpu, jax's backends() still *initializes*
   every registered plugin factory, and the axon factory blocks forever
   whenever the TPU tunnel is busy or wedged (root cause of the round-1
   MULTICHIP rc=124 hang at parallel/mesh.py jax.devices()). The
   factories must be deregistered outright.

force_cpu_backend() performs both steps plus the virtual device-count
flag, and is safe to call multiple times. It must run BEFORE the first
backend initialization (first jax.devices()/jit execution); calling it
after is a no-op for the already-initialized process and raises only if
strict=True.
"""

from __future__ import annotations

import os


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge as _xb
        return bool(_xb._backends)
    except Exception:
        return False


def force_cpu_backend(n_devices: int | None = None,
                      strict: bool = False) -> None:
    """Pin this process to the XLA CPU backend with `n_devices` virtual
    devices. Must be called before jax initializes any backend."""
    if _backends_initialized():
        if strict:
            raise RuntimeError(
                "force_cpu_backend called after jax backend init; "
                "the platform can no longer be changed")
        return

    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{n_devices}").strip()

    import jax

    # Pallas (via checkify) registers per-platform lowerings at import
    # time against the CURRENT platform registry; import it while
    # "tpu" is still a known platform, or interpret-mode kernels fail
    # to even import after the factories are popped below (same
    # ordering trap tests/conftest.py documents).
    try:
        from jax.experimental import pallas as _pl  # noqa: F401
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:
        pass  # pallas unavailable: kernels fall back to XLA anyway

    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    _xb._backend_factories.pop("tpu", None)
    jax.config.update("jax_platforms", "cpu")
    if n_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except Exception:
            pass  # older jax: XLA_FLAGS above covers it


def probe_backend(retries: int = 3, backoff_s: float = 5.0):
    """Initialize the default backend with retry/backoff.

    Returns the device list on success; raises the last error after
    exhausting retries. Used by bench.py so a transiently-wedged TPU
    tunnel doesn't waste the whole benchmark run (round-1 BENCH rc=1).
    """
    import time

    import jax

    last = None
    for attempt in range(retries):
        try:
            return jax.devices()
        except Exception as e:  # backend init failure is runtime-typed
            last = e
            if attempt < retries - 1:
                time.sleep(backoff_s * (2 ** attempt))
    raise last
