"""Network fault injection: a runtime-controllable outbound rule table.

The reference proves fault tolerance with Jepsen nemeses that cut real
networks from the outside (contrib/jepsen/main.go: partition-ring,
partition-half, skew-clock); utils/failpoint.py already covers the
*surgical in-process* half of that matrix. This module is the network
half, enforced at the two process-egress choke points —
cluster/transport.py `send` (Raft frames) and cluster/client.py
`_rpc_once` (every wire RPC: client->server, alpha->zero, federated
tasks, 2PC stage/finalize) — so a rule armed in one process shapes
every byte it tries to put on the wire.

The table is PROCESS-LOCAL and OUTBOUND-ONLY (the iptables-OUTPUT
model): the src of every rule is implicitly "this process", the dst is
matched against the destination listener address. A symmetric
partition between nodes A and B is therefore two rules — one armed on
A covering B's addresses, one on B covering A's — which is exactly how
tools/dgchaos.py builds its partition nemeses via the `{"op":"fault"}`
wire op / POST /debug/fault. One-way partitions arm one side only.
Responses flowing back over an already-accepted connection are NOT
intercepted (in-flight packets survive real partitions too); cutting
both directions of fresh traffic is what the symmetric rule pair does.

Rule shape (a plain dict, JSON-serializable end to end):

    {"id": "r1",                     # auto-assigned when omitted
     "dst": "127.0.0.1:7080" | [..] | "*",   # listener addr(s)
     "drop": 1.0,                    # P(frame/RPC dropped); 1.0 = cut
     "delay_ms": 40.0,               # fixed delay before each send
     "jitter_ms": 25.0,              # + uniform[0, jitter) extra
     "dup": 0.0}                     # P(Raft frame sent twice)

First matching rule wins (exact dst before "*", in arm order).
`dup` applies to Raft frames only: transport messages are idempotent
by protocol, while duplicating a framed RPC would desynchronize the
request/response pairing on the pooled client connection.

Inert cost: `armed()` is one falsy-dict check — the transport seam
gate (`bench_micro.py --netfault-overhead`) holds it under 1% of the
summary mix. Determinism: `seed()` pins the module RNG so a chaos
schedule replays; the env var DGRAPH_TPU_NETFAULT (a JSON rule list)
arms subprocess cluster nodes at boot, like DGRAPH_TPU_FAILPOINTS.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Optional, Union

from dgraph_tpu.utils.metrics import inc_counter, set_gauge

ENV_VAR = "DGRAPH_TPU_NETFAULT"

# verdicts act() hands back to the enforcement seams
DROP = "drop"
DUP = "dup"

_MAX_DELAY_S = 5.0  # clamp: a fat-fingered delay must not wedge a node

_LOCK = threading.Lock()
_RULES: dict[str, dict] = {}   # id -> rule (insertion order = priority)
_RNG = random.Random()
_SEQ = [0]


def armed() -> bool:
    """One falsy-dict check: the whole inert-path cost at the seams."""
    return bool(_RULES)


def _norm_dst(dst: Union[str, list, tuple]) -> tuple[str, ...]:
    if isinstance(dst, str):
        return (dst,)
    return tuple(str(d) for d in dst)


def _validate(rule: dict) -> dict:
    out = {
        "id": str(rule.get("id") or ""),
        "dst": _norm_dst(rule.get("dst", "*")),
        "drop": min(1.0, max(0.0, float(rule.get("drop", 0.0)))),
        "delay_ms": max(0.0, float(rule.get("delay_ms", 0.0))),
        "jitter_ms": max(0.0, float(rule.get("jitter_ms", 0.0))),
        "dup": min(1.0, max(0.0, float(rule.get("dup", 0.0)))),
    }
    if not (out["drop"] or out["delay_ms"] or out["jitter_ms"]
            or out["dup"]):
        raise ValueError(
            f"inert fault rule {rule!r}: want drop/delay_ms/"
            "jitter_ms/dup")
    return out


def add_rule(rule: dict) -> str:
    """Arm one rule; returns its id. Validation is eager so a typo'd
    nemesis fails at arm time, not silently mid-schedule."""
    r = _validate(rule)
    with _LOCK:
        if not r["id"]:
            _SEQ[0] += 1
            r["id"] = f"r{_SEQ[0]}"
        _RULES[r["id"]] = r
        n = len(_RULES)
    set_gauge("dgraph_net_fault_rules", n)
    return r["id"]


def set_rules(rule_list: list) -> list[str]:
    """Replace the whole table atomically (the nemesis 'arm schedule'
    op): either every rule parses or nothing changes."""
    parsed = [_validate(dict(r)) for r in rule_list]
    with _LOCK:
        _RULES.clear()
        ids = []
        for r in parsed:
            if not r["id"]:
                _SEQ[0] += 1
                r["id"] = f"r{_SEQ[0]}"
            _RULES[r["id"]] = r
            ids.append(r["id"])
        n = len(_RULES)
    set_gauge("dgraph_net_fault_rules", n)
    return ids


def remove(rule_id: str) -> bool:
    with _LOCK:
        found = _RULES.pop(rule_id, None) is not None
        n = len(_RULES)
    set_gauge("dgraph_net_fault_rules", n)
    return found


def clear():
    with _LOCK:
        _RULES.clear()
    set_gauge("dgraph_net_fault_rules", 0)


def rules() -> list[dict]:
    """JSON-ready snapshot of the armed table (the /debug/fault and
    /debug/stats payload — an operator can SEE a partition)."""
    with _LOCK:
        return [dict(r, dst=list(r["dst"])) for r in _RULES.values()]


def seed(n: int):
    """Pin the probabilistic rolls so a chaos schedule replays."""
    _RNG.seed(n)


def _match(addr: str) -> Optional[dict]:
    # exact dst beats "*" regardless of arm order; within a class,
    # first armed wins
    wild = None
    for r in _RULES.values():
        if addr in r["dst"]:
            return r
        if wild is None and "*" in r["dst"]:
            wild = r
    return wild


def act(addr: Union[str, tuple],
        can_dup: bool = True) -> Optional[str]:
    """Evaluate the table for one outbound send to `addr`
    ("host:port" or a (host, port) tuple). Applies any delay INLINE
    (sleeping the sending thread — the coarse model of a slow link),
    then returns DROP, DUP or None. Callers must check `armed()`
    first; this function assumes a non-empty table is likely.

    `can_dup=False` (the RPC seams, where duplicating a framed
    request would desynchronize the pooled request/response pairing)
    skips the dup roll entirely — the dup counter only ever counts
    duplications that actually happen."""
    if not isinstance(addr, str):
        addr = f"{addr[0]}:{addr[1]}"
    with _LOCK:
        r = _match(addr)
        if r is None:
            return None
        # independent rolls, all drawn under the lock so a seeded
        # schedule replays byte-for-byte under thread interleaving
        dropped = r["drop"] and _RNG.random() < r["drop"]
        duped = (can_dup and not dropped and r["dup"]
                 and _RNG.random() < r["dup"])
        delay_s = 0.0
        if not dropped and (r["delay_ms"] or r["jitter_ms"]):
            delay_s = min(_MAX_DELAY_S,
                          (r["delay_ms"]
                           + _RNG.random() * r["jitter_ms"]) / 1e3)
    if dropped:
        # a dropped frame pays no delay: the seam fails fast, like a
        # blackholed packet (the sender's own timeouts model the wait)
        inc_counter("dgraph_net_fault_drops_total")
        return DROP
    # sleep OUTSIDE the lock: one delayed link must not serialize
    # verdicts for every other destination
    if delay_s:
        inc_counter("dgraph_net_fault_delays_total")
        time.sleep(delay_s)
    if duped:
        inc_counter("dgraph_net_fault_dups_total")
        return DUP
    return None


def handle_control(req: dict) -> dict:
    """The one fault-control dispatch shared by the `{"op":"fault"}`
    wire op and POST /debug/fault: {"action": "list"|"add"|"set"|
    "remove"|"clear", "rules": [...], "rule": {...}, "id": "...",
    "seed": N}. Returns the post-action table."""
    action = req.get("action", "list")
    if "seed" in req:
        seed(int(req["seed"]))
    if action == "add":
        add_rule(dict(req["rule"]))
    elif action == "set":
        set_rules(list(req.get("rules", ())))
    elif action == "remove":
        remove(str(req.get("id", "")))
    elif action == "clear":
        clear()
    elif action != "list":
        raise ValueError(f"unknown fault action {action!r}")
    return {"rules": rules()}


def arm_from_env(env: Optional[str] = None):
    """Arm from DGRAPH_TPU_NETFAULT (a JSON rule list) — subprocess
    cluster nodes booted mid-nemesis inherit the fault plane the same
    way they inherit failpoints. Unset/empty stays inert."""
    raw = os.environ.get(ENV_VAR, "") if env is None else env
    raw = raw.strip()
    if not raw:
        return
    set_rules(json.loads(raw))


arm_from_env()
