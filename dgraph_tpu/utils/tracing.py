"""Tracing: hierarchical request spans + device profiler hooks.

The reference instruments requests with OpenCensus spans
(x/metrics.go + go.opencensus.io trace throughout edgraph/worker) and
exposes pprof profiles. Here:

- `span(name, **attrs)` records a wall-time span into a bounded
  in-process ring. Spans are HIERARCHICAL: each record carries
  `(trace_id, span_id, parent_id, node)`, and nesting is automatic —
  a contextvar tracks the active span, so a `span()` opened inside
  another becomes its child without callers threading ids.
- `bind(trace_id, parent_span_id)` joins the current context to an
  existing trace (the serving edges bind the RequestContext's ids so
  every span of a request — across threads and, via the wire fields,
  across nodes — shares one trace_id). An unbound span roots its own
  trace (trace_id = its span_id).
- W3C `traceparent` helpers (`format_traceparent`/`parse_traceparent`)
  carry the context over HTTP and gRPC metadata; the cluster wire
  carries raw `trace_id`/`parent_span` fields.
- `export_chrome_trace()` renders the ring in the Chrome trace-event
  format (load in chrome://tracing or Perfetto) with pid = node, so a
  multi-node merge (tools/trace_merge.py) shows one lane per node.
- `profile_device(dir)` wraps jax.profiler.trace: a TensorBoard-
  loadable device profile of everything jitted inside the block — the
  TPU analogue of the reference's pprof CPU profiles.

Spans are cheap (two clock reads + an 8-byte id + a deque append under
GIL; budget < 5 µs each, enforced by bench_micro.py --span-overhead
and tier-1) and on by default; the ring bounds memory. `set_enabled`
turns recording off entirely for benchmarking the overhead itself.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Iterator, Optional

_MAX_SPANS = 4096
_spans: deque = deque(maxlen=_MAX_SPANS)
_lock = threading.Lock()
_enabled = True

# span-close observers: the always-on stats plane (utils/coststore)
# subscribes here and aggregates per-stage durations without the
# tracing module knowing about it. Observers run OUTSIDE _lock, on the
# recording thread, with the finished span record; they MUST be cheap
# (the per-span budget includes them) and MUST NOT raise — a raising
# observer is dropped from the list rather than poisoning every span.
_observers: list = []


def add_span_observer(fn) -> None:
    """Register `fn(record)` to run at every span close. The record is
    the live ring entry — observers read, never mutate."""
    if fn not in _observers:
        _observers.append(fn)


def remove_span_observer(fn) -> None:
    try:
        _observers.remove(fn)
    except ValueError:
        pass

# Registry of every span name the tree emits. Span names are API the
# same way metric names are (trace queries and the Perfetto merge key
# on them), so dglint DG08 checks each literal span(...) name against
# this tuple — a typo'd name forks a trace nobody queries. Keep sorted.
SPAN_NAMES = (
    "batch.wait",
    "block",
    "commit",
    "device.tile_load",
    "encode",
    "eq",
    "execute",
    "expand",
    "ineq",
    "match",
    "mutate",
    "parse",
    "plan.compile",
    "query",
    "raft.apply",
    "rpc.recv",
    "rpc.send",
    "setops",
    "similar_to",
    "sort",
    "tablet.rollup",
    "vector.build",
    "wal.append",
)

# node identity: one process-global default (a deployed node is one
# process) plus a contextvar override for in-process multi-node
# harnesses, where each serving thread belongs to one logical node
_NODE = "local"
_NODE_CV: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dgraph_tpu_trace_node", default=None)
# (trace_id, span_id) of the active span / bound request, or None
_CUR: contextvars.ContextVar[Optional[tuple[str, str]]] = \
    contextvars.ContextVar("dgraph_tpu_trace_ctx", default=None)


def set_enabled(on: bool) -> None:
    """Gate span RETENTION (the ring + /debug/traces). Registered span
    observers — notably the coststore's always-on aggregation — keep
    firing while disabled; silence those at their own switch (e.g.
    coststore.set_enabled)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def set_node(name: str) -> None:
    """Process-global node identity stamped on every span (pid lane in
    the merged Perfetto view). Cluster servers set e.g. alpha-g1-n2."""
    global _NODE
    _NODE = str(name)


def set_thread_node(name: str) -> None:
    """Node identity for THIS thread/context only — long-running
    serving threads of in-process multi-node harnesses call it once at
    thread start (no reset needed; the context dies with the thread)."""
    _NODE_CV.set(str(name))


def node() -> str:
    return _NODE_CV.get() or _NODE


# span ids: sequential from a random 64-bit per-process base — one
# C-level next() + a format beats os.urandom().hex() by ~1 µs/span,
# and the random base keeps ids distinct across the cluster's nodes
_ID_SEQ = itertools.count(int.from_bytes(os.urandom(8), "big"))


def new_span_id() -> str:
    return f"{next(_ID_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}"


def current() -> Optional[tuple[str, str]]:
    """(trace_id, span_id) of the innermost active span or bound
    request context; None outside any trace."""
    return _CUR.get()


@contextlib.contextmanager
def bind(trace_id: str, parent_span_id: str = "",
         node: Optional[str] = None) -> Iterator[None]:
    """Join this context to an existing trace: spans opened inside
    become children of `parent_span_id` (the caller's span on the other
    side of the wire). `node` overrides the node identity for the
    block (in-process multi-node harnesses)."""
    tok = _CUR.set((str(trace_id), str(parent_span_id or "")))
    ntok = _NODE_CV.set(str(node)) if node is not None else None
    try:
        yield
    finally:
        _CUR.reset(tok)
        if ntok is not None:
            _NODE_CV.reset(ntok)


@contextlib.contextmanager
def bind_request(ctx) -> Iterator[None]:
    """Bind the trace of a RequestContext (None = no-op). Idempotent
    per trace: when the context is already bound to the same trace
    (e.g. the rpc.recv span of the serving loop), spans keep nesting
    under the CURRENT span instead of re-rooting at the wire parent."""
    if ctx is None:
        yield
        return
    cur = _CUR.get()
    if cur is not None and cur[0] == ctx.trace_id:
        yield
        return
    with bind(ctx.trace_id, getattr(ctx, "parent_span", "") or ""):
        yield


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[dict]:
    """Record one wall-time span; yields the attr dict so callers can
    attach results (e.g. result counts) before the span closes."""
    # observers (the coststore's always-on aggregation) outlive the
    # ring's enabled flag: set_enabled(False) stops RETAINING spans,
    # not MEASURING them. Sheds to a true no-op only when nobody is
    # listening at all.
    if not _enabled and not _observers:
        yield attrs
        return
    cur = _CUR.get()
    sid = new_span_id()
    if cur is None:
        trace_id, parent = sid, ""  # self-rooted trace
    else:
        trace_id, parent = cur
    # wall clock: chrome://tracing renders these as absolute instants.
    # `attrs` is the call's own fresh kwargs dict — no defensive copy
    rec = {"name": name, "trace_id": trace_id, "span_id": sid,
           "parent_id": parent, "node": _NODE_CV.get() or _NODE,
           "ts_us": time.time() * 1e6,  # dglint: disable=DG06
           "tid": threading.get_ident(), "args": attrs}
    tok = _CUR.set((trace_id, sid))
    t0 = time.perf_counter_ns()
    try:
        yield rec["args"]
    finally:
        rec["dur_us"] = (time.perf_counter_ns() - t0) / 1e3
        _CUR.reset(tok)
        if _enabled:
            with _lock:
                _spans.append(rec)
        if _observers:
            for fn in list(_observers):
                try:
                    fn(rec)
                except Exception:
                    remove_span_observer(fn)


# ------------------------------------------------------- W3C traceparent

_HEX = set("0123456789abcdef")


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


def format_traceparent(trace_id: str, span_id: str = "") -> str:
    """`00-<32 hex trace>-<16 hex parent>-01`. Short hex ids (the
    16-hex RequestContext default) zero-pad; non-hex ids hash to a
    stable 32-hex form so the header is always well-formed."""
    t = str(trace_id).lower()
    if _is_hex(t) and len(t) <= 32:
        t = t.rjust(32, "0")
    else:
        import hashlib
        t = hashlib.blake2b(t.encode(), digest_size=16).hexdigest()
    s = str(span_id).lower()
    if not (_is_hex(s) and len(s) <= 16):
        s = new_span_id()
    return f"00-{t}-{s.rjust(16, '0')}-01"


def parse_traceparent(header: str) -> Optional[tuple[str, str]]:
    """-> (trace_id, parent_span_id), or None for a malformed header.
    The 32-hex trace id is kept VERBATIM as the request's trace_id so
    every node of the cluster reports the same id the caller sent."""
    parts = str(header or "").strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid = parts[0], parts[1], parts[2]
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16:
        return None
    if not (_is_hex(ver) and _is_hex(tid) and _is_hex(sid)):
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return tid, sid


def current_traceparent() -> Optional[str]:
    cur = _CUR.get()
    if cur is None:
        return None
    return format_traceparent(cur[0], cur[1])


# ------------------------------------------------------------- ring reads


def recent_spans(limit: int = 200) -> list[dict]:
    with _lock:
        return list(_spans)[-limit:]


def spans_for(trace_id: str, limit: int = _MAX_SPANS) -> list[dict]:
    """The node-local slice of one trace (what /debug/traces?trace_id=
    and the cluster `traces` op return; tools/trace_merge.py stitches
    slices from several nodes into one timeline)."""
    with _lock:
        out = [s for s in _spans if s.get("trace_id") == trace_id]
    return out[-limit:]


def clear() -> None:
    with _lock:
        _spans.clear()


def node_pids(spans: list[dict]) -> dict[str, int]:
    """Node name -> Chrome trace pid lane (sorted node names,
    1-based). THE pid assignment for every event kind derived from a
    span set — chrome_events 'X' spans and trace_merge counter tracks
    must agree or counters land in the wrong process lane."""
    return {n: i + 1 for i, n in
            enumerate(sorted({s.get("node", "local") for s in spans}))}


def chrome_events(spans: list[dict]) -> list[dict]:
    """Span records -> Chrome trace-event JSON: one metadata
    process_name per node (pid = node lane) plus 'X' complete events
    carrying the span ids in args for parent-link inspection."""
    pid = node_pids(spans)
    nodes = sorted(pid)
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid[n], "tid": 0,
         "args": {"name": n}} for n in nodes]
    for s in spans:
        args = dict(s.get("args", ()))
        args["trace_id"] = s.get("trace_id", "")
        args["span_id"] = s.get("span_id", "")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append({"name": s["name"], "ph": "X", "ts": s["ts_us"],
                       "dur": s.get("dur_us", 0.0),
                       "pid": pid[s.get("node", "local")],
                       "tid": s["tid"], "args": args})
    return events


def export_chrome_trace(trace_id: Optional[str] = None) -> list[dict]:
    """Chrome trace-event JSON ('X' complete events): load the result
    of /debug/traces straight into chrome://tracing / Perfetto. With
    trace_id, only that trace's node-local slice."""
    with _lock:
        spans = list(_spans)
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace_id") == trace_id]
    return chrome_events(spans)


@contextlib.contextmanager
def profile_device(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace (XLA compilation + kernel
    timeline) for everything run inside the block. View with
    TensorBoard's profile plugin pointed at log_dir."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
