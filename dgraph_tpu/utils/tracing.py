"""Tracing: query spans + device profiler hooks.

The reference instruments requests with OpenCensus spans
(x/metrics.go + go.opencensus.io trace throughout edgraph/worker) and
exposes pprof profiles. Here:

- `span(name, **attrs)` records wall-time spans into a bounded
  in-process ring; `export_chrome_trace()` renders them in the Chrome
  trace-event format (load in chrome://tracing or Perfetto).
- `profile_device(dir)` wraps jax.profiler.trace: a TensorBoard-
  loadable device profile of everything jitted inside the block — the
  TPU analogue of the reference's pprof CPU profiles.

Spans are cheap (two clock reads + a deque append under GIL) and on by
default; the ring bounds memory.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Iterator

_MAX_SPANS = 4096
_spans: deque = deque(maxlen=_MAX_SPANS)
_lock = threading.Lock()


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[dict]:
    """Record one wall-time span; yields the attr dict so callers can
    attach results (e.g. result counts) before the span closes."""
    # wall clock: chrome://tracing renders these as absolute instants
    rec = {"name": name, "ts_us": time.time() * 1e6,  # dglint: disable=DG06
           "tid": threading.get_ident(), "args": dict(attrs)}
    t0 = time.perf_counter_ns()
    try:
        yield rec["args"]
    finally:
        rec["dur_us"] = (time.perf_counter_ns() - t0) / 1e3
        with _lock:
            _spans.append(rec)


def recent_spans(limit: int = 200) -> list[dict]:
    with _lock:
        return list(_spans)[-limit:]


def clear() -> None:
    with _lock:
        _spans.clear()


def export_chrome_trace() -> list[dict]:
    """Chrome trace-event JSON ('X' complete events): load the result
    of /debug/traces straight into chrome://tracing / Perfetto."""
    with _lock:
        spans = list(_spans)
    return [{"name": s["name"], "ph": "X", "ts": s["ts_us"],
             "dur": s["dur_us"], "pid": 1, "tid": s["tid"],
             "args": s["args"]} for s in spans]


@contextlib.contextmanager
def profile_device(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace (XLA compilation + kernel
    timeline) for everything run inside the block. View with
    TensorBoard's profile plugin pointed at log_dir."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
