"""Alert rule engine: SLO burn-rate + stall-watchdog evaluation.

Pure state machines, no threads — utils/watchdog.py owns the tick
loop, signal collection and the incident flight recorder; this module
owns WHAT fires and WHEN. Two rule families:

  BurnRateRule   multi-window error-budget burn (the Google SRE
                 "multiwindow, multi-burn-rate" recipe): a series of
                 per-second (total, bad) buckets per op-class and per
                 tenant, fed from the request log's completion
                 observer; the rule breaches only when BOTH the fast
                 window (default 1 m) and the slow window (default
                 30 m) burn above threshold — the fast window gives
                 detection latency, the slow window keeps a short
                 blip from paging.

  ThresholdRule  a named scalar signal (raft apply lag, WAL fsync
                 p99, CDC subscriber lag, DR standby lag, stuck-move
                 age, result-cache hit collapse, tile-cache thrash,
                 shed rate, silent raft peer) compared against a
                 threshold.

Both carry hysteresis: `for_ticks` consecutive breaching evaluations
to transition to firing, `clear_ticks` consecutive healthy ones to
resolve — a boundary-oscillating signal holds its current state
instead of flapping. Transitions append to a bounded event ring
(`events`), and `evaluate()` returns them so the watchdog can trigger
flight-recorder captures exactly on ok->firing edges.

Thresholds/windows are env-tunable (DGRAPH_TPU_ALERT_*): production
defaults are deliberately conservative (zero false positives on a
healthy cluster is an acceptance gate dgchaos enforces), while chaos
harnesses shrink the windows to fit second-scale fault injection.

Ref: the reference Dgraph ships no alerting (only /health + /state);
the rule catalog is documented in docs/deployment.md "Alerting &
incident response".
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

# outcomes that consume error budget: a shed is backpressure working
# as designed, an abort is the transaction protocol working as
# designed, a client cancel is the client's choice — only deadline
# blowouts and real errors are SLO-bad
BAD_OUTCOMES = frozenset({"error", "deadline"})

_EVENTS_MAX = 256


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SloWindow:
    """Per-second (total, bad) buckets over a fixed horizon — one per
    tracked series (an op class or a tenant). O(1) add; rate queries
    sum the last N seconds. Monotonic-second indexed ring."""

    __slots__ = ("horizon", "_ring")

    def __init__(self, horizon_s: int):
        self.horizon = int(horizon_s)
        # slot = [second, total, bad]; second stamps validity so a
        # sparse series never reads a lapped slot
        self._ring = [[-1, 0, 0] for _ in range(self.horizon)]

    def add(self, now_s: int, bad: bool) -> None:
        slot = self._ring[now_s % self.horizon]
        if slot[0] != now_s:
            slot[0], slot[1], slot[2] = now_s, 0, 0
        slot[1] += 1
        if bad:
            slot[2] += 1

    def rates(self, now_s: int, window_s: int) -> tuple[int, int]:
        """(total, bad) over the window ending at now_s inclusive."""
        window_s = min(int(window_s), self.horizon)
        total = bad = 0
        for s in range(now_s - window_s + 1, now_s + 1):
            slot = self._ring[s % self.horizon]
            if slot[0] == s:
                total += slot[1]
                bad += slot[2]
        return total, bad


class _RuleState:
    __slots__ = ("state", "breach_ticks", "ok_ticks", "idle_ticks",
                 "since", "value", "acked", "silenced_until")

    def __init__(self):
        self.state = "ok"
        self.breach_ticks = 0
        self.ok_ticks = 0
        self.idle_ticks = 0     # consecutive no-data evaluations
        self.since = 0.0        # monotonic ts of the last transition
        self.value = None       # last evaluated value
        self.acked = False
        self.silenced_until = 0.0


class Rule:
    """Base: subclasses implement breached(...) -> (bool|None, value).
    None means "not enough data — hold current state without counting
    toward hysteresis either way"."""

    kind = "threshold"

    def __init__(self, name: str, *, for_ticks: int = 3,
                 clear_ticks: int = 5, severity: str = "page",
                 summary: str = ""):
        self.name = name
        self.for_ticks = max(1, int(for_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.severity = severity
        self.summary = summary

    def describe(self) -> dict:
        return {"rule": self.name, "kind": self.kind,
                "severity": self.severity, "summary": self.summary,
                "for_ticks": self.for_ticks,
                "clear_ticks": self.clear_ticks}


class ThresholdRule(Rule):
    """signal `op` threshold — the stall-watchdog family. `signal`
    names a key in the signals dict the watchdog tick assembles;
    a missing key holds state (the subsystem isn't running here)."""

    def __init__(self, name: str, signal: str, threshold: float,
                 op: str = ">", **kw):
        super().__init__(name, **kw)
        self.signal = signal
        self.threshold = float(threshold)
        self.op = op

    def describe(self) -> dict:
        d = super().describe()
        d.update(signal=self.signal, threshold=self.threshold,
                 op=self.op)
        return d

    def breached(self, signals: dict) -> tuple[Optional[bool], object]:
        v = signals.get(self.signal)
        if v is None:
            return None, None
        if self.op == "<":
            return v < self.threshold, v
        return v > self.threshold, v


class BurnRateRule(Rule):
    """Multi-window error-budget burn over one SloWindow series.

    burn = bad_fraction / error_budget, error_budget = 1 - target.
    Breaches only when burn >= threshold over BOTH windows and the
    fast window saw >= min_volume requests (a two-request blip on an
    idle node is noise, not an outage)."""

    kind = "burn_rate"

    def __init__(self, name: str, *, target: float, burn: float,
                 fast_s: int, slow_s: int, min_volume: int, **kw):
        super().__init__(name, **kw)
        self.target = float(target)
        self.budget = max(1e-6, 1.0 - self.target)
        self.burn = float(burn)
        self.fast_s = int(fast_s)
        self.slow_s = int(slow_s)
        self.min_volume = int(min_volume)

    def describe(self) -> dict:
        d = super().describe()
        d.update(target=self.target, burn=self.burn,
                 fast_s=self.fast_s, slow_s=self.slow_s,
                 min_volume=self.min_volume)
        return d

    def breached_window(self, win: SloWindow, now_s: int
                        ) -> tuple[Optional[bool], object]:
        ft, fb = win.rates(now_s, self.fast_s)
        st, sb = win.rates(now_s, self.slow_s)
        if ft < self.min_volume:
            # not enough traffic to judge: holds state, and a firing
            # alert over a series that went quiet resolves via the
            # manager's idle-series cleanup, not a phantom "healthy"
            return None, None
        fast_burn = (fb / ft) / self.budget
        slow_burn = (sb / st) / self.budget if st else 0.0
        return (fast_burn >= self.burn and slow_burn >= self.burn), \
            round(min(fast_burn, slow_burn), 3)


class AlertManager:
    """Rule registry + per-series state machines + event ring.

    Burn-rate rules fan out over the live series (op classes and
    tenants seen by the request-log observer); threshold rules are
    one series each. All mutation happens under one lock; `evaluate`
    is called from the watchdog tick, `observe_request` from serving
    threads via the reqlog observer (O(1) per request)."""

    MAX_SERIES = 64  # bound per-tenant window growth

    def __init__(self, rules: Optional[list[Rule]] = None,
                 horizon_s: Optional[int] = None):
        self._lock = threading.Lock()
        self.rules: list[Rule] = list(rules if rules is not None
                                      else default_rules())
        slow = max([r.slow_s for r in self.rules
                    if isinstance(r, BurnRateRule)] or [1800])
        self.horizon_s = int(horizon_s or (slow + 60))
        self._windows: dict[str, SloWindow] = {}   # series key -> win
        self._states: dict[str, _RuleState] = {}   # series -> state
        self.events: deque = deque(maxlen=_EVENTS_MAX)
        self._started_mono = time.monotonic()

    # ------------------------------------------------------ ingestion

    def observe_request(self, rec: dict) -> None:
        """reqlog observer: one completed request into the per-second
        windows — per op-class, and per tenant when tagged."""
        outcome = rec.get("outcome", "ok")
        bad = outcome in BAD_OUTCOMES
        now_s = int(time.monotonic())
        op = str(rec.get("op") or "other")
        tenant = str(rec.get("tenant") or "")
        with self._lock:
            self._window("op:" + op).add(now_s, bad)
            self._window("op:_all").add(now_s, bad)
            if tenant:
                self._window("tenant:" + tenant).add(now_s, bad)

    def _window(self, series: str) -> SloWindow:
        win = self._windows.get(series)
        if win is None:
            if len(self._windows) >= self.MAX_SERIES:
                # bounded: drop the oldest tracked series that isn't
                # the aggregate (tenant explosion guard); op:_all
                # always stays
                for victim in self._windows:
                    if victim != "op:_all":
                        del self._windows[victim]
                        break
            win = self._windows[series] = SloWindow(self.horizon_s)
        return win

    # ----------------------------------------------------- evaluation

    def evaluate(self, signals: Optional[dict] = None,
                 now_mono: Optional[float] = None) -> list[dict]:
        """One tick: run every rule, advance hysteresis, return the
        TRANSITIONS ([{rule, series, state, value, ts}...]) — the
        watchdog captures an incident bundle per ok->firing edge."""
        signals = signals or {}
        now = now_mono if now_mono is not None else time.monotonic()
        now_s = int(now)
        transitions: list[dict] = []
        with self._lock:
            for rule in self.rules:
                if isinstance(rule, BurnRateRule):
                    for series, win in list(self._windows.items()):
                        breached, value = rule.breached_window(
                            win, now_s)
                        self._advance(rule, f"{rule.name}[{series}]",
                                      breached, value, now,
                                      transitions)
                else:
                    self._advance(rule, rule.name,
                                  *rule.breached(signals), now,
                                  transitions)
        return transitions

    def _advance(self, rule: Rule, series: str,
                 breached: Optional[bool], value, now: float,
                 out: list[dict]) -> None:
        st = self._states.get(series)
        if st is None:
            if not breached:
                return  # don't materialize state for healthy series
            st = self._states[series] = _RuleState()
        st.value = value
        if breached is None:
            # insufficient data: hold, no hysteresis movement — but a
            # FIRING series that stays data-starved long enough (the
            # traffic evaporated, or the subsystem shut down) resolves
            # rather than paging forever on a ghost
            st.idle_ticks += 1
            if st.state == "firing" \
                    and st.idle_ticks >= 4 * rule.clear_ticks:
                st.state = "ok"
                st.since = now
                out.append(self._event(rule, series, "resolved",
                                       None, now))
            return
        st.idle_ticks = 0
        if breached:
            st.breach_ticks += 1
            st.ok_ticks = 0
            if st.state == "ok" \
                    and st.breach_ticks >= rule.for_ticks \
                    and now >= st.silenced_until:
                st.state = "firing"
                st.since = now
                st.acked = False
                out.append(self._event(rule, series, "firing",
                                       value, now))
        else:
            st.ok_ticks += 1
            st.breach_ticks = 0
            if st.state == "firing" \
                    and st.ok_ticks >= rule.clear_ticks:
                st.state = "ok"
                st.since = now
                out.append(self._event(rule, series, "resolved",
                                       value, now))

    def _event(self, rule: Rule, series: str, state: str, value,
               now: float) -> dict:
        ev = {"rule": rule.name, "series": series, "state": state,
              "value": value, "severity": rule.severity,
              "mono": round(now, 3),
              # wall clock: operators join events against external
              # logs and the incident bundles' manifests
              "ts": time.time()}  # dglint: disable=DG06
        self.events.append(ev)
        return ev

    # -------------------------------------------------------- control

    def ack(self, series: str) -> bool:
        """Mark a firing alert acknowledged (it keeps evaluating and
        still resolves; ack is operator bookkeeping, not a mute)."""
        with self._lock:
            st = self._states.get(series)
            if st is None or st.state != "firing":
                return False
            st.acked = True
            return True

    def silence(self, series: str, ttl_s: float) -> None:
        """Suppress NEW firings of a series for ttl_s (an already-
        firing alert resolves normally; it just can't re-fire)."""
        with self._lock:
            st = self._states.setdefault(series, _RuleState())
            st.silenced_until = time.monotonic() + float(ttl_s)

    # ------------------------------------------------------- payloads

    def firing(self) -> list[dict]:
        with self._lock:
            return [{"series": s, "rule": s.split("[", 1)[0],
                     "value": st.value, "acked": st.acked,
                     "since_s": round(time.monotonic() - st.since, 1)}
                    for s, st in sorted(self._states.items())
                    if st.state == "firing"]

    def payload(self) -> dict:
        """The /debug/alerts body: rule catalog, firing set, recent
        transition events."""
        firing = self.firing()
        with self._lock:
            events = list(self.events)[-64:]
        return {"rules": [r.describe() for r in self.rules],
                "firing": firing, "events": events,
                "uptime_s": round(
                    time.monotonic() - self._started_mono, 1)}


def default_rules() -> list[Rule]:
    """The shipped rule catalog (docs/deployment.md has the prose
    version). Every number here is env-tunable: production defaults
    are conservative — the dgchaos acceptance gate requires ZERO
    firings on a healthy cluster — while chaos smokes shrink windows
    to match second-scale fault injection."""
    for_t = int(_env_f("DGRAPH_TPU_ALERT_FOR_TICKS", 3))
    clear_t = int(_env_f("DGRAPH_TPU_ALERT_CLEAR_TICKS", 5))
    hy = dict(for_ticks=for_t, clear_ticks=clear_t)
    return [
        BurnRateRule(
            "slo_error_burn",
            target=_env_f("DGRAPH_TPU_ALERT_SLO_TARGET", 0.99),
            burn=_env_f("DGRAPH_TPU_ALERT_SLO_BURN", 10.0),
            fast_s=int(_env_f("DGRAPH_TPU_ALERT_SLO_FAST_S", 60)),
            slow_s=int(_env_f("DGRAPH_TPU_ALERT_SLO_SLOW_S", 1800)),
            min_volume=int(_env_f(
                "DGRAPH_TPU_ALERT_SLO_MIN_VOLUME", 20)),
            summary="error-budget burn (deadline/error outcomes) "
                    "over fast AND slow windows", **hy),
        ThresholdRule(
            "raft_apply_lag", "raft_apply_lag",
            _env_f("DGRAPH_TPU_ALERT_APPLY_LAG", 5000),
            summary="committed-applied raft entries: the apply path "
                    "has stalled behind consensus", **hy),
        ThresholdRule(
            "raft_peer_silent", "raft_peer_silent_s",
            _env_f("DGRAPH_TPU_ALERT_PEER_SILENT_S", 10.0),
            summary="seconds since the quietest raft peer was heard "
                    "(several election timeouts = a partition)", **hy),
        ThresholdRule(
            "report_silent", "report_silent_s",
            _env_f("DGRAPH_TPU_ALERT_REPORT_SILENT_S", 90.0),
            summary="seconds since the quietest alpha's heat/status "
                    "report reached zero (node down or partitioned "
                    "from the coordinator; works at replicas=1)",
            **hy),
        ThresholdRule(
            "wal_fsync_stall", "wal_fsync_p99_s",
            _env_f("DGRAPH_TPU_ALERT_FSYNC_P99_S", 0.5),
            summary="WAL fsync p99 over the last tick window: the "
                    "durability volume is dying", **hy),
        ThresholdRule(
            "cdc_lag", "cdc_max_lag",
            _env_f("DGRAPH_TPU_ALERT_CDC_LAG", 10000),
            summary="slowest CDC subscriber's unread entries", **hy),
        ThresholdRule(
            "dr_standby_lag", "dr_lag_entries",
            _env_f("DGRAPH_TPU_ALERT_DR_LAG", 10000),
            summary="cross-cluster standby replication lag", **hy),
        ThresholdRule(
            "move_stuck", "move_stuck_age_s",
            _env_f("DGRAPH_TPU_ALERT_MOVE_STUCK_S", 600.0),
            summary="a tablet move/split has sat in one phase too "
                    "long", **hy),
        ThresholdRule(
            "result_cache_collapse", "result_cache_hit_frac",
            _env_f("DGRAPH_TPU_ALERT_CACHE_HIT_FRAC", 0.02),
            op="<",
            summary="result-cache hit rate collapsed under real "
                    "lookup volume (invalidation storm)", **hy),
        ThresholdRule(
            "tile_cache_thrash", "tile_evictions_per_s",
            _env_f("DGRAPH_TPU_ALERT_TILE_EVICT_S", 200.0),
            summary="device tile-cache evictions/s: working set no "
                    "longer fits", **hy),
        ThresholdRule(
            "shed_rate", "sheds_per_s",
            _env_f("DGRAPH_TPU_ALERT_SHED_S", 10.0),
            summary="admission sheds/s (global + tenant QoS): "
                    "sustained overload", **hy),
    ]


# nothing here touches the process-global metrics/reqlog state: the
# watchdog owns the one shared AlertManager instance per process
_SIGNAL_DOC: dict[str, str] = {
    "raft_apply_lag": "cluster/service.py _drain_ready",
    "raft_peer_silent_s": "RaftServer.peer_ages max",
    "report_silent_s": "zero leader's per-alpha heat-report clock",
    "wal_fsync_p99_s": "dgraph_wal_fsync_seconds tick-delta p99",
    "cdc_max_lag": "cdc/changelog.py stats() subscriber lag",
    "dr_lag_entries": "dgraph_repl_lag_entries gauge max",
    "move_stuck_age_s": "zero move ledger phase age",
    "result_cache_hit_frac": "result-cache counters tick delta",
    "tile_evictions_per_s": "device_cache_evictions tick delta",
    "sheds_per_s": "shed counters tick delta",
}

SignalFn = Callable[[], dict]
