"""Reader-writer lock for the server front ends.

The reference gets read concurrency from Go's per-list RWMutex + MVCC
(posting/list.go RLock readers, goroutine-per-request); the in-process
engine equivalent is one server-level RW lock: snapshot reads share,
writes are exclusive. Writer-preference so a mutation burst cannot be
starved by a steady query stream.
"""

from __future__ import annotations

import threading


class RWLock:
    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    # -- read side --

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    # -- write side --

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    # -- context managers --

    @property
    def read(self):
        return _Guard(self.acquire_read, self.release_read)

    @property
    def write(self):
        return _Guard(self.acquire_write, self.release_write)


class _Guard:
    __slots__ = ("_enter", "_exit")

    def __init__(self, enter, exit_):
        self._enter = enter
        self._exit = exit_

    def __enter__(self):
        self._enter()
        return self

    def __exit__(self, *exc):
        self._exit()
        return False
