"""Bounded slow-query/request log backing /debug/requests.

The reference keeps /debug surfaces for "what has this node been
doing" (x/metrics.go pprof + expvar). This module is the request-level
equivalent: two bounded views of completed requests —

  recent   the last _RECENT_MAX requests in arrival order
  slowest  the _SLOW_MAX highest-latency requests seen since reset

Each entry carries the op, trace_id (the handle into /debug/traces and
the merged Perfetto view), total latency, the per-phase breakdown when
the engine measured one (extensions.server_latency), and the outcome —
"ok", or how the request died ("shed", "deadline", "cancelled",
"aborted", "error") so overload/abort behavior is inspectable after
the fact.

The engine records successful query/mutate completions (it owns the
phase breakdown); the serving edges record every failure outcome
(sheds never reach the engine). Recording is a deque append + a
bounded heap push under one lock — cheap enough for every request.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import itertools
import threading
import time
from collections import deque
from typing import Iterator, Optional

from dgraph_tpu.utils import tracing

_RECENT_MAX = 256
_SLOW_MAX = 32

_lock = threading.Lock()
_recent: deque = deque(maxlen=_RECENT_MAX)
_slow_heap: list[tuple[float, int, dict]] = []  # min-heap of (ms, seq, rec)
_seq = itertools.count()

# the micro-batcher (engine/batcher.py) binds its dispatch id around
# the drive so the engine's query records join against the batch
# without threading an argument through db.query_json
_BATCH_CV: contextvars.ContextVar[str] = contextvars.ContextVar(
    "dgraph_tpu_reqlog_batch", default="")

# Completion observers (mirrors tracing.add_span_observer): each
# registered callable sees every record() dict AFTER it lands in the
# rings — the SLO burn-rate evaluator (utils/alerts.py) feeds its
# per-second outcome windows from here without the serving edges
# growing a second reporting path. Observers must be cheap and never
# raise; they run outside _lock.
_observers: list = []


def add_observer(fn) -> None:
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn) -> None:
    try:
        _observers.remove(fn)
    except ValueError:
        pass


@contextlib.contextmanager
def bind_batch(batch_id: str) -> Iterator[None]:
    """Stamp `batch_id` on every record() inside the block (the
    micro-batcher wraps each batch dispatch)."""
    tok = _BATCH_CV.set(str(batch_id))
    try:
        yield
    finally:
        _BATCH_CV.reset(tok)


def record(op: str, trace_id: str = "", latency_ms: float = 0.0,
           outcome: str = "ok",
           breakdown: Optional[dict] = None,
           plan_key: str = "", batch_id: str = "",
           tenant: str = "") -> None:
    """`plan_key` is the compiled plan's 16-hex skeleton hash ("" for
    interpreted requests) — the join key into the plan cache and the
    coststore's per-plan summaries; `batch_id` joins against the
    micro-batcher's dispatch (defaults to the bound batch context);
    `tenant` is the QoS plane's accounting namespace ("" = untagged),
    so /debug/requests answers "whose requests were shed"."""
    rec = {"op": str(op), "trace_id": str(trace_id),
           "latency_ms": round(float(latency_ms), 3),
           "outcome": str(outcome), "node": tracing.node(),
           "plan_key": str(plan_key),
           "batch_id": str(batch_id) or _BATCH_CV.get(),
           "tenant": str(tenant),
           # wall clock: operators correlate these with external logs
           "ts": time.time()}  # dglint: disable=DG06
    if breakdown:
        rec["breakdown"] = dict(breakdown)
    with _lock:
        _recent.append(rec)
        heapq.heappush(_slow_heap,
                       (rec["latency_ms"], next(_seq), rec))
        if len(_slow_heap) > _SLOW_MAX:
            heapq.heappop(_slow_heap)  # drop the fastest
    for fn in list(_observers):
        try:
            fn(rec)
        except Exception:  # noqa: BLE001 — an alerting-plane bug  # dglint: disable=DG07 (observer runs on serving threads; no ctx owned here)
            pass  # must never kill the request that fed it


def outcome_of(exc: BaseException) -> str:
    """Classify a request-killing exception for the log (the serving
    edges share this so HTTP and gRPC report identical outcomes)."""
    from dgraph_tpu.utils.reqctx import (
        Cancelled, DeadlineExceeded, Overloaded,
    )
    if isinstance(exc, Overloaded):
        return "shed"
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, Cancelled):
        return "cancelled"
    if type(exc).__name__ == "TxnAborted":
        return "aborted"
    return "error"


def snapshot() -> dict:
    with _lock:
        slow = sorted(_slow_heap, key=lambda t: (-t[0], t[1]))
        return {"recent": list(_recent),
                "slowest": [rec for _, _, rec in slow]}


def reset() -> None:
    with _lock:
        _recent.clear()
        _slow_heap.clear()
