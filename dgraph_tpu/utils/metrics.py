"""Metrics registry: counters, gauges, histograms + Prometheus text render.

Re-provides the reference's OpenCensus stat surface (x/metrics.go:40-100 —
num_queries_total, num_mutations_total, num_edges_total, latency, pending
work, memory gauges) with a dependency-free registry; the HTTP server
exposes it at /debug/prometheus_metrics like the reference's bridged
Prometheus exporter (x/metrics.go:258 RegisterExporters).
"""

from __future__ import annotations

import threading
from bisect import bisect_right

_LOCK = threading.Lock()
_COUNTERS: dict[tuple[str, tuple], float] = {}
_GAUGES: dict[tuple[str, tuple], float] = {}
_HISTOGRAMS: dict[tuple[str, tuple], list[int]] = {}
_HISTO_SUM: dict[tuple[str, tuple], float] = {}

# latency buckets in ms (ref x/metrics.go defaultLatencyMsDistribution)
BUCKETS = [0.1, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
           5000, 10000]

# Histograms whose unit is NOT milliseconds get their own bucket
# table (the global one spans 0.1ms..10s and would collapse a
# sub-millisecond fsync into one bucket). Keyed by metric name; every
# snapshot/render path consults this so the exposition's `le` edges
# always match the counts.
BUCKETS_BY_NAME: dict[str, list[float]] = {
    # seconds: fsync on a healthy NVMe is ~50-500us, a dying volume
    # is 0.1-2.5s — the watchdog's p99 stall rule needs resolution at
    # both ends
    "dgraph_wal_fsync_seconds": [
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
        0.05, 0.1, 0.25, 0.5, 1.0, 2.5],
}


def buckets_for(name: str) -> list[float]:
    return BUCKETS_BY_NAME.get(name, BUCKETS)

# Registry of every metric name the tree emits. Metric names are API
# (dashboards and alerts key on them), so dglint DG08 checks each
# literal inc_counter/set_gauge/observe name against this tuple — a
# typo'd name forks a series nobody reads, a duplicate entry here is
# a copy-paste smell. Keep sorted within each group.
REGISTERED = (
    # engine (engine/db.py, engine/lazy_tablets.py, engine/tile_cache.py)
    "codec_scratch_bytes",
    "device_cache_bytes",
    "device_cache_evictions",
    "device_cache_tiles",
    "dgraph_num_edges_total",
    "dgraph_num_mutations_total",
    "dgraph_num_queries_total",
    "dgraph_query_latency_ms",
    "dgraph_txn_aborts_total",
    "host_tile_bytes",
    "tablet_store_evictions",
    "tablet_store_loads",
    # serving edge (server/http.py)
    "dgraph_pending_queries",
    "dgraph_queries_shed_total",
    # compiled plan cache + micro-batcher (query/plan.py,
    # engine/batcher.py)
    "batch_dispatches",
    "batch_occupancy",
    "plan_cache_evictions",
    "plan_cache_hits",
    "plan_cache_misses",
    # adaptive planner (query/planner.py)
    "planner_decisions_total",
    "planner_estimate_violations_total",
    "planner_explored_total",
    "planner_reoptimized_total",
    "planner_replans_suppressed_total",
    # whole-plan fusion + cold-store prefetch (query/fusion.py,
    # engine/prefetch.py)
    "prefetch_bytes_total",
    "prefetch_hits_total",
    "prefetch_misses_total",
    "prefetch_queue_depth",
    "query_fused_dispatch_total",
    # query executor tier counters (query/executor.py)
    "query_columnar_var_bind_total",
    "query_colvar_hits_total",
    "query_compressed_fallback_total",
    "query_compressed_setops_total",
    "query_device_count_page_total",
    "query_device_expand_total",
    "query_device_multisort_total",
    "query_device_orderkeys_total",
    "query_device_overlay_expand_total",
    "query_device_range_total",
    "query_device_setops_total",
    "query_device_sort_page_total",
    "query_device_sssp_total",
    "query_flat_json_total",
    "query_groupby_fast_total",
    "query_index_csr_probe_total",
    "query_match_batch_total",
    "query_order_presorted_total",
    "query_postings_fallback_total",
    "query_regexp_batch_total",
    "query_sharded_expand_total",
    "query_similar_device_total",
    "query_similar_quantized_total",
    "query_similar_sharded_total",
    # quantized vector index (ops/ivf.py, storage/vecstore.py)
    "vector_index_builds_total",
    "vector_index_bytes",
    "vector_quantized_searches_total",
    # change streams (cdc/changelog.py)
    "dgraph_cdc_appended_total",
    "dgraph_cdc_delivered_total",
    "dgraph_cdc_heartbeats_total",
    "dgraph_cdc_tail_entries",
    "dgraph_cdc_truncated_total",
    # distributed ingest (ingest/distributed.py)
    "dgraph_ingest_mapped_total",
    "dgraph_ingest_reduced_total",
    "dgraph_ingest_shuffled_bytes_total",
    # cluster (cluster/transport.py, cluster/service.py apply path)
    "dgraph_raft_apply_lag",
    "raft_send_drops",
    # WAL durability (storage/wal.py fsync sites)
    "dgraph_wal_fsync_seconds",
    # alerting / incident flight recorder (utils/watchdog.py,
    # utils/alerts.py)
    "dgraph_alerts_firing",
    "dgraph_incidents_total",
    "dgraph_watchdog_ticks_total",
    # live tablet moves / rebalancer (cluster/service.py ZeroServer)
    "dgraph_move_catchup_lag",
    "dgraph_move_duration_ms",
    "dgraph_move_streamed_bytes_total",
    "dgraph_tablet_moves_total",
    # cross-cluster async replication (cluster/replication.py)
    "dgraph_repl_lag_entries",
    "dgraph_repl_promote_rto_ms",
    "dgraph_repl_streamed_bytes_total",
    # read scale-out serving tier (engine/result_cache.py,
    # cluster/service.py learner/follower reads, server/qos.py)
    "dgraph_learner_lag",
    "dgraph_result_cache_entries",
    "dgraph_result_cache_hits_total",
    "dgraph_result_cache_invalidations_total",
    "dgraph_result_cache_misses_total",
    "dgraph_stale_reads_total",
    "dgraph_tenant_shed_total",
    # network fault plane (utils/netfault.py)
    "dgraph_net_fault_delays_total",
    "dgraph_net_fault_drops_total",
    "dgraph_net_fault_dups_total",
    "dgraph_net_fault_rules",
    # process gauges (utils/metrics.py collect_memory_gauges /
    # collect_runtime_gauges)
    "memory_inuse_bytes",
    "memory_proc_bytes",
    "process_gc_collections",
    "process_gc_objects",
    "process_open_fds",
    "process_threads",
    "process_uptime_seconds",
)


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    return name, tuple(sorted((labels or {}).items()))


def inc_counter(name: str, value: float = 1, labels: dict | None = None):
    k = _key(name, labels)
    with _LOCK:
        _COUNTERS[k] = _COUNTERS.get(k, 0) + value


def set_gauge(name: str, value: float, labels: dict | None = None):
    with _LOCK:
        _GAUGES[_key(name, labels)] = value


def get_counter(name: str, labels: dict | None = None) -> float:
    """One counter's current value (0 when never incremented) — for
    derived stats like the result cache's hit rate."""
    with _LOCK:
        return _COUNTERS.get(_key(name, labels), 0.0)


def observe(name: str, value_ms: float, labels: dict | None = None):
    """One histogram observation. The value's unit is milliseconds
    for default-bucket metrics; BUCKETS_BY_NAME entries define their
    own unit (the name says which, e.g. *_seconds)."""
    k = _key(name, labels)
    edges = buckets_for(name)
    with _LOCK:
        h = _HISTOGRAMS.get(k)
        if h is None:
            h = [0] * (len(edges) + 1)
            _HISTOGRAMS[k] = h
        h[bisect_right(edges, value_ms)] += 1
        _HISTO_SUM[k] = _HISTO_SUM.get(k, 0) + value_ms


def reset():
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTOGRAMS.clear()
        _HISTO_SUM.clear()


def snapshot() -> dict:
    with _LOCK:
        return {
            "counters": {_fmt_key(k): v for k, v in _COUNTERS.items()},
            "gauges": {_fmt_key(k): v for k, v in _GAUGES.items()},
        }


def histograms_snapshot() -> dict:
    """Histogram state keyed by formatted series name: bucket counts
    (aligned to BUCKETS + one +Inf tail) and the running sum. The
    machine-readable side of render_prometheus — /debug/stats carries
    it so dgtop computes rate/percentile deltas without scraping and
    re-parsing the text exposition."""
    with _LOCK:
        return {_fmt_key(k): {"buckets": list(h),
                              "sum": _HISTO_SUM.get(k, 0.0),
                              "le": list(buckets_for(k[0]))}
                for k, h in _HISTOGRAMS.items()}


def _escape_label(v) -> str:
    """Prometheus text-format 0.0.4 label-value escaping: backslash,
    double-quote and newline must be escaped or the emitted series is
    malformed (a bare quote in a value ends the label early)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_key(k: tuple[str, tuple]) -> str:
    name, labels = k
    if not labels:
        return name
    inner = ",".join(f'{lk}="{_escape_label(lv)}"' for lk, lv in labels)
    return f"{name}{{{inner}}}"


def gauges_snapshot() -> dict[str, float]:
    """Gauge state keyed by formatted series name — /debug/stats
    carries it so dgtop's per-node RSS/thread columns (and any other
    collector) read the process gauges without scraping and re-parsing
    the text exposition."""
    with _LOCK:
        return {_fmt_key(k): v for k, v in _GAUGES.items()}


def counters_snapshot() -> dict[str, float]:
    """Counter state keyed by formatted series name — the 'before'
    half of a per-request profile diff (server/http.py debug=true)."""
    with _LOCK:
        return {_fmt_key(k): v for k, v in _COUNTERS.items()}


def counters_delta(before: dict[str, float]) -> dict[str, float]:
    """Non-zero counter movement since `before` (a counters_snapshot):
    the per-request tier-routing profile — columnar hits, device ops,
    postings fallbacks, cache evictions — as a metrics diff instead of
    bespoke plumbing through the executor."""
    out: dict[str, float] = {}
    for k, v in counters_snapshot().items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# Linux procfs probe, evaluated once: the /proc/self sources below
# are Linux-only, and a gauge plane must DEGRADE on macOS / locked-
# down sandboxes (gauges simply absent) — never raise out of a
# scrape. The per-call try/excepts stay as a second belt: a probe
# that passed at import can still fail later (fd limits, seccomp).
import os as _os_mod  # noqa: E402

_PROC_SELF_OK = _os_mod.path.isdir("/proc/self")


def collect_memory_gauges():
    """Process memory gauges (ref x/metrics.go MemoryInUse/MemoryProc:
    the reference samples Go runtime + proc stats into gauges). Reads
    /proc/self/statm — free on Linux; silently skipped elsewhere."""
    if not _PROC_SELF_OK:
        return
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        page = _os_mod.sysconf("SC_PAGE_SIZE")
        set_gauge("memory_proc_bytes", int(parts[0]) * page)   # vsize
        set_gauge("memory_inuse_bytes", int(parts[1]) * page)  # rss
    except (OSError, ValueError, IndexError):
        pass


# process start, for the uptime gauge: monotonic on purpose — an NTP
# step must not make a node's uptime jump in a scrape series
import time as _time_mod  # noqa: E402

_STARTED_AT_MONO = _time_mod.monotonic()


def collect_runtime_gauges():
    """Process runtime gauges next to the memory ones (ref
    x/metrics.go sampling Go runtime stats: goroutines, GC cycles):
    open fds (a leaking transport shows here first), live threads, GC
    generation object counts + cumulative collections, and uptime.
    Cheap enough to run on every scrape/stats poll."""
    import gc

    set_gauge("process_threads", threading.active_count())
    set_gauge("process_uptime_seconds",
              round(_time_mod.monotonic() - _STARTED_AT_MONO, 3))
    for gen, count in enumerate(gc.get_count()):
        set_gauge("process_gc_objects", count,
                  labels={"gen": str(gen)})
    for gen, st in enumerate(gc.get_stats()):
        set_gauge("process_gc_collections", st.get("collections", 0),
                  labels={"gen": str(gen)})
    if not _PROC_SELF_OK:
        return  # non-Linux: no cheap fd count — gauge stays absent
    try:
        set_gauge("process_open_fds",
                  len(_os_mod.listdir("/proc/self/fd")))
    except OSError:
        pass  # probe raced a sandbox tightening; degrade, don't raise


def collect_process_gauges():
    """Memory + runtime gauges in one call — what the /debug/stats
    handlers refresh so a poll always reads current values."""
    collect_memory_gauges()
    collect_runtime_gauges()


# extra exposition renderers: other always-on stat planes (the
# observed-cost store, utils/coststore.py) register a zero-arg
# callable returning pre-formatted exposition text ("" when empty);
# render_prometheus appends each so every registered plane rides the
# one /debug/prometheus_metrics endpoint
_RENDERERS: list = []


def register_renderer(fn) -> None:
    if fn not in _RENDERERS:
        _RENDERERS.append(fn)


def render_prometheus() -> str:
    """Prometheus text exposition format 0.0.4."""
    collect_memory_gauges()
    collect_runtime_gauges()
    lines: list[str] = []
    typed: set[str] = set()  # one TYPE line per metric name

    def _type_line(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    with _LOCK:
        for k, v in sorted(_COUNTERS.items()):
            _type_line(k[0], "counter")
            lines.append(f"{_fmt_key(k)} {v}")
        for k, v in sorted(_GAUGES.items()):
            _type_line(k[0], "gauge")
            lines.append(f"{_fmt_key(k)} {v}")
        for k, h in sorted(_HISTOGRAMS.items()):
            name, labels = k
            _type_line(name, "histogram")
            cum = 0
            for i, b in enumerate(buckets_for(name)):
                cum += h[i]
                lb = dict(labels)
                lb["le"] = str(b)
                lines.append(f"{_fmt_key((name + '_bucket', tuple(sorted(lb.items()))))} {cum}")
            cum += h[-1]
            lb = dict(labels)
            lb["le"] = "+Inf"
            lines.append(f"{_fmt_key((name + '_bucket', tuple(sorted(lb.items()))))} {cum}")
            lines.append(f"{_fmt_key((name + '_count', labels))} {cum}")
            lines.append(f"{_fmt_key((name + '_sum', labels))} "
                         f"{_HISTO_SUM.get(k, 0)}")
    for fn in list(_RENDERERS):
        try:
            extra = fn()
        except Exception:
            continue
        if extra:
            lines.append(extra.rstrip("\n"))
    return "\n".join(lines) + "\n"
