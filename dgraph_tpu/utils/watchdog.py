"""Per-node watchdog: alert evaluation tick + incident flight recorder.

One daemon thread per process (tick ~1 s, DGRAPH_TPU_WATCHDOG_TICK_S)
drives utils/alerts.py's AlertManager over two inputs:

  - the request log's completion stream (SLO burn-rate windows; wired
    through reqlog.add_observer at start())
  - a signals dict assembled each tick: metric-derived signals
    computed HERE (WAL fsync p99, shed rate, result-cache hit
    fraction, tile-cache thrash, DR standby lag) plus whatever the
    hosting server registered via register_signals (raft apply lag,
    silent peers, CDC subscriber lag, stuck-move age).

On any rule's ok->firing transition the flight recorder captures an
incident bundle — the artifact set dgbench's evidence phase collects,
but triggered automatically at the moment of damage, BEFORE the
bounded rings evict it: metrics+gauges snapshot, the request ring
(slowest entries carry trace ids), the span ring's recent traces, a
2 s pprof profile, planner/plan-cache state (context providers), and
the active netfault rules. Bundles live in a bounded on-disk ring
(default 8, oldest evicted first) that survives process restarts (the
recorder re-scans its directory on boot).

Surfaces: /debug/alerts + /debug/incidents on both HTTP listeners,
{"op": "alerts"} / {"op": "incidents"} on the cluster wire,
dgraph_alerts_firing{rule} in Prometheus, the dgtop ALERTS panel, and
tools/dgalert.py. The module-level singleton keeps all of them
serving (empty-but-valid) even when no watchdog thread was started —
library embeddings and unit tests pay nothing.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Callable, Optional

from dgraph_tpu.utils import alerts as alerts_mod
from dgraph_tpu.utils import metrics

_BUNDLE_FILES = ("manifest.json", "metrics.json", "requests.json",
                 "traces.json", "pprof.json", "netfault.json",
                 "context.json")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class IncidentRecorder:
    """Bounded on-disk ring of incident bundles.

    Each bundle is one directory `inc-<seq>-<rule>` under `root`;
    `max_bundles` newest are kept, oldest evicted first. The seq
    counter resumes past existing bundles on boot, so the ring (and
    its eviction order) survives process restarts."""

    def __init__(self, root: str, max_bundles: int = 8):
        self.root = root
        self.max_bundles = max(1, int(max_bundles))
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self._seq = 1 + max(
            [self._seq_of(d) for d in self._scan()] or [0])

    def _scan(self) -> list[str]:
        try:
            return sorted(d for d in os.listdir(self.root)
                          if d.startswith("inc-"))
        except OSError:
            return []

    @staticmethod
    def _seq_of(dirname: str) -> int:
        try:
            return int(dirname.split("-")[1])
        except (IndexError, ValueError):
            return 0

    def list(self) -> list[dict]:
        """Manifests of every bundle on disk, oldest first."""
        out = []
        for d in sorted(self._scan(), key=self._seq_of):
            try:
                with open(os.path.join(self.root, d,
                                       "manifest.json")) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                m = {}
            m["id"] = d
            out.append(m)
        return out

    def read(self, bundle_id: str) -> dict:
        """One bundle's full contents (JSON files inlined)."""
        base = os.path.join(self.root, os.path.basename(bundle_id))
        if not os.path.isdir(base):
            raise KeyError(f"no incident bundle {bundle_id!r}")
        out: dict = {"id": os.path.basename(bundle_id)}
        for fn in _BUNDLE_FILES:
            p = os.path.join(base, fn)
            if not os.path.exists(p):
                continue
            try:
                with open(p) as f:
                    out[fn.rsplit(".", 1)[0]] = json.load(f)
            except (OSError, ValueError) as e:
                out[fn.rsplit(".", 1)[0]] = {"unreadable": str(e)}
        return out

    def capture(self, event: dict, node: str,
                context_providers: dict[str, Callable[[], dict]],
                pprof_s: float = 2.0) -> str:
        """Write one bundle; returns its id. Runs on the capture
        thread — the pprof window blocks HERE, never the tick."""
        from dgraph_tpu.utils import failpoint, netfault, pprof, \
            reqlog, tracing
        # chaos seam: delay/fail a capture mid-incident (a full disk
        # at the worst moment must not take the evaluator down)
        failpoint.fire("watchdog.capture")
        with self._lock:
            seq = self._seq
            self._seq += 1
        rule = "".join(c if c.isalnum() or c in "_." else "_"
                       for c in str(event.get("rule", "rule")))
        bid = f"inc-{seq:06d}-{rule}"
        tmp = os.path.join(self.root, "." + bid)
        os.makedirs(tmp, exist_ok=True)

        def _dump(fn: str, obj) -> None:
            with open(os.path.join(tmp, fn), "w") as f:
                json.dump(obj, f, default=str)

        metrics.collect_process_gauges()
        _dump("metrics.json",
              {"counters": metrics.counters_snapshot(),
               "gauges": metrics.gauges_snapshot(),
               "histograms": metrics.histograms_snapshot()})
        _dump("requests.json", reqlog.snapshot())
        spans = tracing.recent_spans(512)
        _dump("traces.json",
              {"spans": spans,
               "trace_ids": sorted({s.get("trace_id") for s in spans
                                    if s.get("trace_id")})})
        _dump("netfault.json", {"rules": netfault.rules()})
        ctx = {}
        for name, fn in context_providers.items():
            try:
                ctx[name] = fn()
            except Exception as e:  # noqa: BLE001 — a provider bug  # dglint: disable=DG07 (capture thread; no request context)
                ctx[name] = {"error": str(e)}  # can't lose the bundle
        _dump("context.json", ctx)
        try:
            prof = pprof.collect(seconds=pprof_s) \
                .to_payload("collapsed")
        except RuntimeError as e:
            # another collection in flight: record why, keep bundle
            prof = {"error": str(e)}
        _dump("pprof.json", prof)
        _dump("manifest.json", {
            "rule": event.get("rule"), "series": event.get("series"),
            "value": event.get("value"),
            "severity": event.get("severity"),
            "node": node, "seq": seq,
            "captured_at": event.get("ts"),
            "files": list(_BUNDLE_FILES)})
        final = os.path.join(self.root, bid)
        os.replace(tmp, final)  # readers never see a half bundle
        self._evict()
        return bid

    def _evict(self) -> None:
        with self._lock:
            dirs = sorted(self._scan(), key=self._seq_of)
            while len(dirs) > self.max_bundles:
                victim = dirs.pop(0)  # oldest-first
                shutil.rmtree(os.path.join(self.root, victim),
                              ignore_errors=True)


class Watchdog:
    """The per-process evaluator. Construct via ensure_started()."""

    # dglint: guarded-by=_signal_providers:atomic,_context_providers:atomic,node:write-once
    # (provider registries are copy-on-write: register_* rebinds a
    # fresh dict, readers snapshot the reference — never mutated in
    # place under an iterating tick/capture thread; node is set once
    # before the loop/capture threads exist)

    def __init__(self, tick_s: float = 1.0,
                 incident_dir: Optional[str] = None,
                 max_bundles: int = 8,
                 manager: Optional[alerts_mod.AlertManager] = None):
        self.tick_s = float(tick_s)
        self.manager = manager or _manager()
        self.recorder = IncidentRecorder(
            incident_dir, max_bundles) if incident_dir else None
        self.node = ""
        self._signal_providers: dict[str, Callable[[], dict]] = {}
        self._context_providers: dict[str, Callable[[], dict]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # None until the first tick: rates need a baseline — deltas
        # against an empty dict would read the process's LIFETIME
        # counters as one tick's worth and false-fire every rate rule
        self._prev_counters: Optional[dict[str, float]] = None
        self._prev_fsync: Optional[dict] = None
        self._prev_mono = time.monotonic()
        self._capture_cooldown_s = _env_f(
            "DGRAPH_TPU_INCIDENT_COOLDOWN_S", 60.0)
        self._pprof_s = _env_f("DGRAPH_TPU_INCIDENT_PPROF_S", 2.0)
        self._last_capture: dict[str, float] = {}  # series -> mono
        self._capturing = threading.Lock()

    # ---------------------------------------------------- registration

    def register_signals(self, name: str,
                         fn: Callable[[], dict]) -> None:
        """fn() -> partial signals dict, merged into each tick (the
        hosting AlphaServer/ZeroServer contributes raft/CDC/move
        signals this module must not compute itself)."""
        self._signal_providers = {**self._signal_providers,
                                  name: fn}

    def register_context(self, name: str,
                         fn: Callable[[], dict]) -> None:
        """fn() -> one section of the incident bundle's context.json
        (planner/plan-cache state, zero's move ledger, ...)."""
        self._context_providers = {**self._context_providers,
                                   name: fn}

    # ----------------------------------------------------------- tick

    def start(self, node: str = "") -> None:
        if self._thread is not None:
            return
        self.node = node or self.node
        from dgraph_tpu.utils import reqlog
        reqlog.add_observer(self.manager.observe_request)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"watchdog-{self.node or 'node'}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        from dgraph_tpu.utils import reqlog
        reqlog.remove_observer(self.manager.observe_request)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the watchdog must  # dglint: disable=DG07 (daemon loop; no request context flows here)
                pass  # outlive any one bad tick/provider

    def tick(self) -> list[dict]:
        """One evaluation: assemble signals, run the rules, export
        the firing gauge, trigger captures on ok->firing edges.
        Public for tests and the overhead gate."""
        signals = self.collect_signals()
        transitions = self.manager.evaluate(signals)
        metrics.inc_counter("dgraph_watchdog_ticks_total")
        # one gauge per RULE (not per series: label cardinality is
        # API) — count of firing series under that rule
        per_rule: dict[str, int] = {r.name: 0
                                    for r in self.manager.rules}
        for f in self.manager.firing():
            per_rule[f["rule"]] = per_rule.get(f["rule"], 0) + 1
        for rule, n in per_rule.items():
            metrics.set_gauge("dgraph_alerts_firing", n,
                              labels={"rule": rule})
        for ev in transitions:
            if ev["state"] == "firing":
                self._maybe_capture(ev)
        return transitions

    # -------------------------------------------------------- signals

    def collect_signals(self) -> dict:
        now = time.monotonic()
        dt = max(1e-3, now - self._prev_mono)
        self._prev_mono = now
        cur = metrics.counters_snapshot()
        prev, self._prev_counters = self._prev_counters, cur
        if prev is None:
            prev = cur  # baseline tick: every rate reads 0

        def rate(prefix: str) -> float:
            d = 0.0
            for k, v in cur.items():
                if k.startswith(prefix):
                    d += v - prev.get(k, 0.0)
            return max(0.0, d) / dt

        signals = {
            "sheds_per_s": rate("dgraph_queries_shed_total")
            + rate("dgraph_tenant_shed_total"),
            "tile_evictions_per_s": rate("device_cache_evictions"),
        }
        # result-cache hit fraction over the tick's lookups (hit
        # collapse needs volume context: an idle cache is not a
        # collapsed one)
        hits = rate("dgraph_result_cache_hits_total") * dt
        misses = rate("dgraph_result_cache_misses_total") * dt
        if hits + misses >= _env_f(
                "DGRAPH_TPU_ALERT_CACHE_MIN_LOOKUPS", 100.0):
            signals["result_cache_hit_frac"] = \
                hits / (hits + misses)
        # DR standby lag: max over the per-predicate gauge series
        lags = [v for k, v in metrics.gauges_snapshot().items()
                if k.startswith("dgraph_repl_lag_entries")]
        if lags:
            signals["dr_lag_entries"] = max(lags)
        # WAL fsync p99 from the histogram's tick delta
        p99 = self._fsync_p99()
        if p99 is not None:
            signals["wal_fsync_p99_s"] = p99
        for name, fn in self._signal_providers.items():
            try:
                got = fn()
            except Exception:  # noqa: BLE001 — a provider bug must  # dglint: disable=DG07 (watchdog tick; no request context)
                continue  # not kill the tick
            if got:
                signals.update(got)
        return signals

    def _fsync_p99(self) -> Optional[float]:
        snap = metrics.histograms_snapshot()
        # merge every dgraph_wal_fsync_seconds series (labels differ
        # per wal file) into one bucket vector
        merged: Optional[list[float]] = None
        edges: list[float] = []
        for k, h in snap.items():
            if not k.startswith("dgraph_wal_fsync_seconds"):
                continue
            edges = h["le"]
            if merged is None:
                merged = [0.0] * len(h["buckets"])
            for i, c in enumerate(h["buckets"]):
                merged[i] += c
        if merged is None:
            self._prev_fsync = None
            return None
        prev, self._prev_fsync = self._prev_fsync, \
            {"b": list(merged)}
        if prev is None or len(prev["b"]) != len(merged):
            return None  # baseline tick: lifetime counts are not a
            # tick window
        delta = [c - p for c, p in zip(merged, prev["b"])]
        total = sum(delta)
        if total < _env_f("DGRAPH_TPU_ALERT_FSYNC_MIN_OBS", 5.0):
            return None  # too few fsyncs this tick to judge a p99
        want = 0.99 * total
        cum = 0.0
        for i, c in enumerate(delta):
            cum += c
            if cum >= want:
                return edges[i] if i < len(edges) else edges[-1] * 2
        return edges[-1] * 2

    # -------------------------------------------------------- capture

    def _maybe_capture(self, event: dict) -> None:
        if self.recorder is None:
            return
        series = event.get("series", "")
        now = time.monotonic()
        last = self._last_capture.get(series)
        if last is not None \
                and now - last < self._capture_cooldown_s:
            return  # per-series cooldown: a flapping rule must not
            # churn the whole ring
        self._last_capture[series] = now
        threading.Thread(target=self._capture, args=(event,),
                         daemon=True,
                         name="watchdog-capture").start()

    def _capture(self, event: dict) -> None:
        with self._capturing:  # one bundle at a time (pprof is
            # process-wide anyway); a burst of transitions queues
            try:
                self.recorder.capture(
                    event, self.node, self._context_providers,
                    pprof_s=self._pprof_s)
                metrics.inc_counter("dgraph_incidents_total")
            except Exception:  # noqa: BLE001 — a full disk must not  # dglint: disable=DG07 (capture thread; no request context)
                pass  # take the watchdog down with it


# ------------------------------------------------------------ process
# One watchdog (and one AlertManager) per process: a deployed node is
# one process, and every surface (wire op, both HTTP listeners,
# Prometheus, dgtop, dgalert) reads the same state. The manager
# exists even when no thread was started, so surfaces stay valid
# (rule catalog + empty firing set) in library embeddings and tests.

_LOCK = threading.Lock()
_WATCHDOG: Optional[Watchdog] = None
_MANAGER: Optional[alerts_mod.AlertManager] = None


def _manager() -> alerts_mod.AlertManager:
    global _MANAGER
    with _LOCK:
        if _MANAGER is None:
            _MANAGER = alerts_mod.AlertManager()
        return _MANAGER


def get() -> Optional[Watchdog]:
    return _WATCHDOG


def ensure_started(tick_s: Optional[float] = None,
                   incident_dir: Optional[str] = None,
                   node: str = "",
                   max_bundles: Optional[int] = None) -> Watchdog:
    """Start (or return) the process watchdog. Idempotent; the first
    caller's configuration wins. Env: DGRAPH_TPU_WATCHDOG_TICK_S,
    DGRAPH_TPU_INCIDENT_MAX."""
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None:
            return _WATCHDOG
    wd = Watchdog(
        tick_s=tick_s if tick_s is not None
        else _env_f("DGRAPH_TPU_WATCHDOG_TICK_S", 1.0),
        incident_dir=incident_dir,
        max_bundles=int(max_bundles if max_bundles is not None
                        else _env_f("DGRAPH_TPU_INCIDENT_MAX", 8)))
    with _LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = wd
        wd = _WATCHDOG
    wd.start(node=node)
    return wd


def stop() -> None:
    """Stop and forget the process watchdog (tests; also resets the
    shared manager so rule state never leaks across tests)."""
    global _WATCHDOG, _MANAGER
    with _LOCK:
        wd, _WATCHDOG = _WATCHDOG, None
        _MANAGER = None
    if wd is not None:
        wd.stop()


def alerts_payload() -> dict:
    """The /debug/alerts + {"op":"alerts"} body. Always valid — a
    node without a started watchdog reports its rule catalog and an
    empty firing set."""
    out = _manager().payload()
    wd = _WATCHDOG
    out["watchdog"] = {
        "running": wd is not None and wd._thread is not None,
        "tick_s": wd.tick_s if wd is not None else None,
        "incident_dir": wd.recorder.root
        if wd is not None and wd.recorder is not None else None}
    return out


def incidents_payload(limit: int = 16,
                      bundle: Optional[str] = None) -> dict:
    """The /debug/incidents + {"op":"incidents"} body: the bundle
    ring's manifests (newest last), or one full bundle by id."""
    wd = _WATCHDOG
    if wd is None or wd.recorder is None:
        return {"incidents": [], "enabled": False}
    if bundle:
        return {"enabled": True, "bundle": wd.recorder.read(bundle)}
    items = wd.recorder.list()
    return {"enabled": True, "incidents": items[-int(limit):]}


def firing_summary() -> list[dict]:
    """Compact firing set for the heat-report piggyback (alphas ship
    this to zero on their existing reports; [] rides free)."""
    return _manager().firing()


def ack(series: str) -> bool:
    return _manager().ack(series)


def silence(series: str, ttl_s: float) -> None:
    _manager().silence(series, ttl_s)
