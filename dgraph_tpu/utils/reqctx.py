"""Request context: end-to-end deadlines, cancellation, trace ids.

The reference threads a context.Context from the gRPC/HTTP edge through
edgraph -> query -> worker RPCs, so a client deadline cancels work
everywhere it runs (edgraph/server.go attaches the request ctx;
worker/task.go ProcessTaskOverNetwork forwards it on the wire). This
module is that capability as an explicit object: a `RequestContext`
carries an absolute deadline (monotonic clock), a cancellation flag and
a trace id, and is created once at the serving edge
(`X-Dgraph-Deadline-Ms` header / the gRPC timeout field), threaded
through GraphDB.query/mutate/alter into the executor (checked at
per-block and per-level boundaries), and propagated on the wire to
cross-group federated tasks as a remaining-budget `deadline_ms` so
remote workers inherit the budget with a small skew allowance.

Error mapping at the edges:
  DeadlineExceeded -> HTTP 408 / gRPC DEADLINE_EXCEEDED  (retryable)
  Cancelled        -> HTTP 499 / gRPC CANCELLED
  Overloaded       -> HTTP 429 / gRPC RESOURCE_EXHAUSTED (retryable)
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

# extra budget a remote worker grants on top of the propagated
# remaining_ms: the coordinator's clock read and the RPC hop are not
# free, and a worker that times out a hair before its coordinator
# produces a confusing double error (ref x/x.go GetOutOfOrderTimestamp
# style skew allowances)
PROPAGATION_SKEW_S = 0.05


class RequestAborted(Exception):
    """Base for every give-up-now condition a RequestContext signals."""


class DeadlineExceeded(RequestAborted):
    """The request's deadline passed; work must stop mid-flight."""


class Cancelled(RequestAborted):
    """The request was explicitly cancelled (client gone, admin)."""


class Overloaded(RequestAborted):
    """Admission control shed this request: the server is saturated.

    Retryable by contract (the reference answers RESOURCE_EXHAUSTED
    from its pending-query throttle, edgraph/server.go rateLimiter)."""


class RequestContext:
    """Deadline + cancellation + trace id for one request.

    Cheap to check (`expired` is one monotonic read) so the executor
    can consult it at every traversal level. Thread-safe: the HTTP
    handler thread owns it, but /admin/cancel may cancel from another
    thread.
    """

    __slots__ = ("deadline", "trace_id", "parent_span", "tenant",
                 "_cancel")

    def __init__(self, deadline: Optional[float] = None,
                 trace_id: str = "", parent_span: str = "",
                 tenant: str = ""):
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        # span id of the CALLER's span on the other side of the wire
        # (W3C traceparent parent-id / the `parent_span` RPC field):
        # the serving edge binds it so this node's spans link into the
        # originating trace (utils/tracing.bind_request)
        self.parent_span = parent_span or ""
        # ACL namespace / tenant the request bills against (the QoS
        # plane's token-bucket key and the reqlog `tenant` field);
        # empty = untagged, accounted as "default" at admission
        self.tenant = tenant or ""
        self._cancel = threading.Event()

    # -------------------------------------------------- constructors

    @classmethod
    def with_timeout(cls, seconds: Optional[float],
                     trace_id: str = "",
                     parent_span: str = "",
                     tenant: str = "") -> "RequestContext":
        """Context expiring `seconds` from now (None = no deadline)."""
        dl = None if seconds is None else time.monotonic() + max(
            0.0, float(seconds))
        return cls(deadline=dl, trace_id=trace_id,
                   parent_span=parent_span, tenant=tenant)

    @classmethod
    def from_deadline_ms(cls, ms, trace_id: str = "",
                         skew_s: float = 0.0,
                         parent_span: str = "",
                         tenant: str = "") -> "RequestContext":
        """Context from a wire-propagated remaining budget in ms (the
        `deadline_ms` RPC field / `X-Dgraph-Deadline-Ms` header).
        `skew_s` widens the budget for workers inheriting it over the
        network (PROPAGATION_SKEW_S)."""
        return cls.with_timeout(int(ms) / 1000.0 + skew_s,
                                trace_id=trace_id,
                                parent_span=parent_span,
                                tenant=tenant)

    @classmethod
    def background(cls, trace_id: str = "",
                   parent_span: str = "",
                   tenant: str = "") -> "RequestContext":
        """No deadline, cancellable — internal/maintenance work."""
        return cls(deadline=None, trace_id=trace_id,
                   parent_span=parent_span, tenant=tenant)

    # ------------------------------------------------------- queries

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    @property
    def expired(self) -> bool:
        return self.deadline is not None \
            and time.monotonic() >= self.deadline

    def remaining(self) -> Optional[float]:
        """Seconds left (never negative), or None without a deadline."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    def remaining_ms(self) -> Optional[int]:
        rem = self.remaining()
        return None if rem is None else int(rem * 1000)

    # ------------------------------------------------------- control

    def cancel(self):
        self._cancel.set()

    def check(self, where: str = ""):
        """Raise if this request must stop. Called at executor
        block/level boundaries, before RPC fan-outs, and between
        mutation phases — the cooperative-cancellation points the
        reference gets from ctx.Err() checks."""
        if self._cancel.is_set():
            raise Cancelled(
                "request cancelled" + (f" at {where}" if where else "")
                + f" (trace {self.trace_id})")
        if self.expired:
            raise DeadlineExceeded(
                "deadline exceeded" + (f" at {where}" if where else "")
                + f" (trace {self.trace_id})")
