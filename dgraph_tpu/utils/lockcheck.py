"""Runtime lock-order witness: a mini-TSan for the test suite.

dglint DG12 proves lock-order acyclicity for every acquisition it can
attribute statically; callbacks, dynamic dispatch and data-dependent
paths stay invisible to it. This module is the dynamic complement:
under tests (opt-in via the `lockcheck` pytest marker), every lock the
project creates is witness-wrapped, acquisitions maintain a
thread-local held stack, and the FIRST time lock B is taken while A is
held the edge A -> B is recorded with its acquisition stack. A later
acquisition of A while B is held is an inversion: both stacks — the
recorded first-seen one and the current one — are attached to the
violation, and the owning test fails.

Design constraints (mirrors Go's lock-rank witness, not a full TSan):

  - thread-local acquisition stacks via `threading.local()` —
    deliberately contextvar-free, since locks are a thread property
    and an executor-hopping task must NOT drag its held-set along;
  - lock identity = construction site (`file:line`), so every
    instance of a class shares one rank and cross-instance inversions
    of the same lock pair are caught (same granularity as DG12's
    `Class.attr` identity);
  - stacks are captured ONLY when an edge is first seen or violated
    (rare); the per-acquisition cost is a list walk of the held stack
    plus one dict probe per held lock — the overhead budget on the
    lock-heavy batcher workload is < 3% (tests/test_lockcheck.py
    enforces it, decomposed like the tools/check.sh stats gate);
  - `enable()` patches `threading.Lock` (the factory) so locks
    created AFTER enable are wrapped — pre-existing locks (pytest's
    own, the interpreter's) stay untouched — and hooks the project's
    RWLock so reader/writer acquisition shares the same order table.
    Writer preference inside RWLock lives on an internal Condition
    and is invisible here by design: an RWLock is ONE name in the
    order table, whatever mode it was taken in.

Violations are recorded always and raised in the acquiring thread
only when `strict=True` (product threads swallowing an exception must
not hide the report — the conftest fixture fails the test off the
recorded list either way).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Optional

__all__ = [
    "LockOrderViolation", "enable", "disable", "reset", "enabled",
    "violations", "stats", "wrap_lock", "held_locks",
]


class LockOrderViolation(AssertionError):
    """Locks acquired in both orders: `acquiring` was taken while
    `held` was held, but the order `acquiring` -> `held` had been
    established earlier. Both witness stacks attached."""

    def __init__(self, held: str, acquiring: str, first_stack: str,
                 second_stack: str):
        self.edge = (held, acquiring)
        self.first_stack = first_stack   # the earlier acquiring->held
        self.second_stack = second_stack  # now: acquiring under held
        super().__init__(
            f"lock-order inversion: `{acquiring}` acquired while "
            f"holding `{held}`, but the order `{acquiring}` -> "
            f"`{held}` was established earlier\n"
            f"--- first-seen `{acquiring}` -> `{held}` at:\n"
            f"{first_stack}"
            f"--- now `{acquiring}` (holding `{held}`) at:\n"
            f"{second_stack}")


_tls = threading.local()
_table_lock = threading.Lock()  # guards _edges/_violations mutation
_edges: dict[tuple[str, str], str] = {}   # (a, b) -> first-seen stack
_violations: list[LockOrderViolation] = []
_acquires = 0           # total witnessed acquisitions (overhead math)
_enabled = False
_strict = False
_epoch = 0              # bumped by reset(): stale per-thread held
                        # stacks from a previous armed window are
                        # discarded lazily (reset() cannot reach
                        # other threads' TLS)
_orig_lock = None
_rwlock_orig: dict[str, object] = {}

_THIS_FILE = os.path.abspath(__file__)
# witness scope: only locks CONSTRUCTED by project code are wrapped
# (wrapping jax/stdlib internals would both cost overhead and report
# third-party ordering protocols the project does not own)
_PROJECT_ROOT = os.path.dirname(os.path.dirname(_THIS_FILE))


def _held() -> list[str]:
    h = getattr(_tls, "held", None)
    if h is None or getattr(_tls, "epoch", -1) != _epoch:
        # first touch in this thread since the last reset(): drop any
        # phantom entries a prior armed window left behind (a lock
        # acquired while armed but released after disable)
        h = _tls.held = []
        _tls.epoch = _epoch
    return h


def _site_frame():
    """Nearest stack frame outside this module and threading.py."""
    import sys

    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and not fn.endswith("threading.py"):
            return f
        f = f.f_back
    return None


def _site() -> str:
    f = _site_frame()
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _stack() -> str:
    return "".join(traceback.format_stack(limit=14)[:-2])


def _check_acquire(name: str):
    """Order check BEFORE blocking on the real lock: a would-deadlock
    attempt is reported even if it never returns."""
    global _acquires
    _acquires += 1
    held = _held()
    if not held:
        return
    for outer in held:
        if outer == name:
            return  # reentrant/same-rank: never an order edge
    for outer in dict.fromkeys(held):
        edge = (outer, name)
        rev = (name, outer)
        if edge in _edges:
            continue
        with _table_lock:
            if edge in _edges:
                continue
            first_stack = _edges.get(rev)
            if first_stack is not None:
                v = LockOrderViolation(outer, name, first_stack,
                                       _stack())
                _violations.append(v)
                if _strict:
                    raise v
                continue
            _edges[edge] = _stack()


def _push(name: str):
    _held().append(name)


def _pop(name: str):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class _WitnessLock:
    """Duck-compatible stand-in for `threading.Lock()` while the
    witness is enabled. Everything the stdlib expects of a lock
    (Condition's probe-release dance included) delegates to the real
    lock; the order table sees acquire/release."""

    __slots__ = ("_real", "_name")

    def __init__(self, real, name: str):
        self._real = real
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _enabled:
            _check_acquire(self._name)
        got = self._real.acquire(blocking, timeout)
        if got and _enabled:
            _push(self._name)
        return got

    def release(self):
        # pop unconditionally: a lock acquired while armed may be
        # released after disable(); gating on _enabled would leave a
        # phantom held entry in this thread forever (_pop of an
        # un-pushed name is a no-op, so the unarmed case is free)
        _pop(self._name)
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self._name} of {self._real!r}>"


def wrap_lock(lock=None, name: Optional[str] = None) -> _WitnessLock:
    """Explicitly witness-wrap a lock (for locks created before
    enable(), or for naming one by hand in a test)."""
    real = lock if lock is not None else (
        _orig_lock() if _orig_lock is not None else
        threading.Lock())
    return _WitnessLock(real, name or _site())


def _lock_factory():
    f = _site_frame()
    if f is None or not os.path.abspath(
            f.f_code.co_filename).startswith(_PROJECT_ROOT):
        # a lock created by jax/stdlib/test-framework internals:
        # not the project's to rank — hand back a real lock
        return _orig_lock()
    return _WitnessLock(
        _orig_lock(),
        f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}")


# ------------------------------------------------------ RWLock hooks


def _patch_rwlock():
    from dgraph_tpu.utils import rwlock as _rw

    if _rwlock_orig:
        return
    _rwlock_orig.update({
        "__init__": _rw.RWLock.__init__,
        "acquire_read": _rw.RWLock.acquire_read,
        "release_read": _rw.RWLock.release_read,
        "acquire_write": _rw.RWLock.acquire_write,
        "release_write": _rw.RWLock.release_write,
    })

    def init(self, *a, **k):
        _rwlock_orig["__init__"](self, *a, **k)
        self._lc_name = f"rw@{_site()}"

    def _name(self) -> str:
        n = getattr(self, "_lc_name", None)
        if n is None:
            n = self._lc_name = "rw@<pre-enable>"
        return n

    def acquire_read(self):
        if _enabled:
            _check_acquire(_name(self))
        _rwlock_orig["acquire_read"](self)
        if _enabled:
            _push(_name(self))

    def release_read(self):
        _pop(_name(self))  # unconditional: see _WitnessLock.release
        _rwlock_orig["release_read"](self)

    def acquire_write(self):
        if _enabled:
            _check_acquire(_name(self))
        _rwlock_orig["acquire_write"](self)
        if _enabled:
            _push(_name(self))

    def release_write(self):
        _pop(_name(self))  # unconditional: see _WitnessLock.release
        _rwlock_orig["release_write"](self)

    _rw.RWLock.__init__ = init
    _rw.RWLock.acquire_read = acquire_read
    _rw.RWLock.release_read = release_read
    _rw.RWLock.acquire_write = acquire_write
    _rw.RWLock.release_write = release_write


def _unpatch_rwlock():
    from dgraph_tpu.utils import rwlock as _rw

    if not _rwlock_orig:
        return
    _rw.RWLock.__init__ = _rwlock_orig["__init__"]
    _rw.RWLock.acquire_read = _rwlock_orig["acquire_read"]
    _rw.RWLock.release_read = _rwlock_orig["release_read"]
    _rw.RWLock.acquire_write = _rwlock_orig["acquire_write"]
    _rw.RWLock.release_write = _rwlock_orig["release_write"]
    _rwlock_orig.clear()


# --------------------------------------------------------- lifecycle


def enable(strict: bool = False):
    """Arm the witness: locks created from here on are wrapped, the
    order table starts empty. `strict=True` additionally raises the
    violation in the acquiring thread (deterministic unit tests);
    the recorded list is authoritative either way."""
    global _enabled, _strict, _orig_lock

    reset()
    _strict = strict
    if not _enabled:
        _orig_lock = threading.Lock
        threading.Lock = _lock_factory
        _patch_rwlock()
        _enabled = True


def disable() -> list[LockOrderViolation]:
    """Disarm and return the violations recorded while armed.
    Witness-wrapped locks created during the window keep working
    (their hooks become no-ops once disabled)."""
    global _enabled, _orig_lock

    if _enabled:
        threading.Lock = _orig_lock
        _orig_lock = None
        _unpatch_rwlock()
        _enabled = False
    return list(_violations)


def reset():
    global _acquires, _epoch

    with _table_lock:
        _edges.clear()
        _violations.clear()
        _acquires = 0
        _epoch += 1  # invalidates every thread's held stack lazily
    _tls.held = []
    _tls.epoch = _epoch


def enabled() -> bool:
    return _enabled


def held_locks() -> tuple:
    """The calling thread's currently-held witnessed locks, outermost
    first (lock identity = construction site, same as the order
    table). utils/racecheck consumes this as the lockset of each
    attribute access it samples."""
    return tuple(_held())


def violations() -> list[LockOrderViolation]:
    return list(_violations)


def stats() -> dict:
    return {"acquires": _acquires, "edges": len(_edges),
            "violations": len(_violations)}
