"""Structured logging: JSON lines to stderr.

The reference logs through glog with -v levels (x/x.go init,
worker/draft.go event logging); operators scrape those lines. Here
every event is one JSON object — machine-parseable, grep-friendly —
with a process-wide minimum level and no dependencies.

    from dgraph_tpu.utils.logger import log
    log.info("leader_changed", group=1, leader=2, term=7)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


class _Logger:
    def __init__(self):
        self._lock = threading.Lock()
        self.min_level = _LEVELS.get(
            os.environ.get("DGRAPH_TPU_LOG_LEVEL", "info"), 20)
        self.stream = sys.stderr

    def _emit(self, level: str, event: str, fields: dict):
        if _LEVELS[level] < self.min_level:
            return
        # wall clock: log timestamps are user-visible instants
        rec = {"ts": round(time.time(), 3), "level": level,  # dglint: disable=DG06
               "event": event}
        for k, v in fields.items():
            if k not in rec:
                rec[k] = v if isinstance(
                    v, (str, int, float, bool, type(None))) else str(v)
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            try:
                self.stream.write(line + "\n")
                self.stream.flush()
            except (OSError, ValueError):
                pass  # closed stream during shutdown

    def debug(self, event: str, **fields):
        self._emit("debug", event, fields)

    def info(self, event: str, **fields):
        self._emit("info", event, fields)

    def warning(self, event: str, **fields):
        self._emit("warning", event, fields)

    def error(self, event: str, **fields):
        self._emit("error", event, fields)


log = _Logger()
