"""Utilities: key codec, config, metrics, tracing."""
