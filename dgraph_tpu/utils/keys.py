"""Storage key model.

Re-provides the reference's key taxonomy (x/keys.go:113-220: DataKey,
IndexKey, ReverseKey, CountKey, SchemaKey, TypeKey + split keys at
x/keys.go:450) with a canonical sortable binary encoding shared by the
Python store, the WAL, and the C++ storage backend.

Layout (byte-sortable, groups a predicate's keys contiguously like the
reference's Badger layout so tablet moves are range scans):

    [0x00][len(attr):u16BE][attr bytes][kind:u8][suffix]

    kind DATA    0x00  suffix = uid:u64BE
    kind REVERSE 0x01  suffix = uid:u64BE
    kind INDEX   0x02  suffix = token bytes (tokenizer ident prefixed)
    kind COUNT   0x03  suffix = count:u32BE [0x01 if reverse]
    kind SCHEMA  0x04  suffix = empty
    kind TYPE    0x05  suffix = empty (attr = type name)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

DATA = 0x00
REVERSE = 0x01
INDEX = 0x02
COUNT = 0x03
SCHEMA = 0x04
TYPE = 0x05

_KIND_NAMES = {DATA: "data", REVERSE: "reverse", INDEX: "index",
               COUNT: "count", SCHEMA: "schema", TYPE: "type"}


@dataclass(frozen=True)
class Key:
    attr: str
    kind: int
    uid: int = 0
    token: bytes = b""
    count: int = 0
    count_reverse: bool = False

    def pack(self) -> bytes:
        ab = self.attr.encode()
        head = b"\x00" + struct.pack(">H", len(ab)) + ab + bytes([self.kind])
        if self.kind in (DATA, REVERSE):
            return head + struct.pack(">Q", self.uid)
        if self.kind == INDEX:
            return head + self.token
        if self.kind == COUNT:
            return head + struct.pack(">I", self.count) + (
                b"\x01" if self.count_reverse else b"\x00")
        return head

    def __repr__(self):
        kind = _KIND_NAMES.get(self.kind, "?")
        extra = ""
        if self.kind in (DATA, REVERSE):
            extra = f" uid={self.uid:#x}"
        elif self.kind == INDEX:
            extra = f" token={self.token!r}"
        elif self.kind == COUNT:
            extra = f" count={self.count}"
        return f"<Key {kind}:{self.attr}{extra}>"


def data_key(attr: str, uid: int) -> Key:
    return Key(attr, DATA, uid=uid)


def reverse_key(attr: str, uid: int) -> Key:
    return Key(attr, REVERSE, uid=uid)


def index_key(attr: str, token: bytes) -> Key:
    return Key(attr, INDEX, token=token)


def count_key(attr: str, count: int, reverse: bool = False) -> Key:
    return Key(attr, COUNT, count=count, count_reverse=reverse)


def schema_key(attr: str) -> Key:
    return Key(attr, SCHEMA)


def type_key(name: str) -> Key:
    return Key(name, TYPE)


def unpack(raw: bytes) -> Key:
    if raw[0] != 0x00:
        raise ValueError("bad key prefix")
    (alen,) = struct.unpack_from(">H", raw, 1)
    attr = raw[3 : 3 + alen].decode()
    kind = raw[3 + alen]
    suffix = raw[4 + alen :]
    if kind in (DATA, REVERSE):
        (uid,) = struct.unpack(">Q", suffix)
        return Key(attr, kind, uid=uid)
    if kind == INDEX:
        return Key(attr, kind, token=suffix)
    if kind == COUNT:
        (count,) = struct.unpack_from(">I", suffix, 0)
        return Key(attr, kind, count=count, count_reverse=suffix[4] == 1)
    return Key(attr, kind)


def token_bytes(ident: int, token) -> bytes:
    """Index token -> bytes with tokenizer-identifier prefix so different
    tokenizers on one predicate never collide and sortable tokenizers
    keep byte order (ref tok/tok.go identifier bytes; int64 tokens use
    order-preserving offset encoding)."""
    if isinstance(token, int):
        return bytes([ident]) + struct.pack(">Q", token + (1 << 63))
    if isinstance(token, bytes):
        return bytes([ident]) + token
    return bytes([ident]) + str(token).encode()
