"""Sampled attribute-level data-race witness: dglint DG13's dynamic
complement (as utils/lockcheck is DG12's).

The reference Dgraph keeps its Raft/txn/move machinery honest with
`go test -race`; this module restores a slice of that safety net for
the Python port. Under tests (opt-in via the `racecheck` pytest
marker), a registry of project classes gets its `__setattr__` and
`__getattribute__` instrumented: every sampled attribute access
records (object, attr, thread, kind, lockset) where the lockset is the
calling thread's held-lock stack already maintained by
utils/lockcheck. Two accesses to the same (object, attr) from
different threads, at least one a write, with NO common lock and no
witnessed happens-before edge between them, raise `RaceViolation`
carrying both access stacks — the Python rendition of a TSan report.

Design constraints (a lockset sampler in the Eraser lineage, not a
vector-clock TSan):

  - OPT-IN per-class registry (`TARGETS` / `register()`): wholesale
    `__getattribute__` wrapping would tax every test; the registry
    names the concurrency-plane classes the static half (DG13) cares
    most about, with per-class ignore sets for intentional lock-free
    publishes (e.g. CdcPlane.on_invalidate, a write-once observer);
  - reads are only witnessed for attributes some write has touched
    (per-class written-attr set): a read of never-written state — a
    method, a class constant — costs one set probe and no record;
  - locksets come from lockcheck's thread-local held stacks (enable()
    arms lockcheck's lock wrapping if the test did not), so lock
    identity is the same construction-site name DG12/DG13 use; the
    candidate lockset of each (obj, attr, thread, kind) record is the
    INTERSECTION over its accesses (Eraser's refinement), its stack
    the first-seen one — steady-state cost per sampled access is a
    few dict probes, stacks are captured only on first record or
    violation, via a fast manual frame walk (no linecache I/O);
  - happens-before is witnessed coarsely through thread lifecycle:
    `Thread.start()` retires the PARENT's prior records (everything
    the parent did happens-before the child's first step) and
    `Thread.join()` retires the JOINED thread's records (and with
    them any alias from thread-id reuse) — the classic
    construct-then-spawn and join-then-read patterns are not races.
    Queue/Future handoffs between two long-lived threads are NOT
    modeled; state published that way belongs in a per-class ignore
    set or a dglint guarded-by discipline annotation, not silently
    unsampled;
  - only objects CONSTRUCTED while the witness is armed are
    witnessed (the `_born` registry): an older object's locks predate
    lockcheck's factory patch, so its guarded accesses would all show
    empty locksets — unwitnessable state can only false-positive.
    Module-scoped fixtures are therefore invisible by design; a test
    that wants them witnessed constructs them under the marker;
  - constructor writes are suppressed by an init-depth counter (an
    object under construction is thread-confined by definition) but
    still seed the written-attr set so later reads are witnessed;
  - the access table lives behind a raw `_thread.allocate_lock()` so
    the witness's own lock never enters lockcheck's order table or
    any held stack;
  - violations are recorded always and raised in the accessing thread
    only when `strict=True`; each (class, attr) pair reports at most
    once per armed window (a real race fires on every loop iteration
    — one report with both stacks is the signal, a thousand is log
    spam).

Overhead is budgeted, not hoped for: bench_micro.py's
`racecheck_overhead_bench` decomposes per-sampled-access cost ×
sampled-access count on the batcher workload and tools/check.sh gates
the product at < 5% (DGRAPH_TPU_RACECHECK_BUDGET).
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
from typing import Iterable, Optional

from dgraph_tpu.utils import lockcheck

__all__ = [
    "RaceViolation", "TARGETS", "register", "enable", "disable",
    "reset", "enabled", "violations", "stats",
]


class RaceViolation(AssertionError):
    """Two accesses to the same attribute from different threads, at
    least one a write, no common lock, no witnessed happens-before
    edge. Both witness stacks attached."""

    def __init__(self, cls_name: str, attr: str,
                 first_kind: str, first_thread: str, first_locks,
                 first_stack: str,
                 second_kind: str, second_thread: str, second_locks,
                 second_stack: str):
        self.cls_name = cls_name
        self.attr = attr
        self.first = (first_kind, first_thread, first_locks)
        self.second = (second_kind, second_thread, second_locks)
        word = {"r": "read", "w": "write"}
        super().__init__(
            f"data race on `{cls_name}.{attr}`: "
            f"{word[second_kind]} in thread {second_thread!r} holding "
            f"{sorted(second_locks) or '{}'} conflicts with "
            f"{word[first_kind]} in thread {first_thread!r} holding "
            f"{sorted(first_locks) or '{}'} — no common lock, no "
            "happens-before edge\n"
            f"--- first access ({word[first_kind]}, "
            f"{first_thread!r}) at:\n{first_stack}"
            f"--- second access ({word[second_kind]}, "
            f"{second_thread!r}) at:\n{second_stack}")


# Opt-in registry: (module, class, ignored attrs). These are the
# concurrency-plane classes PRs 15-18 grew — the ones whose races cost
# 3-6 review passes each. Ignores are intentional lock-free publishes,
# each mirrored by a dglint guarded-by annotation at the access site.
TARGETS = (
    ("dgraph_tpu.engine.prefetch", "PrefetchPool", ()),
    ("dgraph_tpu.engine.result_cache", "ResultCache", ()),
    ("dgraph_tpu.engine.batcher", "MicroBatcher", ()),
    # on_invalidate: write-once observer wiring (engine attach time),
    # read lock-free by the apply path forever after; cap/raw_cap:
    # init-time config ints the truncation tests poke on live planes
    # (a GIL-atomic rebind the reader is allowed to see late)
    ("dgraph_tpu.cdc.changelog", "CdcPlane",
     ("on_invalidate", "cap", "raw_cap")),
    ("dgraph_tpu.cluster.client", "ClusterClient", ()),
)

_THIS_FILE = os.path.abspath(__file__)

_tls = threading.local()
# raw lock: never wrapped by lockcheck's factory, never in held stacks
_table_lock = _thread.allocate_lock()

# (id(obj), attr) -> {(tid, kind): [lockset, stack|None, epoch, name]}
_accesses: dict = {}
_born: set = set()              # ids constructed while armed
_tepoch: dict[int, int] = {}   # thread ident -> lifecycle epoch
_written: dict[type, set] = {}  # class -> attrs some write touched
_ignored: dict[type, frozenset] = {}
_violations: list[RaceViolation] = []
_reported: set = set()          # (cls_name, attr) dedup
_samples = 0                    # recorded accesses (overhead math)
_probes = 0                     # wrapper entries incl. unsampled reads
_enabled = False
_strict = False
_sample = 1                     # record every Nth witnessed read
_read_tick = 0
_extra: list[tuple] = []        # register()-added targets
_patched: dict = {}             # class -> original methods
_thread_orig: dict = {}
_own_lockcheck = False


def register(cls: type, ignore: Iterable[str] = ()) -> None:
    """Add a class to the witness registry (tests register fixture
    classes; product classes belong in TARGETS). Takes effect at the
    next enable()."""
    _extra.append((cls, tuple(ignore)))


def _fast_stack(limit: int = 12) -> str:
    """Manual frame walk: file:line/function only, no source-line
    lookup — cheap enough to capture inside the table lock."""
    f = sys._getframe(2)
    parts = []
    while f is not None and len(parts) < limit:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE:
            parts.append(f"  {os.path.basename(fn)}:{f.f_lineno} "
                         f"in {f.f_code.co_name}")
        f = f.f_back
    parts.reverse()
    return "\n".join(parts) + "\n"


def _live(rec) -> bool:
    """A record is live while its thread's lifecycle epoch is
    unchanged; start()/join() bumps retire it (happens-before)."""
    return rec[2] == _tepoch.get(rec[4], 0)


def _lockset() -> frozenset:
    """The calling thread's held locks as a frozenset, cached per
    thread on the held tuple (it rarely changes between consecutive
    sampled accesses — the allocation is the steady-state cost)."""
    held = lockcheck.held_locks()
    if getattr(_tls, "lk_key", None) == held:
        return _tls.lk_fs
    fs = frozenset(held)
    _tls.lk_key = held
    _tls.lk_fs = fs
    return fs


def _record(cls: type, obj, attr: str, kind: str):
    global _samples
    if id(obj) not in _born:
        # constructed before arming: its locks are unwrapped (empty
        # locksets), so any record could only be a false positive
        return
    held_fs = _lockset()
    tid = _thread.get_ident()
    key = (id(obj), attr)
    k2 = (tid, kind)
    # Lock-free fast path: this thread already holds a live record for
    # (obj, attr, kind) with the same lockset — nothing to refine, and
    # the conflict scan already ran when the record was created (a
    # later conflicting access creates ITS record under the table
    # lock and scans against ours). Pure GIL-atomic dict reads.
    tbl = _accesses.get(key)
    if tbl is not None:
        rec = tbl.get(k2)
        if rec is not None \
                and (rec[0] is held_fs or rec[0] == held_fs) \
                and rec[2] == _tepoch.get(tid, 0):
            _samples += 1  # stat only: a lost racy increment is fine
            return
    v: Optional[RaceViolation] = None
    with _table_lock:
        if not _enabled:
            return
        _samples += 1
        ep = _tepoch.get(tid, 0)
        tbl = _accesses.get(key)
        if tbl is None:
            tbl = _accesses[key] = {}
        rec = tbl.get(k2)
        if rec is None or not _live(rec):
            rec = tbl[k2] = [held_fs, _fast_stack(), ep,
                             threading.current_thread().name, tid]
        elif held_fs is not rec[0] and held_fs != rec[0]:
            rec[0] &= held_fs  # Eraser refinement: candidate lockset
        dk = (cls.__name__, attr)
        if dk not in _reported and len(tbl) > 1:
            for (otid, okind), other in tbl.items():
                if otid == tid:
                    continue
                if kind != "w" and okind != "w":
                    continue
                if not _live(other):
                    continue
                if other[0] & held_fs:
                    continue
                _reported.add(dk)
                v = RaceViolation(
                    cls.__name__, attr,
                    okind, other[3], other[0],
                    other[1] or "  <stack not captured>\n",
                    kind, threading.current_thread().name,
                    held_fs, _fast_stack())
                _violations.append(v)
                break
    if v is not None and _strict:
        raise v


# ------------------------------------------------------ class patching


def _patch_class(cls: type, ignore: Iterable[str]):
    if cls in _patched:
        return
    ign = _ignored[cls] = frozenset(ignore)
    written = _written.setdefault(cls, set())
    orig_set = cls.__setattr__
    orig_get = cls.__getattribute__
    orig_init = cls.__init__
    _patched[cls] = (orig_set, orig_get, orig_init)

    def rc_setattr(self, name, value):
        if _enabled and name not in ign:
            written.add(name)
            if not getattr(_tls, "init_depth", 0):
                global _probes
                _probes += 1
                _record(cls, self, name, "w")
        orig_set(self, name, value)

    def rc_getattribute(self, name):
        val = orig_get(self, name)
        if _enabled and name in written and name not in ign \
                and not getattr(_tls, "init_depth", 0):
            global _probes, _read_tick
            _probes += 1
            _read_tick += 1  # racy increment: sampling, not counting
            if _read_tick % _sample == 0:
                _record(cls, self, name, "r")
        return val

    def rc_init(self, *a, **k):
        if _enabled:
            _born.add(id(self))  # GIL-atomic set add
        # an object under construction is thread-confined: suppress
        # records (the written-attr set still fills via rc_setattr)
        _tls.init_depth = getattr(_tls, "init_depth", 0) + 1
        try:
            orig_init(self, *a, **k)
        finally:
            _tls.init_depth -= 1

    cls.__setattr__ = rc_setattr
    cls.__getattribute__ = rc_getattribute
    cls.__init__ = rc_init


def _unpatch_classes():
    for cls, (orig_set, orig_get, orig_init) in _patched.items():
        cls.__setattr__ = orig_set
        cls.__getattribute__ = orig_get
        cls.__init__ = orig_init
    _patched.clear()
    _ignored.clear()


def _resolve_targets():
    import importlib

    out = []
    for mod, name, ignore in TARGETS:
        cls = getattr(importlib.import_module(mod), name)
        out.append((cls, ignore))
    out.extend(_extra)
    return out


# ------------------------------------------- thread lifecycle hooks


def _patch_threads():
    if _thread_orig:
        return
    _thread_orig["start"] = threading.Thread.start
    _thread_orig["join"] = threading.Thread.join

    def start(self):
        # everything the parent did happens-before the child's first
        # step: retire the parent's records
        with _table_lock:
            me = _thread.get_ident()
            _tepoch[me] = _tepoch.get(me, 0) + 1
        return _thread_orig["start"](self)

    def join(self, timeout=None):
        r = _thread_orig["join"](self, timeout)
        if not self.is_alive() and self.ident is not None:
            # the joined thread happens-before the joiner's next step
            # (also invalidates any id-reuse alias of its records)
            with _table_lock:
                _tepoch[self.ident] = _tepoch.get(self.ident, 0) + 1
        return r

    threading.Thread.start = start
    threading.Thread.join = join


def _unpatch_threads():
    if not _thread_orig:
        return
    threading.Thread.start = _thread_orig["start"]
    threading.Thread.join = _thread_orig["join"]
    _thread_orig.clear()


# --------------------------------------------------------- lifecycle


def enable(strict: bool = False, sample: int = 1):
    """Arm the witness on every registered class. `sample=N` records
    every Nth witnessed read (writes are always recorded); `strict`
    additionally raises in the accessing thread. Arms lockcheck's
    lock wrapping too (held stacks are the locksets) when the test
    has not already done so."""
    global _enabled, _strict, _sample, _own_lockcheck

    reset()
    _strict = bool(strict)
    _sample = max(1, int(sample))
    if _enabled:
        return
    if not lockcheck.enabled():
        lockcheck.enable()
        _own_lockcheck = True
    for cls, ignore in _resolve_targets():
        _patch_class(cls, ignore)
    _patch_threads()
    _enabled = True


def disable() -> list[RaceViolation]:
    """Disarm and return the violations recorded while armed."""
    global _enabled, _own_lockcheck

    if _enabled:
        with _table_lock:
            _enabled = False
        _unpatch_classes()
        _unpatch_threads()
        if _own_lockcheck:
            lockcheck.disable()
            _own_lockcheck = False
    return list(_violations)


def reset():
    global _samples, _probes, _read_tick

    with _table_lock:
        _accesses.clear()
        _born.clear()
        _tepoch.clear()
        _violations.clear()
        _reported.clear()
        _written.clear()
        _samples = 0
        _probes = 0
        _read_tick = 0


def enabled() -> bool:
    return _enabled


def violations() -> list[RaceViolation]:
    return list(_violations)


def stats() -> dict:
    return {"probes": _probes, "samples": _samples,
            "tracked_keys": len(_accesses),
            "violations": len(_violations)}
