"""Deterministic failpoints: named injection sites for chaos tests.

The reference builds its Jepsen nemeses from the outside (SIGKILL,
partitions, clock skew — contrib/jepsen/main.go); failpoints complement
that with *surgical*, deterministic faults inside the process, the
x/debug.go / gofail style: a named site in production code evaluates to
a no-op unless a test (or the DGRAPH_TPU_FAILPOINTS env var, for
subprocess clusters) armed an action for it.

Injection sites (grep `failpoint.fire`; the SITES registry below is
the authoritative list, dglint DG08-checked):
    transport.send      cluster/transport.py — before a Raft frame send
    tablet.apply        storage/tablet.py    — before a commit delta lands
    executor.level      query/executor.py    — every block/level boundary
    wal.append          storage/wal.py       — before a record frames
    snapshot.install    cluster/service.py   — before a raft snapshot restores
    txn.xstage          cluster/service.py   — before a 2PC fragment stages
    txn.xfinalize       cluster/service.py   — before a decided 2PC
                                               fragment's finalize applies

Actions (spec grammar, `;`-separated in the env var):
    sleep(S)      delay S seconds (float) at the site
    error(MSG)    raise FailpointError(MSG) from the site
    off           registered but inert (hit counting only)
    N*ACTION      only the first N hits run ACTION, then the point
                  goes inert (still counted) — deterministic "fail
                  twice then recover" schedules

Example: DGRAPH_TPU_FAILPOINTS='executor.level=sleep(0.2);tablet.apply=2*error(boom)'

Production cost: `fire()` is one falsy-dict check when nothing is
armed. Tests arm programmatically and MUST clear: tests/conftest.py
fails any test that leaks an armed failpoint.
"""

from __future__ import annotations

import os
import re
import threading
import time

ENV_VAR = "DGRAPH_TPU_FAILPOINTS"

# Registry of every production injection site (the names `fire()` is
# called with outside tests). dglint DG08 checks each literal
# `failpoint.fire("...")` in dgraph_tpu/ against this tuple, so a
# renamed or removed site cannot silently turn chaos tests into
# no-ops. Tests may arm ad-hoc fixture names freely.
SITES = (
    "transport.send",    # cluster/transport.py — before a Raft frame
    "tablet.apply",      # storage/tablet.py    — before a commit delta
    "executor.level",    # query/executor.py    — block/level boundary
    "wal.append",        # storage/wal.py       — before a record frames
    "snapshot.install",  # cluster/service.py   — before a raft snapshot
    #                      restores (error = apply path dies mid-install)
    "txn.xstage",        # cluster/service.py   — before a 2PC fragment
    #                      stages on a participant group
    "txn.xfinalize",     # cluster/service.py   — before a DECIDED 2PC
    #                      fragment's finalize applies (error = one
    #                      transient failed delivery; reconcile retries)
    "ingest.shuffle",    # ingest/distributed.py — before a map worker
    #                      streams one shuffle part to a reduce group
    #                      (sleep = slow link; error = worker dies and
    #                      its chunk is reassigned)
    "ingest.reduce",     # ingest/distributed.py — before a reduce
    #                      group reduces one predicate's spill runs
    "cdc.append",        # cdc/changelog.py     — before a committed
    #                      txn's ops tail into the change logs (error
    #                      behaves like a WAL append failure)
    "cdc.deliver",       # cdc/changelog.py     — on every subscriber
    #                      poll before entries are served (sleep =
    #                      slow delivery; error = failed poll, the
    #                      subscriber retries/resumes by offset)
    "vecstore.build",    # storage/vecstore.py  — before a quantized
    #                      ANN index trains over a clean base block
    #                      (error = build dies, exact tiers keep
    #                      serving; sleep = slow k-means)
    "move.snapshot_chunk",  # cluster/service.py — source side, before
    #                      one snapshot chunk of a live tablet move is
    #                      served (sleep = slow stream; error = chunk
    #                      delivery fails, the driver retries/re-begins)
    "move.catchup",      # cluster/service.py   — destination side,
    #                      before a CDC catch-up batch replicates
    #                      (sleep = lag stays high, the fence defers)
    "move.fence",        # cluster/service.py   — zero's driver, before
    #                      the single-predicate write fence is proposed
    "move.flip",         # cluster/service.py   — zero's driver, before
    #                      the ownership flip commits (error/SIGKILL
    #                      here = the crash-safety acceptance seam)
    "watchdog.capture",  # utils/watchdog.py    — before an incident
    #                      bundle writes (error = full disk at the
    #                      worst moment; the evaluator must survive)
)


class FailpointError(RuntimeError):
    """Raised by an armed error(...) action at its injection site."""


class _Point:
    __slots__ = ("action", "arg", "limit", "hits")

    def __init__(self, action: str, arg, limit):
        self.action = action  # "sleep" | "error" | "off"
        self.arg = arg
        self.limit = limit    # None = every hit, N = first N hits
        self.hits = 0


_LOCK = threading.Lock()
_ARMED: dict[str, _Point] = {}

_SPEC = re.compile(
    r"^(?:(?P<n>\d+)\*)?(?P<action>sleep|error|off)"
    r"(?:\((?P<arg>[^)]*)\))?$")


def _parse(spec: str) -> _Point:
    m = _SPEC.match(spec.strip())
    if m is None:
        raise ValueError(f"bad failpoint spec {spec!r} "
                         "(want [N*]sleep(S)|error(MSG)|off)")
    action = m.group("action")
    limit = int(m.group("n")) if m.group("n") else None
    arg = m.group("arg")
    if action == "sleep":
        arg = float(arg if arg else 0)
    return _Point(action, arg, limit)


def arm(name: str, spec: str):
    """Arm `name` with an action spec (parsed eagerly so a typo fails
    the arming test, not the production code path)."""
    pt = _parse(spec)
    with _LOCK:
        _ARMED[name] = pt


def disarm(name: str):
    with _LOCK:
        _ARMED.pop(name, None)


def clear():
    with _LOCK:
        _ARMED.clear()


def armed() -> list[str]:
    with _LOCK:
        return sorted(_ARMED)


def hits(name: str) -> int:
    with _LOCK:
        pt = _ARMED.get(name)
        return pt.hits if pt is not None else 0


def fire(name: str):
    """Evaluate the failpoint `name`. No-op (one dict check) unless a
    test armed it."""
    if not _ARMED:
        return
    with _LOCK:
        pt = _ARMED.get(name)
        if pt is None:
            return
        pt.hits += 1
        if pt.limit is not None and pt.hits > pt.limit:
            return
        action, arg = pt.action, pt.arg
    # act OUTSIDE the lock: a sleep must not serialize other sites
    if action == "sleep":
        time.sleep(arg)
    elif action == "error":
        raise FailpointError(
            arg if arg else f"failpoint {name} fired")


def arm_from_env(env: str | None = None):
    """Arm from DGRAPH_TPU_FAILPOINTS ('name=spec;name=spec') — how
    subprocess cluster nodes under chaos tests inherit failpoints.
    Unset/empty leaves everything inert (the production default)."""
    raw = os.environ.get(ENV_VAR, "") if env is None else env
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, spec = part.partition("=")
        arm(name.strip(), spec)


arm_from_env()
