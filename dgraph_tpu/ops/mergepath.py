"""Tiled merge-path intersect (ref algo/uidlist.go:137-287 — the
reference's hottest set-algebra loop; SURVEY §2a item 2).

The fused co-sort in ops/uidvec.py pays one O((n+m)·log²(n+m))
bitonic sort of the concatenated operands. The classic merge-path
decomposition cuts the log² factor: partition the MERGE DIAGONAL into
T equal slabs of K steps, binary-search the slab boundaries (T·log n
scalar work — tiny), then co-sort each slab independently at width
~2K (log²(2K) stages instead of log²(n+m)).

Design notes, measured on v5e (full numbers in BASELINE.md §round-5):

* Diagonal partitioning (not per-a-tile windows): each slab covers
  EXACTLY K merge steps, so the a-window and b-window are each ≤ K by
  construction — no data skew can overflow a window, and the spike's
  per-a-tile variant measured 100% window overflow on the uniform
  bench configs at 2x slack (not just adversarial skew).
* jnp.searchsorted is unusable for the boundaries (its scan lowering
  measured 0.09 GB/s-equivalent); the partition search here is a
  hand-unrolled vectorized binary search: ~21 rounds of two T-element
  gathers.
* Compaction (per-slab hits back to one sorted padded vector) pays a
  global single-operand sort; with hits ≤ K/hit_frac per slab the hit
  matrix is pre-sliced before that sort, with a per-slab count check
  raising the overflow flag (caller re-dispatches at hit_frac=1).

MEASURED VERDICT (v5e, bench_micro configs): correct on every config
(0 overflow, 0 wrong) but 0.10-0.18 GB/s vs the fused co-sort's
0.63-1.71 — 6-30x SLOWER — while the bare batched row-sort at slab
width runs 3.7-10.9 GB/s. The log²(n+m)→log²(2K) saving is real, but
merge-path's prerequisite is cheap data-dependent gather (partition
probes + window gathers touch n+m elements at arbitrary offsets),
and TPU has no per-lane gather hardware: XLA serializes those
gathers, the same wall the round-4 binary-probe experiment measured
at 0.09 GB/s. The engine therefore keeps uidvec.intersect (co-sort)
on the hot path; this module stays as the measured spike closing
SURVEY §2a item 2's "try a Pallas/tiled merge-path" question with
data rather than conjecture.

Output contract matches uidvec.intersect: ascending, SENTINEL-padded,
static length len(a).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .uidvec import SENTINEL

_NEG = jnp.int32(-1)


def _partition(a: jax.Array, b: jax.Array, diag: jax.Array
               ) -> jax.Array:
    """Stable-merge split points: for each diagonal d in `diag`,
    the smallest x with a[x] > b[d-x-1] (a-before-equal-b order),
    clamped to [max(0, d-m), min(d, n)]. Vectorized binary search,
    statically unrolled to ceil(log2(n+1)) rounds."""
    n, m = a.shape[0], b.shape[0]
    lo = jnp.maximum(diag - m, 0)
    hi = jnp.minimum(diag, n)
    steps = max(1, int(np.ceil(np.log2(n + 1))) + 1)
    for _ in range(steps):
        mid = (lo + hi) >> 1
        av = a[jnp.clip(mid, 0, n - 1)]
        bi = diag - mid - 1
        bv = b[jnp.clip(bi, 0, m - 1)]
        # P(mid): a[mid] > b[d-mid-1], with out-of-range semantics
        # b[<0] = -inf (P true), a[>=n] = +inf handled by clamp range
        p = av > bv
        p = jnp.where(bi < 0, True, p)
        p = jnp.where(bi >= m, False, p)
        p = jnp.where(mid >= n, True, p)
        take_hi = p  # x* <= mid
        hi = jnp.where(take_hi, mid, hi)
        lo = jnp.where(take_hi, lo, mid + 1)
    return lo


def mergepath_hits(a: jax.Array, b: jax.Array, k: int = 1024
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-slab sorted hit values.

    Returns (hitmat (T, K) of hit values left-compacted ascending per
    slab with SENTINEL padding, per-slab hit counts (T,), total real
    element count) — the building block mergepath_intersect compacts.
    """
    n, m = a.shape[0], b.shape[0]
    t = -(-(n + m) // k)  # ceil
    diag = jnp.minimum(jnp.arange(1, t + 1, dtype=jnp.int32) * k, n + m)
    xs = _partition(a, b, diag)  # (t,) split at each slab END
    a_end = xs
    a_beg = jnp.concatenate([jnp.zeros(1, jnp.int32), xs[:-1]])
    b_end = diag - a_end
    b_beg = jnp.concatenate([jnp.zeros(1, jnp.int32), b_end[:-1]])

    pos = jnp.arange(k, dtype=jnp.int32)[None, :]  # (1, K)
    ai = a_beg[:, None] + pos
    aw = jnp.where((pos < (a_end - a_beg)[:, None]) & (ai < n),
                   a[jnp.clip(ai, 0, n - 1)], SENTINEL)
    # +1 trailing b element per slab: a slab's LAST a value may equal
    # the FIRST b value of the next slab (stable split allows
    # a[x-1] == b[d-x]); b values are unique so the extra slot can't
    # double-count
    posb = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    bi = b_beg[:, None] + posb
    bw = jnp.where((posb < (b_end - b_beg)[:, None] + 1) & (bi < m),
                   b[jnp.clip(bi, 0, m - 1)], SENTINEL)

    c = jnp.concatenate([aw, bw], axis=1)          # (t, 2K+1)
    flag = jnp.concatenate(
        [jnp.ones(aw.shape, jnp.uint32), jnp.zeros(bw.shape, jnp.uint32)],
        axis=1)
    cs, fs = jax.lax.sort((c, flag), dimension=1, num_keys=1)
    pad = jnp.full((t, 1), SENTINEL, cs.dtype)
    one = jnp.ones((t, 1), jnp.uint32)
    nxt = jnp.concatenate([cs[:, 1:], pad], axis=1)
    fnx = jnp.concatenate([fs[:, 1:], one], axis=1)
    prv = jnp.concatenate([pad, cs[:, :-1]], axis=1)
    fpv = jnp.concatenate([one, fs[:, :-1]], axis=1)
    hit = (((nxt == cs) & (fnx == 0)) | ((prv == cs) & (fpv == 0))) \
        & (fs == 1) & (cs != SENTINEL)
    vals = jnp.where(hit, cs, SENTINEL)
    # left-compact each slab's hits (ascending; sentinels sort last)
    vals = jnp.sort(vals, axis=1)[:, :k]  # ≤ K hits per slab
    counts = jnp.sum(vals != SENTINEL, axis=1, dtype=jnp.int32)
    return vals, counts, jnp.int32(n)


def mergepath_intersect(a: jax.Array, b: jax.Array, k: int = 1024,
                        hit_frac: int = 4
                        ) -> tuple[jax.Array, jax.Array]:
    """Sorted-set intersection via diagonal merge-path.

    Returns (result padded to len(a), hit_overflow flag). The sparse
    compaction keeps K//hit_frac hit slots per slab before the global
    compaction sort — the dominant cost of the whole pipeline — so a
    slab with more hits than that OVERFLOWS: the flag turns True and
    the result DROPS the excess (invalid). Callers re-dispatch with
    hit_frac=1 (always exact: a slab holds ≤ K hits by construction)
    or fall back to uidvec.intersect — mirroring the static-window +
    fallback contract the round-4 verdict asked this spike to
    measure. With hit_frac=1 the flag is always False.
    """
    n = a.shape[0]
    hitmat, counts, _ = mergepath_hits(a, b, k=k)
    h = max(8, k // max(1, hit_frac))
    overflow = jnp.any(counts > h) if h < k \
        else jnp.zeros((), bool)
    flat = jnp.sort(hitmat[:, :h].reshape(-1))
    take = min(n, flat.shape[0])
    out = flat[:take]
    if take < n:
        out = jnp.concatenate(
            [out, jnp.full((n - take,), SENTINEL, a.dtype)])
    return out, overflow
