"""Edge-centric bitmap traversal kernels — the fast BFS/SSSP data plane.

TPU re-design of the reference's multi-hop traversal hot path
(query/recurse.go:29 per-level goroutine fan-out, query/shortest.go:451
Dijkstra, worker/task.go:581 posting-list fan-out + algo/uidlist.go:354
MergeSorted heaps).

The sorted-UID-vector kernels in ops/graph.py pay one large sort per
level to rebuild a deduped frontier; for dense analytical traversals
that sort dominates. Here the frontier is a *bitmap over a permuted
node-slot space* and one BFS level is only gathers + reductions +
concats — no sort, no scatter:

  1. Node slots are assigned grouped by in-degree class (caps from the
     ~1.5x-step ladder {1,2,3} ∪ {4·2^k, 6·2^k}), rows sorted by uid
     inside a bucket, in-degree-0 nodes last. The reverse adjacency
     ("which slots point at me") is a dense padded [rows, cap] int32
     matrix per bucket.
  2. One level:  reach = concat_b( any(frontier_ext[b.in_nb], axis=1) )
     Because bucket rows occupy *contiguous* slot ranges in exactly
     concat order, the per-bucket hit vectors ARE the new bitmap — the
     scatter the textbook edge-centric BFS needs is compiled away by
     the slot permutation.
  3. dedup (`new = reach & ~visited`) is elementwise on bitmaps,
     replacing member_mask + compact (a search + a sort) per level.

Work per level is Θ(padded in-edges) row-gathers (padding waste < 1.33x
per row with the ladder caps). The gather unit is descriptor-rate bound
(~20-40M row-fetches/s on v5e, measured), so the batched kernels below
amortize each descriptor across thousands of bit-packed queries.

SSSP follows the same layout with an int32 distance vector and a
min-reduction instead of any(): Bellman-Ford over dense tiles, with
optional per-edge weights aligned to the in-neighbor matrices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

INT32_INF = np.int32(2**31 - 1)


@dataclass
class RevBucket:
    """One in-degree class. Rows r of `in_nb` describe slots
    [offset, offset + rows): the slot's in-neighbor slots, padded with
    n_slots (a dummy always-unreachable slot)."""

    in_nb: jax.Array                 # [M, D] int32
    weights: Optional[jax.Array]     # [M, D] int32 or None
    degree: int
    offset: int
    # host copy of in_nb, kept so build_core_adjacency can re-derive
    # (dst, src) pairs without a device->host transfer (the tunnel
    # makes that expensive); None for buckets built before this field
    in_nb_host: Optional[np.ndarray] = None


@dataclass
class BitAdjacency:
    """A predicate's reverse adjacency in slot space.

    slot_uids[s] is the uid living in slot s. uids_sorted/slots_by_uid
    are the uid->slot lookup (host numpy; traversal entry points are
    host-driven like the reference's query planner).
    """

    slot_uids: np.ndarray            # [N] uint32, host
    uids_sorted: np.ndarray          # [N] uint32 sorted, host
    slots_by_uid: np.ndarray         # [N] int32 aligned to uids_sorted
    buckets: list[RevBucket]
    n_slots: int
    n_covered: int                   # slots with in-degree > 0 (prefix)
    n_edges: int

    @property
    def shape_sig(self):
        return (self.n_slots,
                tuple((b.in_nb.shape[0], b.degree) for b in self.buckets))


def _bucket_ladder(max_cap: int = 2**31) -> np.ndarray:
    """Degree-class caps {1,2,3} ∪ {4·2^k, 6·2^k}: ~1.5x steps, so a
    row wastes <33% padding instead of <50% with pure pow-2 classes.
    The gather unit is descriptor-rate bound, so padded slots cost the
    same as real edges — tighter classes are a direct speedup."""
    caps = [1, 2, 3]
    k = 4
    while k < max_cap:
        caps.append(k)
        if k + k // 2 < max_cap:
            caps.append(k + k // 2)
        k *= 2
    return np.asarray(caps, np.int64)


_LADDER = _bucket_ladder()


def build_bitadjacency(edges: dict[int, np.ndarray],
                       weights: Optional[dict[int, np.ndarray]] = None,
                       min_degree_bucket: int = 1) -> BitAdjacency:
    """Host: {src_uid -> sorted dst uint32 array} -> BitAdjacency.

    Runs at rollup time like ops/graph.build_adjacency (the analogue of
    posting.List.Rollup, posting/list.go:708). `weights`, if given,
    must mirror `edges`' shapes (per-edge int costs for SSSP).
    """
    if not edges:
        return BitAdjacency(np.empty(0, np.uint32), np.empty(0, np.uint32),
                            np.empty(0, np.int32), [], 0, 0, 0)
    srcs = np.fromiter(edges.keys(), np.uint32, len(edges))
    degs = np.fromiter((len(edges[int(s)]) for s in srcs), np.int64,
                       len(srcs))
    src_rep = np.repeat(srcs, degs)
    dst_all = np.concatenate([np.asarray(edges[int(s)], dtype=np.uint32)
                              for s in srcs]) if len(srcs) else \
        np.empty(0, np.uint32)
    w_all = None
    if weights is not None:
        w_all = np.concatenate([np.asarray(weights[int(s)], dtype=np.int32)
                                for s in srcs])

    uids = np.unique(np.concatenate([srcs, dst_all]))
    n = len(uids)
    dst_idx = np.searchsorted(uids, dst_all)
    indeg = np.bincount(dst_idx, minlength=n)
    floor = np.maximum(indeg, min_degree_bucket)
    cap = np.where(
        indeg > 0,
        _LADDER[np.searchsorted(_LADDER, floor)],
        np.int64(1) << 62)
    perm = np.lexsort((uids, cap))            # slot -> uid index
    slot_of = np.empty(n, np.int32)
    slot_of[perm] = np.arange(n, dtype=np.int32)
    slot_uids = uids[perm]
    n_covered = int(np.sum(indeg > 0))

    src_slot = slot_of[np.searchsorted(uids, src_rep)]
    dst_slot = slot_of[dst_idx]
    eorder = np.argsort(dst_slot, kind="stable")
    src_slot = src_slot[eorder]
    dst_slot = dst_slot[eorder]
    if w_all is not None:
        w_all = w_all[eorder]
    counts = np.bincount(dst_slot, minlength=n)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(len(dst_slot), dtype=np.int64) - starts[dst_slot]

    cap_by_slot = cap[perm][:n_covered]
    buckets: list[RevBucket] = []
    offset = 0
    for c in np.unique(cap_by_slot):
        c = int(c)
        m = int(np.sum(cap_by_slot == c))
        nb = np.full((m, c), n, np.int32)
        sel = (dst_slot >= offset) & (dst_slot < offset + m)
        nb[dst_slot[sel] - offset, pos[sel]] = src_slot[sel]
        wb = None
        if w_all is not None:
            warr = np.zeros((m, c), np.int32)
            warr[dst_slot[sel] - offset, pos[sel]] = w_all[sel]
            wb = jnp.asarray(warr)
        buckets.append(RevBucket(jnp.asarray(nb), wb, c, offset,
                                 in_nb_host=nb))
        offset += m

    order = np.argsort(slot_uids, kind="stable")
    return BitAdjacency(slot_uids, slot_uids[order],
                        order.astype(np.int32), buckets, n, n_covered,
                        int(len(dst_all)))


# -- host <-> bitmap ---------------------------------------------------------


def _uid_slots(badj: BitAdjacency,
               u: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uid uint32 array -> (slot array, keep mask); unknown uids have
    keep=False. Shared by the single and batched packers."""
    idx = np.searchsorted(badj.uids_sorted, u)
    idx = np.clip(idx, 0, len(badj.uids_sorted) - 1)
    hit = badj.uids_sorted[idx] == u
    return badj.slots_by_uid[idx[hit]], hit


def uids_to_bits(badj: BitAdjacency, uids_np: np.ndarray) -> np.ndarray:
    """Seed uid array -> bool[N] bitmap (unknown uids dropped)."""
    bits = np.zeros(badj.n_slots, bool)
    if badj.n_slots == 0 or len(uids_np) == 0:
        return bits
    slots, _ = _uid_slots(badj, np.asarray(uids_np, np.uint32))
    bits[slots] = True
    return bits


def bits_to_uids(badj: BitAdjacency, bits: np.ndarray) -> np.ndarray:
    """bool[N] bitmap -> sorted uid uint32 array."""
    return np.sort(badj.slot_uids[np.asarray(bits, bool)])


# -- kernels -----------------------------------------------------------------


def _level(badj: BitAdjacency, f: jax.Array) -> jax.Array:
    """One frontier expansion: bool[N] -> bool[N] (reachable-in-1)."""
    fe = jnp.concatenate([f, jnp.zeros((1,), jnp.bool_)])
    parts = [jnp.any(fe[b.in_nb], axis=1) for b in badj.buckets]
    tail = badj.n_slots - badj.n_covered
    if tail:
        parts.append(jnp.zeros((tail,), jnp.bool_))
    if not parts:
        return jnp.zeros((badj.n_slots,), jnp.bool_)
    return jnp.concatenate(parts)


def make_bfs_bits(badj: BitAdjacency, depth: int,
                  dedup: bool = True) -> Callable:
    """Compile BFS: seed bitmap bool[N] -> tuple of per-level frontier
    bitmaps (newly reached per level when dedup, raw reach otherwise).
    Matches @recurse semantics incl. loop:true via dedup=False
    (ref gql RecurseArgs.AllowLoop)."""

    def bfs(seed_bits: jax.Array):
        levels = []
        visited = seed_bits
        frontier = seed_bits
        for _ in range(depth):
            reach = _level(badj, frontier)
            if dedup:
                new = reach & ~visited
                visited = visited | new
            else:
                new = reach
            levels.append(new)
            frontier = new
        return tuple(levels)

    return jax.jit(bfs)


def bfs_bits_reach(badj: BitAdjacency, seeds_np: np.ndarray, depth: int,
                   dedup: bool = True) -> list[np.ndarray]:
    """Host wrapper: per-level sorted frontier uid arrays."""
    if badj.n_slots == 0:
        return [np.empty(0, np.uint32) for _ in range(depth)]
    fn = _bfs_cache(badj, depth, dedup)
    levels = fn(jnp.asarray(uids_to_bits(badj, seeds_np)))
    return [bits_to_uids(badj, np.asarray(lv)) for lv in levels]


def _bfs_cache(badj: BitAdjacency, depth: int, dedup: bool) -> Callable:
    cache = getattr(badj, "_bfs_cache", None)
    if cache is None:
        cache = badj._bfs_cache = {}
    fn = cache.get((depth, dedup))
    if fn is None:
        fn = cache[(depth, dedup)] = make_bfs_bits(badj, depth, dedup)
    return fn


# -- batched (multi-query) kernels -------------------------------------------
#
# The TPU's gather unit is descriptor-rate bound (~20M row-fetches/s on
# v5e, measured): the cost of `f[in_nb]` is per *edge*, independent of
# row width up to HBM bandwidth. So the throughput design packs MANY
# queries into the lane dimension — frontier[n, w] is a uint32 whose
# bit b is query (w*32+b)'s membership — and one traversal pass answers
# 32*W queries for the price of one. This is the idiomatic TPU
# replacement for the reference's one-goroutine-per-request model
# (worker/task.go:581): batch across requests, not threads.


def uids_to_bits_batched(badj: BitAdjacency,
                         seed_lists: list[np.ndarray]) -> np.ndarray:
    """[B seed uid arrays] -> packed uint32[N+1, ceil(B/32)] frontier.

    Row N is the dummy always-empty slot targeted by adjacency padding,
    so kernels need no separate mask concat."""
    B = len(seed_lists)
    W = (B + 31) // 32
    out = np.zeros((badj.n_slots + 1, W), np.uint32)
    if badj.n_slots == 0 or B == 0:
        return out
    q, slots = _flat_query_slots(badj, seed_lists)
    np.bitwise_or.at(out, (slots, q // 32),
                     (np.uint32(1) << (q % 32).astype(np.uint32)))
    return out


def _flat_query_slots(badj: BitAdjacency, seed_lists: list[np.ndarray]
                      ) -> tuple[np.ndarray, np.ndarray]:
    """One vectorized pass over all (query, uid) pairs -> aligned
    (query index, slot) arrays with unknown uids dropped. Shared by the
    bitmap and seed-slot packers."""
    B = len(seed_lists)
    lens = np.fromiter((len(s) for s in seed_lists), np.int64, B)
    if lens.sum() == 0:
        return np.empty(0, np.int64), np.empty(0, np.int32)
    u = np.concatenate([np.asarray(s, np.uint32) for s in seed_lists])
    q = np.repeat(np.arange(B, dtype=np.int64), lens)
    slots, hit = _uid_slots(badj, u)
    return q[hit], slots


def bits_to_uids_batched(badj: BitAdjacency, packed: np.ndarray,
                         n_queries: int) -> list[np.ndarray]:
    """packed uint32[N+1, W] -> per-query sorted uid arrays."""
    packed = np.asarray(packed)[:badj.n_slots]
    out = []
    for q in range(n_queries):
        bits = (packed[:, q // 32] >> np.uint32(q % 32)) & np.uint32(1)
        out.append(np.sort(badj.slot_uids[bits.astype(bool)]))
    return out


def _gather_or(f: jax.Array, in_nb: jax.Array, degree: int) -> jax.Array:
    """OR of gathered frontier rows over the degree axis, in chunks of
    <=8 so no [M, D, W] intermediate is materialized and the unroll
    stays bounded for the huge-degree hub buckets."""
    Dc = next(c for c in (8, 6, 4, 3, 2, 1) if degree % c == 0)
    M = in_nb.shape[0]
    nb = in_nb.reshape(M * (degree // Dc), Dc)
    acc = f[nb[:, 0]]
    for d in range(1, Dc):
        acc = acc | f[nb[:, d]]
    if degree > Dc:
        acc = jnp.bitwise_or.reduce(acc.reshape(M, degree // Dc, -1), axis=1)
    return acc


def make_bfs_bits_batched(badj: BitAdjacency, depth: int,
                          dedup: bool = True,
                          use_pallas: bool | None = None,
                          pallas_interpret: bool | None = None
                          ) -> Callable:
    """Compile multi-query BFS: packed uint32[N+1, W] seed frontier ->
    tuple of per-level packed frontiers (same shape).

    One device call runs 32*W independent traversals. Per-edge work is
    one row-gather + OR — under XLA as D separate [M, W] gathers (no
    [M, D, W] intermediate), or with use_pallas as the scalar-prefetch
    Pallas kernel (ops/pallas_kernels.bucket_or_pallas) that DMAs each
    needed frontier row HBM->VMEM directly. use_pallas=None auto-picks
    pallas on the TPU backend; callers should warm up the returned fn
    once and fall back (see bench.py) since pallas compilation is the
    newer path."""
    ncov = badj.n_covered
    n = badj.n_slots
    # explicit opt-in (None -> XLA): callers that enable pallas own the
    # warmup + fallback (bench.py does); silently auto-enabling would
    # put an unproven compile path under every existing caller
    if use_pallas is None:
        use_pallas = False

    def bucket_or(f, b):
        if use_pallas and f.shape[1] % 128 == 0:
            from dgraph_tpu.ops.pallas_kernels import bucket_or_pallas
            return bucket_or_pallas(f, b.in_nb,
                                    interpret=pallas_interpret)
        return _gather_or(f, b.in_nb, b.degree)

    def level(f):
        parts = [bucket_or(f, b) for b in badj.buckets]
        W = f.shape[1]
        tail = n - ncov
        if tail:
            parts.append(jnp.zeros((tail, W), jnp.uint32))
        if not parts:
            return jnp.zeros_like(f)
        reach = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        # re-append the dummy slot row (always empty)
        return jnp.concatenate([reach, jnp.zeros((1, W), jnp.uint32)])

    def bfs(seed_packed: jax.Array):
        levels = []
        visited = seed_packed
        frontier = seed_packed
        for _ in range(depth):
            reach = level(frontier)
            if dedup:
                new = reach & ~visited
                visited = visited | new
            else:
                new = reach
            levels.append(new)
            frontier = new
        return tuple(levels)

    return jax.jit(bfs)


def make_frontier_counts_batched(n_queries: int) -> Callable:
    """Compile: packed uint32[N+1, W] -> int32[n_queries] per-query
    popcounts (set sizes), fully on device."""

    @jax.jit
    def counts(packed: jax.Array):
        # popcount per word, but per bit-position: extract each of the
        # 32 bit planes and reduce over rows.
        per_word_bit = []
        for b in range(32):
            plane = (packed >> np.uint32(b)) & np.uint32(1)
            per_word_bit.append(jnp.sum(plane, axis=0, dtype=jnp.int32))
        stacked = jnp.stack(per_word_bit, axis=1)  # [W, 32]
        return stacked.reshape(-1)[:n_queries]

    return counts


# -- core-space digest kernels -----------------------------------------------
#
# At reference scale (21M edges over 2M nodes) the bitmap memory
# [N+1, W] caps the query batch — and QPS is proportional to W because
# the gather unit is descriptor-bound (row width is nearly free). Two
# structural facts about any graph break that cap:
#   1. only slots with in-degree > 0 can appear in levels >= 1, and
#      those slots are a PREFIX of slot space by construction
#      (n_covered) — measured 27% of slots on the zipf bench graph;
#   2. only edges whose SOURCE is itself covered can contribute to
#      levels >= 2 — 27% of edges on the same graph.
# So level 1 runs once over the full adjacency into core space
# [n_covered+1, W], and deeper levels run entirely in core space with a
# re-bucketed core adjacency: ~3.7x less bitmap HBM and ~3.7x fewer
# gather descriptors per deep level, which buys back the batch width.


@dataclass
class CoreAdjacency:
    """Reverse adjacency restricted to covered->covered edges, in its
    own ROW space.

    Every covered slot owns exactly one row (slots with no covered
    in-neighbor sit in the cap-1 bucket gathering only the dummy), rows
    grouped by core-degree class — so the per-bucket concat order IS
    the core frontier layout and deep levels need no permutation.
    in_nb entries are ROW POSITIONS of source slots (dummy = n_core);
    `row_slots[r]` is the covered slot living in row r, used once at
    the level-1 boundary to permute slot-ordered bitmaps into row
    order."""

    buckets: list[RevBucket]
    row_slots: jax.Array             # [n_core] int32
    n_core: int


def build_core_adjacency(badj: BitAdjacency) -> CoreAdjacency:
    """Derive the covered->covered re-bucketed adjacency from the full
    buckets' host copies (no device transfer)."""
    ncov = badj.n_covered
    if ncov == 0 or not badj.buckets:
        return CoreAdjacency([], jnp.zeros((0,), jnp.int32), ncov)
    dsts, srcs = [], []
    for b in badj.buckets:
        nb = b.in_nb_host if b.in_nb_host is not None \
            else np.asarray(b.in_nb)
        rr, cc = np.nonzero(nb < ncov)       # covered sources only
        dsts.append((rr + b.offset).astype(np.int64))
        srcs.append(nb[rr, cc])
    dst = np.concatenate(dsts)
    src = np.concatenate(srcs)
    indeg = np.bincount(dst, minlength=ncov)
    # every covered slot gets a row; 0-degree rows take cap 1 (one
    # dummy gather each — cheap, and it keeps row space == covered set)
    cap_all = _LADDER[np.searchsorted(_LADDER, np.maximum(indeg, 1))]
    order = np.lexsort((np.arange(ncov), cap_all))
    row_slots = order.astype(np.int32)       # row -> slot
    caps_o = cap_all[order]
    pos_of = np.empty(ncov, np.int64)        # slot -> row
    pos_of[order] = np.arange(ncov)
    rp = pos_of[dst]
    eorder = np.argsort(rp, kind="stable")
    rp, srco = rp[eorder], pos_of[src[eorder]]   # sources in ROW space
    starts = np.zeros(ncov + 1, np.int64)
    np.cumsum(np.bincount(rp, minlength=ncov), out=starts[1:])
    posin = np.arange(len(srco), dtype=np.int64) - starts[rp]
    buckets: list[RevBucket] = []
    offset = 0
    for c in np.unique(caps_o):
        c = int(c)
        m = int(np.sum(caps_o == c))
        nb = np.full((m, c), ncov, np.int32)
        sel = (rp >= offset) & (rp < offset + m)
        nb[rp[sel] - offset, posin[sel]] = srco[sel]
        # no in_nb_host: nothing re-derives edges from a CoreAdjacency,
        # so pinning the host copy would only hold memory
        buckets.append(RevBucket(jnp.asarray(nb), None, c, offset))
        offset += m
    return CoreAdjacency(buckets, jnp.asarray(row_slots), ncov)


def uid_lists_to_seed_slots(badj: BitAdjacency,
                            seed_lists: list[np.ndarray],
                            n_seeds: int | None = None) -> np.ndarray:
    """[B seed uid arrays] -> int32[B, S] slot matrix for the digest
    kernel; unknown uids and padding map to the dummy slot n_slots.
    Deduplicates (query, slot) pairs so the kernel's scatter-ADD packing
    is an exact OR. A query with more than S distinct known seeds is an
    error — silent truncation would answer a different query."""
    B = len(seed_lists)
    S = n_seeds if n_seeds is not None else \
        max((len(s) for s in seed_lists), default=1)
    out = np.full((B, max(S, 1)), badj.n_slots, np.int32)
    if badj.n_slots == 0 or B == 0:
        return out
    q, slots = _flat_query_slots(badj, seed_lists)
    if not len(q):
        return out
    pairs = np.unique((q << 32) | slots.astype(np.int64))
    q, slots = pairs >> 32, pairs & 0xFFFFFFFF
    starts = np.zeros(B + 1, np.int64)
    np.cumsum(np.bincount(q, minlength=B), out=starts[1:])
    pos = np.arange(len(q), dtype=np.int64) - starts[q]
    if pos.max(initial=-1) >= out.shape[1]:
        over = int(q[pos >= out.shape[1]][0])
        raise ValueError(
            f"query {over} has {int((q == over).sum())} distinct seeds "
            f"> n_seeds={out.shape[1]}")
    out[q, pos] = slots.astype(np.int32)
    return out


def make_bfs_digest_batched(badj: BitAdjacency, core: CoreAdjacency,
                            depth: int, n_queries: int,
                            n_seeds: int,
                            use_pallas: bool | None = None,
                            pallas_interpret: bool | None = None
                            ) -> Callable:
    """Compile the serving-shape BFS: int32[B, S] seed slots ->
    (uint32[depth] per-level popcount checksums,
     uint32[n_core+1, 1] final level's first word column).

    The packed frontier is built ON DEVICE (scatter-add of one bit per
    (query, seed)) so only the [B, S] slot matrix crosses the host link
    per batch — never an [N, W] bitmap. Level 1 gathers the full
    adjacency once; every deeper level runs in core slot space. Only
    frontier+visited (+ the level's reach) are live — no per-level
    bitmap pile-up, which is what held BENCH_BATCH at 8192 on a 16GB
    chip (ref regime: worker/task.go:581 fan-out at systest/21million
    scale). The first-word column ships ~n_core*4 bytes so the caller
    can parity-check queries 0..31 via make_frontier_counts_batched
    without pulling a full bitmap."""
    N, ncov = badj.n_slots, badj.n_covered
    W = (n_queries + 31) // 32
    # same opt-in convention as make_bfs_bits_batched: None -> XLA;
    # callers that enable pallas own warmup + fallback (bench.py
    # --pallas does). The pallas kernel needs lane-aligned W.
    if use_pallas is None:
        use_pallas = False

    def gather_or(f, b):
        if use_pallas and f.shape[1] % 128 == 0:
            from dgraph_tpu.ops.pallas_kernels import bucket_or_pallas
            return bucket_or_pallas(f, b.in_nb,
                                    interpret=pallas_interpret)
        return _gather_or(f, b.in_nb, b.degree)

    def digest(seed_slots: jax.Array):
        q = jnp.arange(n_queries, dtype=jnp.uint32)
        bit = jnp.uint32(1) << (q % jnp.uint32(32))
        word = (q // jnp.uint32(32)).astype(jnp.int32)
        f = jnp.zeros((N + 1, W), jnp.uint32)
        f = f.at[seed_slots.reshape(-1),
                 jnp.repeat(word, n_seeds)].add(jnp.repeat(bit, n_seeds))
        f = f.at[N].set(jnp.uint32(0))   # dummy slot absorbs padding
        zrow = jnp.zeros((1, W), jnp.uint32)
        if badj.buckets:
            parts = [gather_or(f, b) for b in badj.buckets]
            reach1 = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        else:
            reach1 = jnp.zeros((ncov, W), jnp.uint32)
        seeds_core = f[:ncov]
        new = reach1 & ~seeds_core
        sums = [jnp.sum(jax.lax.population_count(new), dtype=jnp.uint32)]
        # one boundary permutation into core ROW space; every deeper
        # level's bucket-concat then IS the next frontier layout
        vis_s = seeds_core | new
        frontier = jnp.concatenate([new[core.row_slots], zrow])
        visited = jnp.concatenate([vis_s[core.row_slots], zrow])
        for _ in range(depth - 1):
            parts = [gather_or(frontier, b) for b in core.buckets]
            reach = jnp.concatenate(parts + [zrow])
            frontier = reach & ~visited
            visited = visited | frontier
            sums.append(jnp.sum(jax.lax.population_count(frontier),
                                dtype=jnp.uint32))
        return jnp.stack(sums), frontier[:, :1]

    return jax.jit(digest)


def bfs_bits_reach_batched(badj: BitAdjacency,
                           seed_lists: list[np.ndarray], depth: int,
                           dedup: bool = True) -> list[list[np.ndarray]]:
    """Host wrapper: per-query, per-level sorted frontier uid arrays.
    Returns result[q][lvl]."""
    B = len(seed_lists)
    if badj.n_slots == 0 or B == 0:
        return [[np.empty(0, np.uint32) for _ in range(depth)]
                for _ in range(B)]
    cache = getattr(badj, "_bfsb_cache", None)
    if cache is None:
        cache = badj._bfsb_cache = {}
    W = (B + 31) // 32
    fn = cache.get((depth, dedup, W))
    if fn is None:
        fn = cache[(depth, dedup, W)] = make_bfs_bits_batched(
            badj, depth, dedup)
    packed = uids_to_bits_batched(badj, seed_lists)
    levels = fn(jnp.asarray(packed))
    per_level = [bits_to_uids_batched(badj, np.asarray(lv), B)
                 for lv in levels]
    return [[per_level[lvl][q] for lvl in range(depth)]
            for q in range(B)]


def make_sssp_bits(badj: BitAdjacency, max_iters: int,
                   weighted: bool = False) -> Callable:
    """Compile Bellman-Ford distances: seed bitmap -> int32[N] dist
    (INT32_INF = unreachable). With weighted=True uses the per-edge
    weights captured at build time (ref query/shortest.go:451 route()
    — the priority queue becomes dense relaxation rounds)."""
    ncov = badj.n_covered

    def sssp(seed_bits: jax.Array):
        dist = jnp.where(seed_bits, jnp.int32(0), INT32_INF)
        for _ in range(max_iters):
            de = jnp.concatenate([dist, jnp.full((1,), INT32_INF,
                                                 jnp.int32)])
            parts = []
            for b in badj.buckets:
                d = de[b.in_nb]                          # [M, D]
                w = b.weights if (weighted and b.weights is not None) \
                    else jnp.int32(1)
                # d + w can exceed int32 (long weighted paths) and must
                # saturate at INT32_INF, not wrap to a bogus negative
                # distance (advisor finding). int64 is unavailable
                # without jax_enable_x64, so test overflow before
                # adding: safe iff w <= INT32_INF - d (both sides
                # in-range int32 since 0 <= d < INT32_INF).
                w_arr = jnp.broadcast_to(jnp.asarray(w, jnp.int32),
                                         d.shape)
                safe = (d < INT32_INF) & (w_arr <= INT32_INF - d)
                cand = jnp.where(safe, d + w_arr, INT32_INF)
                parts.append(jnp.min(cand, axis=1))
            if parts:
                cand = jnp.concatenate(parts)
                dist = jnp.concatenate(
                    [jnp.minimum(dist[:ncov], cand), dist[ncov:]])
        return dist

    return jax.jit(sssp)


def sssp_dist(badj: BitAdjacency, seeds_np: np.ndarray, max_iters: int,
              weighted: bool = False) -> dict[int, int]:
    """Host wrapper: {uid -> hop/weighted distance} for reachable uids."""
    if badj.n_slots == 0:
        return {}
    cache = getattr(badj, "_sssp_cache", None)
    if cache is None:
        cache = badj._sssp_cache = {}
    fn = cache.get((max_iters, weighted))
    if fn is None:
        fn = cache[(max_iters, weighted)] = make_sssp_bits(
            badj, max_iters, weighted)
    dist = np.asarray(fn(jnp.asarray(uids_to_bits(badj, seeds_np))))
    ok = dist < INT32_INF
    return {int(u): int(d) for u, d in zip(badj.slot_uids[ok], dist[ok])}
