"""Edge-centric bitmap traversal kernels — the fast BFS/SSSP data plane.

TPU re-design of the reference's multi-hop traversal hot path
(query/recurse.go:29 per-level goroutine fan-out, query/shortest.go:451
Dijkstra, worker/task.go:581 posting-list fan-out + algo/uidlist.go:354
MergeSorted heaps).

The sorted-UID-vector kernels in ops/graph.py pay one large sort per
level to rebuild a deduped frontier; for dense analytical traversals
that sort dominates. Here the frontier is a *bitmap over a permuted
node-slot space* and one BFS level is only gathers + reductions +
concats — no sort, no scatter:

  1. Node slots are assigned grouped by in-degree bucket (pow-2 cap),
     rows sorted by uid inside a bucket, in-degree-0 nodes last. The
     reverse adjacency ("which slots point at me") is a dense padded
     [rows, cap] int32 matrix per bucket.
  2. One level:  reach = concat_b( any(frontier_ext[b.in_nb], axis=1) )
     Because bucket rows occupy *contiguous* slot ranges in exactly
     concat order, the per-bucket hit vectors ARE the new bitmap — the
     scatter the textbook edge-centric BFS needs is compiled away by
     the slot permutation.
  3. dedup (`new = reach & ~visited`) is elementwise on bitmaps,
     replacing member_mask + compact (a search + a sort) per level.

Work per level is Θ(padded in-edges) ≈ 2·|E| gathers of one byte — HBM
bandwidth bound, which is the right regime for a TPU. Padding waste is
< 2× per row (pow-2 caps).

SSSP follows the same layout with an int32 distance vector and a
min-reduction instead of any(): Bellman-Ford over dense tiles, with
optional per-edge weights aligned to the in-neighbor matrices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

INT32_INF = np.int32(2**31 - 1)


@dataclass
class RevBucket:
    """One in-degree class. Rows r of `in_nb` describe slots
    [offset, offset + rows): the slot's in-neighbor slots, padded with
    n_slots (a dummy always-unreachable slot)."""

    in_nb: jax.Array                 # [M, D] int32
    weights: Optional[jax.Array]     # [M, D] int32 or None
    degree: int
    offset: int


@dataclass
class BitAdjacency:
    """A predicate's reverse adjacency in slot space.

    slot_uids[s] is the uid living in slot s. uids_sorted/slots_by_uid
    are the uid->slot lookup (host numpy; traversal entry points are
    host-driven like the reference's query planner).
    """

    slot_uids: np.ndarray            # [N] uint32, host
    uids_sorted: np.ndarray          # [N] uint32 sorted, host
    slots_by_uid: np.ndarray         # [N] int32 aligned to uids_sorted
    buckets: list[RevBucket]
    n_slots: int
    n_covered: int                   # slots with in-degree > 0 (prefix)
    n_edges: int

    @property
    def shape_sig(self):
        return (self.n_slots,
                tuple((b.in_nb.shape[0], b.degree) for b in self.buckets))


def build_bitadjacency(edges: dict[int, np.ndarray],
                       weights: Optional[dict[int, np.ndarray]] = None,
                       min_degree_bucket: int = 8) -> BitAdjacency:
    """Host: {src_uid -> sorted dst uint32 array} -> BitAdjacency.

    Runs at rollup time like ops/graph.build_adjacency (the analogue of
    posting.List.Rollup, posting/list.go:708). `weights`, if given,
    must mirror `edges`' shapes (per-edge int costs for SSSP).
    """
    if not edges:
        return BitAdjacency(np.empty(0, np.uint32), np.empty(0, np.uint32),
                            np.empty(0, np.int32), [], 0, 0, 0)
    srcs = np.fromiter(edges.keys(), np.uint32, len(edges))
    degs = np.fromiter((len(edges[int(s)]) for s in srcs), np.int64,
                       len(srcs))
    src_rep = np.repeat(srcs, degs)
    dst_all = np.concatenate([np.asarray(edges[int(s)], dtype=np.uint32)
                              for s in srcs]) if len(srcs) else \
        np.empty(0, np.uint32)
    w_all = None
    if weights is not None:
        w_all = np.concatenate([np.asarray(weights[int(s)], dtype=np.int32)
                                for s in srcs])

    uids = np.unique(np.concatenate([srcs, dst_all]))
    n = len(uids)
    dst_idx = np.searchsorted(uids, dst_all)
    indeg = np.bincount(dst_idx, minlength=n)
    cap = np.where(
        indeg > 0,
        np.maximum(min_degree_bucket,
                   1 << np.ceil(np.log2(np.maximum(indeg, 1))).astype(np.int64)),
        np.int64(1) << 62)
    perm = np.lexsort((uids, cap))            # slot -> uid index
    slot_of = np.empty(n, np.int32)
    slot_of[perm] = np.arange(n, dtype=np.int32)
    slot_uids = uids[perm]
    n_covered = int(np.sum(indeg > 0))

    src_slot = slot_of[np.searchsorted(uids, src_rep)]
    dst_slot = slot_of[dst_idx]
    eorder = np.argsort(dst_slot, kind="stable")
    src_slot = src_slot[eorder]
    dst_slot = dst_slot[eorder]
    if w_all is not None:
        w_all = w_all[eorder]
    counts = np.bincount(dst_slot, minlength=n)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(len(dst_slot), dtype=np.int64) - starts[dst_slot]

    cap_by_slot = cap[perm][:n_covered]
    buckets: list[RevBucket] = []
    offset = 0
    for c in np.unique(cap_by_slot):
        c = int(c)
        m = int(np.sum(cap_by_slot == c))
        nb = np.full((m, c), n, np.int32)
        sel = (dst_slot >= offset) & (dst_slot < offset + m)
        nb[dst_slot[sel] - offset, pos[sel]] = src_slot[sel]
        wb = None
        if w_all is not None:
            warr = np.zeros((m, c), np.int32)
            warr[dst_slot[sel] - offset, pos[sel]] = w_all[sel]
            wb = jnp.asarray(warr)
        buckets.append(RevBucket(jnp.asarray(nb), wb, c, offset))
        offset += m

    order = np.argsort(slot_uids, kind="stable")
    return BitAdjacency(slot_uids, slot_uids[order],
                        order.astype(np.int32), buckets, n, n_covered,
                        int(len(dst_all)))


# -- host <-> bitmap ---------------------------------------------------------


def uids_to_bits(badj: BitAdjacency, uids_np: np.ndarray) -> np.ndarray:
    """Seed uid array -> bool[N] bitmap (unknown uids dropped)."""
    bits = np.zeros(badj.n_slots, bool)
    if badj.n_slots == 0 or len(uids_np) == 0:
        return bits
    u = np.asarray(uids_np, np.uint32)
    idx = np.searchsorted(badj.uids_sorted, u)
    idx = np.clip(idx, 0, len(badj.uids_sorted) - 1)
    hit = badj.uids_sorted[idx] == u
    bits[badj.slots_by_uid[idx[hit]]] = True
    return bits


def bits_to_uids(badj: BitAdjacency, bits: np.ndarray) -> np.ndarray:
    """bool[N] bitmap -> sorted uid uint32 array."""
    return np.sort(badj.slot_uids[np.asarray(bits, bool)])


# -- kernels -----------------------------------------------------------------


def _level(badj: BitAdjacency, f: jax.Array) -> jax.Array:
    """One frontier expansion: bool[N] -> bool[N] (reachable-in-1)."""
    fe = jnp.concatenate([f, jnp.zeros((1,), jnp.bool_)])
    parts = [jnp.any(fe[b.in_nb], axis=1) for b in badj.buckets]
    tail = badj.n_slots - badj.n_covered
    if tail:
        parts.append(jnp.zeros((tail,), jnp.bool_))
    if not parts:
        return jnp.zeros((badj.n_slots,), jnp.bool_)
    return jnp.concatenate(parts)


def make_bfs_bits(badj: BitAdjacency, depth: int,
                  dedup: bool = True) -> Callable:
    """Compile BFS: seed bitmap bool[N] -> tuple of per-level frontier
    bitmaps (newly reached per level when dedup, raw reach otherwise).
    Matches @recurse semantics incl. loop:true via dedup=False
    (ref gql RecurseArgs.AllowLoop)."""

    def bfs(seed_bits: jax.Array):
        levels = []
        visited = seed_bits
        frontier = seed_bits
        for _ in range(depth):
            reach = _level(badj, frontier)
            if dedup:
                new = reach & ~visited
                visited = visited | new
            else:
                new = reach
            levels.append(new)
            frontier = new
        return tuple(levels)

    return jax.jit(bfs)


def bfs_bits_reach(badj: BitAdjacency, seeds_np: np.ndarray, depth: int,
                   dedup: bool = True) -> list[np.ndarray]:
    """Host wrapper: per-level sorted frontier uid arrays."""
    if badj.n_slots == 0:
        return [np.empty(0, np.uint32) for _ in range(depth)]
    fn = _bfs_cache(badj, depth, dedup)
    levels = fn(jnp.asarray(uids_to_bits(badj, seeds_np)))
    return [bits_to_uids(badj, np.asarray(lv)) for lv in levels]


def _bfs_cache(badj: BitAdjacency, depth: int, dedup: bool) -> Callable:
    cache = getattr(badj, "_bfs_cache", None)
    if cache is None:
        cache = badj._bfs_cache = {}
    fn = cache.get((depth, dedup))
    if fn is None:
        fn = cache[(depth, dedup)] = make_bfs_bits(badj, depth, dedup)
    return fn


def make_sssp_bits(badj: BitAdjacency, max_iters: int,
                   weighted: bool = False) -> Callable:
    """Compile Bellman-Ford distances: seed bitmap -> int32[N] dist
    (INT32_INF = unreachable). With weighted=True uses the per-edge
    weights captured at build time (ref query/shortest.go:451 route()
    — the priority queue becomes dense relaxation rounds)."""
    ncov = badj.n_covered

    def sssp(seed_bits: jax.Array):
        dist = jnp.where(seed_bits, jnp.int32(0), INT32_INF)
        for _ in range(max_iters):
            de = jnp.concatenate([dist, jnp.full((1,), INT32_INF,
                                                 jnp.int32)])
            parts = []
            for b in badj.buckets:
                d = de[b.in_nb]                          # [M, D]
                w = b.weights if (weighted and b.weights is not None) \
                    else jnp.int32(1)
                # d + w can exceed int32 (long weighted paths) and must
                # saturate at INT32_INF, not wrap to a bogus negative
                # distance (advisor finding). int64 is unavailable
                # without jax_enable_x64, so test overflow before
                # adding: safe iff w <= INT32_INF - d (both sides
                # in-range int32 since 0 <= d < INT32_INF).
                w_arr = jnp.broadcast_to(jnp.asarray(w, jnp.int32),
                                         d.shape)
                safe = (d < INT32_INF) & (w_arr <= INT32_INF - d)
                cand = jnp.where(safe, d + w_arr, INT32_INF)
                parts.append(jnp.min(cand, axis=1))
            if parts:
                cand = jnp.concatenate(parts)
                dist = jnp.concatenate(
                    [jnp.minimum(dist[:ncov], cand), dist[ncov:]])
        return dist

    return jax.jit(sssp)


def sssp_dist(badj: BitAdjacency, seeds_np: np.ndarray, max_iters: int,
              weighted: bool = False) -> dict[int, int]:
    """Host wrapper: {uid -> hop/weighted distance} for reachable uids."""
    if badj.n_slots == 0:
        return {}
    cache = getattr(badj, "_sssp_cache", None)
    if cache is None:
        cache = badj._sssp_cache = {}
    fn = cache.get((max_iters, weighted))
    if fn is None:
        fn = cache[(max_iters, weighted)] = make_sssp_bits(
            badj, max_iters, weighted)
    dist = np.asarray(fn(jnp.asarray(uids_to_bits(badj, seeds_np))))
    ok = dist < INT32_INF
    return {int(u): int(d) for u, d in zip(badj.slot_uids[ok], dist[ok])}
