"""k-way sorted-UID set algebra — the host half of the reference's
hottest loop (algo/uidlist.go:137 IntersectWith, :287 IntersectSorted,
:354 MergeSorted) plus device variants over the uidvec co-sort kernels.

Every input is a sorted-unique uint64 uid vector (the repo-wide
invariant).  The pairwise folds the executor used to run — k-1
``np.union1d`` calls re-sorting the accumulator each step, or a left
fold of intersections ignoring set sizes — are replaced by:

  * union_many:     one concat + ONE sort (np.unique) over all k sets,
                    O(N log N) total instead of O(k N log N);
  * intersect_many: smallest-first fold (the reference's
                    IntersectSorted sorts lists by length for exactly
                    this reason) where each step is a galloping
                    ``searchsorted`` probe of the larger side when the
                    sizes are lopsided — the lin/jump/bin strategy pick
                    of algo/uidlist.go:151 collapsed to the two numpy
                    regimes that matter;
  * difference:     setdiff1d with the uniqueness invariant asserted.

The *_device variants stack the sets into one padded uint32 matrix and
run the ops/uidvec co-sort kernels (merge_many / intersect_many) in a
single dispatch — used by the executor when the estimated host cost
clears the measured dispatch round-trip (`Executor._device_worth`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dgraph_tpu.ops import codec as _codec

_EMPTY = np.empty(0, dtype=np.uint64)

# a searchsorted probe of the big side beats the full merge once the
# sizes diverge by this much (same ratio the pairwise fold used; ref
# algo/uidlist.go:151 picks its strategy by the same ratio)
_GALLOP_RATIO = 16

# device sets are uint32 with 0xFFFFFFFF reserved as padding
_MAX_U32 = 0xFFFFFFFE


def intersect_pair(a: np.ndarray, b: np.ndarray,
                   gallop_ratio: int = _GALLOP_RATIO) -> np.ndarray:
    """Intersection of two sorted-unique uid vectors. `gallop_ratio`
    is the size-skew threshold past which the searchsorted probe of
    the big side replaces the full merge — the adaptive planner
    passes a density-derived value (query/planner.py gallop_ratio:
    sparse expected intersections gallop from 4x skew, dense ones
    merge until 48x — the SIMD-intersection paper's pivot) where the
    static default stays 16."""
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        return _EMPTY
    if la > lb:
        a, b = b, a
        la, lb = lb, la
    if lb >= gallop_ratio * la:
        idx = np.searchsorted(b, a)
        np.minimum(idx, lb - 1, out=idx)
        return a[b[idx] == a]
    return np.intersect1d(a, b, assume_unique=True)


def union_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two sorted-unique uid vectors."""
    if not len(a):
        return np.asarray(b)
    if not len(b):
        return np.asarray(a)
    return np.union1d(a, b)


def difference(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """a \\ b over sorted-unique uid vectors."""
    return np.setdiff1d(a, b, assume_unique=True)


def union_many(parts: Sequence[np.ndarray]) -> np.ndarray:
    """k-way union: one concat + one sort + adjacent-unique — the k-1
    ``union1d`` accumulator re-sorts become a single O(N log N) pass
    (ref algo.MergeSorted's uint64Heap loop, algo/uidlist.go:354)."""
    live = [p for p in parts if len(p)]
    if not live:
        return _EMPTY
    if len(live) == 1:
        return np.asarray(live[0])
    return np.unique(np.concatenate(live))


def intersect_many(parts: Sequence[np.ndarray],
                   gallop_ratio=_GALLOP_RATIO) -> np.ndarray:
    """k-way intersection, smallest set first so every galloping probe
    runs over the narrowest possible accumulator (ref
    algo.IntersectSorted sorts by length, algo/uidlist.go:287).
    `gallop_ratio` tunes the per-pair gallop-vs-merge pivot (see
    intersect_pair): one int for every fold, or a sequence of
    per-FOLD ratios aligned with the ascending fold order (the
    planner's intersect_schedule — the accumulator gets sparser as
    folds proceed, so late folds gallop earlier). A ratio only picks
    the strategy; results are byte-identical either way."""
    if not len(parts):
        return _EMPTY
    ordered = sorted(parts, key=len)
    per_fold = None
    if not isinstance(gallop_ratio, int):
        per_fold = tuple(gallop_ratio)
        gallop_ratio = _GALLOP_RATIO
    acc = np.asarray(ordered[0])
    for i, p in enumerate(ordered[1:]):
        if not len(acc):
            return _EMPTY
        r = per_fold[i] if per_fold is not None \
            and i < len(per_fold) else gallop_ratio
        acc = intersect_pair(acc, p, r)
    return acc


def count_filter(parts: Sequence[np.ndarray], need: int) -> np.ndarray:
    """Uids appearing in at least `need` of the sorted-unique sets —
    the q-gram count filter of fuzzy match (ref worker/match.go
    uidsForMatch + the T-3d counting bound). Pigeonhole: a uid with
    >= need hits must appear in one of the smallest k-need+1 sets, so
    only THOSE union; counts then come from one vectorized
    searchsorted probe per set over that (much smaller) candidate
    vector — no k-set concat + full sort (which at the 21M regime
    re-sorted ~10M uids per match() call)."""
    k = len(parts)
    if need > k:
        return _EMPTY
    if need <= 1:
        return union_many(parts)
    ordered = sorted(parts, key=len)
    m = k - need + 1
    small = [p for p in ordered[:m] if len(p)]
    if not small:
        return _EMPTY
    # the candidate union's own sort yields the counts WITHIN the
    # small sets for free — only the k-m large sets need probing
    cand, counts = np.unique(np.concatenate(small),
                             return_counts=True) \
        if len(small) > 1 else (small[0], np.ones(len(small[0]),
                                                  np.int64))
    rest = ordered[m:]
    total = sum(len(p) for p in parts)
    # adaptive: k-m membership probes over |cand| (~25ns each) vs one
    # flat sort over every element (~40ns each) — dense-overlap sets
    # (|cand| near the whole uid space) lose the probe race
    if len(cand) * len(rest) * 25 >= total * 40:
        uids, counts = np.unique(np.concatenate(
            [p for p in parts if len(p)]), return_counts=True)
        return uids[counts >= need]
    # probe smallest-first with incremental pruning: after j of the
    # remaining sets a candidate still needs
    # counts >= need - (len(rest) - j), so the LARGEST (most
    # expensive) probes run over an already-thinned vector
    for j, p in enumerate(rest):
        lp = len(p)
        if lp:
            idx = np.searchsorted(p, cand)
            np.minimum(idx, lp - 1, out=idx)
            counts += p[idx] == cand
        floor = need - (len(rest) - j - 1)
        if floor > 0:
            keep = counts >= floor
            if not keep.all():
                cand, counts = cand[keep], counts[keep]
                if not len(cand):
                    return _EMPTY
    return cand[counts >= need]


# -- device variants (ops/uidvec co-sort kernels, one dispatch) --------


def _device_matrix(parts: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Stack k sorted uid vectors into one padded uint32 matrix, or
    None when any uid exceeds the 32-bit device plane (callers fall
    back to the host fold, same contract as the adjacency tiles).

    BOTH dimensions bucket to powers of two so the jitted set-algebra
    executables compile once per (row bucket, width bucket) instead
    of once per distinct set count: surplus rows REPLICATE the last
    row, which is exact for union (dedup absorbs it) and for
    intersection (idempotent), unlike sentinel rows which would empty
    an intersection."""
    from dgraph_tpu.ops.uidvec import SENTINEL, pad_to

    width = pad_to(max((len(p) for p in parts), default=0))
    k = max(len(parts), 1)
    kp = pad_to(k, minimum=2)
    mat = np.full((kp, width), SENTINEL, np.uint32)
    for i, p in enumerate(parts):
        if len(p) and int(p[-1]) > _MAX_U32:
            return None
        mat[i, : len(p)] = np.asarray(p, np.uint64).astype(np.uint32)
    for i in range(k, kp):
        mat[i] = mat[k - 1]
    return mat


def union_many_device(parts: Sequence[np.ndarray]
                      ) -> Optional[np.ndarray]:
    """k-way union in ONE device dispatch (uidvec.merge_many: concat +
    single co-sort + adjacent-unique). None -> caller uses the host
    fold (empty input, >32-bit uids)."""
    live = [p for p in parts if len(p)]
    if len(live) < 2:
        return union_many(live)
    mat = _device_matrix(live)
    if mat is None:
        return None
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import merge_many, to_numpy
    from dgraph_tpu.query.plan import jit_stage

    # ONE compiled executable for the whole co-sort+unique chain
    # instead of an eager op-by-op dispatch; _device_matrix buckets
    # BOTH matrix dimensions to pow-2, so jax's shape-keyed trace
    # cache under this wrapper stays small (log k x log width shapes)
    fn = jit_stage("setops.union_many", lambda: jax.jit(merge_many))
    return to_numpy(fn(jnp.asarray(mat))).astype(np.uint64)


def intersect_many_device(parts: Sequence[np.ndarray]
                          ) -> Optional[np.ndarray]:
    """k-way intersection in one dispatch (uidvec.intersect_many's
    fused co-sort fold). None -> host fold."""
    if not len(parts):
        return _EMPTY
    if any(not len(p) for p in parts):
        return _EMPTY
    if len(parts) == 1:
        return np.asarray(parts[0])
    # smallest-first keeps the accumulator (row 0's static length) tight
    ordered = sorted(parts, key=len)
    mat = _device_matrix(ordered)
    if mat is None:
        return None
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import intersect_many as _dev_isect
    from dgraph_tpu.ops.uidvec import to_numpy
    from dgraph_tpu.query.plan import jit_stage

    fn = jit_stage("setops.intersect_many",
                   lambda: jax.jit(_dev_isect))
    return to_numpy(fn(jnp.asarray(mat))).astype(np.uint64)


# ======================================================================
# Set algebra on COMPRESSED operands (ops/codec.CompressedPack).
#
# The dense entry points above decode-then-intersect; these keep the
# "SIMD Compression and the Intersection of Sorted Integers" shape
# (PAPERS.md): block descriptors are compared first (no key overlap =>
# the block is NEVER decoded), bitmap blocks AND/OR as whole uint64
# word vectors, PACKED-vs-BITMAP probes test bits without decoding the
# bitmap, and only blocks that survive skipping densify into the
# result.  All results are fresh sorted-unique uint64 vectors (the
# repo-wide invariant); `scratch` is an ops/codec.DecodeScratch whose
# views never escape a call.
# ======================================================================


def _pack_keys_intersect(packs) -> np.ndarray:
    """Surviving block keys: k-way intersection of the (sorted-unique)
    per-pack key vectors — the descriptor-skipping pass."""
    keys = packs[0].keys
    for p in packs[1:]:
        if not len(keys):
            return keys
        keys = intersect_pair(keys, p.keys)
    return keys


def _uids_of(key: int, lows: np.ndarray) -> np.ndarray:
    return (np.uint64(key) << np.uint64(16)) | lows.astype(np.uint64)


def intersect_packs(packs, scratch=None, device: bool = False,
                    use_pallas: bool = False) -> np.ndarray:
    """k-way intersection over compressed packs.  Per surviving key the
    SMALLEST block decodes once and the others answer membership in
    compressed form (bitmap bit test / run interval probe); all-bitmap
    keys batch into one vectorized word-AND — on device (jit_stage /
    Pallas) when `device` and enough blocks survive."""
    if not len(packs):
        return _EMPTY
    if any(p.n == 0 for p in packs):
        return _EMPTY
    if len(packs) == 1:
        return packs[0].densify()
    packs = sorted(packs, key=lambda p: p.n)
    keys = _pack_keys_intersect(packs)
    if not len(keys):
        return _EMPTY
    parts: list[np.ndarray] = []
    bi_per = [np.searchsorted(p.keys, keys) for p in packs]
    # keys where EVERY pack's block is a singleton: one vectorized
    # base compare instead of a per-key walk (the ultra-sparse regime
    # — descriptor skipping already pruned everything else)
    all_sing = np.ones(len(keys), bool)
    for p, bis in zip(packs, bi_per):
        all_sing &= p.counts[bis] == 1
    si = np.flatnonzero(all_sing)
    if len(si):
        base_mat = np.stack([p.bases[bis[si]]
                             for p, bis in zip(packs, bi_per)])
        eq = (base_mat == base_mat[0]).all(axis=0)
        if eq.any():
            parts.append((keys[si][eq] << np.uint64(16))
                         | base_mat[0][eq].astype(np.uint64))
    # batch the all-bitmap keys into one word-AND (host or device)
    all_bitmap = np.ones(len(keys), bool)
    for p, bis in zip(packs, bi_per):
        all_bitmap &= p.forms[bis] == _codec.FORM_BITMAP
    all_bitmap &= ~all_sing
    bm_idx = np.flatnonzero(all_bitmap)
    if len(bm_idx):
        mats = []
        for p, bis in zip(packs, bi_per):
            rows = np.stack([p.block_words(int(bis[i]))
                             for i in bm_idx])
            mats.append(rows)
        anded = None
        if device and len(bm_idx) >= 8:
            anded = bitmap_and_device(mats, use_pallas=use_pallas)
        if anded is None:
            anded = mats[0]
            for m in mats[1:]:
                anded = anded & m
        bits = np.unpackbits(anded.view(np.uint8), axis=1,
                             bitorder="little")
        for row, i in enumerate(bm_idx):
            lows = np.flatnonzero(bits[row]).astype(np.uint32)
            if len(lows):
                parts.append(_uids_of(int(keys[i]), lows))
    for i in np.flatnonzero(~all_bitmap & ~all_sing):
        blocks = [(p, int(bis[i])) for p, bis in zip(packs, bi_per)]
        # decode the smallest block once; everyone else answers
        # membership on the compressed form
        blocks.sort(key=lambda pb: int(pb[0].counts[pb[1]]))
        p0, b0 = blocks[0]
        lows = p0.block_lows(b0, scratch=scratch)
        for p, bi in blocks[1:]:
            if not len(lows):
                break
            lows = lows[p.block_member(bi, lows, scratch=scratch)]
        if len(lows):
            parts.append(_uids_of(int(keys[i]), lows))
    if not parts:
        return _EMPTY
    out = np.concatenate(parts)
    out.sort()  # keys interleave between the bitmap and mixed passes
    return out


def _keys_member(keys: np.ndarray, sset: np.ndarray) -> np.ndarray:
    """Bool mask: which (sorted-unique) keys appear in sorted sset."""
    if not len(sset) or not len(keys):
        return np.zeros(len(keys), bool)
    i = np.searchsorted(sset, keys)
    np.minimum(i, len(sset) - 1, out=i)
    return sset[i] == keys


def _singleton_uids(p, mask: np.ndarray) -> np.ndarray:
    return (p.keys[mask] << np.uint64(16)) \
        | p.bases[mask].astype(np.uint64)


def union_packs(packs, scratch=None) -> np.ndarray:
    """k-way union over compressed packs: singleton blocks pool into
    one vectorized unique (the ultra-sparse regime never walks
    per-key python), uncontested blocks decode straight into the
    result, contested dense keys OR as bitmap words."""
    packs = [p for p in packs if p.n]
    if not packs:
        return _EMPTY
    if len(packs) == 1:
        return packs[0].densify()
    all_keys, kcounts = np.unique(
        np.concatenate([p.keys for p in packs]), return_counts=True)
    contested = all_keys[kcounts > 1]
    nonsing = [~p.singleton_mask() for p in packs]
    nonsing_keys = np.unique(np.concatenate(
        [p.keys[m] for p, m in zip(packs, nonsing)])) \
        if any(m.any() for m in nonsing) else _EMPTY
    # per-key python only where a contested key holds a real block
    loop_keys = intersect_pair(contested, nonsing_keys) \
        if len(contested) and len(nonsing_keys) else _EMPTY
    parts: list[np.ndarray] = []
    sing_pool: list[np.ndarray] = []
    for p, nsm in zip(packs, nonsing):
        in_loop = _keys_member(p.keys, loop_keys)
        free_sing = ~nsm & ~in_loop
        if free_sing.any():
            sing_pool.append(_singleton_uids(p, free_sing))
        for bi in np.flatnonzero(nsm & ~in_loop).tolist():
            parts.append(_uids_of(int(p.keys[bi]),
                                  p.block_lows(bi, scratch=scratch)))
    for key in loop_keys.tolist():
        blocks = [(p, p.block_of(key)) for p in packs]
        blocks = [(p, bi) for p, bi in blocks if bi >= 0]
        if any(int(p.forms[bi]) == _codec.FORM_BITMAP
               for p, bi in blocks) \
                or sum(int(p.counts[bi]) for p, bi in blocks) > 4096:
            words = _take(scratch, _codec.BITMAP_WORDS)
            words[:] = 0
            for p, bi in blocks:
                words |= p.block_bitmap(bi)
            bits = np.unpackbits(words.view(np.uint8),
                                 bitorder="little")
            lows = np.flatnonzero(bits).astype(np.uint32)
        else:
            lows = np.unique(np.concatenate(
                [p.block_lows(bi, scratch=scratch)
                 for p, bi in blocks]))
        parts.append(_uids_of(key, lows))
    if sing_pool:
        # contested all-singleton keys repeat across packs: ONE unique
        parts.append(np.unique(np.concatenate(sing_pool)))
    if not parts:
        return _EMPTY
    out = np.concatenate(parts)
    out.sort()  # parts are key-disjoint but interleave in key order
    return out


def difference_pack(a, b, scratch=None) -> np.ndarray:
    """a \\ b over compressed packs: keys absent from b decode whole
    (descriptor skipping), singleton-vs-singleton keys compare bases
    vectorized, the rest mask by compressed membership."""
    if a.n == 0:
        return _EMPTY
    if b.n == 0:
        return a.densify()
    parts: list[np.ndarray] = []
    b_at = np.searchsorted(b.keys, a.keys)
    np.minimum(b_at, max(len(b.keys) - 1, 0), out=b_at)
    shared = (b.keys[b_at] == a.keys) if len(b.keys) else \
        np.zeros(len(a.keys), bool)
    sing_a = a.singleton_mask()
    keep = sing_a & ~shared  # singleton, key not in b: survives whole
    b_sing = b.counts[b_at] == 1
    both_sing = sing_a & shared & b_sing
    if both_sing.any():
        keep = keep | (both_sing
                       & (a.bases != b.bases[b_at]))
    if keep.any():
        parts.append(_singleton_uids(a, keep))
    for i in np.flatnonzero(sing_a & shared & ~b_sing).tolist():
        low = np.asarray([a.bases[i]], np.uint32)
        if not b.block_member(int(b_at[i]), low, scratch=scratch)[0]:
            parts.append(_uids_of(int(a.keys[i]), low))
    for i in np.flatnonzero(~sing_a).tolist():
        lows = a.block_lows(i, scratch=scratch)
        if shared[i]:
            lows = lows[~b.block_member(int(b_at[i]), lows,
                                        scratch=scratch)]
        if len(lows):
            parts.append(_uids_of(int(a.keys[i]), lows))
    if not parts:
        return _EMPTY
    out = np.concatenate(parts)
    out.sort()
    return out


def count_filter_packs(packs, need: int, scratch=None) -> np.ndarray:
    """Uids in >= `need` packs (the match() q-gram bound) without
    densifying: keys held by < need packs skip entirely; all-singleton
    keys count in one vectorized unique; the rest accumulate per-low
    hit counts in one 2^16 counter — bitmap blocks add their unpacked
    bits, runs add slice-wise, PACKED lows scatter-add."""
    k = len(packs)
    if need > k:
        return _EMPTY
    if need <= 1:
        return union_packs(packs, scratch=scratch)
    packs = [p for p in packs if p.n]
    if len(packs) < need:
        return _EMPTY
    all_keys, kcounts = np.unique(
        np.concatenate([p.keys for p in packs]), return_counts=True)
    live = all_keys[kcounts >= need]
    if not len(live):
        return _EMPTY
    nonsing = [~p.singleton_mask() for p in packs]
    nonsing_keys = np.unique(np.concatenate(
        [p.keys[m] for p, m in zip(packs, nonsing)])) \
        if any(m.any() for m in nonsing) else _EMPTY
    loop_keys = intersect_pair(live, nonsing_keys) \
        if len(nonsing_keys) else _EMPTY
    parts: list[np.ndarray] = []
    # all-singleton live keys: pooled unique-with-counts
    pool = []
    for p in packs:
        m = p.singleton_mask() & _keys_member(p.keys, live) \
            & ~_keys_member(p.keys, loop_keys)
        if m.any():
            pool.append(_singleton_uids(p, m))
    if pool:
        uids, ucounts = np.unique(np.concatenate(pool),
                                  return_counts=True)
        hit = uids[ucounts >= need]
        if len(hit):
            parts.append(hit)
    counts = _take(scratch, _codec.BLOCK_SPAN, np.uint16)
    for key in loop_keys.tolist():
        counts[:] = 0
        for p in packs:
            bi = p.block_of(key)
            if bi < 0:
                continue
            form = int(p.forms[bi])
            if form == _codec.FORM_BITMAP:
                counts += np.unpackbits(p.block_payload(bi),
                                        bitorder="little")
            elif form == _codec.FORM_RUN:
                runs = p.block_runs(bi)
                for s, lm1 in runs.tolist():
                    counts[s: s + lm1 + 1] += 1
            else:
                counts[p.block_lows(bi, scratch=scratch)] += 1
        lows = np.flatnonzero(counts >= need).astype(np.uint32)
        if len(lows):
            parts.append(_uids_of(key, lows))
    if not parts:
        return _EMPTY
    out = np.concatenate(parts)
    out.sort()
    return out


def _take(scratch, n, dtype=np.uint64):
    if scratch is None:
        return np.empty(n, dtype)
    return scratch.take(n, dtype)


def bitmap_and_device(mats, use_pallas: bool = False):
    """k-way AND of stacked bitmap word matrices ([B, 1024] uint64) in
    ONE device dispatch: uint64 splits into two uint32 lanes (TPUs
    have no 64-bit integer ALU), the jitted fold ANDs all k mats, and
    `use_pallas` routes the pairwise word-AND through the Mosaic
    kernel (ops/pallas_kernels.bitmap_and_pallas).  None -> caller
    folds on host (no device / import failure)."""
    try:
        import jax
        import jax.numpy as jnp

        from dgraph_tpu.query.plan import jit_stage
    except Exception:  # pragma: no cover - jax always importable in CI
        return None
    k = len(mats)
    mats32 = [np.ascontiguousarray(m).view(np.uint32) for m in mats]
    if use_pallas:
        from dgraph_tpu.ops.pallas_kernels import bitmap_and_pallas
        acc = mats32[0]
        for m in mats32[1:]:
            acc = np.asarray(bitmap_and_pallas(jnp.asarray(acc),
                                               jnp.asarray(m)))
        return np.ascontiguousarray(acc).view(np.uint64)

    def _fold(stack):
        out = stack[0]
        for i in range(1, stack.shape[0]):
            out = out & stack[i]
        return out

    # one executable per k (k is tiny: the query's token count bucket)
    fn = jit_stage(f"setops.bitmap_and.{k}", lambda: jax.jit(_fold))
    got = np.asarray(fn(jnp.stack(mats32)))
    return np.ascontiguousarray(got).view(np.uint64)


# -- mixed operands: dense vectors alongside compressed packs ----------
#
# The hybrid token index (storage/tablet.CompressedTokenIndex) hands
# out dense slices for its small-list tail and CompressedPacks for the
# long lists; these entry points take either form per operand, keeping
# the dense side on the vectorized numpy kernels and the compressed
# side on block-descriptor skipping.  The dense-vs-pack boundary runs
# membership probes INTO the compressed side (the reference's lin/bin
# strategy pick, algo/uidlist.go:151, applied at the form boundary).


def _op_len(op) -> int:
    return len(op) if isinstance(op, np.ndarray) else op.n


def pack_member(p, uids: np.ndarray, scratch=None) -> np.ndarray:
    """Bool mask: which sorted uids are in pack `p` — block-descriptor
    skipping first (uids in absent blocks never touch a payload)."""
    if not len(uids) or p.n == 0:
        return np.zeros(len(uids), bool)
    uids = np.asarray(uids, np.uint64)
    keys = uids >> np.uint64(16)
    bi = np.searchsorted(p.keys, keys)
    np.minimum(bi, max(len(p.keys) - 1, 0), out=bi)
    hit = p.keys[bi] == keys
    out = np.zeros(len(uids), bool)
    if not hit.any():
        return out
    lows = (uids & np.uint64(0xFFFF)).astype(np.uint32)
    for b in np.unique(bi[hit]).tolist():
        rows = hit & (bi == b)
        out[rows] = p.block_member(b, lows[rows], scratch=scratch)
    return out


def union_mixed(ops, scratch=None) -> np.ndarray:
    """k-way union over mixed operands: dense slices ride the one
    concat + one sort.  Packs pick their own regime: dense blocks
    (bitmap territory) OR as word vectors compressed-side first;
    sparse packs decode through the scratch block cache into the same
    single vectorized unique — per-key python on a mostly-packed
    sparse union would cost more than the decode it avoids."""
    dense = [o for o in ops if isinstance(o, np.ndarray)]
    packs = [o for o in ops if not isinstance(o, np.ndarray)]
    if packs:
        blocks = sum(len(p.keys) for p in packs)
        if blocks and sum(p.n for p in packs) / blocks >= 4096:
            dense.append(union_packs(packs, scratch=scratch))
        else:
            dense.extend(p.densify(scratch=scratch) for p in packs)
    return union_many(dense)


def intersect_mixed(ops, scratch=None, device: bool = False,
                    use_pallas: bool = False) -> np.ndarray:
    """k-way intersection over mixed operands: the dense sides
    intersect smallest-first, then the (small) survivor vector probes
    each pack's membership in compressed form — blocks the survivors
    never land in are skipped by descriptor compare alone."""
    if not len(ops):
        return _EMPTY
    if any(_op_len(o) == 0 for o in ops):
        return _EMPTY
    dense = [o for o in ops if isinstance(o, np.ndarray)]
    packs = [o for o in ops if not isinstance(o, np.ndarray)]
    if not packs:
        return intersect_many(dense)
    if not dense:
        return intersect_packs(packs, scratch=scratch, device=device,
                               use_pallas=use_pallas)
    acc = intersect_many(dense) if len(dense) > 1 \
        else np.asarray(dense[0])
    for p in sorted(packs, key=lambda q: q.n):
        if not len(acc):
            return _EMPTY
        acc = acc[pack_member(p, acc, scratch=scratch)]
    return acc


def count_filter_mixed(ops, need: int, scratch=None) -> np.ndarray:
    """Uids in >= `need` of the mixed operands — setops.count_filter's
    pigeonhole shape with compressed membership probes: candidates
    come from the k-need+1 SMALLEST operands (densified only if
    packed), the larger operands answer by probe — dense via
    searchsorted, packs via block-skipping pack_member."""
    k = len(ops)
    if need > k:
        return _EMPTY
    if need <= 1:
        return union_mixed(ops, scratch=scratch)
    ops = [o for o in ops if _op_len(o)]
    if len(ops) < need:
        return _EMPTY
    if all(not isinstance(o, np.ndarray) for o in ops):
        return count_filter_packs(ops, need, scratch=scratch)
    ordered = sorted(ops, key=_op_len)
    m = len(ops) - need + 1
    small = [o if isinstance(o, np.ndarray) else o.densify()
             for o in ordered[:m]]
    cand, counts = np.unique(np.concatenate(small),
                             return_counts=True) \
        if len(small) > 1 else (np.asarray(small[0]),
                                np.ones(len(small[0]), np.int64))
    rest = ordered[m:]
    for j, o in enumerate(rest):
        if isinstance(o, np.ndarray):
            lp = len(o)
            idx = np.searchsorted(o, cand)
            np.minimum(idx, lp - 1, out=idx)
            counts += o[idx] == cand
        else:
            counts += pack_member(o, cand, scratch=scratch)
        floor = need - (len(rest) - j - 1)
        if floor > 0:
            keep = counts >= floor
            if not keep.all():
                cand, counts = cand[keep], counts[keep]
                if not len(cand):
                    return _EMPTY
    return cand[counts >= need]
