"""k-way sorted-UID set algebra — the host half of the reference's
hottest loop (algo/uidlist.go:137 IntersectWith, :287 IntersectSorted,
:354 MergeSorted) plus device variants over the uidvec co-sort kernels.

Every input is a sorted-unique uint64 uid vector (the repo-wide
invariant).  The pairwise folds the executor used to run — k-1
``np.union1d`` calls re-sorting the accumulator each step, or a left
fold of intersections ignoring set sizes — are replaced by:

  * union_many:     one concat + ONE sort (np.unique) over all k sets,
                    O(N log N) total instead of O(k N log N);
  * intersect_many: smallest-first fold (the reference's
                    IntersectSorted sorts lists by length for exactly
                    this reason) where each step is a galloping
                    ``searchsorted`` probe of the larger side when the
                    sizes are lopsided — the lin/jump/bin strategy pick
                    of algo/uidlist.go:151 collapsed to the two numpy
                    regimes that matter;
  * difference:     setdiff1d with the uniqueness invariant asserted.

The *_device variants stack the sets into one padded uint32 matrix and
run the ops/uidvec co-sort kernels (merge_many / intersect_many) in a
single dispatch — used by the executor when the estimated host cost
clears the measured dispatch round-trip (`Executor._device_worth`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

_EMPTY = np.empty(0, dtype=np.uint64)

# a searchsorted probe of the big side beats the full merge once the
# sizes diverge by this much (same ratio the pairwise fold used; ref
# algo/uidlist.go:151 picks its strategy by the same ratio)
_GALLOP_RATIO = 16

# device sets are uint32 with 0xFFFFFFFF reserved as padding
_MAX_U32 = 0xFFFFFFFE


def intersect_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Intersection of two sorted-unique uid vectors."""
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        return _EMPTY
    if la > lb:
        a, b = b, a
        la, lb = lb, la
    if lb >= _GALLOP_RATIO * la:
        idx = np.searchsorted(b, a)
        np.minimum(idx, lb - 1, out=idx)
        return a[b[idx] == a]
    return np.intersect1d(a, b, assume_unique=True)


def union_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two sorted-unique uid vectors."""
    if not len(a):
        return np.asarray(b)
    if not len(b):
        return np.asarray(a)
    return np.union1d(a, b)


def difference(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """a \\ b over sorted-unique uid vectors."""
    return np.setdiff1d(a, b, assume_unique=True)


def union_many(parts: Sequence[np.ndarray]) -> np.ndarray:
    """k-way union: one concat + one sort + adjacent-unique — the k-1
    ``union1d`` accumulator re-sorts become a single O(N log N) pass
    (ref algo.MergeSorted's uint64Heap loop, algo/uidlist.go:354)."""
    live = [p for p in parts if len(p)]
    if not live:
        return _EMPTY
    if len(live) == 1:
        return np.asarray(live[0])
    return np.unique(np.concatenate(live))


def intersect_many(parts: Sequence[np.ndarray]) -> np.ndarray:
    """k-way intersection, smallest set first so every galloping probe
    runs over the narrowest possible accumulator (ref
    algo.IntersectSorted sorts by length, algo/uidlist.go:287)."""
    if not len(parts):
        return _EMPTY
    ordered = sorted(parts, key=len)
    acc = np.asarray(ordered[0])
    for p in ordered[1:]:
        if not len(acc):
            return _EMPTY
        acc = intersect_pair(acc, p)
    return acc


def count_filter(parts: Sequence[np.ndarray], need: int) -> np.ndarray:
    """Uids appearing in at least `need` of the sorted-unique sets —
    the q-gram count filter of fuzzy match (ref worker/match.go
    uidsForMatch + the T-3d counting bound). Pigeonhole: a uid with
    >= need hits must appear in one of the smallest k-need+1 sets, so
    only THOSE union; counts then come from one vectorized
    searchsorted probe per set over that (much smaller) candidate
    vector — no k-set concat + full sort (which at the 21M regime
    re-sorted ~10M uids per match() call)."""
    k = len(parts)
    if need > k:
        return _EMPTY
    if need <= 1:
        return union_many(parts)
    ordered = sorted(parts, key=len)
    m = k - need + 1
    small = [p for p in ordered[:m] if len(p)]
    if not small:
        return _EMPTY
    # the candidate union's own sort yields the counts WITHIN the
    # small sets for free — only the k-m large sets need probing
    cand, counts = np.unique(np.concatenate(small),
                             return_counts=True) \
        if len(small) > 1 else (small[0], np.ones(len(small[0]),
                                                  np.int64))
    rest = ordered[m:]
    total = sum(len(p) for p in parts)
    # adaptive: k-m membership probes over |cand| (~25ns each) vs one
    # flat sort over every element (~40ns each) — dense-overlap sets
    # (|cand| near the whole uid space) lose the probe race
    if len(cand) * len(rest) * 25 >= total * 40:
        uids, counts = np.unique(np.concatenate(
            [p for p in parts if len(p)]), return_counts=True)
        return uids[counts >= need]
    # probe smallest-first with incremental pruning: after j of the
    # remaining sets a candidate still needs
    # counts >= need - (len(rest) - j), so the LARGEST (most
    # expensive) probes run over an already-thinned vector
    for j, p in enumerate(rest):
        lp = len(p)
        if lp:
            idx = np.searchsorted(p, cand)
            np.minimum(idx, lp - 1, out=idx)
            counts += p[idx] == cand
        floor = need - (len(rest) - j - 1)
        if floor > 0:
            keep = counts >= floor
            if not keep.all():
                cand, counts = cand[keep], counts[keep]
                if not len(cand):
                    return _EMPTY
    return cand[counts >= need]


# -- device variants (ops/uidvec co-sort kernels, one dispatch) --------


def _device_matrix(parts: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Stack k sorted uid vectors into one padded uint32 matrix, or
    None when any uid exceeds the 32-bit device plane (callers fall
    back to the host fold, same contract as the adjacency tiles).

    BOTH dimensions bucket to powers of two so the jitted set-algebra
    executables compile once per (row bucket, width bucket) instead
    of once per distinct set count: surplus rows REPLICATE the last
    row, which is exact for union (dedup absorbs it) and for
    intersection (idempotent), unlike sentinel rows which would empty
    an intersection."""
    from dgraph_tpu.ops.uidvec import SENTINEL, pad_to

    width = pad_to(max((len(p) for p in parts), default=0))
    k = max(len(parts), 1)
    kp = pad_to(k, minimum=2)
    mat = np.full((kp, width), SENTINEL, np.uint32)
    for i, p in enumerate(parts):
        if len(p) and int(p[-1]) > _MAX_U32:
            return None
        mat[i, : len(p)] = np.asarray(p, np.uint64).astype(np.uint32)
    for i in range(k, kp):
        mat[i] = mat[k - 1]
    return mat


def union_many_device(parts: Sequence[np.ndarray]
                      ) -> Optional[np.ndarray]:
    """k-way union in ONE device dispatch (uidvec.merge_many: concat +
    single co-sort + adjacent-unique). None -> caller uses the host
    fold (empty input, >32-bit uids)."""
    live = [p for p in parts if len(p)]
    if len(live) < 2:
        return union_many(live)
    mat = _device_matrix(live)
    if mat is None:
        return None
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import merge_many, to_numpy
    from dgraph_tpu.query.plan import jit_stage

    # ONE compiled executable for the whole co-sort+unique chain
    # instead of an eager op-by-op dispatch; _device_matrix buckets
    # BOTH matrix dimensions to pow-2, so jax's shape-keyed trace
    # cache under this wrapper stays small (log k x log width shapes)
    fn = jit_stage("setops.union_many", lambda: jax.jit(merge_many))
    return to_numpy(fn(jnp.asarray(mat))).astype(np.uint64)


def intersect_many_device(parts: Sequence[np.ndarray]
                          ) -> Optional[np.ndarray]:
    """k-way intersection in one dispatch (uidvec.intersect_many's
    fused co-sort fold). None -> host fold."""
    if not len(parts):
        return _EMPTY
    if any(not len(p) for p in parts):
        return _EMPTY
    if len(parts) == 1:
        return np.asarray(parts[0])
    # smallest-first keeps the accumulator (row 0's static length) tight
    ordered = sorted(parts, key=len)
    mat = _device_matrix(ordered)
    if mat is None:
        return None
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import intersect_many as _dev_isect
    from dgraph_tpu.ops.uidvec import to_numpy
    from dgraph_tpu.query.plan import jit_stage

    fn = jit_stage("setops.intersect_many",
                   lambda: jax.jit(_dev_isect))
    return to_numpy(fn(jnp.asarray(mat))).astype(np.uint64)
