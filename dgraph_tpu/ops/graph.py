"""Device-resident adjacency + expansion kernels.

This is the TPU re-design of the reference's posting-list fan-out hot loop
(worker/task.go:581 handleUidPostings: per-UID goroutines doing Badger
reads + codec decode + per-list intersect). Here a whole predicate
("tablet") lives in HBM as degree-bucketed padded neighbor matrices, and
one jitted call expands an entire frontier level:

    rows    = searchsorted(bucket.src, frontier)        (vectorized lookup)
    cand    = bucket.neighbors[rows]                    (one batched gather)
    next    = sort+unique(concat over buckets)          (merge)

Degree bucketing bounds padding waste: a src uid lands in the bucket whose
width is the next power of two >= its degree, so padding is < 2x and each
bucket's gather is a dense [F, D] tile — MXU/VPU-friendly, no ragged
shapes inside jit.  The reference's analogue of "one list too big for a
node" (multi-part posting lists, posting/list.go:1149) maps to splitting a
bucket row across the mesh's uid axis — see parallel/.

Value postings (for order-by and inequality) live as two aligned sorted
views so both directions are one searchsorted: by-uid (gather a
candidate's sort key) and by-key (range select for le/ge/between).
Ref: worker/sort.go:177 sortWithIndex + worker/tokens.go:113
getInequalityTokens, re-designed as array kernels instead of index-bucket
walks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.uidvec import (
    SENTINEL, compact, lookup_idx, member_mask, pad_to,
)

INT64_MAX = np.int64(2**63 - 1)


@dataclass
class AdjBucket:
    """One degree class of a predicate's adjacency."""

    src: jax.Array        # [M] uint32 sorted, SENTINEL padded
    neighbors: jax.Array  # [M, D] uint32, SENTINEL padded
    degree: int           # D


@dataclass
class DeviceAdjacency:
    """A predicate's full edge set on device.

    src_uids/degrees give O(log N) per-frontier-element count lookup
    (ref worker/task.go handleHasFunction + count index reads).
    """

    src_uids: jax.Array   # [N] uint32 sorted, SENTINEL padded
    degrees: jax.Array    # [N] int32 aligned to src_uids
    buckets: list[AdjBucket] = field(default_factory=list)
    n_edges: int = 0
    n_dst: int = 0        # distinct destination uids (bounds any union)
    n_src: int = 0        # real (unpadded) source count

    @property
    def shape_sig(self):
        return (self.src_uids.shape[0],
                tuple((b.src.shape[0], b.degree) for b in self.buckets))


def build_adjacency(edges: dict[int, np.ndarray],
                    min_degree_bucket: int = 8) -> DeviceAdjacency:
    """Host: {src_uid -> sorted dst uint32 array} -> DeviceAdjacency.

    Runs at rollup time (the analogue of posting.List.Rollup,
    posting/list.go:708): the committed state is re-packed into dense
    device tiles.
    """
    srcs = np.fromiter(edges.keys(), dtype=np.uint32, count=len(edges))
    order = np.argsort(srcs, kind="stable")
    srcs = srcs[order]
    degs = np.fromiter((len(edges[int(s)]) for s in srcs), dtype=np.int32,
                       count=len(srcs))

    n_pad = pad_to(len(srcs))
    src_pad = np.full(n_pad, SENTINEL, np.uint32)
    src_pad[: len(srcs)] = srcs
    deg_pad = np.zeros(n_pad, np.int32)
    deg_pad[: len(srcs)] = degs

    buckets: list[AdjBucket] = []
    n_edges = int(degs.sum())
    if len(srcs):
        caps = np.maximum(min_degree_bucket,
                          2 ** np.ceil(np.log2(np.maximum(degs, 1))).astype(np.int64))
        for cap in sorted(set(caps.tolist())):
            sel = srcs[caps == cap]
            m_pad = pad_to(len(sel))
            bsrc = np.full(m_pad, SENTINEL, np.uint32)
            bsrc[: len(sel)] = sel
            nb = np.full((m_pad, int(cap)), SENTINEL, np.uint32)
            for i, s in enumerate(sel):
                dst = edges[int(s)]
                nb[i, : len(dst)] = dst
            buckets.append(AdjBucket(jnp.asarray(bsrc), jnp.asarray(nb),
                                     int(cap)))
    n_dst = 0
    if edges:
        n_dst = len(np.unique(np.concatenate(
            [np.asarray(v) for v in edges.values()])))
    return DeviceAdjacency(jnp.asarray(src_pad), jnp.asarray(deg_pad),
                           buckets, n_edges, n_dst, len(srcs))


def _bucket_candidates(frontier: jax.Array, b: AdjBucket) -> jax.Array:
    """Flat (unsorted, SENTINEL-masked) neighbor candidates of `frontier`
    rows present in bucket `b`.

    Two duals of the same lookup, chosen at trace time by static shape:
      frontier smaller than bucket  -> gather rows for each frontier uid
                                       ([F, D] work)
      bucket smaller than frontier  -> mask bucket rows that appear in
                                       the frontier ([M, D] work)
    Work per hop is thus bounded by min(F, M) * D per bucket — a large
    frontier can never blow past the bucket's own edge count.
    """
    F = frontier.shape[0]
    M = b.src.shape[0]
    if F <= M:
        idx = jnp.clip(lookup_idx(b.src, frontier), 0, M - 1)
        hit = (b.src[idx] == frontier) & (frontier != SENTINEL)
        cand = b.neighbors[idx]                 # [F, D]
        cand = jnp.where(hit[:, None], cand, SENTINEL)
    else:
        hit = member_mask(b.src, frontier)      # [M]
        cand = jnp.where(hit[:, None], b.neighbors, SENTINEL)
    return cand.reshape(-1)


def expand(adj: DeviceAdjacency, frontier: jax.Array,
           out_size: int) -> jax.Array:
    """One BFS level: union of all neighbors of `frontier`.

    `frontier` MUST be sorted (SENTINEL-padded): the bucket membership
    test binary-searches into it when the frontier is larger than the
    bucket. Host entry points (device_cache.expand_np, bfs_reach) sort.

    Result is a padded sorted UID vector of static length `out_size`
    (truncates if the true union exceeds it — caller sizes via
    `max_expansion`). Replaces the reference's per-uid goroutine loop +
    MergeSorted heap (worker/task.go:581, algo/uidlist.go:354) with one
    gather + one sort.
    """
    parts = [_bucket_candidates(frontier, b) for b in adj.buckets]
    if not parts:
        return jnp.full((out_size,), SENTINEL, dtype=jnp.uint32)
    flat = jnp.sort(jnp.concatenate(parts))
    prev = jnp.concatenate(
        [jnp.full((1,), SENTINEL, dtype=flat.dtype), flat[:-1]])
    uniq = jnp.where(flat != prev, flat, SENTINEL)
    uniq = compact(uniq)
    if uniq.shape[0] >= out_size:
        return uniq[:out_size]
    return jnp.concatenate(
        [uniq, jnp.full((out_size - uniq.shape[0],), SENTINEL,
                        dtype=jnp.uint32)])


def max_expansion(adj: DeviceAdjacency, frontier_size: int) -> int:
    """Static bound on expand() output size for a frontier of F uids:
    the union can never exceed the distinct-destination count, nor the
    per-bucket work bound."""
    total = sum(min(b.src.shape[0], frontier_size) * b.degree
                for b in adj.buckets)
    cap = pad_to(adj.n_dst or adj.n_edges)
    return max(8, min(total, cap))


def count_gather(adj: DeviceAdjacency, uids: jax.Array) -> jax.Array:
    """Per-uid out-degree (0 for uids without the predicate); `uids`
    must be sorted (lookup_idx precondition).
    Ref: count-index reads (posting/index.go:284 updateCount)."""
    idx = jnp.clip(lookup_idx(adj.src_uids, uids), 0,
                   adj.src_uids.shape[0] - 1)
    hit = (adj.src_uids[idx] == uids) & (uids != SENTINEL)
    return jnp.where(hit, adj.degrees[idx], 0)


def has_uids(adj: DeviceAdjacency) -> jax.Array:
    """All uids carrying this predicate — the has() root function
    (ref worker/task.go:2075 handleHasFunction)."""
    return adj.src_uids


# -- value postings ----------------------------------------------------------


# int64 is unavailable on device without jax_enable_x64 (jnp silently
# downcasts to int32), so the device never sees raw sort keys: it holds
# order-preserving int32 RANKS into the host-side sorted unique-key
# table. Ordering and range selection are exact; raw-key bounds resolve
# to rank bounds with one host searchsorted.
RANK_MISSING = np.int32(2**31 - 1)


@dataclass
class DeviceValues:
    """Scalar predicate's sortable view: aligned (uid -> key rank) plus
    the rank-sorted permutation for range scans."""

    uids: jax.Array          # [N] uint32 sorted, SENTINEL padded
    ranks: jax.Array         # [N] int32 aligned (pad = RANK_MISSING)
    ranks_sorted: jax.Array  # [N] int32 sorted
    uids_by_key: jax.Array   # [N] uint32 aligned to ranks_sorted
    host_keys: np.ndarray    # [U] int64 sorted unique raw keys (host)
    n: int = 0               # real (unpadded) uid count
    # Dense uid -> rank table when the tablet's uid range is compact
    # (span <= max(2^20, 4n)): rank_lut[uid - lut_base] == rank, holes
    # hold RANK_MISSING. Turns the per-candidate rank gather into ONE
    # indexed load instead of a log2(N)-round binary search — the
    # difference between the fused page kernel winning and losing on
    # backends where searchsorted lowers to a sequential scan.
    rank_lut: jax.Array | None = None
    lut_base: jax.Array | None = None  # scalar uint32


# uid-span budget multiplier and floor for materializing rank_lut
_LUT_SPAN_FLOOR = 1 << 20
_LUT_SPAN_MULT = 4


def build_values(pairs: dict[int, int]) -> DeviceValues:
    """Host: {uid -> int64 sort key} -> DeviceValues."""
    n = len(pairs)
    n_pad = pad_to(n)
    uids = np.full(n_pad, SENTINEL, np.uint32)
    ranks = np.full(n_pad, RANK_MISSING, np.int32)
    host_keys = np.empty(0, np.int64)
    lut = base = None
    if n:
        u = np.fromiter(pairs.keys(), dtype=np.uint32, count=n)
        k = np.fromiter(pairs.values(), dtype=np.int64, count=n)
        order = np.argsort(u, kind="stable")
        host_keys, inv = np.unique(k, return_inverse=True)
        uids[:n] = u[order]
        ranks[:n] = inv[order].astype(np.int32)
        umin = int(u.min())
        span = int(u.max()) - umin + 1
        if span <= max(_LUT_SPAN_FLOOR, _LUT_SPAN_MULT * n):
            table = np.full(pad_to(span), RANK_MISSING, np.int32)
            table[u - np.uint32(umin)] = inv.astype(np.int32)
            lut = jnp.asarray(table)
            base = jnp.asarray(np.uint32(umin))
    by_key = np.lexsort((uids, ranks))
    return DeviceValues(jnp.asarray(uids), jnp.asarray(ranks),
                        jnp.asarray(ranks[by_key]),
                        jnp.asarray(uids[by_key]), host_keys, n,
                        lut, base)


def dv_view(dv: DeviceValues) -> tuple[tuple[jax.Array, jax.Array], bool]:
    """(payload, is_lut) pair for view_ranks: the dense-LUT form when the
    tablet carries one, else the binary-search form. The bool is a
    STATIC trace parameter — callers must thread it into their jit_stage
    statics so LUT and search executables never alias."""
    if dv.rank_lut is not None:
        return (dv.rank_lut, dv.lut_base), True
    return (dv.uids, dv.ranks), False


def view_ranks(cand: jax.Array, view: tuple[jax.Array, jax.Array],
               is_lut: bool, valid: jax.Array) -> jax.Array:
    """Ranks aligned to candidate uids from a dv_view payload; absent or
    invalid candidates get RANK_MISSING. LUT form is one gather; search
    form binary-searches the sorted uid plane (cand must be sorted)."""
    if is_lut:
        lut, lbase = view
        off = cand - lbase  # uint32: wraps huge for cand < base
        in_range = valid & (off < jnp.uint32(lut.shape[0]))
        idx = jnp.clip(off, 0, jnp.uint32(lut.shape[0] - 1)).astype(jnp.int32)
        return jnp.where(in_range, lut[idx], RANK_MISSING)
    du, dr = view
    idx = jnp.clip(lookup_idx(du, cand), 0, du.shape[0] - 1)
    hit = (du[idx] == cand) & valid
    return jnp.where(hit, dr[idx], RANK_MISSING)


def key_gather(dv: DeviceValues, uids: jax.Array,
               missing: int = int(RANK_MISSING)) -> jax.Array:
    """Sort-key ranks for candidate uids; `missing` for absent ones.
    `uids` must be sorted (lookup_idx precondition)."""
    idx = jnp.clip(lookup_idx(dv.uids, uids), 0, dv.uids.shape[0] - 1)
    hit = (dv.uids[idx] == uids) & (uids != SENTINEL)
    return jnp.where(hit, dv.ranks[idx], jnp.int32(missing))


def range_select(dv: DeviceValues, lo, hi,
                 lo_open: bool = False, hi_open: bool = False) -> jax.Array:
    """UIDs whose raw key is in [lo, hi] (open per flags) — le/lt/ge/gt/
    between root functions in one mask + compact. Raw int64 bounds
    become rank bounds on host.
    Ref: worker/tokens.go:113 getInequalityTokens bucket walk."""
    lo_rank = np.searchsorted(dv.host_keys, np.int64(lo),
                              side="right" if lo_open else "left")
    hi_rank = np.searchsorted(dv.host_keys, np.int64(hi),
                              side="left" if hi_open else "right")
    rs = dv.ranks_sorted
    in_range = (rs >= jnp.int32(lo_rank)) & (rs < jnp.int32(hi_rank))
    valid = dv.uids_by_key != SENTINEL
    return compact(jnp.where(in_range & valid, dv.uids_by_key, SENTINEL))


@partial(jax.jit, static_argnames=("descs",))
def multisort(cand: jax.Array, dv_uids: tuple, dv_ranks: tuple,
              descs: tuple) -> jax.Array:
    """Stable multi-key order-by fully on device: gather each order
    attr's rank column for the (sorted, SENTINEL-padded) candidates,
    then ONE lax.sort with the columns as leading keys and the uid
    vector as the final tiebreak — the reference's multiSort
    (worker/sort.go:300) without its per-attr re-sort passes. Missing
    values keep RANK_MISSING so they sink last under asc AND desc
    (the host path's missing-flag-dominates rule); SENTINEL padding
    sinks below real uids via the uid operand."""
    cols = _rank_cols(cand, dv_uids, dv_ranks, descs)
    out = jax.lax.sort(tuple(cols) + (cand,), num_keys=len(cols) + 1)
    return out[-1]


def _rank_cols(cand: jax.Array, dv_uids: tuple, dv_ranks: tuple,
               descs: tuple) -> list:
    """Per-order-attr rank columns aligned with `cand` (missing values
    keep RANK_MISSING so they sink last under asc AND desc — the host
    path's missing-flag-dominates rule)."""
    cols = []
    for du, dr, desc in zip(dv_uids, dv_ranks, descs):
        idx = jnp.clip(lookup_idx(du, cand), 0, du.shape[0] - 1)
        hit = (du[idx] == cand) & (cand != SENTINEL)
        ranks = jnp.where(hit, dr[idx], RANK_MISSING)
        if desc:
            ranks = jnp.where(hit, -ranks, RANK_MISSING)
        cols.append(ranks)
    return cols


def _page_slice(suids, after_uid, offset, window: int, limit=None):
    """Shared paging tail (traced inside the page kernels): after-
    cursor position -> start -> fixed `window` slice. `limit` treats
    cursor positions >= limit as absent. The SENTINEL tail keeps
    dynamic_slice exact for any start <= n_pad (an over-the-end start
    clamps onto pure padding = empty page); callers bound `offset`
    (host guard) so start stays far from int32 overflow."""
    hit_after = suids == after_uid.astype(suids.dtype)
    pos = jnp.argmax(hit_after)
    found = jnp.any(hit_after)
    if limit is not None:
        found = found & (pos < limit)
    start = jnp.where(found, pos + 1, 0) + offset.astype(jnp.int32)
    ext = jnp.concatenate(
        [suids, jnp.full((window,), SENTINEL, suids.dtype)])
    return jax.lax.dynamic_slice(ext, (start,), (window,)), start


@partial(jax.jit, static_argnames=("descs", "window"))
def multisort_page(cand: jax.Array, dv_uids: tuple, dv_ranks: tuple,
                   descs: tuple, window: int, after_uid: jax.Array,
                   offset: jax.Array):
    """multisort + after-cursor + offset + first in ONE dispatch,
    returning only the `window`-sized page instead of the whole sorted
    vector — at the 21M regime the full vector is ~4MB each way over
    the device tunnel while the page is a few KB (q006 device path:
    1.06s -> one RTT). Ref worker/sort.go:177 processSort applying
    offset+count inside the sort request.

    Returns one packed uint32 array [page..., start]: `start` is the
    UNCLAMPED index the page begins at in the sorted stream; the host
    derives the valid length as clip(n_real - start, 0, window). An
    absent after-cursor skips nothing (the host path's semantics)."""
    cols = _rank_cols(cand, dv_uids, dv_ranks, descs)
    suids = jax.lax.sort(tuple(cols) + (cand,),
                         num_keys=len(cols) + 1)[-1]
    page, start = _page_slice(suids, after_uid, offset, window)
    # one packed array = one tunnel fetch: [page..., start]
    return jnp.concatenate(
        [page, start[None].astype(jnp.uint32)])


@partial(jax.jit, static_argnames=("descs", "window"))
def count_filter_sort_page(cand: jax.Array, degrees: jax.Array,
                           lo: jax.Array, hi: jax.Array,
                           dv_uids: tuple, dv_ranks: tuple,
                           descs: tuple, window: int,
                           after_uid: jax.Array, offset: jax.Array):
    """has(A) root + count(A)-threshold filter + order + paginate in
    ONE dispatch over the predicate's RESIDENT adjacency (cand =
    adj.src_uids, degrees aligned): nothing is uploaded and only the
    page comes back (q010's device path was two full-vector round
    trips). Filtered-out uids sink below even missing-value uids via
    a leading exclusion key. Ref worker/task.go:1111 handleCompare
    over the count index + sort.go:177.

    Returns one packed uint32 array [page..., start, n_kept]."""
    keep = (degrees >= lo) & (degrees <= hi) & (cand != SENTINEL)
    excl = jnp.where(keep, jnp.int32(0), jnp.int32(1))
    cols = _rank_cols(cand, dv_uids, dv_ranks, descs)
    suids = jax.lax.sort((excl,) + tuple(cols) + (cand,),
                         num_keys=len(cols) + 2)[-1]
    n_kept = jnp.sum(keep)
    # a cursor uid the filter excluded sank past n_kept: treat it as
    # ABSENT (skip nothing), exactly the host path's absent-uid rule —
    # matching it in the excluded region would return an empty page
    page, start = _page_slice(suids, after_uid, offset, window,
                              limit=n_kept)
    return jnp.concatenate(
        [page, start[None].astype(jnp.uint32),
         n_kept[None].astype(jnp.uint32)])


# Selection geometry of the fused whole-block kernel: candidates
# histogram into FUSED_SEL_BUCKETS primary-rank buckets and at most
# FUSED_SEL_CAP survivors reach the (small, cheap) exact multi-key
# sort. Both are STATIC — the cap bounds the sort operand so the
# executable's cost never scales with the candidate set, only the
# linear passes do. A page that cannot be proven inside the cap
# (boundary-bucket tie mass > cap) makes the kernel report
# sel_count > cap and the executor re-runs the staged chain.
FUSED_SEL_BUCKETS = 4096
FUSED_SEL_CAP = 4096


def fused_rank_page(cand: jax.Array,
                    rank_views: tuple, rank_luts: tuple,
                    rank_los: tuple, rank_his: tuple, rank_negs: tuple,
                    fparts: tuple, set_negs: tuple, set_aligned: bool,
                    fop: str,
                    ord_views: tuple, ord_luts: tuple, descs: tuple,
                    base0: jax.Array, shift: int, window: int,
                    offset: jax.Array):
    """Whole-block chain — filter algebra + multi-key order + offset/
    first page — as ONE traceable program: the fused tier's kernel
    (query/fusion.py jits it through the `jit_stage` seam, which also
    owns the mesh sharding constraints — this function stays pure and
    un-jitted so the seam is the only compile site, dglint DG02).

    Filter leaves come in two forms and fold under `fop` ("none" |
    "and" | "or") with per-leaf negation flags:

      rank leaves — dv_view payloads of the leaf predicate (dense
        rank LUT when the tablet's uid span is compact, else the
        sorted uid/rank planes; `rank_luts` carries the STATIC form
        flags) plus TRACED [lo, hi) rank bounds: eq/ineq on predicates
        whose sort key is injective (int/float/bool/datetime) evaluate
        as a gather + range test, no host index probe and no per-query
        upload; a threshold change re-binds two scalars, ZERO
        recompiles.
      set leaves — host-evaluated leaf sets (string eq, has,
        lang/list predicates), the general fallback form. When
        `set_aligned` (candidates host-known: the common eq-root
        shape) each fpart arrives as a bool mask ALIGNED to cand —
        the membership test already happened in one host searchsorted
        and the device sees a pure vector operand; otherwise (device-
        resident roots) fparts are sorted padded uid vectors and
        membership runs on device.

    Ordering avoids the full-width device sort (O(n log n) comparator
    sorts dwarf every linear pass at 500M-regime candidate counts)
    AND full-width scatters (XLA lowers scatter serially on sub-TPU
    backends; measured 12ms of a 23ms kernel at 2^17 candidates):
    kept candidates bucket by the desc-adjusted PRIMARY order rank
    (missing ranks bucket just past the real ones — the host path's
    missing-sinks-last rule), an unrolled binary search of masked
    REDUCTIONS finds the bucket threshold covering offset+window
    rows, and survivors compact through cumsum + searchsorted +
    gather — every full-width pass is a map or a reduce. Only the
    <= FUSED_SEL_CAP survivors take the exact multi-key lax.sort, and
    secondary order keys gather on the survivor vector alone. Buckets
    are monotone in the primary rank, so the sorted survivors are a
    byte-exact prefix of the staged full ordering — the page slice is
    identical. `base0` recenters desc-negated ranks (traced: domain
    growth re-binds, only a `shift` change recompiles).

    Returns one packed uint32 array [page..., sel_count, n_kept]; a
    sel_count > FUSED_SEL_CAP means the boundary tie mass overflowed
    the cap and the caller must use the staged chain."""
    valid = cand != SENTINEL
    masks = []
    for view, is_lut, lo, hi in zip(rank_views, rank_luts, rank_los,
                                    rank_his):
        r = view_ranks(cand, view, is_lut, valid)
        masks.append((r != RANK_MISSING) & (r >= lo) & (r < hi))
    for fp in fparts:
        masks.append((fp & valid) if set_aligned
                     else member_mask(cand, fp))
    if fop == "and":
        keep = valid
        for m, neg in zip(masks, rank_negs + set_negs):
            keep = keep & (~m if neg else m)
    elif fop == "or":
        hit = jnp.zeros(cand.shape[0], bool)
        for m, neg in zip(masks, rank_negs + set_negs):
            hit = hit | (~m if neg else m)
        keep = valid & hit
    else:
        keep = valid
    keep = keep & valid  # a negated leaf must never resurrect padding
    n_kept = jnp.sum(keep)

    nb = jnp.int32(FUSED_SEL_BUCKETS)
    c0 = view_ranks(cand, ord_views[0], ord_luts[0], valid)
    if descs[0]:
        c0 = jnp.where(c0 == RANK_MISSING, c0, -c0)
    miss0 = c0 == RANK_MISSING
    # miss0 rows shift from base0 (not RANK_MISSING - base0, which
    # overflows int32 under a desc recenter) and rebucket to nb after
    b = jnp.clip((jnp.where(miss0, base0, c0) - base0) >> shift,
                 0, nb - 1)
    b = jnp.where(miss0, nb, b)
    b = jnp.where(keep, b, nb + 1)
    # smallest bucket threshold covering offset+window kept rows
    # (= searchsorted-left of the bucket cumulative), found by an
    # UNROLLED binary search of masked reductions — no histogram
    # scatter. Dropped rows sit in bucket nb+1, outside every probe.
    target = offset + jnp.int32(window)
    lo_t = jnp.int32(0)
    hi_t = nb
    for _ in range(FUSED_SEL_BUCKETS.bit_length()):
        open_ = lo_t < hi_t
        mid = (lo_t + hi_t) >> 1
        cnt = jnp.sum(b <= mid, dtype=jnp.int32)
        pred = cnt >= target
        hi_t = jnp.where(open_ & pred, mid, hi_t)
        lo_t = jnp.where(open_ & ~pred, mid + 1, lo_t)
    thresh = lo_t
    sel = keep & (b <= thresh)
    # scatter-free compaction: survivor o (1-based) lives at the first
    # index whose selection prefix sum reaches o; one sorted-query
    # searchsorted + gather replaces the serial scatter
    pos = jnp.cumsum(sel.astype(jnp.int32))
    sel_count = pos[-1]
    sidx = jnp.clip(
        jnp.searchsorted(pos, jnp.arange(1, FUSED_SEL_CAP + 1,
                                         dtype=jnp.int32),
                         side="left"),
        0, cand.shape[0] - 1)
    got = jnp.arange(1, FUSED_SEL_CAP + 1, dtype=jnp.int32) <= sel_count
    # compaction preserves cand's ascending order, so the survivor
    # vector satisfies the sorted-query precondition of the search-
    # form gathers below; unfilled slots carry RANK_MISSING keys +
    # SENTINEL uid and the uid operand sinks them last
    out_u = jnp.where(got, cand[sidx], SENTINEL)
    svalid = out_u != SENTINEL
    outs = []
    for view, is_lut, desc in zip(ord_views, ord_luts, descs):
        r = view_ranks(out_u, view, is_lut, svalid)
        if desc:
            r = jnp.where(r == RANK_MISSING, r, -r)
        outs.append(r)
    suids = jax.lax.sort(tuple(outs) + (out_u,),
                         num_keys=len(outs) + 1)[-1]
    ext = jnp.concatenate(
        [suids, jnp.full((window,), SENTINEL, suids.dtype)])
    page = jax.lax.dynamic_slice(ext, (offset.astype(jnp.int32),),
                                 (window,))
    return jnp.concatenate(
        [page, sel_count[None].astype(jnp.uint32),
         n_kept[None].astype(jnp.uint32)])


@partial(jax.jit, static_argnames=("k", "desc"))
def order_topk(dv_uids, dv_ranks, cand: jax.Array, k: int,
               desc: bool = False):
    """First-k of `cand` ordered by value rank (uid tiebreak), returning
    (uids, valid_count). Ranks come from a DeviceValues view.

    Ref: worker/sort.go:412 processSort — the index-bucket walk +
    intersect per bucket becomes gather + one argsort; lax.sort's
    multi-operand form gives the stable uid tiebreak. `cand` must be
    a sorted padded uid vector (lookup_idx precondition).
    """
    idx = jnp.clip(lookup_idx(dv_uids, cand), 0, dv_uids.shape[0] - 1)
    hit = (dv_uids[idx] == cand) & (cand != SENTINEL)
    ranks = jnp.where(hit, dv_ranks[idx], RANK_MISSING)
    if desc:
        ranks = jnp.where(hit, -ranks, RANK_MISSING)
    # sort (rank, uid) pairs; absent uids (RANK_MISSING) sink to the end
    sranks, suids = jax.lax.sort((ranks, cand), num_keys=2)
    return suids[:k], jnp.minimum(jnp.sum(hit), k)
