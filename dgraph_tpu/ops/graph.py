"""Device-resident adjacency + expansion kernels.

This is the TPU re-design of the reference's posting-list fan-out hot loop
(worker/task.go:581 handleUidPostings: per-UID goroutines doing Badger
reads + codec decode + per-list intersect). Here a whole predicate
("tablet") lives in HBM as degree-bucketed padded neighbor matrices, and
one jitted call expands an entire frontier level:

    rows    = searchsorted(bucket.src, frontier)        (vectorized lookup)
    cand    = bucket.neighbors[rows]                    (one batched gather)
    next    = sort+unique(concat over buckets)          (merge)

Degree bucketing bounds padding waste: a src uid lands in the bucket whose
width is the next power of two >= its degree, so padding is < 2x and each
bucket's gather is a dense [F, D] tile — MXU/VPU-friendly, no ragged
shapes inside jit.  The reference's analogue of "one list too big for a
node" (multi-part posting lists, posting/list.go:1149) maps to splitting a
bucket row across the mesh's uid axis — see parallel/.

Value postings (for order-by and inequality) live as two aligned sorted
views so both directions are one searchsorted: by-uid (gather a
candidate's sort key) and by-key (range select for le/ge/between).
Ref: worker/sort.go:177 sortWithIndex + worker/tokens.go:113
getInequalityTokens, re-designed as array kernels instead of index-bucket
walks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.uidvec import SENTINEL, compact, member_mask, pad_to

INT64_MAX = np.int64(2**63 - 1)


@dataclass
class AdjBucket:
    """One degree class of a predicate's adjacency."""

    src: jax.Array        # [M] uint32 sorted, SENTINEL padded
    neighbors: jax.Array  # [M, D] uint32, SENTINEL padded
    degree: int           # D


@dataclass
class DeviceAdjacency:
    """A predicate's full edge set on device.

    src_uids/degrees give O(log N) per-frontier-element count lookup
    (ref worker/task.go handleHasFunction + count index reads).
    """

    src_uids: jax.Array   # [N] uint32 sorted, SENTINEL padded
    degrees: jax.Array    # [N] int32 aligned to src_uids
    buckets: list[AdjBucket] = field(default_factory=list)
    n_edges: int = 0
    n_dst: int = 0        # distinct destination uids (bounds any union)

    @property
    def shape_sig(self):
        return (self.src_uids.shape[0],
                tuple((b.src.shape[0], b.degree) for b in self.buckets))


def build_adjacency(edges: dict[int, np.ndarray],
                    min_degree_bucket: int = 8) -> DeviceAdjacency:
    """Host: {src_uid -> sorted dst uint32 array} -> DeviceAdjacency.

    Runs at rollup time (the analogue of posting.List.Rollup,
    posting/list.go:708): the committed state is re-packed into dense
    device tiles.
    """
    srcs = np.fromiter(edges.keys(), dtype=np.uint32, count=len(edges))
    order = np.argsort(srcs, kind="stable")
    srcs = srcs[order]
    degs = np.fromiter((len(edges[int(s)]) for s in srcs), dtype=np.int32,
                       count=len(srcs))

    n_pad = pad_to(len(srcs))
    src_pad = np.full(n_pad, SENTINEL, np.uint32)
    src_pad[: len(srcs)] = srcs
    deg_pad = np.zeros(n_pad, np.int32)
    deg_pad[: len(srcs)] = degs

    buckets: list[AdjBucket] = []
    n_edges = int(degs.sum())
    if len(srcs):
        caps = np.maximum(min_degree_bucket,
                          2 ** np.ceil(np.log2(np.maximum(degs, 1))).astype(np.int64))
        for cap in sorted(set(caps.tolist())):
            sel = srcs[caps == cap]
            m_pad = pad_to(len(sel))
            bsrc = np.full(m_pad, SENTINEL, np.uint32)
            bsrc[: len(sel)] = sel
            nb = np.full((m_pad, int(cap)), SENTINEL, np.uint32)
            for i, s in enumerate(sel):
                dst = edges[int(s)]
                nb[i, : len(dst)] = dst
            buckets.append(AdjBucket(jnp.asarray(bsrc), jnp.asarray(nb),
                                     int(cap)))
    n_dst = 0
    if edges:
        n_dst = len(np.unique(np.concatenate(
            [np.asarray(v) for v in edges.values()])))
    return DeviceAdjacency(jnp.asarray(src_pad), jnp.asarray(deg_pad),
                           buckets, n_edges, n_dst)


def _bucket_candidates(frontier: jax.Array, b: AdjBucket) -> jax.Array:
    """Flat (unsorted, SENTINEL-masked) neighbor candidates of `frontier`
    rows present in bucket `b`.

    Two duals of the same lookup, chosen at trace time by static shape:
      frontier smaller than bucket  -> gather rows for each frontier uid
                                       ([F, D] work)
      bucket smaller than frontier  -> mask bucket rows that appear in
                                       the frontier ([M, D] work)
    Work per hop is thus bounded by min(F, M) * D per bucket — a large
    frontier can never blow past the bucket's own edge count.
    """
    F = frontier.shape[0]
    M = b.src.shape[0]
    if F <= M:
        idx = jnp.clip(jnp.searchsorted(b.src, frontier), 0, M - 1)
        hit = (b.src[idx] == frontier) & (frontier != SENTINEL)
        cand = b.neighbors[idx]                 # [F, D]
        cand = jnp.where(hit[:, None], cand, SENTINEL)
    else:
        hit = member_mask(b.src, frontier)      # [M]
        cand = jnp.where(hit[:, None], b.neighbors, SENTINEL)
    return cand.reshape(-1)


def expand(adj: DeviceAdjacency, frontier: jax.Array,
           out_size: int) -> jax.Array:
    """One BFS level: union of all neighbors of `frontier`.

    `frontier` MUST be sorted (SENTINEL-padded): the bucket membership
    test binary-searches into it when the frontier is larger than the
    bucket. Host entry points (device_cache.expand_np, bfs_reach) sort.

    Result is a padded sorted UID vector of static length `out_size`
    (truncates if the true union exceeds it — caller sizes via
    `max_expansion`). Replaces the reference's per-uid goroutine loop +
    MergeSorted heap (worker/task.go:581, algo/uidlist.go:354) with one
    gather + one sort.
    """
    parts = [_bucket_candidates(frontier, b) for b in adj.buckets]
    if not parts:
        return jnp.full((out_size,), SENTINEL, dtype=jnp.uint32)
    flat = jnp.sort(jnp.concatenate(parts))
    prev = jnp.concatenate(
        [jnp.full((1,), SENTINEL, dtype=flat.dtype), flat[:-1]])
    uniq = jnp.where(flat != prev, flat, SENTINEL)
    uniq = compact(uniq)
    if uniq.shape[0] >= out_size:
        return uniq[:out_size]
    return jnp.concatenate(
        [uniq, jnp.full((out_size - uniq.shape[0],), SENTINEL,
                        dtype=jnp.uint32)])


def max_expansion(adj: DeviceAdjacency, frontier_size: int) -> int:
    """Static bound on expand() output size for a frontier of F uids:
    the union can never exceed the distinct-destination count, nor the
    per-bucket work bound."""
    total = sum(min(b.src.shape[0], frontier_size) * b.degree
                for b in adj.buckets)
    cap = pad_to(adj.n_dst or adj.n_edges)
    return max(8, min(total, cap))


def count_gather(adj: DeviceAdjacency, uids: jax.Array) -> jax.Array:
    """Per-uid out-degree (0 for uids without the predicate).
    Ref: count-index reads (posting/index.go:284 updateCount)."""
    idx = jnp.clip(jnp.searchsorted(adj.src_uids, uids), 0,
                   adj.src_uids.shape[0] - 1)
    hit = (adj.src_uids[idx] == uids) & (uids != SENTINEL)
    return jnp.where(hit, adj.degrees[idx], 0)


def has_uids(adj: DeviceAdjacency) -> jax.Array:
    """All uids carrying this predicate — the has() root function
    (ref worker/task.go:2075 handleHasFunction)."""
    return adj.src_uids


# -- value postings ----------------------------------------------------------


@dataclass
class DeviceValues:
    """Scalar predicate's sortable view: aligned (uid -> key) plus the
    key-sorted permutation for range scans."""

    uids: jax.Array          # [N] uint32 sorted, SENTINEL padded
    keys: jax.Array          # [N] int64, aligned to uids (pad = INT64_MAX)
    keys_sorted: jax.Array   # [N] int64 sorted
    uids_by_key: jax.Array   # [N] uint32 aligned to keys_sorted


def build_values(pairs: dict[int, int]) -> DeviceValues:
    """Host: {uid -> int64 sort key} -> DeviceValues."""
    n = len(pairs)
    n_pad = pad_to(n)
    uids = np.full(n_pad, SENTINEL, np.uint32)
    keys = np.full(n_pad, INT64_MAX, np.int64)
    if n:
        u = np.fromiter(pairs.keys(), dtype=np.uint32, count=n)
        k = np.fromiter(pairs.values(), dtype=np.int64, count=n)
        order = np.argsort(u, kind="stable")
        uids[:n] = u[order]
        keys[:n] = k[order]
    by_key = np.lexsort((uids, keys))
    return DeviceValues(jnp.asarray(uids), jnp.asarray(keys),
                        jnp.asarray(keys[by_key]),
                        jnp.asarray(uids[by_key]))


def key_gather(dv: DeviceValues, uids: jax.Array,
               missing: int = int(INT64_MAX)) -> jax.Array:
    """Sort keys for candidate uids; `missing` for absent ones."""
    idx = jnp.clip(jnp.searchsorted(dv.uids, uids), 0, dv.uids.shape[0] - 1)
    hit = (dv.uids[idx] == uids) & (uids != SENTINEL)
    return jnp.where(hit, dv.keys[idx], jnp.int64(missing))


def range_select(dv: DeviceValues, lo, hi,
                 lo_open: bool = False, hi_open: bool = False) -> jax.Array:
    """UIDs whose key is in [lo, hi] (open per flags) — le/lt/ge/gt/between
    root functions in one searchsorted + mask + sort.
    Ref: worker/tokens.go:113 getInequalityTokens bucket walk."""
    lo = jnp.int64(lo)
    hi = jnp.int64(hi)
    ks = dv.keys_sorted
    in_range = (ks > lo if lo_open else ks >= lo) & \
               (ks < hi if hi_open else ks <= hi)
    valid = dv.uids_by_key != SENTINEL
    return compact(jnp.where(in_range & valid, dv.uids_by_key, SENTINEL))


@partial(jax.jit, static_argnames=("k", "desc"))
def order_topk(dv_uids, dv_keys, cand: jax.Array, k: int,
               desc: bool = False):
    """First-k of `cand` ordered by value key (uid tiebreak), returning
    (uids, valid_count). Keys come from key_gather'd arrays.

    Ref: worker/sort.go:412 processSort — the index-bucket walk +
    intersect per bucket becomes gather + one argsort; lax.sort's
    multi-operand form gives the stable uid tiebreak.
    """
    idx = jnp.clip(jnp.searchsorted(dv_uids, cand), 0, dv_uids.shape[0] - 1)
    hit = (dv_uids[idx] == cand) & (cand != SENTINEL)
    keys = jnp.where(hit, dv_keys[idx], INT64_MAX)
    if desc:
        keys = jnp.where(hit, -keys, INT64_MAX)
    # sort (key, uid) pairs; absent uids (INT64_MAX) sink to the end
    skeys, suids = jax.lax.sort((keys, cand), num_keys=2)
    return suids[:k], jnp.minimum(jnp.sum(hit), k)
