"""Device kernels: the hot data plane of the framework.

Everything here operates on *padded sorted UID vectors* (see uidvec) and is
jit/vmap-friendly: static shapes, masked ops, no data-dependent Python
control flow.
"""

from dgraph_tpu.ops.uidvec import (
    SENTINEL,
    UID_DTYPE,
    from_numpy,
    to_numpy,
    pad_to,
    count,
    compact,
    intersect,
    union,
    difference,
    member_mask,
    merge_many,
    intersect_many,
    first_k,
)
