"""Sorted-UID vector kernels — the TPU equivalent of the reference's
``algo/uidlist.go`` (IntersectWith/IntersectSorted/MergeSorted/Difference,
ref algo/uidlist.go:137,287,354,322) and the decode side of
``codec/codec.go``.

Representation
--------------
A UID set lives on device as a 1-D ``uint32`` array of static length in
which valid UIDs are sorted ascending and all padding slots hold
``SENTINEL`` (0xFFFFFFFF).  Because the sentinel is the maximum value, the
*whole* array is sorted — every kernel below exploits that invariant:

  * membership is one vectorized binary search (``searchsorted``),
  * compaction after masking is one ``sort``,
  * k-way merge is concat + sort + adjacent-unique (no heap — the
    reference's uint64Heap at algo/heap.go:39 becomes a single XLA sort,
    which maps onto the TPU's sorting networks instead of branchy
    pointer-chasing).

UID width: the reference uses uint64 UIDs. On TPU, 64-bit integer ops are
emulated, so the device plane works in uint32 with a per-tablet 32-bit base
(the reference's own UidPack blocks guarantee a shared high word — the
"32 MSB block boundary" rule at codec/codec.go:43-109 — so this matches its
design, not just its behavior).  The host layer (storage/) owns full-width
UIDs and rebases before upload.  0xFFFFFFFF is reserved as padding and may
not be a live UID low-word.

All functions are pure and shape-polymorphic only in the Python sense: each
distinct input length compiles once.  Callers should bucket lengths to
powers of two (see pad_to) to bound recompiles.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

UID_DTYPE = jnp.uint32
SENTINEL = np.uint32(0xFFFFFFFF)


def _ceil_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


def pad_to(n: int, minimum: int = 8) -> int:
    """Bucketed padded length for a set of n UIDs: next power of two,
    floored at `minimum`. Bounds the number of distinct compiled shapes."""
    return max(minimum, _ceil_pow2(n))


def from_numpy(uids: np.ndarray, size: int | None = None) -> jax.Array:
    """Host sorted uint32 UIDs -> padded device vector."""
    uids = np.asarray(uids, dtype=np.uint32)
    if size is None:
        size = pad_to(len(uids))
    if len(uids) > size:
        raise ValueError(f"{len(uids)} uids exceed padded size {size}")
    out = np.full(size, SENTINEL, dtype=np.uint32)
    out[: len(uids)] = uids
    return jnp.asarray(out)


def to_numpy(vec: jax.Array) -> np.ndarray:
    """Padded device vector -> compact host numpy array (drops padding)."""
    arr = np.asarray(vec)
    return arr[arr != SENTINEL]


def count(a: jax.Array) -> jax.Array:
    """Number of valid UIDs. Ref: codec.ExactLen (codec/codec.go:334)."""
    return jnp.sum(a != SENTINEL, dtype=jnp.int32)


def compact(a: jax.Array) -> jax.Array:
    """Re-establish the sorted/padded invariant after masking: one sort."""
    return jnp.sort(a)


def _sort_backend() -> bool:
    """True when comparator sorts are the fast membership lowering
    (TPU sorting networks); False on CPU, where XLA's generic
    single-thread comparator sort loses to searchsorted's binary-scan
    lowering by ~50x at every size that matters. Backend is fixed per
    process, so the verdict is a constant fold inside traces."""
    return jax.default_backend() != "cpu"


def member_mask(a: jax.Array, b: jax.Array) -> jax.Array:
    """Boolean mask over `a`: a[i] valid and present in `b`.

    Replaces the reference's per-pair lin/jump/bin strategy switch
    (algo/uidlist.go:151-159) with a co-sort: jnp.searchsorted's scan
    lowering is catastrophically slow on TPU (measured 1.8s where two
    stable lax.sorts finish in single-digit ms at 8x2^20), so
    membership is ONE two-operand key sort over concat(a, b) with an
    origin flag + original index as payloads, an adjacency check
    (valid because uid vectors are duplicate-free by invariant;
    sentinels are excluded explicitly), and a second key sort on the
    original index to restore a's order — sorts map onto the TPU's
    sorting networks, branch-free.

    On CPU the trade inverts (generic comparator sorts are the slow
    path there), so membership gathers through searchsorted instead.
    """
    if not _sort_backend():
        idx = jnp.clip(jnp.searchsorted(b, a), 0, b.shape[0] - 1)
        return (b[idx] == a) & (a != SENTINEL)
    n = a.shape[0]
    c = jnp.concatenate([a, b])
    flag = jnp.concatenate([
        jnp.ones(n, jnp.uint32),
        jnp.zeros(b.shape[0], jnp.uint32)])
    idx = jnp.concatenate([
        jnp.arange(n, dtype=jnp.uint32),
        jnp.full(b.shape[0], n, jnp.uint32)])
    cs, fs, ix = jax.lax.sort((c, flag, idx), dimension=0, num_keys=1)
    pad = jnp.full((1,), SENTINEL, dtype=cs.dtype)
    one = jnp.ones((1,), jnp.uint32)
    nxt = jnp.concatenate([cs[1:], pad])
    prv = jnp.concatenate([pad, cs[:-1]])
    fnx = jnp.concatenate([fs[1:], one])
    fpv = jnp.concatenate([one, fs[:-1]])
    hit = (((nxt == cs) & (fnx == 0)) | ((prv == cs) & (fpv == 0))) \
        & (fs == 1) & (cs != SENTINEL)
    # restore a's order: sort hits by original index (b rows key to n,
    # landing past every a row)
    _, hit_in_order = jax.lax.sort(
        (ix, hit.astype(jnp.uint32)), dimension=0, num_keys=1)
    return hit_in_order[:n].astype(bool)


def sorted_lookup(table: jax.Array, q: jax.Array) -> jax.Array:
    """Left-insertion indices of SORTED queries `q` in sorted `table`
    (what jnp.searchsorted returns), via the same co-sort trick as
    member_mask: in the stable key-sort of concat(q, table), a q-row's
    position minus its own q-rank equals the number of table elements
    strictly below it. Two lax.sorts replace the scan lowering that is
    pathologically slow on TPU for large query vectors."""
    n = q.shape[0]
    c = jnp.concatenate([q, table])
    ix = jnp.concatenate([
        jnp.arange(n, dtype=jnp.uint32),
        jnp.full(table.shape[0], n, jnp.uint32)])
    _, ixs = jax.lax.sort((c, ix), dimension=0, num_keys=1)
    pos = jnp.arange(c.shape[0], dtype=jnp.uint32)
    bidx = jnp.where(ixs < n, pos - ixs, 0)
    _, out = jax.lax.sort((ixs, bidx), dimension=0, num_keys=1)
    return out[:n].astype(jnp.int32)


# static query size from which the co-sort lookup beats the scan
# lowering of jnp.searchsorted (measured on v5e: scan is fine for
# small frontiers, catastrophic for ~1M-query vectors)
_LOOKUP_COSORT_MIN = 4096


def lookup_idx(table: jax.Array, q: jax.Array) -> jax.Array:
    """searchsorted(table, q), picking the implementation by static
    query size.

    PRECONDITION (unlike jnp.searchsorted): `q` must be sorted
    ascending — the repo-wide padded-sorted-uid-vector invariant. The
    co-sort path computes each query's table rank as (position in the
    co-sorted concat) - (its own q-rank), which underflows to garbage
    for out-of-order queries. Callers passing value-ordered or
    otherwise unsorted vectors must sort first."""
    if q.shape[0] >= _LOOKUP_COSORT_MIN and _sort_backend():
        return sorted_lookup(table, q)
    return jnp.searchsorted(table, q)


def _cosort_hits(a: jax.Array, b: jax.Array):
    """One stable key-sort of concat(a, b) with an origin flag, plus
    the adjacency hit mask for a-rows (a[i] present in b).  The
    building block of the FUSED set ops below: because the co-sorted
    values are already ascending, masking + one single-operand sort
    re-establishes the padded invariant — no order-restore sort and
    no separate compact() (the three-sort pipeline this replaces
    measured ~1.3 GB/s; two sorts with fewer payloads roughly halve
    the HBM traffic per element)."""
    n = a.shape[0]
    c = jnp.concatenate([a, b])
    flag = jnp.concatenate([
        jnp.ones(n, jnp.uint32),
        jnp.zeros(b.shape[0], jnp.uint32)])
    cs, fs = jax.lax.sort((c, flag), dimension=0, num_keys=1)
    pad = jnp.full((1,), SENTINEL, dtype=cs.dtype)
    one = jnp.ones((1,), jnp.uint32)
    nxt = jnp.concatenate([cs[1:], pad])
    prv = jnp.concatenate([pad, cs[:-1]])
    fnx = jnp.concatenate([fs[1:], one])
    fpv = jnp.concatenate([one, fs[:-1]])
    hit = (((nxt == cs) & (fnx == 0)) | ((prv == cs) & (fpv == 0))) \
        & (fs == 1) & (cs != SENTINEL)
    return cs, fs, hit


def intersect(a: jax.Array, b: jax.Array) -> jax.Array:
    """Sorted-set intersection. Ref algo.IntersectWith (algo/uidlist.go:137).

    Result has a's static length.  Always the fused co-sort — a
    binary-search probe of the larger side (the reference's bin pick,
    algo/uidlist.go:151) was measured 7x SLOWER here: XLA's
    searchsorted lowers to a sequential scan on TPU at these query
    sizes (0.09 GB/s vs 0.64 co-sort on the ratio=8 config).
    """
    cs, _fs, hit = _cosort_hits(a, b)
    vals = jnp.where(hit, cs, SENTINEL)
    return jnp.sort(vals)[: a.shape[0]]


def difference(a: jax.Array, b: jax.Array) -> jax.Array:
    """a \\ b. Ref algo.Difference (algo/uidlist.go:322)."""
    cs, fs, hit = _cosort_hits(a, b)
    keep = (fs == 1) & ~hit & (cs != SENTINEL)
    vals = jnp.where(keep, cs, SENTINEL)
    return jnp.sort(vals)[: a.shape[0]]


def union(a: jax.Array, b: jax.Array) -> jax.Array:
    """Sorted-set union with dedup. Ref algo.MergeSorted
    (algo/uidlist.go:354). Result length = |a|+|b| (static)."""
    return merge_many(jnp.concatenate([a, b]).reshape(1, -1))


def merge_many(mat: jax.Array) -> jax.Array:
    """K-way merge + dedup of k padded rows -> one padded vector of length
    k*n.  Ref algo.MergeSorted's uint64Heap loop (algo/uidlist.go:354,
    algo/heap.go:39) re-designed as sort + adjacent-unique."""
    flat = jnp.sort(mat.reshape(-1))
    prev = jnp.concatenate([jnp.full((1,), SENTINEL, dtype=flat.dtype), flat[:-1]])
    first_occurrence = flat != prev
    return compact(jnp.where(first_occurrence, flat, SENTINEL))


def intersect_many(mat: jax.Array) -> jax.Array:
    """Intersection of k padded rows (k static).  Ref algo.IntersectSorted
    (algo/uidlist.go:287), which intersects smallest-first; on device we
    fold pairwise — each fold is one searchsorted+sort, and XLA fuses the
    masking."""
    k = mat.shape[0]
    acc = mat[0]
    for i in range(1, k):
        acc = intersect(acc, mat[i])
    return acc


def first_k(a: jax.Array, k: int, offset: int = 0) -> jax.Array:
    """Pagination: the k-wide window after `offset` of a compact-sorted
    vector, SENTINEL-padded when the window runs off the end — never
    clamped backwards (lax.dynamic_slice clamps its start, which would
    duplicate the previous page's uids on the final page). Ref
    algo.IndexOf-based windowing in query pagination (query.go:2231)."""
    take = max(0, min(k, a.shape[0] - offset))
    pad = jnp.full((k - take,), SENTINEL, a.dtype)
    if not take:
        return pad
    sl = jax.lax.slice_in_dim(a, offset, offset + take)
    return jnp.concatenate([sl, pad]) if k > take else sl
