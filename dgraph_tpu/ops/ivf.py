"""Quantized IVF kernels: the approximate tier of similar_to().

The brute-force tiers (ops/knn.py) score every row; at the 10-100M
regime that is two orders of magnitude too much arithmetic even at
peak MXU FLOP/s. This module implements the coarse-then-rerank recipe
both retrieved papers point at (PAPERS.md):

  TPU-KNN (2206.14286) — keep the distance computation a dense matmul
    so it runs at peak throughput: centroid scoring is a (q, d) x
    (d, nc) dot, candidate scoring a gathered (R, d) int8
    dequant-and-dot, both MXU-shaped (the Pallas tile kernel is
    ops/pallas_kernels.score_int8_pallas; the jitted XLA contraction
    below is the CPU-parity fallback).

  A Faster Generalized Two-Stage Approximate Top-K (2506.04165) —
    budget the approximate stage from a recall target and finish with
    an EXACT reduction over the survivors: here stage one is the IVF
    probe (nprobe lists) + int8 approximate scores, stage two an exact
    float64 re-rank of the top `rerank` survivors, so the only recall
    loss is candidate-set truncation, never score noise.

Index layout (built once per clean base block, storage/vecstore.py):

  centroids  (nc, d) f32   k-means centers, trained on a seeded sample
  order      (n,)   i32    base-block row of clustered slot i — rows
                           sorted by (assigned centroid, row), so one
                           probed list is one CONTIGUOUS slice
  starts     (nc+1,) i64   list offsets into `order`
  codes      (n, d) i8     per-row scalar-quantized residual
                           (row - centroid), clustered order
  scales     (n,)   f32    per-row dequant scale (maxabs/127)
  norms2     (n,)   f32    exact squared L2 of the ORIGINAL rows,
                           clustered order — cosine/euclidean use the
                           true norm, only the dot is approximated

nprobe and the re-rank depth are not knobs the caller must guess:
build() measures recall@k_ref on a held-out sample of base rows
against a blocked exact scan and picks the smallest nprobe on a
doubling ladder that clears the target (conservative default 0.98,
twice the distance to 1.0 of the 0.95 acceptance floor).

Everything is deterministic: seeded rng, stable sorts, fixed-shape
jitted reductions — two builds over the same block byte-match, the
property the snapshot plane's determinism contract leans on.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax

from dgraph_tpu.ops import knn
from dgraph_tpu.utils.metrics import inc_counter

# calibration reference k: nprobe is tuned for recall@K_REF; query-time
# k above k_max (below) falls back to the exact tiers
K_REF = 10
# recall target the build calibrates nprobe against (conservative:
# the acceptance floor is 0.95, the default budget aims past it)
TARGET_RECALL = 0.98
# re-rank depth: max(RERANK_MIN, RERANK_MULT * k) survivors get the
# exact float64 re-rank
RERANK_MULT = 4
RERANK_MIN = 64
# calibration sample size (held-out base rows scored exactly, blocked)
CALIB_QUERIES = 64
# nprobe doubling ladder the calibration walks
NPROBE_LADDER = (4, 8, 16, 32, 64, 128, 256)
# k-means: Lloyd iterations over a seeded sample
KMEANS_ITERS = 6
KMEANS_SAMPLE_PER_LIST = 128
# assignment matmul block (rows per jitted step — bounds peak memory
# at nlist * BLOCK f32 scores)
ASSIGN_BLOCK = 1 << 18


def default_nlist(n: int) -> int:
    """Power-of-two near sqrt(n), floored so the mean list still holds
    enough rows for the coarse quantizer to pay (>= ~32/list), min 8."""
    if n <= 0:
        return 8
    target = int(math.sqrt(n))
    nlist = 1 << max(3, target.bit_length() - 1)
    while nlist * 32 > n and nlist > 8:
        nlist //= 2
    return nlist


def rerank_depth(k: int) -> int:
    return max(RERANK_MIN, RERANK_MULT * int(k))


@dataclass
class IVFIndex:
    """The trained quantized index over one base block (immutable;
    versioned by the owning cache per (base_ts, schema))."""

    dim: int
    nlist: int
    centroids: np.ndarray   # (nc, d) f32
    order: np.ndarray       # (n,) i32
    starts: np.ndarray      # (nc+1,) i64
    codes: np.ndarray       # (n, d) i8
    scales: np.ndarray      # (n,) f32
    norms2: np.ndarray      # (n,) f32
    nprobe: int             # calibrated default
    sample_recall: float    # measured recall@K_REF at `nprobe`
    target_recall: float
    seed: int

    @property
    def n_rows(self) -> int:
        return len(self.order)

    @property
    def nbytes(self) -> int:
        return (self.centroids.nbytes + self.order.nbytes
                + self.starts.nbytes + self.codes.nbytes
                + self.scales.nbytes + self.norms2.nbytes)

    def scanned_rows(self, nprobe: int | None = None) -> int:
        """Expected rows the approximate stage scores per query — the
        planner's per-row cost driver for the quantized tier."""
        p = min(self.nlist, nprobe or self.nprobe)
        return int(round(self.n_rows * p / max(1, self.nlist)))

    def describe(self) -> dict:
        return {"rows": self.n_rows, "dim": self.dim,
                "nlist": self.nlist, "nprobe": self.nprobe,
                "bytes": int(self.nbytes),
                "codeBytes": int(self.codes.nbytes),
                "sampleRecall": round(float(self.sample_recall), 4),
                "targetRecall": float(self.target_recall)}


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------


@jax.jit
def _assign_jit(block, cents, cn2):
    import jax.numpy as jnp
    # nearest centroid by squared L2: argmin ||x||^2 - 2 x.c + ||c||^2
    # (the ||x||^2 term is constant per row — dropped)
    d = jnp.dot(block, cents.T, preferred_element_type=jnp.float32)
    return jnp.argmin(cn2[None, :] - 2.0 * d, axis=1)


def _assign(vecs: np.ndarray, cents: np.ndarray) -> np.ndarray:
    """Blocked nearest-centroid assignment (jitted matmul per block)."""
    import jax.numpy as jnp

    cn2 = jnp.asarray((cents.astype(np.float64) ** 2)
                      .sum(axis=1).astype(np.float32))
    cd = jnp.asarray(cents)
    out = np.empty(len(vecs), np.int32)
    for s in range(0, len(vecs), ASSIGN_BLOCK):
        blk = jnp.asarray(vecs[s:s + ASSIGN_BLOCK])
        out[s:s + ASSIGN_BLOCK] = np.asarray(
            _assign_jit(blk, cd, cn2), np.int32)
    return out


def _kmeans(vecs: np.ndarray, nlist: int, seed: int,
            iters: int = KMEANS_ITERS) -> np.ndarray:
    """Seeded Lloyd's over a deterministic sample; float64 mean
    accumulation (np.add.at) keeps the result order-independent."""
    n, d = vecs.shape
    rng = np.random.default_rng(seed)
    sample_n = min(n, KMEANS_SAMPLE_PER_LIST * nlist)
    sample = vecs if sample_n == n else \
        vecs[np.sort(rng.choice(n, sample_n, replace=False))]
    init = rng.choice(len(sample), nlist, replace=False)
    cents = sample[np.sort(init)].astype(np.float32).copy()
    for _ in range(iters):
        a = _assign(sample, cents)
        sums = np.zeros((nlist, d), np.float64)
        np.add.at(sums, a, sample.astype(np.float64))
        counts = np.bincount(a, minlength=nlist).astype(np.float64)
        nonempty = counts > 0
        cents[nonempty] = (sums[nonempty]
                           / counts[nonempty, None]).astype(np.float32)
        # empty clusters keep their previous center (deterministic)
    return cents


def exact_topk_blocked(vecs: np.ndarray, queries: np.ndarray, k: int,
                       metric: str = "dot",
                       block: int = 1 << 20) -> np.ndarray:
    """Exact top-k indices over an (n, d) block without materializing
    the full (q, n) score matrix — the calibration oracle at 10M+
    rows (f32 accumulate; ties break low-index like every tier).
    Supports dot and cosine (euclidean orders like dot for the
    calibration's near-duplicate queries only — not offered)."""
    if metric not in ("dot", "cosine"):
        raise ValueError(f"unsupported blocked metric {metric!r}")
    q = np.atleast_2d(np.asarray(queries, np.float32))
    nq, n = len(q), len(vecs)
    k = min(k, n)
    qn = np.linalg.norm(q, axis=1).astype(np.float32) \
        if metric == "cosine" else None
    best_s = np.full((nq, k), -np.inf, np.float32)
    best_i = np.zeros((nq, k), np.int64)
    for s in range(0, n, block):
        sc = q @ vecs[s:s + block].T
        if metric == "cosine":
            bn = np.linalg.norm(vecs[s:s + block], axis=1) \
                .astype(np.float32)
            denom = np.outer(qn, bn)
            sc = np.divide(sc, denom, out=np.zeros_like(sc),
                           where=denom > 0)
        cat_s = np.concatenate([best_s, sc], axis=1)
        cat_i = np.concatenate(
            [best_i, np.arange(s, s + sc.shape[1], dtype=np.int64)
             [None, :].repeat(nq, 0)], axis=1)
        part = np.argpartition(-cat_s, k - 1, axis=1)[:, :k]
        ps = np.take_along_axis(cat_s, part, axis=1)
        pi = np.take_along_axis(cat_i, part, axis=1)
        ordr = np.lexsort((pi, -ps), axis=1)
        best_s = np.take_along_axis(ps, ordr, axis=1)
        best_i = np.take_along_axis(pi, ordr, axis=1)
    return best_i


def build(vecs: np.ndarray, *, nlist: int | None = None, seed: int = 0,
          target_recall: float = TARGET_RECALL,
          calibrate: bool = True) -> IVFIndex:
    """Train the quantized index over one clean base block. The block
    is the float32 (n, d) array the exact tiers already score; the
    index adds ~d+9 bytes/row (int8 codes + scale/norm/order) and the
    (nc, d) codebook."""
    vecs = np.ascontiguousarray(vecs, np.float32)
    n, d = vecs.shape
    if n == 0 or d == 0:
        raise ValueError("cannot build an IVF index over an empty block")
    nlist = int(nlist) if nlist else default_nlist(n)
    nlist = max(1, min(nlist, n))
    cents = _kmeans(vecs, nlist, seed)
    assign = _assign(vecs, cents)
    # cluster-order rows: stable sort by (centroid, row) so every list
    # is one contiguous slice and the layout is deterministic
    order = np.argsort(assign, kind="stable").astype(np.int32)
    counts = np.bincount(assign, minlength=nlist)
    starts = np.zeros(nlist + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    # residual quantization runs BLOCKWISE: a full clustered copy +
    # float64 norm temp would cost ~5x the corpus bytes transient,
    # which OOMs exactly at the 10-100M regime this tier targets
    codes = np.empty((n, d), np.int8)
    scales = np.empty(n, np.float32)
    norms2 = np.empty(n, np.float32)
    for s in range(0, n, ASSIGN_BLOCK):
        e = min(n, s + ASSIGN_BLOCK)
        blk = vecs[order[s:e]]
        norms2[s:e] = np.einsum("ij,ij->i", blk, blk,
                                dtype=np.float64).astype(np.float32)
        resid = blk - cents[assign[order[s:e]]]
        sc = (np.abs(resid).max(axis=1) / 127.0).astype(np.float32)
        sc = np.where(sc > 0, sc, np.float32(1.0))
        scales[s:e] = sc
        codes[s:e] = np.rint(resid / sc[:, None]).astype(np.int8)
    ivf = IVFIndex(dim=d, nlist=nlist, centroids=cents, order=order,
                   starts=starts, codes=codes, scales=scales,
                   norms2=norms2, nprobe=min(nlist, NPROBE_LADDER[0]),
                   sample_recall=0.0, target_recall=float(target_recall),
                   seed=int(seed))
    if calibrate and n > K_REF:
        _calibrate(ivf, vecs, seed)
    inc_counter("vector_index_builds_total")
    return ivf


def _calibrate(ivf: IVFIndex, vecs: np.ndarray, seed: int) -> None:
    """Pick the smallest ladder nprobe whose measured recall@K_REF on
    a seeded sample of base rows clears the target; record what was
    achieved so EXPLAIN/tabstats can surface the real budget.
    Calibration runs the DEFAULT serving metric (cosine): on
    heterogeneous-norm data the dot ordering can diverge from the
    cosine one, and a dot-calibrated nprobe would overstate the
    served recall. The sample queries ARE base rows, so each query's
    own row — a guaranteed top-1 hit dead-center its probed list —
    is EXCLUDED from both the oracle and the probe sets: counting it
    would bias recall high and let the calibrated nprobe undershoot
    on real (out-of-corpus) queries."""
    n = len(vecs)
    rng = np.random.default_rng(seed + 1)
    nq = min(CALIB_QUERIES, n)
    rows = np.sort(rng.choice(n, nq, replace=False))
    queries = vecs[rows]
    want = exact_topk_blocked(vecs, queries, K_REF + 1,
                              metric="cosine")
    # rank-ordered true neighbors, self excluded, at most K_REF each
    want_sets = [set([g for g in want[i].tolist()
                      if g != int(rows[i])][:K_REF])
                 for i in range(nq)]
    total = sum(len(s) for s in want_sets)
    best = (ivf.nprobe, 0.0)
    for p in NPROBE_LADDER:
        p = min(p, ivf.nlist)
        idx, _ = search(ivf, vecs, queries, K_REF + 1, "cosine",
                        nprobe=p, count=False)
        hits = 0
        for i in range(nq):
            got = [g for g in idx[i].tolist()
                   if g >= 0 and g != int(rows[i])][:len(want_sets[i])]
            hits += len(set(got) & want_sets[i])
        rec = hits / float(total) if total else 1.0
        if rec > best[1]:
            best = (p, rec)
        if rec >= ivf.target_recall or p >= ivf.nlist:
            best = (p, rec)
            break
    ivf.nprobe, ivf.sample_recall = int(best[0]), float(best[1])


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("nprobe", "metric"))
def _probe_jit(queries, cents, nprobe, metric):
    """Coarse stage: one (q, d) x (d, nc) MXU matmul -> top-nprobe
    list ids per query. The ranking is METRIC-SHAPED:

      euclidean/dot  negated squared distance 2 q.c - ||c||^2 (the
                     ||q||^2 term is per-query constant) — the
                     geometry the k-means partition was built in; a
                     raw dot ranking would favor large-norm centroids
                     over NEAR ones and collapse low-nprobe recall.
      cosine         angular, q.c / ||c|| — scale-INVARIANT in the
                     query, exactly like the metric itself: the
                     euclidean ranking depends on ||q||, so the same
                     direction at a different magnitude would probe
                     different lists and silently fall below the
                     calibrated recall budget.

    The raw dot scores still return: the approximate candidate score
    reconstructs q.x = q.centroid + q.residual from them."""
    import jax.numpy as jnp
    cs = jnp.dot(queries, cents.T, preferred_element_type=jnp.float32)
    cn2 = jnp.sum(cents * cents, axis=1)
    if metric == "cosine":
        rank = cs / jnp.sqrt(jnp.maximum(cn2, 1e-30))[None, :]
    else:
        rank = 2.0 * cs - cn2[None, :]
    _, lists = jax.lax.top_k(rank, nprobe)
    return cs, lists


def _approx_scores_host(ivf: IVFIndex, lists: np.ndarray,
                        cs: np.ndarray, q: np.ndarray,
                        lo: int = 0, hi: int | None = None
                        ) -> tuple[list, list]:
    """Approximate residual-dot scores of every probed candidate,
    grouped by LIST instead of by query: a batch's queries share
    probed lists, so each list's int8 block dequantizes ONCE and
    scores all m sharing queries in one (len, d) x (d, m) sgemm —
    convert bandwidth bounded by the probed fraction of `codes` per
    call, never per query. No row gather happens at all: a probed
    list is one contiguous slice of the clustered layout.

    [lo, hi) restricts scoring to a clustered-slot range (the
    sharded tier's per-shard partition, parallel/dist_knn) — the
    intersection with a list's slice is plain arithmetic.

    Returns per-query (slot-id arrays, approx-dot arrays) parallel
    lists, concat order = (list id, slot) — deterministic."""
    nq, p = lists.shape
    if hi is None:
        hi = ivf.n_rows
    by_list: dict[int, list[int]] = {}
    for qi in range(nq):
        for li in lists[qi]:
            by_list.setdefault(int(li), []).append(qi)
    slot_parts: list[list[np.ndarray]] = [[] for _ in range(nq)]
    dot_parts: list[list[np.ndarray]] = [[] for _ in range(nq)]
    for li in sorted(by_list):
        s = max(lo, int(ivf.starts[li]))
        e = min(hi, int(ivf.starts[li + 1]))
        if e <= s:
            continue
        qis = by_list[li]
        block = ivf.codes[s:e].astype(np.float32)       # dequant once
        dots = block @ q[qis].T                         # (len, m)
        dots *= ivf.scales[s:e, None]
        slots = np.arange(s, e, dtype=np.int64)
        for col, qi in enumerate(qis):
            slot_parts[qi].append(slots)
            # + q . centroid term: approx q.x = q.c + q.residual
            dot_parts[qi].append(dots[:, col] + cs[qi, li])
    return ([np.concatenate(sp) if sp else np.empty(0, np.int64)
             for sp in slot_parts],
            [np.concatenate(dp) if dp else np.empty(0, np.float32)
             for dp in dot_parts])


def _approx_scores_pallas(ivf: IVFIndex, lists: np.ndarray,
                          cs: np.ndarray, q: np.ndarray,
                          interpret: bool | None
                          ) -> tuple[list, list]:
    """The same per-query (slots, approx dots) through the MXU tile
    kernel (ops/pallas_kernels.score_int8_pallas): per query, gather
    the probed slices into one padded int8 block and run the
    dequant-and-dot kernel. The TPU serving path; CPU CI exercises it
    in interpret mode on small corpora (test parity vs the host
    engine)."""
    from dgraph_tpu.ops.pallas_kernels import (
        SCORE_TILE_N, score_int8_pallas,
    )
    import jax.numpy as jnp

    slot_out: list[np.ndarray] = []
    dot_out: list[np.ndarray] = []
    for qi in range(len(lists)):
        parts = []
        cent = []
        for li in lists[qi]:
            s, e = int(ivf.starts[li]), int(ivf.starts[li + 1])
            if e > s:
                parts.append(np.arange(s, e, dtype=np.int64))
                cent.append(np.full(e - s, cs[qi, li], np.float32))
        if not parts:
            slot_out.append(np.empty(0, np.int64))
            dot_out.append(np.empty(0, np.float32))
            continue
        slots = np.concatenate(parts)
        n_pad = -len(slots) % SCORE_TILE_N
        codes_g = ivf.codes[slots]
        if n_pad:
            codes_g = np.concatenate(
                [codes_g, np.zeros((n_pad, ivf.dim), np.int8)])
        dots = np.asarray(score_int8_pallas(
            jnp.asarray(codes_g), jnp.asarray(q[qi][None]),
            interpret=interpret))[0][:len(slots)]
        dot_out.append(dots * ivf.scales[slots]
                       + np.concatenate(cent))
        slot_out.append(slots)
    return slot_out, dot_out


def _cut_top_r(slots: np.ndarray, approx: np.ndarray, r: int
               ) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic top-r truncation by (-approx, slot): every slot
    strictly above the boundary value survives, boundary ties fill by
    LOWEST slot id. O(R) via argpartition — a plain argpartition cut
    would keep an arbitrary tied subset, and the sharded merge
    (parallel/dist_knn) must reproduce this set exactly for its
    parity-by-construction claim to hold on duplicate-vector data."""
    if len(slots) <= r:
        return slots, approx
    part = np.argpartition(-approx, r - 1)[:r]
    v = approx[part].min()
    above = approx > v
    need = r - int(above.sum())
    at_v = approx == v
    tie_keep = at_v & np.isin(slots, np.sort(slots[at_v])[:need])
    keep = above | tie_keep
    return slots[keep], approx[keep]


def _filter_cut(ivf: IVFIndex, slots: np.ndarray, adot: np.ndarray,
                keep_b: np.ndarray | None, qn2: float, metric: str,
                r_depth: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-query tail of the approximate stage — keep-mask, metric
    transform, deterministic (-approx, slot) cut — shared by
    ops/ivf.search and the sharded path (parallel/dist_knn), whose
    parity-by-construction claim depends on this being ONE
    implementation. `keep_b` is the UNPERMUTED base-row mask; it is
    gathered at the probed slots only (O(scanned)) — permuting the
    full mask per query would put an O(n) floor under the sub-linear
    scan the tier exists for."""
    if not len(slots):
        return slots, adot.astype(np.float64)
    if keep_b is not None:
        m = keep_b[ivf.order[slots]]
        slots, adot = slots[m], adot[m]
        if not len(slots):
            return slots, adot.astype(np.float64)
    approx = _metric_transform(ivf, slots, adot, qn2, metric)
    return _cut_top_r(slots, approx, r_depth)


def _rerank_one(ivf: IVFIndex, vecs: np.ndarray, slots: np.ndarray,
                q1: np.ndarray, k: int, metric: str
                ) -> tuple[np.ndarray, np.ndarray]:
    """Exact float64 re-rank of one query's surviving slots ->
    (base rows, scores), shared by the single-device and sharded
    paths. The unique() sort makes subset order == base-row order,
    so topk_host's (-score, subset idx) tiebreak IS (-score, row)."""
    rows = np.unique(ivf.order[slots].astype(np.int64))
    idx, sc = knn.topk_host(vecs[rows], q1[None], k, metric)
    return rows[idx[0]], sc[0]


def _metric_transform(ivf: IVFIndex, slots: np.ndarray,
                      adot: np.ndarray, qn2: float,
                      metric: str) -> np.ndarray:
    """Approximate metric score from the approximate dot + the stored
    EXACT row norms (only the dot term carries quantization error)."""
    if metric == "dot":
        return adot
    n2 = ivf.norms2[slots]
    if metric == "cosine":
        denom = math.sqrt(qn2) * np.sqrt(n2)
        return np.where(denom > 0, adot / np.where(denom > 0, denom, 1),
                        0.0)
    return -(qn2 - 2.0 * adot + n2)  # euclidean, higher = closer


def search(ivf: IVFIndex, vecs: np.ndarray, queries: np.ndarray,
           k: int, metric: str = "cosine",
           keep: np.ndarray | None = None,
           nprobe: int | None = None, rerank: int | None = None,
           use_pallas: bool = False,
           pallas_interpret: bool | None = None,
           count: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Quantized top-k: IVF probe -> int8 approximate scores ->
    exact float64 re-rank of the top `rerank` survivors. Returns
    (idx (q, k'), scores (q, k')) with idx into the BASE block row
    axis; the re-rank runs knn.topk_host (float64, same formula as
    the host-exact tier) on the original vectors, so a surviving row
    carries the exact score up to BLAS summation order and the
    (-score, idx) tiebreak order matches every tier.

    `keep` masks base rows out (MVCC overlay-touched rows, candidate
    filters); masked rows never reach the re-rank."""
    import jax.numpy as jnp

    if metric not in knn.METRICS:
        raise ValueError(f"unknown metric {metric!r}")
    q = np.atleast_2d(np.asarray(queries, np.float32))
    nq = len(q)
    p = min(ivf.nlist, int(nprobe or ivf.nprobe))
    r_depth = int(rerank or rerank_depth(k))
    cs, lists = _probe_jit(jnp.asarray(q), jnp.asarray(ivf.centroids),
                           p, str(metric))
    cs = np.asarray(cs)
    lists = np.asarray(lists, np.int64)
    if use_pallas:
        slot_l, dot_l = _approx_scores_pallas(ivf, lists, cs, q,
                                              pallas_interpret)
    else:
        slot_l, dot_l = _approx_scores_host(ivf, lists, cs, q)
    keep_b = np.asarray(keep, bool) if keep is not None else None
    qn2 = (q.astype(np.float64) ** 2).sum(axis=1)
    out_i = np.full((nq, k), -1, np.int64)
    out_s = np.full((nq, k), -np.inf, np.float64)
    width = 0
    for qi in range(nq):
        slots, _ = _filter_cut(ivf, slot_l[qi], dot_l[qi], keep_b,
                               float(qn2[qi]), metric, r_depth)
        if not len(slots):
            continue
        rws, sc = _rerank_one(ivf, vecs, slots, q[qi], k, metric)
        w = len(rws)
        out_i[qi, :w] = rws
        out_s[qi, :w] = sc
        width = max(width, w)
    if count:
        # count=False keeps build-time calibration's ladder walks out
        # of the serving-rate series
        inc_counter("vector_quantized_searches_total")
    return out_i[:, :width], out_s[:, :width]
