"""UID block codec — TPU re-design of the reference's group-varint delta
codec (codec/codec.go:43-274 Encoder/Decoder, SSE decode via go-groupvarint).

The reference compresses sorted uint64 UID lists as blocks of <=BlockSize
deltas group-varint-encoded against a per-block Base, with the invariant
that all UIDs in a block share their 32 MSBs (codec/codec.go:43).

Bit-twiddling varints are hostile to the MXU/VPU, so the TPU layout is:

  UidPack32:
    bases  : [num_blocks]            uint32  first UID of each block
    deltas : [num_blocks, block_sz]  uint16  successive differences,
                                             0 in padding slots
    counts : [num_blocks]            int32   valid deltas per block (incl.
                                             the implicit base element)

  decode  = bases[:, None] + cumsum(deltas, axis=1)   (associative scan,
            one VPU pass — the reference's per-integer branchy decode loop
            at codec/codec.go:128 becomes a single fused cumsum)

Deltas that overflow uint16 force a new block, mirroring how the reference
starts a new block on a 32-MSB change.  Typical graph posting lists are
locally dense (the reference claims ~13% of raw size, codec/codec.go:281);
uint16 deltas + uint32 bases give 2 bytes/UID asymptotically vs 8 raw.

Encode runs on host (numpy) at rollup time — it is ingest-path, not
query-path.  Decode is the jit-side kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.uidvec import SENTINEL, compact

BLOCK_SIZE = 256  # multiple of the 128-lane VPU; ref uses 256 (wire.go)
_MAX_DELTA = np.uint32(0xFFFF)


@dataclass
class UidPack32:
    """Host-side handle; arrays may be numpy or jax."""

    bases: jax.Array   # [B] uint32
    deltas: jax.Array  # [B, BLOCK_SIZE-1] uint16
    counts: jax.Array  # [B] int32, 1..BLOCK_SIZE
    n: int             # total number of UIDs

    def device(self) -> "UidPack32":
        return UidPack32(
            jnp.asarray(self.bases), jnp.asarray(self.deltas),
            jnp.asarray(self.counts), self.n,
        )

    @property
    def nbytes(self) -> int:
        return (np.asarray(self.bases).nbytes
                + np.asarray(self.deltas).nbytes
                + np.asarray(self.counts).nbytes)


def encode(uids: np.ndarray) -> UidPack32:
    """Sorted uint32 UIDs -> UidPack32. Host-side, vectorized numpy.

    Ref: codec.Encode (codec/codec.go:283) + Encoder.packBlock.
    Block boundaries: every BLOCK_SIZE elements, plus wherever a delta
    exceeds uint16 (analogue of the reference's 32-MSB boundary rule).
    """
    uids = np.asarray(uids, dtype=np.uint32)
    n = len(uids)
    if n == 0:
        return UidPack32(
            np.zeros(0, np.uint32),
            np.zeros((0, BLOCK_SIZE - 1), np.uint16),
            np.zeros(0, np.int32), 0)

    deltas = np.diff(uids.astype(np.uint64)).astype(np.uint32)
    # A block starts at 0, after every big delta, and at BLOCK_SIZE fill.
    big = np.flatnonzero(deltas > _MAX_DELTA) + 1
    starts = [0]
    next_forced = iter(big.tolist() + [n])
    forced = next(next_forced)
    i = 0
    while i < n:
        end = min(i + BLOCK_SIZE, n)
        while forced <= i:
            forced = next(next_forced)
        if forced < end:
            end = forced
        i = end
        if i < n:
            starts.append(i)
    starts_arr = np.asarray(starts, dtype=np.int64)
    ends = np.append(starts_arr[1:], n)
    nb = len(starts_arr)

    bases = uids[starts_arr]
    counts = (ends - starts_arr).astype(np.int32)
    dmat = np.zeros((nb, BLOCK_SIZE - 1), dtype=np.uint16)
    for bi in range(nb):
        s, e = starts_arr[bi], ends[bi]
        if e - s > 1:
            dmat[bi, : e - s - 1] = deltas[s : e - 1].astype(np.uint16)
    return UidPack32(bases, dmat, counts, n)


def decode_padded(pack: UidPack32, size: int) -> jax.Array:
    """UidPack32 -> padded sorted UID vector of static length `size`.

    Ref: codec.Decode / Decoder.unpackBlock (codec/codec.go:319,128).
    One cumsum over the delta matrix; padding slots become SENTINEL via the
    per-block count mask, then one sort re-establishes the invariant.
    """
    bases = jnp.asarray(pack.bases, dtype=jnp.uint32)
    deltas = jnp.asarray(pack.deltas, dtype=jnp.uint32)
    counts = jnp.asarray(pack.counts, dtype=jnp.int32)
    if bases.shape[0] == 0:
        return jnp.full((size,), SENTINEL, dtype=jnp.uint32)
    # [B, BLOCK_SIZE]: base, base+d0, base+d0+d1, ...
    csum = jnp.cumsum(deltas, axis=1, dtype=jnp.uint32)
    vals = jnp.concatenate([bases[:, None], bases[:, None] + csum], axis=1)
    lane = jnp.arange(vals.shape[1], dtype=jnp.int32)[None, :]
    vals = jnp.where(lane < counts[:, None], vals, SENTINEL)
    flat = compact(vals.reshape(-1))
    if flat.shape[0] >= size:
        return flat[:size]
    return jnp.concatenate(
        [flat, jnp.full((size - flat.shape[0],), SENTINEL, dtype=jnp.uint32)])
