"""UID block codec — TPU re-design of the reference's group-varint delta
codec (codec/codec.go:43-274 Encoder/Decoder, SSE decode via go-groupvarint).

The reference compresses sorted uint64 UID lists as blocks of <=BlockSize
deltas group-varint-encoded against a per-block Base, with the invariant
that all UIDs in a block share their 32 MSBs (codec/codec.go:43).

Bit-twiddling varints are hostile to the MXU/VPU, so the TPU layout is:

  UidPack32:
    bases  : [num_blocks]            uint32  first UID of each block
    deltas : [num_blocks, block_sz]  uint16  successive differences,
                                             0 in padding slots
    counts : [num_blocks]            int32   valid deltas per block (incl.
                                             the implicit base element)

  decode  = bases[:, None] + cumsum(deltas, axis=1)   (associative scan,
            one VPU pass — the reference's per-integer branchy decode loop
            at codec/codec.go:128 becomes a single fused cumsum)

Deltas that overflow uint16 force a new block, mirroring how the reference
starts a new block on a 32-MSB change.  Typical graph posting lists are
locally dense (the reference claims ~13% of raw size, codec/codec.go:281);
uint16 deltas + uint32 bases give 2 bytes/UID asymptotically vs 8 raw.

Encode runs on host (numpy) at rollup time — it is ingest-path, not
query-path.  Decode is the jit-side kernel.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # jax is imported lazily: the compressed block plane
    import jax     # below must be usable by engines that never touch XLA

BLOCK_SIZE = 256  # multiple of the 128-lane VPU; ref uses 256 (wire.go)
_MAX_DELTA = np.uint32(0xFFFF)


@dataclass
class UidPack32:
    """Host-side handle; arrays may be numpy or jax."""

    bases: jax.Array   # [B] uint32
    deltas: jax.Array  # [B, BLOCK_SIZE-1] uint16
    counts: jax.Array  # [B] int32, 1..BLOCK_SIZE
    n: int             # total number of UIDs

    def device(self) -> "UidPack32":
        import jax.numpy as jnp

        return UidPack32(
            jnp.asarray(self.bases), jnp.asarray(self.deltas),
            jnp.asarray(self.counts), self.n,
        )

    @property
    def nbytes(self) -> int:
        return (np.asarray(self.bases).nbytes
                + np.asarray(self.deltas).nbytes
                + np.asarray(self.counts).nbytes)


def encode(uids: np.ndarray) -> UidPack32:
    """Sorted uint32 UIDs -> UidPack32. Host-side, vectorized numpy.

    Ref: codec.Encode (codec/codec.go:283) + Encoder.packBlock.
    Block boundaries: every BLOCK_SIZE elements, plus wherever a delta
    exceeds uint16 (analogue of the reference's 32-MSB boundary rule).
    """
    uids = np.asarray(uids, dtype=np.uint32)
    n = len(uids)
    if n == 0:
        return UidPack32(
            np.zeros(0, np.uint32),
            np.zeros((0, BLOCK_SIZE - 1), np.uint16),
            np.zeros(0, np.int32), 0)

    deltas = np.diff(uids.astype(np.uint64)).astype(np.uint32)
    # A block starts at 0, after every big delta, and at BLOCK_SIZE fill.
    big = np.flatnonzero(deltas > _MAX_DELTA) + 1
    starts = [0]
    next_forced = iter(big.tolist() + [n])
    forced = next(next_forced)
    i = 0
    while i < n:
        end = min(i + BLOCK_SIZE, n)
        while forced <= i:
            forced = next(next_forced)
        if forced < end:
            end = forced
        i = end
        if i < n:
            starts.append(i)
    starts_arr = np.asarray(starts, dtype=np.int64)
    ends = np.append(starts_arr[1:], n)
    nb = len(starts_arr)

    bases = uids[starts_arr]
    counts = (ends - starts_arr).astype(np.int32)
    dmat = np.zeros((nb, BLOCK_SIZE - 1), dtype=np.uint16)
    for bi in range(nb):
        s, e = starts_arr[bi], ends[bi]
        if e - s > 1:
            dmat[bi, : e - s - 1] = deltas[s : e - 1].astype(np.uint16)
    return UidPack32(bases, dmat, counts, n)


def decode_padded(pack: UidPack32, size: int) -> jax.Array:
    """UidPack32 -> padded sorted UID vector of static length `size`.

    Ref: codec.Decode / Decoder.unpackBlock (codec/codec.go:319,128).
    One cumsum over the delta matrix; padding slots become SENTINEL via the
    per-block count mask, then one sort re-establishes the invariant.
    """
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import SENTINEL, compact

    bases = jnp.asarray(pack.bases, dtype=jnp.uint32)
    deltas = jnp.asarray(pack.deltas, dtype=jnp.uint32)
    counts = jnp.asarray(pack.counts, dtype=jnp.int32)
    if bases.shape[0] == 0:
        return jnp.full((size,), SENTINEL, dtype=jnp.uint32)
    # [B, BLOCK_SIZE]: base, base+d0, base+d0+d1, ...
    csum = jnp.cumsum(deltas, axis=1, dtype=jnp.uint32)
    vals = jnp.concatenate([bases[:, None], bases[:, None] + csum], axis=1)
    lane = jnp.arange(vals.shape[1], dtype=jnp.int32)[None, :]
    vals = jnp.where(lane < counts[:, None], vals, SENTINEL)
    flat = compact(vals.reshape(-1))
    if flat.shape[0] >= size:
        return flat[:size]
    return jnp.concatenate(
        [flat, jnp.full((size - flat.shape[0],), SENTINEL, dtype=jnp.uint32)])


# ======================================================================
# Compressed block plane: set-algebra operands that stay compressed.
#
# UidPack32 above is a DECODE format (one cumsum -> dense vector).  The
# forms below are OPERAND formats: ops/setops.py intersects/unions them
# without densifying, decoding only blocks that survive descriptor
# skipping ("SIMD Compression and the Intersection of Sorted Integers",
# PAPERS.md; the reference keeps the same at-rest split in codec/ +
# algo/uidlist.go).
#
# A CompressedPack partitions a sorted-unique uint64 uid set into
# 2^16-uid-span blocks keyed by `uid >> 16` (the roaring container
# rule; also the reference's shared-32-MSB block boundary, codec.go:43).
# Each block picks the smallest of three forms by density:
#
#   PACKED  delta + bitpacked lows: per-block descriptor (base = first
#           low uint16, bit width, count); count-1 deltas packed at
#           `width` bits, little-endian bit order.  Sparse blocks.
#   BITMAP  1024 x uint64 little-endian words (8 KiB).  Dense blocks —
#           AND/OR become word ops at vector width.
#   RUN     (start, length-1) uint16 pairs.  Runny blocks (dense
#           consecutive ranges compress to 4 bytes per run).
#
# Encode is host/numpy at export time (rollup-path, like UidPack32);
# the decode/membership kernels are vectorized numpy on host with the
# bitmap word ops mirrored on device (ops/setops.py + the Pallas
# bitmap kernel in ops/pallas_kernels.py).
# ======================================================================

BLOCK_SPAN = 1 << 16          # uid space per block (key = uid >> 16)
BITMAP_WORDS = BLOCK_SPAN // 64   # 1024 uint64 words = 8 KiB
_BITMAP_BYTES = BLOCK_SPAN // 8

FORM_PACKED = 0
FORM_BITMAP = 1
FORM_RUN = 2

# Files allowed to densify compressed packs (CompressedPack.densify /
# decompress / CompressedTokenIndex.probe).  Everything else must keep
# operating on the compressed forms through ops/setops — dglint DG09
# checks eager-decode calls against this registry the same way DG08
# checks metric names, so the memory win cannot silently erode one
# convenient .densify() at a time.
DECODE_SITES = (
    "dgraph_tpu/ops/codec.py",
    "dgraph_tpu/ops/setops.py",
    "dgraph_tpu/query/executor.py",
    "dgraph_tpu/storage/snapshot.py",
    "dgraph_tpu/storage/tablet.py",
)


def _bitpack(vals: np.ndarray, width: int) -> np.ndarray:
    """uint32 values < 2^width -> little-endian packed uint8 bits."""
    if width == 0 or not len(vals):
        return np.zeros(0, np.uint8)
    bits = ((vals[:, None] >> np.arange(width, dtype=np.uint32)) & 1
            ).astype(np.uint8)
    return np.packbits(bits.reshape(-1), bitorder="little")


def _bitunpack(buf: np.ndarray, n: int, width: int) -> np.ndarray:
    """Inverse of _bitpack: n values of `width` bits -> uint32."""
    if n == 0:
        return np.zeros(0, np.uint32)
    if width == 0:
        return np.zeros(n, np.uint32)
    bits = np.unpackbits(buf, count=n * width,
                         bitorder="little").reshape(n, width)
    weights = (np.uint32(1) << np.arange(width, dtype=np.uint32))
    return (bits.astype(np.uint32) * weights).sum(
        axis=1, dtype=np.uint32)


class CompressedPack:
    """One sorted-unique uint64 uid set as adaptive compressed blocks.

    Arrays (aligned per block, keys ascending):
      keys     uint64[B]  block key (uid >> 16)
      forms    uint8[B]   FORM_PACKED / FORM_BITMAP / FORM_RUN
      counts   int64[B]   uids in the block (1..65536)
      widths   uint8[B]   PACKED delta bit width (0 otherwise)
      bases    uint16[B]  PACKED first low value (0 otherwise)
      offsets  int64[B+1] payload byte offsets, 8-byte aligned so
                          BITMAP word views and RUN uint16 views are
                          zero-copy
      sizes    int64[B]   exact payload bytes (offsets include pad)
      payload  uint8[...] per-block payload bytes (see module header)

    `host_resident` marks it as host memory for the tile LRU's
    device/host byte split (engine/tile_cache._tile_bytes)."""

    host_resident = True

    __slots__ = ("keys", "forms", "counts", "widths", "bases",
                 "offsets", "sizes", "payload", "n", "nbytes", "sid")

    def __init__(self, keys, forms, counts, widths, bases, offsets,
                 sizes, payload, n):
        # process-unique id for the decode-block cache: id() recycles
        # after GC, a stale cache hit would corrupt results
        self.sid = _next_sid()
        self.keys = keys
        self.forms = forms
        self.counts = counts
        self.widths = widths
        self.bases = bases
        self.offsets = offsets
        self.sizes = sizes
        self.payload = payload
        self.n = int(n)
        self.nbytes = int(keys.nbytes + forms.nbytes + counts.nbytes
                          + widths.nbytes + bases.nbytes
                          + offsets.nbytes + sizes.nbytes
                          + payload.nbytes)

    def __len__(self) -> int:
        return self.n

    # -- per-block access (ops/setops' kernels) ------------------------

    def block_of(self, key: int) -> int:
        """Index of block `key`, or -1."""
        i = int(np.searchsorted(self.keys, np.uint64(key)))
        if i < len(self.keys) and int(self.keys[i]) == int(key):
            return i
        return -1

    def block_payload(self, bi: int) -> np.ndarray:
        off = int(self.offsets[bi])
        return self.payload[off: off + int(self.sizes[bi])]

    def block_words(self, bi: int) -> np.ndarray:
        """A BITMAP block's 1024 uint64 words, zero-copy (offsets are
        8-byte aligned by construction)."""
        return self.block_payload(bi).view(np.uint64)

    def block_runs(self, bi: int) -> np.ndarray:
        """A RUN block's (start, length-1) uint16 pairs, zero-copy."""
        return self.block_payload(bi).view(np.uint16).reshape(-1, 2)

    def block_lows(self, bi: int, scratch=None) -> np.ndarray:
        """One block's sorted low-16 values as uint32.  With a
        DecodeScratch, decoded blocks land in its bounded block cache
        (read-only to callers): repeated queries over the same warm
        posting blocks skip the unpack entirely, and the pool bound
        caps what decoding can ever hold resident."""
        if scratch is not None:
            got = scratch.cache_get(self.sid, bi)
            if got is None:
                got = self._decode_lows(bi)
                scratch.cache_put(self.sid, bi, got)
            return got
        return self._decode_lows(bi)

    def _decode_lows(self, bi: int) -> np.ndarray:
        form = int(self.forms[bi])
        cnt = int(self.counts[bi])
        buf = self.block_payload(bi)
        if form == FORM_PACKED:
            deltas = _bitunpack(buf, cnt - 1, int(self.widths[bi]))
            out = np.empty(cnt, np.uint32)
            out[0] = self.bases[bi]
            if cnt > 1:
                np.cumsum(deltas, out=out[1:])
                out[1:] += np.uint32(self.bases[bi])
            return out
        if form == FORM_BITMAP:
            bits = np.unpackbits(buf, bitorder="little")
            return np.flatnonzero(bits).astype(np.uint32)
        # FORM_RUN
        runs = self.block_runs(bi)
        starts = runs[:, 0].astype(np.uint32)
        lens = runs[:, 1].astype(np.uint32) + 1
        total = int(lens.sum())
        out = np.empty(total, np.uint32)
        # concat of aranges: index - repeat(start offsets) + starts
        ends = np.cumsum(lens)
        out[:] = np.arange(total, dtype=np.uint32)
        out -= np.repeat((ends - lens).astype(np.uint32), lens)
        out += np.repeat(starts, lens)
        return out

    def block_bitmap(self, bi: int, scratch=None) -> np.ndarray:
        """One block as a 1024-word uint64 bitmap (BITMAP blocks view
        their payload zero-copy; other forms materialize)."""
        form = int(self.forms[bi])
        if form == FORM_BITMAP:
            return self.block_words(bi)
        words = _take_scratch(scratch, BITMAP_WORDS, np.uint64)
        words[:] = 0
        if form == FORM_RUN:
            runs = self.block_runs(bi)
            for s, lm1 in runs.tolist():
                e = s + lm1 + 1
                ws, we = s >> 6, (e - 1) >> 6
                if ws == we:
                    span = ~np.uint64(0) if e - s == 64 \
                        else (np.uint64(1) << np.uint64(e - s)) \
                        - np.uint64(1)
                    words[ws] |= span << np.uint64(s & 63)
                else:
                    words[ws] |= ~np.uint64(0) << np.uint64(s & 63)
                    words[ws + 1: we] = ~np.uint64(0)
                    words[we] |= ~np.uint64(0) >> np.uint64(
                        63 - ((e - 1) & 63))
            return words
        lows = self.block_lows(bi, scratch=None)
        np.bitwise_or.at(words, lows >> 6,
                         np.uint64(1) << (lows & np.uint64(63)))
        return words

    def block_member(self, bi: int, lows: np.ndarray,
                     scratch=None) -> np.ndarray:
        """Bool mask: which `lows` (uint32) are in block `bi` — the
        no-decode membership probe (bitmap bit test / run interval
        probe; PACKED blocks decode, they are the sparse form, via
        the scratch block cache when one is given)."""
        form = int(self.forms[bi])
        if form == FORM_BITMAP:
            words = self.block_words(bi)
            return ((words[lows >> 6] >> (lows.astype(np.uint64)
                                          & np.uint64(63)))
                    & np.uint64(1)).astype(bool)
        if form == FORM_RUN:
            runs = self.block_runs(bi)
            starts = runs[:, 0].astype(np.uint32)
            ends = starts + runs[:, 1] + 1  # exclusive
            i = np.searchsorted(starts, lows, side="right") - 1
            ok = i >= 0
            i = np.maximum(i, 0)
            return ok & (lows < ends[i])
        mine = self.block_lows(bi, scratch=scratch)
        i = np.searchsorted(mine, lows)
        np.minimum(i, max(len(mine) - 1, 0), out=i)
        return mine[i] == lows if len(mine) else \
            np.zeros(len(lows), bool)

    def singleton_mask(self) -> np.ndarray:
        """Bool per block: count == 1. Singleton blocks are always
        PACKED with an empty payload (base IS the low value), so
        consumers vectorize them wholesale — the escape hatch that
        keeps ultra-sparse sets (every block a singleton, descriptor
        overhead dominated) at dense-path speed instead of a
        per-block python walk."""
        return self.counts == 1

    def densify(self, out: np.ndarray | None = None,
                scratch=None) -> np.ndarray:
        """Decode the whole pack to a sorted uint64 uid vector (block
        decodes ride the scratch block cache when given).  THE
        eager-decode seam: calls outside DECODE_SITES are a dglint
        DG09 violation — batch consumers go through ops/setops."""
        if out is None:
            out = np.empty(self.n, np.uint64)
        offs = np.cumsum(self.counts) - self.counts
        sing = self.singleton_mask()
        if sing.any():
            out[offs[sing]] = (self.keys[sing] << np.uint64(16)) \
                | self.bases[sing].astype(np.uint64)
        for bi in np.flatnonzero(~sing).tolist():
            cnt = int(self.counts[bi])
            pos = int(offs[bi])
            lows = self.block_lows(bi, scratch=scratch)
            out[pos: pos + cnt] = (np.uint64(self.keys[bi])
                                   << np.uint64(16)) \
                | lows.astype(np.uint64)
        return out[:self.n]


def _take_scratch(scratch, n: int, dtype) -> np.ndarray:
    if scratch is None:
        return np.empty(n, dtype)
    return scratch.take(n, dtype)


_SID_LOCK = threading.Lock()
_SID = [0]


def _next_sid() -> int:
    with _SID_LOCK:
        _SID[0] += 1
        return _SID[0]


def _encode_block(lows: np.ndarray):
    """sorted-unique uint32 lows (< 2^16) -> (form, width, base,
    payload uint8).  Picks the byte-smallest of the three forms —
    the density-adaptive roaring rule."""
    cnt = len(lows)
    deltas = np.diff(lows)
    n_runs = int((deltas != 1).sum()) + 1 if cnt else 0
    run_bytes = 4 * n_runs
    width = int(deltas.max()).bit_length() if cnt > 1 else 0
    packed_bytes = ((cnt - 1) * width + 7) >> 3
    best = min(run_bytes, packed_bytes, _BITMAP_BYTES)
    if run_bytes == best:
        runs = np.empty((n_runs, 2), np.uint16)
        bounds = np.flatnonzero(deltas != 1)
        starts = np.concatenate(([0], bounds + 1))
        ends = np.concatenate((bounds, [cnt - 1]))
        runs[:, 0] = lows[starts]
        runs[:, 1] = (lows[ends] - lows[starts]).astype(np.uint16)
        return FORM_RUN, 0, 0, runs.reshape(-1).view(np.uint8)
    if packed_bytes == best:
        return (FORM_PACKED, width, int(lows[0]),
                _bitpack(deltas.astype(np.uint32), width))
    words = np.zeros(BITMAP_WORDS, np.uint64)
    np.bitwise_or.at(words, lows >> 6,
                     np.uint64(1) << (lows & np.uint64(63)))
    return FORM_BITMAP, 0, 0, words.view(np.uint8)


def compress(uids: np.ndarray) -> CompressedPack:
    """Sorted-unique uint64 uids -> CompressedPack (host, numpy)."""
    uids = np.asarray(uids, dtype=np.uint64)
    n = len(uids)
    if n == 0:
        return CompressedPack(
            np.zeros(0, np.uint64), np.zeros(0, np.uint8),
            np.zeros(0, np.int64), np.zeros(0, np.uint8),
            np.zeros(0, np.uint16), np.zeros(1, np.int64),
            np.zeros(0, np.int64), np.zeros(0, np.uint8), 0)
    hi = uids >> np.uint64(16)
    keys, starts = np.unique(hi, return_index=True)
    bounds = np.append(starts, n)
    nb = len(keys)
    forms = np.zeros(nb, np.uint8)
    counts = np.zeros(nb, np.int64)
    widths = np.zeros(nb, np.uint8)
    bases = np.zeros(nb, np.uint16)
    offsets = np.zeros(nb + 1, np.int64)
    sizes = np.zeros(nb, np.int64)
    payloads: list[np.ndarray] = []
    blk_counts = np.diff(bounds)
    counts[:] = blk_counts
    # singleton blocks (the ultra-sparse regime) wholesale: PACKED,
    # width 0, empty payload, base = the low value — no per-block
    # encode call
    sing = blk_counts == 1
    bases[sing] = (uids[bounds[:-1][sing]]
                   & np.uint64(0xFFFF)).astype(np.uint16)
    for bi in np.flatnonzero(~sing).tolist():
        lows = uids[bounds[bi]: bounds[bi + 1]].astype(np.uint32) \
            & np.uint32(0xFFFF)
        form, width, base, payload = _encode_block(lows)
        forms[bi] = form
        widths[bi] = width
        bases[bi] = base
        sizes[bi] = len(payload)
        payloads.append(payload)
        padded = (len(payload) + 7) & ~7  # keep offsets 8-aligned
        if padded != len(payload):
            payloads.append(np.zeros(padded - len(payload), np.uint8))
    np.cumsum((sizes + 7) & ~7, out=offsets[1:])
    payload = np.concatenate(payloads) if payloads \
        else np.zeros(0, np.uint8)
    return CompressedPack(keys, forms, counts, widths, bases,
                          offsets, sizes, payload, n)


def decompress(pack: CompressedPack) -> np.ndarray:
    """CompressedPack -> sorted uint64 uid vector (module-level
    densify; same DG09 discipline as CompressedPack.densify)."""
    return pack.densify()


# -- bounded decode scratch pool ---------------------------------------


class DecodeScratch:
    """Per-thread bounded decode pool for the compressed set-algebra
    kernels: a reusable arena for transient intermediates (bitmap
    accumulators, 2^16 counters) plus a bounded LRU of DECODED
    posting blocks, so the queries' lazy decodes land in one small
    pool instead of re-materializing per probe — THE "decode lazily
    per query into a bounded scratch pool" half of the compressed
    tier (the other half is never decoding skipped blocks at all).

    Contracts: a `take()` view is valid until the NEXT take of the
    same arena — callers use it for intermediates consumed
    immediately, never for results that escape the query (results are
    always fresh allocations).  `cache_get`/`cache_put` views are
    READ-ONLY to callers and evict LRU-first past `cache_budget`.
    Requests past `budget_bytes` allocate fresh and are not retained,
    so one adversarial block cannot pin memory; the high-water mark
    is exported as the `codec_scratch_bytes` gauge by the engine's
    stats plane."""

    def __init__(self, budget_bytes: int = 4 << 20,
                 cache_budget: int = 8 << 20):
        self.budget = int(budget_bytes)
        self.cache_budget = int(cache_budget)
        # dglint: guarded-by=_tls:contextvar,high_water:atomic,overflows:atomic
        # (the arena is threading.local — every thread sees only its
        # own cells; the gauges are stats-grade max-folds/counters
        # where a lost update is acceptable)
        self._tls = threading.local()
        self.high_water = 0
        self.overflows = 0

    def _cache(self):
        c = getattr(self._tls, "cache", None)
        if c is None:
            from collections import OrderedDict
            c = self._tls.cache = OrderedDict()
            self._tls.cache_bytes = 0
        return c

    def cache_get(self, sid: int, bi: int):
        c = self._cache()
        got = c.get((sid, bi))
        if got is not None:
            c.move_to_end((sid, bi))
        return got

    def cache_put(self, sid: int, bi: int, arr) -> None:
        if arr.nbytes > self.cache_budget:
            return  # a whole-budget block: serve it, never retain it
        c = self._cache()
        c[(sid, bi)] = arr
        self._tls.cache_bytes += arr.nbytes
        while self._tls.cache_bytes > self.cache_budget:
            _, old = c.popitem(last=False)
            self._tls.cache_bytes -= old.nbytes
        self.high_water = max(self.high_water,
                              self._tls.cache_bytes)

    def take(self, n: int, dtype=np.uint64) -> np.ndarray:
        nbytes = int(n) * np.dtype(dtype).itemsize
        if nbytes > self.budget:
            self.overflows += 1
            return np.empty(n, dtype)
        buf = getattr(self._tls, "buf", None)
        if buf is None or buf.nbytes < nbytes:
            size = max(nbytes, min(self.budget,
                                   max(64 << 10, nbytes * 2)))
            buf = self._tls.buf = np.empty(size, np.uint8)
            # plain max: a statistic (stats plane), not a correctness
            # counter — same discipline as Tablet.touches
            self.high_water = max(self.high_water, size)
        return buf[:nbytes].view(dtype)

    def stats(self) -> dict:
        return {"budget": self.budget,
                "cacheBudget": self.cache_budget,
                "cacheBytes": int(getattr(self._tls, "cache_bytes",
                                          0)),
                "highWater": self.high_water,
                "overflows": self.overflows}


# -- group-varint at-rest stream (native fast path + numpy fallback) ---

_GV_WIDTH = np.array([1, 2, 4, 8], np.int64)


def gv_encode_np(uids: np.ndarray) -> bytes:
    """Pure-numpy group-varint delta encoder, byte-identical to the
    native dgt_gv_encode stream (native.cc:984): u64 count, u64 first
    uid, then groups of <=4 deltas behind a 2-bit-per-slot width tag."""
    a = np.ascontiguousarray(np.asarray(uids, np.uint64))
    n = len(a)
    head = int(n).to_bytes(8, "little")
    if n == 0:
        return head
    d = np.diff(a)  # uint64, wraps like the native subtraction
    wc = np.zeros(len(d), np.uint8)
    wc[d >= (1 << 8)] = 1
    wc[d >= (1 << 16)] = 2
    wc[d >= (1 << 32)] = 3
    widths = _GV_WIDTH[wc]
    ng = (len(d) + 3) // 4
    wcp = np.zeros(ng * 4, np.uint8)
    wcp[:len(d)] = wc
    tags = (wcp.reshape(ng, 4)
            * np.array([1, 4, 16, 64], np.uint8)).sum(
                axis=1).astype(np.uint8)
    cw = np.cumsum(widths) - widths        # delta payload bytes before i
    # delta i sits after 16 header bytes, (i//4 + 1) tag bytes, cw[i]
    pos = 16 + (np.arange(len(d)) // 4) + 1 + cw
    total = 16 + ng + int(widths.sum())
    out = np.zeros(total, np.uint8)
    out[:8] = np.frombuffer(head, np.uint8)
    out[8:16] = np.frombuffer(a[:1].tobytes(), np.uint8)
    out[16 + cw[::4][:ng] + np.arange(ng)] = tags
    j = np.arange(int(widths.sum())) - np.repeat(cw, widths)
    src = (d[np.repeat(np.arange(len(d)), widths)]
           >> (np.uint64(8) * j.astype(np.uint64))) & np.uint64(0xFF)
    out[np.repeat(pos, widths) + j] = src.astype(np.uint8)
    return out.tobytes()


def gv_decode_np(buf: bytes) -> np.ndarray:
    """Pure-numpy decoder for the dgt_gv stream (parity'd fallback;
    native.cc:1011)."""
    raw = np.frombuffer(buf, np.uint8)
    if len(raw) < 8:
        raise ValueError("gv decode: truncated header")
    n = int(np.frombuffer(buf[:8], np.uint64)[0])
    if n == 0:
        return np.empty(0, np.uint64)
    if len(raw) < 16:
        raise ValueError("gv decode: truncated first uid")
    first = np.frombuffer(buf[8:16], np.uint64)[0]
    nd = n - 1
    ng = (nd + 3) // 4
    # tag positions depend on prior groups' widths: one cheap python
    # pass over GROUPS (n/4) finds them, the byte gather is vectorized
    tag_pos = np.zeros(ng, np.int64)
    wc = np.zeros(nd, np.uint8)
    p = 16
    for g in range(ng):
        if p >= len(raw):
            raise ValueError("gv decode: truncated tag")
        tag_pos[g] = p
        tag = int(raw[p])
        cnt = min(4, nd - g * 4)
        codes = (tag >> (2 * np.arange(cnt))) & 3
        wc[g * 4: g * 4 + cnt] = codes
        p += 1 + int(_GV_WIDTH[codes].sum())
    if p > len(raw):
        raise ValueError("gv decode: truncated payload")
    widths = _GV_WIDTH[wc]
    cw = np.cumsum(widths) - widths
    pos = np.repeat(tag_pos, np.minimum(
        4, nd - np.arange(ng) * 4)) + 1 + (cw - cw[(np.arange(nd)
                                                    // 4) * 4])
    j = np.arange(int(widths.sum())) - np.repeat(cw, widths)
    b = raw[np.repeat(pos, widths) + j].astype(np.uint64) \
        << (np.uint64(8) * j.astype(np.uint64))
    d = np.zeros(nd, np.uint64)
    np.add.at(d, np.repeat(np.arange(nd), widths), b)
    out = np.empty(n, np.uint64)
    out[0] = first
    np.cumsum(d, out=out[1:])
    out[1:] += first
    return out


_GV_W_OF = {0: 1, 1: 2, 2: 4, 3: 8}


def _gv_encode_py_small(a) -> bytes:
    """Scalar encoder for SHORT lists, byte-identical to gv_encode_np
    (parity fuzz-tested in tests/test_codec_compressed.py). The numpy
    path pays ~30 µs of fixed array-op overhead per call; posting
    surfaces are dominated by short lists (fan-out medians of a few,
    singleton index tokens), and at bulk-ingest scale the per-list
    encode overhead was the single largest line item of writing a
    reduced shard's snapshot. Crossover measured at ~48-64 uids."""
    n = len(a)
    out = bytearray(n.to_bytes(8, "little"))
    if n == 0:
        return bytes(out)
    vals = a.tolist() if isinstance(a, np.ndarray) else list(a)
    out += int(vals[0]).to_bytes(8, "little")
    i = 1
    while i < n:
        grp = vals[i - 1:i + 4]
        tag = 0
        payload = bytearray()
        for k in range(len(grp) - 1):
            d = (grp[k + 1] - grp[k]) % (1 << 64)
            code = 0 if d < (1 << 8) else 1 if d < (1 << 16) \
                else 2 if d < (1 << 32) else 3
            tag |= code << (2 * k)
            payload += d.to_bytes(_GV_W_OF[code], "little")
        out.append(tag)
        out += payload
        i += 4
    return bytes(out)


def gv_encode(uids: np.ndarray) -> bytes:
    """Group-varint delta stream: native dgt_gv_encode when the
    toolchain built (the SSE-decode lineage the reference uses via
    go-groupvarint), byte-identical numpy fallback otherwise (scalar
    for short lists — below the numpy fixed overhead's crossover)."""
    from dgraph_tpu import native
    if native.available():
        return native.gv_encode(np.asarray(uids, np.uint64))
    if len(uids) < 48:
        return _gv_encode_py_small(uids)
    return gv_encode_np(uids)


def gv_decode(buf: bytes) -> np.ndarray:
    from dgraph_tpu import native
    if native.available():
        return native.gv_decode(buf)
    return gv_decode_np(buf)
