"""Brute-force MIPS top-k kernels for similar_to().

Design follows the two retrieved papers (PAPERS.md):

  TPU-KNN: K Nearest Neighbor Search at Peak FLOP/s (2206.14286) —
    brute-force scoring IS a matmul, so a (q, d) x (d, n) dot runs at
    peak MXU throughput; the expensive part is not scoring but the
    top-k reduction over the n axis.

  A Faster Generalized Two-Stage Approximate Top-K (2506.04165) —
    replace the O(n log n)-ish exact top-k with: (1) partial reduce —
    split the n axis into `nb` buckets and take each bucket's top-L
    candidates with a cheap max/argmax (L small); (2) exact
    jax.lax.top_k over the nb*L surviving candidates. For a random
    corpus permutation the expected recall@k is
        E[recall] >= 1 - (k-1) / (2 * nb)          (L = 1)
    so the bucket count is chosen from the recall target and the
    kernel FALLS BACK to exact top-k whenever the corpus cannot
    sustain nb >= (k-1) / (2 * (1 - target)).

Three tiers, matching the repo's conventions:
  host    — numpy exact (float64 accumulate) for small/dirty data;
  device  — jitted scoring + two-stage/exact lax.top_k; scoring can
            route through a Pallas MXU tile kernel behind the existing
            `use_pallas` opt-in convention (ops/bitgraph.py: None
            resolves to False, callers own warmup+fallback);
  sharded — corpus rows sharded over a mesh axis via shard_map
            (parallel/dist_knn.py), per-shard top-k then a k-way merge.

Scores are "higher is better" for every metric: dot is the raw inner
product, cosine normalizes both sides, euclidean is the NEGATED
squared L2 distance (argmax order == nearest order).
"""

from __future__ import annotations

import math
from functools import lru_cache, partial

import numpy as np

import jax

METRICS = ("cosine", "dot", "euclidean")

# two-stage engages only above this corpus size — below it the exact
# top_k is already cheap and the bucket shuffle pure overhead
TWO_STAGE_MIN_ROWS = 4096
BUCKET_SIZE = 128          # n-axis bucket width (lane-aligned)
RECALL_TARGET = 0.99


def expected_loss(nb: int, k: int, l_per_bucket: int) -> float:
    """Expected fraction of the true top-k the two-stage reduce loses,
    for a random corpus order over nb buckets keeping L candidates per
    bucket (2506.04165 §3 collision analysis): item ranked i is lost
    iff its bucket already holds >= L higher-ranked items, so the
    per-item loss is ~ C(i, L)/nb^L and the mean over i < k is
    C(k, L+1) / (k * nb^L)."""
    if k <= l_per_bucket:
        return 0.0
    return math.comb(k, l_per_bucket + 1) / (k * float(nb) ** l_per_bucket)


def plan_two_stage(n: int, k: int,
                   recall: float = RECALL_TARGET) -> int:
    """Candidates-per-bucket L for the two-stage path, or 0 for exact
    fallback. Picks the smallest L in {1, 2} whose EXPECTED loss is
    under a quarter of the recall budget (4x margin so an empirical
    recall assert at `recall` holds with room to spare); corpora too
    small to bucket, or k too large for the budget, fall back to
    exact — the acceptance contract."""
    if n < TWO_STAGE_MIN_ROWS:
        return 0
    nb = n // BUCKET_SIZE
    budget = (1.0 - recall) / 4.0
    for l_per_bucket in (1, 2):
        if expected_loss(nb, k, l_per_bucket) <= budget:
            return l_per_bucket
    return 0


def can_two_stage(n: int, k: int, recall: float = RECALL_TARGET) -> bool:
    return plan_two_stage(n, k, recall) > 0


# ---------------------------------------------------------------------------
# host tier (exact, float64 accumulation)
# ---------------------------------------------------------------------------


def score_host(corpus: np.ndarray, queries: np.ndarray,
               metric: str) -> np.ndarray:
    """(n, d) x (q, d) -> (q, n) float64 scores, higher = closer."""
    c = np.asarray(corpus, np.float64)
    q = np.atleast_2d(np.asarray(queries, np.float64))
    if metric == "cosine":
        cn = np.linalg.norm(c, axis=1)
        qn = np.linalg.norm(q, axis=1)
        dots = q @ c.T
        denom = np.outer(qn, cn)
        with np.errstate(invalid="ignore", divide="ignore"):
            out = np.where(denom > 0, dots / np.where(denom > 0, denom, 1),
                           0.0)
        return out
    if metric == "dot":
        return q @ c.T
    if metric == "euclidean":
        c2 = np.sum(c * c, axis=1)
        q2 = np.sum(q * q, axis=1)
        return -(q2[:, None] - 2.0 * (q @ c.T) + c2[None, :])
    raise ValueError(f"unknown metric {metric!r}")


def _topk_rows(scores: np.ndarray, k: int
               ) -> tuple[np.ndarray, np.ndarray]:
    """Per-row exact top-k with (-score, idx) order over a (q, n)
    float matrix that may contain -inf for masked rows."""
    q, n = scores.shape
    k_eff = min(k, n)
    if k_eff == 0:
        return (np.empty((q, 0), np.int64), np.empty((q, 0), scores.dtype))
    if k_eff < n:
        part = np.argpartition(-scores, k_eff - 1, axis=1)[:, :k_eff]
    else:
        part = np.tile(np.arange(n), (q, 1))
    psc = np.take_along_axis(scores, part, axis=1)
    order = np.lexsort((part, -psc), axis=1)
    idx = np.take_along_axis(part, order, axis=1)
    sc = np.take_along_axis(psc, order, axis=1)
    return idx.astype(np.int64), sc


def topk_host(corpus: np.ndarray, queries: np.ndarray, k: int,
              metric: str = "cosine",
              mask: np.ndarray | None = None
              ) -> tuple[np.ndarray, np.ndarray]:
    """Exact top-k: (idx (q, k'), scores (q, k')) sorted by
    (-score, idx) — the deterministic tiebreak every tier shares."""
    scores = score_host(corpus, queries, metric)
    if mask is not None:
        scores = np.where(np.asarray(mask, bool)[None, :], scores, -np.inf)
    idx, sc = _topk_rows(scores, k)
    # rows are score-descending so -inf entries (masked/absent rows)
    # form a suffix per row; keep the widest per-query valid width and
    # let callers trim per query on -inf
    finite = np.isfinite(sc)
    if not finite.all():
        keep = int(finite.sum(axis=1).max(initial=0))
        idx, sc = idx[:, :keep], sc[:, :keep]
    return idx, sc


# ---------------------------------------------------------------------------
# device tier
# ---------------------------------------------------------------------------


def _score_device(corpus, queries, metric: str, use_pallas: bool,
                  pallas_interpret):
    import jax.numpy as jnp

    if use_pallas:
        from dgraph_tpu.ops.pallas_kernels import score_dot_pallas
        dots = score_dot_pallas(corpus, queries,
                                interpret=pallas_interpret)
    else:
        dots = jnp.dot(queries, corpus.T,
                       preferred_element_type=jnp.float32)
    if metric == "dot":
        return dots
    if metric == "cosine":
        cn = jnp.sqrt(jnp.sum(corpus * corpus, axis=1))
        qn = jnp.sqrt(jnp.sum(queries * queries, axis=1))
        denom = qn[:, None] * cn[None, :]
        return jnp.where(denom > 0, dots / jnp.where(denom > 0, denom, 1),
                         0.0)
    if metric == "euclidean":
        c2 = jnp.sum(corpus * corpus, axis=1)
        q2 = jnp.sum(queries * queries, axis=1)
        return -(q2[:, None] - 2.0 * dots + c2[None, :])
    raise ValueError(f"unknown metric {metric!r}")


@lru_cache(maxsize=64)
def _dispersal_perm(n_pad: int) -> np.ndarray:
    """Deterministic row-dispersal permutation for the two-stage
    bucketing. The recall bound assumes rows land in buckets at
    random, but the scored block is packed uid-ASCENDING — near-
    duplicate embeddings ingested under consecutive uids would share
    one bucket and break the bound. A multiplicative stride coprime
    with n_pad (golden-ratio start) sends any run of consecutive rows
    to positions `stride` apart, i.e. distinct buckets, restoring the
    TPU-KNN precondition without an RNG (stable across processes)."""
    stride = (int(0.6180339887 * n_pad) | 1) or 1
    while math.gcd(stride, n_pad) != 1:
        stride += 2
    # original row j lands at permuted slot (j * stride) % n_pad — the
    # golden stride's three-distance spreading is what disperses runs.
    # As a GATHER (slot i reads original perm[i]) that is the modular
    # inverse; perm doubles as the slot -> original index map.
    inv = pow(stride, -1, n_pad)
    return ((np.arange(n_pad, dtype=np.int64) * inv) % n_pad
            ).astype(np.int32)


def _two_stage_topk_dev(scores, k: int, l_per_bucket: int):
    """Bucketed approximate-then-exact top-k on device. scores is
    (q, n_pad) with -inf in padded/masked columns; returns (vals, idx)
    over the padded axis."""
    import jax.numpy as jnp

    qn, n_pad = scores.shape
    nb = n_pad // BUCKET_SIZE
    # disperse uid-contiguous rows across buckets (see _dispersal_perm)
    perm = jnp.asarray(_dispersal_perm(n_pad))
    scores = scores[:, perm]
    bucketed = scores.reshape(qn, nb, BUCKET_SIZE)
    # stage 1: partial reduce — top-L inside each bucket (L=1 is a
    # plain max+argmax, the TPU-KNN PartialReduce)
    if l_per_bucket == 1:
        bvals = jnp.max(bucketed, axis=2)                     # (q, nb)
        barg = jnp.argmax(bucketed, axis=2)                   # (q, nb)
        cand_vals = bvals
        cand_idx = barg + jnp.arange(nb, dtype=jnp.int32)[None, :] \
            * BUCKET_SIZE
    else:
        bvals, barg = jax.lax.top_k(bucketed, l_per_bucket)   # (q, nb, L)
        base = (jnp.arange(nb, dtype=jnp.int32) * BUCKET_SIZE)[None, :,
                                                               None]
        cand_vals = bvals.reshape(qn, nb * l_per_bucket)
        cand_idx = (barg + base).reshape(qn, nb * l_per_bucket)
    # stage 2: exact top-k over the nb*L candidates, mapped back to
    # the unpermuted row axis
    vals, pos = jax.lax.top_k(cand_vals, min(k, cand_vals.shape[1]))
    idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    return vals, perm[idx]


@partial(jax.jit,
         static_argnames=("k", "metric", "two_stage", "l_per_bucket",
                          "use_pallas", "pallas_interpret", "n_real"))
def _topk_device_jit(corpus, queries, mask, k, metric, two_stage,
                     l_per_bucket, use_pallas, pallas_interpret, n_real):
    import jax.numpy as jnp

    scores = _score_device(corpus, queries, metric, use_pallas,
                           pallas_interpret)
    n_pad = scores.shape[1]
    col = jnp.arange(n_pad)
    invalid = col[None, :] >= n_real
    if mask is not None:
        invalid = invalid | ~mask[None, :]
    scores = jnp.where(invalid, -jnp.inf, scores)
    if two_stage:
        return _two_stage_topk_dev(scores, k, l_per_bucket)
    return jax.lax.top_k(scores, min(k, n_pad))


def pad_rows(corpus: np.ndarray, unit: int = BUCKET_SIZE) -> np.ndarray:
    """Zero-pad the row axis to a `unit` multiple (host-side, ONCE per
    block build) so topk_device never copies the corpus per query."""
    n, d = corpus.shape
    n_pad = max(unit, ((n + unit - 1) // unit) * unit)
    if n_pad == n:
        return corpus
    out = np.zeros((n_pad, d), np.float32)
    out[:n] = corpus
    return out


def topk_device(corpus_dev, queries: np.ndarray, k: int,
                metric: str = "cosine",
                mask: np.ndarray | None = None,
                two_stage: bool | None = None,
                l_per_bucket: int | None = None,
                use_pallas: bool | None = None,
                pallas_interpret: bool | None = None,
                n_real: int | None = None
                ) -> tuple[np.ndarray, np.ndarray]:
    """Device top-k over a (possibly already device-resident) corpus.
    Returns host (idx (q, k'), scores (q, k')) — idx into the corpus
    row axis; rows masked out / padded return -inf scores.

    `n_real` marks a corpus whose trailing rows are zero padding
    (pad_rows): only the first n_real rows are live. Hot-path callers
    should pre-pad their cached block so no per-query device copy
    happens here.

    two_stage=None auto-selects the bucketed approximate path when the
    corpus can hold the RECALL_TARGET bound and falls back to exact
    lax.top_k otherwise (the acceptance contract). use_pallas follows
    the repo convention: None resolves to False (ops/bitgraph.py)."""
    import jax.numpy as jnp

    corpus_dev = jnp.asarray(corpus_dev, jnp.float32)
    n_rows, d = corpus_dev.shape
    n = n_rows if n_real is None else int(n_real)
    q = jnp.atleast_2d(jnp.asarray(queries, jnp.float32))
    if use_pallas is None:
        use_pallas = False
    # pad the n axis so buckets tile exactly (and pallas tiles align —
    # SCORE_TILE_N is a multiple of BUCKET_SIZE); padding scores are
    # forced to -inf via n_real
    unit = BUCKET_SIZE
    if use_pallas:
        from dgraph_tpu.ops.pallas_kernels import SCORE_TILE_N
        unit = SCORE_TILE_N
    n_pad = max(unit, ((n_rows + unit - 1) // unit) * unit)
    if n_pad != n_rows:
        corpus_dev = jnp.concatenate(
            [corpus_dev, jnp.zeros((n_pad - n_rows, d), jnp.float32)])
    plan = plan_two_stage(n, k)
    if two_stage is None:
        two_stage = plan > 0
    elif two_stage and plan == 0:
        two_stage = False  # contract: fall back to exact when the
        #                    bucket count can't hold the recall target
    if l_per_bucket is None:
        l_per_bucket = max(plan, 1)
    mask_dev = None
    if mask is not None:
        m = np.zeros(n_pad, bool)
        m[:n] = np.asarray(mask, bool)
        mask_dev = jnp.asarray(m)
    vals, idx = _topk_device_jit(
        corpus_dev, q, mask_dev, int(k), str(metric), bool(two_stage),
        int(l_per_bucket), bool(use_pallas),
        pallas_interpret if pallas_interpret is None
        else bool(pallas_interpret), int(n))
    vals = np.asarray(vals)
    idx = np.asarray(idx, np.int64)
    # deterministic tiebreak to match the host tier: lax.top_k is
    # stable by index already (ties keep the lower index first)
    return idx, vals


# ---------------------------------------------------------------------------
# k-way merge (per-shard / base+overlay partial results)
# ---------------------------------------------------------------------------


def merge_topk(parts: list[tuple[np.ndarray, np.ndarray]], k: int
               ) -> tuple[np.ndarray, np.ndarray]:
    """Merge [(uids, scores), ...] partial top-k lists into the global
    top-k, ordered by (-score, uid) — the k-way merge after per-shard
    top-k (ref algo/uidlist.go MergeSorted role, score-ordered)."""
    parts = [(np.asarray(u, np.uint64), np.asarray(s, np.float64))
             for u, s in parts if len(np.atleast_1d(u))]
    if not parts:
        return np.empty(0, np.uint64), np.empty(0, np.float64)
    uids = np.concatenate([u for u, _ in parts])
    scores = np.concatenate([s for _, s in parts])
    ok = np.isfinite(scores)
    uids, scores = uids[ok], scores[ok]
    # a uid may appear in several parts (base block + overlay rows
    # must not — callers mask — but be safe): keep its best score
    order = np.lexsort((uids, -scores))
    uids, scores = uids[order], scores[order]
    seen = set()
    out_u, out_s = [], []
    for u, s in zip(uids.tolist(), scores.tolist()):
        if u in seen:
            continue
        seen.add(u)
        out_u.append(u)
        out_s.append(s)
        if len(out_u) == k:
            break
    return np.asarray(out_u, np.uint64), np.asarray(out_s, np.float64)
