"""Multi-hop traversal kernels: BFS frontiers and SSSP relaxation.

TPU re-design of the reference's graph algorithms:
  - query/recurse.go:29   per-level goroutine fan-out over posting lists
  - query/shortest.go:451 route()/Dijkstra with a priority queue
  - query/shortest.go:287 k-shortest paths

Both become dense frontier algebra over the resident adjacency tiles
(ops/graph.py): BFS is `depth` rounds of expand + difference-vs-visited;
SSSP is Bellman-Ford-style relaxation — per round, every bucket does one
gather of source distances, one vectorized add of edge weight, and one
scatter-min onto the distance vector.  No queues, no per-node control
flow; compiled once per (adjacency shape, seed bucket, depth).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.graph import DeviceAdjacency, expand, max_expansion
from dgraph_tpu.ops.uidvec import SENTINEL, compact, member_mask, pad_to

INT32_INF = np.int32(2**31 - 1)


def make_bfs(adj: DeviceAdjacency, seed_size: int, depth: int,
             dedup: bool = True) -> Callable:
    """Compile a BFS: seeds [seed_size] -> tuple of per-level frontiers.

    Level sizes are static, derived from max_expansion per level and
    capped by the distinct-node bound, so the whole unrolled traversal
    is one XLA program. With dedup=False this matches @recurse's
    loop:true mode (ref gql RecurseArgs.AllowLoop).
    """
    sizes = [seed_size]
    for _ in range(depth):
        sizes.append(max_expansion(adj, sizes[-1]))

    def bfs(seeds: jax.Array):
        levels = []
        frontier = seeds
        visited = seeds
        for d in range(depth):
            nxt = expand(adj, frontier, sizes[d + 1])
            if dedup:
                keep = ~member_mask(nxt, visited)
                nxt = compact(jnp.where(keep, nxt, SENTINEL))
                visited = compact(
                    jnp.concatenate([visited, nxt]))[: visited.shape[0]
                                                     + nxt.shape[0]]
            levels.append(nxt)
            frontier = nxt
        return tuple(levels)

    return jax.jit(bfs)


def bfs_reach(adj: DeviceAdjacency, seeds_np: np.ndarray, depth: int,
              dedup: bool = True) -> list[np.ndarray]:
    """Host wrapper: run BFS, return per-level frontier uid arrays."""
    from dgraph_tpu.ops.uidvec import from_numpy, to_numpy

    seeds_np = np.sort(np.asarray(seeds_np, dtype=np.uint32))
    seed_size = pad_to(len(seeds_np))
    fn = make_bfs(adj, seed_size, depth, dedup)
    levels = fn(from_numpy(seeds_np, seed_size))
    return [to_numpy(lv) for lv in levels]


# ---------------------------------------------------------------------------
# SSSP: hop-count (or uniform-weight) distances via frontier relaxation
# ---------------------------------------------------------------------------


def make_sssp(adj: DeviceAdjacency, max_iters: int) -> Callable:
    """Compile single-source (or multi-source) shortest hop-count
    distances over this adjacency.

    Returns fn(seed_mask_uids [S]) -> (node_uids [N], dist [N] int32)
    where node_uids is the adjacency's source vector augmented with
    nothing — distances are tracked for *source* slots; destinations
    that are never sources still get found through the frontier value
    but their final distance comes from the frontier levels.

    Implementation: dist over the adjacency's src slot space; per
    round, for each bucket gather dist of its rows, add 1, scatter-min
    into the slots of the neighbor uids (searchsorted into src_uids).
    Neighbors that are not sources are leaves: they cannot relax
    further, so BFS levels (bfs_reach) cover them; route reconstruction
    happens host-side from the level sets (ref query/shortest.go route).
    """
    src = adj.src_uids
    n = src.shape[0]

    def sssp(seeds: jax.Array):
        seeded = member_mask(src, seeds)
        dist = jnp.where(seeded, jnp.int32(0), INT32_INF)
        for _ in range(max_iters):
            for b in adj.buckets:
                rows = jnp.clip(jnp.searchsorted(src, b.src), 0, n - 1)
                ok = (src[rows] == b.src) & (b.src != SENTINEL)
                d_here = jnp.where(ok, dist[rows], INT32_INF)  # [M]
                cand = jnp.where(
                    (d_here < INT32_INF)[:, None]
                    & (b.neighbors != SENTINEL),
                    d_here[:, None] + 1, INT32_INF)            # [M, D]
                tgt = jnp.clip(jnp.searchsorted(src, b.neighbors.reshape(-1)),
                               0, n - 1)
                tgt_ok = src[tgt] == b.neighbors.reshape(-1)
                tgt = jnp.where(tgt_ok, tgt, n - 1)
                upd = jnp.where(tgt_ok, cand.reshape(-1), INT32_INF)
                dist = dist.at[tgt].min(upd)
        return src, dist

    return jax.jit(sssp)
