"""Bit-parallel batched Levenshtein (Myers 1999 / Hyyrö 2001).

The reference verifies match() candidates with a per-value DP loop
(worker/match.go:35 levenshteinDistance); our native C++ kernel does
the same in C. When the extension isn't built, the executor's fallback
was a per-uid *Python* DP — the whole q015 budget. This module runs
the verify for EVERY candidate at once as ~15 numpy uint64 bit-ops per
payload byte column: the pattern is encoded as per-character position
bitmasks and the DP column is carried as two bit-vectors (PV/MV) per
candidate row, so the work is O(max_len) vectorized passes instead of
O(n * |a| * |b|) interpreted steps.

Byte-level scores equal the codepoint-level distances only for ASCII
rows; non-ASCII rows come back as -1 and the caller re-verifies them
on the exact per-uid path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def levenshtein_scores(want: str, mat: np.ndarray,
                       lens: np.ndarray) -> Optional[np.ndarray]:
    """Edit distances of `want` against N byte rows.

    mat:  (N, W) uint8 payload matrix, rows NUL-padded past lens[i]
    lens: (N,) int payload byte lengths

    Returns int64 scores with -1 marking rows the byte-level pass
    cannot answer (non-ASCII payload bytes — '.'-width differs), or
    None when the PATTERN itself is outside the kernel's domain
    (empty, non-ASCII, or longer than 63 chars — one uint64 word)."""
    m = len(want)
    if m == 0 or m > 63 or not want.isascii():
        return None
    n, width = mat.shape
    if n == 0:
        return np.empty(0, np.int64)
    lens = np.asarray(lens, np.int64)
    peq = np.zeros(256, np.uint64)
    for i, ch in enumerate(want.encode("ascii")):
        peq[ch] |= np.uint64(1 << i)
    pv = np.full(n, (1 << m) - 1, np.uint64)
    mv = np.zeros(n, np.uint64)
    score = np.full(n, m, np.int64)
    out = np.where(lens == 0, np.int64(m), np.int64(-1))
    high = np.uint64(1 << (m - 1))
    one = np.uint64(1)
    full = ~np.uint64(0)
    for j in range(int(lens.max())):
        eq = peq[mat[:, j]]
        xv = eq | mv
        xh = (((eq & pv) + pv) ^ pv) | eq
        ph = mv | ((xh | pv) ^ full)
        mh = pv & xh
        delta = ((ph & high) != 0).astype(np.int64) \
            - ((mh & high) != 0).astype(np.int64)
        ph = (ph << one) | one
        mh = mh << one
        npv = mh | ((xv | ph) ^ full)
        nmv = ph & xv
        active = j < lens
        score = np.where(active, score + delta, score)
        pv = np.where(active, npv, pv)
        mv = np.where(active, nmv, mv)
        out = np.where(lens == j + 1, score, out)
    # byte-level == codepoint-level only for pure-ASCII rows; padding
    # bytes are NUL (< 0x80), so a whole-row test is exact
    out[(mat >= 0x80).any(axis=1)] = -1
    return out
