"""Pallas TPU kernels for the traversal hot path.

The batched BFS level (ops/bitgraph.make_bfs_bits_batched) is a
row-gather + OR-reduce: for every adjacency row r with in-neighbors
nb[r, 0..D), OR the frontier bitmap rows f[nb[r, d]] together. Under
XLA this is D separate gathers; the Pallas version maps it onto the
TPU memory system directly with the scalar-prefetch pattern
(pallas_guide: PrefetchScalarGridSpec): the in-neighbor indices are
prefetched to SMEM, the BlockSpec index_map uses them to DMA exactly
the frontier row each grid step needs HBM->VMEM, and the kernel is a
single VPU OR into the output row accumulated across the degree axis
(TPU grids execute sequentially, so revisiting the same output block
accumulates).

Interpret mode runs the same kernel on CPU for CI parity; real
compilation happens on TPU. Callers must pad the word axis W to a
multiple of 128 (lane width).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax >= 0.6 wants an InterpretParams object to simulate TPU kernels;
# jax <= 0.4 takes interpret=True directly
_INTERPRET_ON = (pltpu.InterpretParams()
                 if hasattr(pltpu, "InterpretParams") else True)

# Max int32 scalar-prefetch elements one kernel instance can hold in
# SMEM (v5e: 2^17 passes, 2^18 fails the Mosaic compile). Buckets whose
# flattened in-neighbor table exceeds this are split across calls.
SMEM_IDX_CAPACITY = 1 << 17


def bucket_or_pallas(f: jax.Array, in_nb: jax.Array,
                     interpret: bool | None = None) -> jax.Array:
    """OR of gathered frontier rows: f uint32[N+1, W], in_nb
    int32[M, D] -> uint32[M, W] where out[m] = OR_d f[in_nb[m, d]].
    Rows that pad with the dummy slot index N contribute zeros exactly
    like the XLA path (f's last row is the always-empty dummy)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, d = in_nb.shape
    w = f.shape[1]
    if w % 128 != 0:
        raise ValueError(f"W={w} must be a multiple of 128 lanes")

    def kernel(idx_ref, f_row, out_ref):
        del idx_ref  # consumed by the index_map, not the body
        step = pl.program_id(1)

        @pl.when(step == 0)
        def _init():
            out_ref[...] = f_row[...]

        @pl.when(step != 0)
        def _acc():
            out_ref[...] = out_ref[...] | f_row[...]

    # Mosaic requires a block's last-two dims to be (8k, 128k)-divisible
    # OR equal to the array's own trailing dims; a (1, W) block over a
    # 2-D [N, W] array violates the sublane rule. Lift to [N, 1, W] so
    # the (1, 1, W) block's trailing dims exactly match the array.
    f3 = f[:, None, :]

    def one_call(nb_chunk: jax.Array) -> jax.Array:
        cm, cd = nb_chunk.shape
        # the prefetched index vector lives in SMEM: it must be FLAT
        # (2-D scalar arrays fail Mosaic above ~1k rows) and within
        # capacity (2^17 int32 ≈ 512 KiB, measured on v5e — larger
        # buckets are chunked below)
        flat_idx = nb_chunk.reshape(-1)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(cm, cd),
            in_specs=[
                pl.BlockSpec((1, 1, w),
                             lambda i, j, idx: (idx[i * cd + j], 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, w), lambda i, j, idx: (i, 0, 0)),
        )
        out = pl.pallas_call(
            kernel, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((cm, 1, w), jnp.uint32),
            # CPU CI simulates the TPU kernel; on real TPU this
            # compiles through Mosaic
            interpret=_INTERPRET_ON if interpret else False,
        )(flat_idx, f3)
        return out[:, 0, :]

    def dispatch(nb: jax.Array) -> jax.Array:
        cm, cd = nb.shape
        if cm * cd <= SMEM_IDX_CAPACITY:
            return one_call(nb)
        if cd > SMEM_IDX_CAPACITY:
            # mega-hub rows: one row's in-neighbors alone overflow
            # SMEM — split the degree axis and OR the partial
            # expansions (OR is associative, padding rows stay
            # all-zero through every part)
            acc = None
            for s in range(0, cd, SMEM_IDX_CAPACITY):
                p = dispatch(nb[:, s:s + SMEM_IDX_CAPACITY])
                acc = p if acc is None else acc | p
            return acc
        rows_per = max(1, SMEM_IDX_CAPACITY // cd)
        return jnp.concatenate([one_call(nb[s:s + rows_per])
                                for s in range(0, cm, rows_per)])

    return dispatch(in_nb)


# -- MIPS scoring tile kernel (ops/knn.py similar_to data plane) -------------

# corpus rows per MXU tile: (SCORE_TILE_N, d) corpus block + (b, d)
# queries + (b, SCORE_TILE_N) out must fit VMEM; at d = 1024 f32 this
# is ~2.5 MiB, comfortably inside the ~16 MiB/core budget
SCORE_TILE_N = 512


def score_dot_pallas(corpus: jax.Array, queries: jax.Array,
                     interpret: bool | None = None) -> jax.Array:
    """Tiled (b, d) x (d, n) -> (b, n) float32 dot scores on the MXU:
    grid over n-axis tiles, each step DMAs one (TILE, d) corpus block
    HBM->VMEM, the queries stay resident, one jnp.dot per tile. This is
    the TPU-KNN scoring matmul written as an explicit Pallas pipeline
    (pallas_guide: Grid and Block Specifications); the XLA path in
    ops/knn._score_device emits the same contraction — callers opt in
    via use_pallas (same convention as bucket_or_pallas)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = corpus.shape
    b = queries.shape[0]
    if n % SCORE_TILE_N != 0:
        raise ValueError(
            f"corpus rows {n} must be a multiple of {SCORE_TILE_N} "
            "(ops/knn pads)")

    def kernel(c_ref, q_ref, out_ref):
        out_ref[...] = jnp.dot(q_ref[...], c_ref[...].T,
                               preferred_element_type=jnp.float32)

    return pl.pallas_call(
        kernel,
        grid=(n // SCORE_TILE_N,),
        in_specs=[
            pl.BlockSpec((SCORE_TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((b, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((b, SCORE_TILE_N), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=_INTERPRET_ON if interpret else False,
    )(corpus, queries)


def score_int8_pallas(codes: jax.Array, queries: jax.Array,
                      interpret: bool | None = None) -> jax.Array:
    """Dequant-and-dot tile kernel for the quantized ANN tier
    (ops/ivf.py): int8 residual codes (n, d) x float32 queries (b, d)
    -> (b, n) float32 approximate dots. Same pipeline shape as
    score_dot_pallas — one (TILE, d) codes block DMAd HBM->VMEM per
    grid step, queries resident — with the int8 -> f32 convert fused
    into the tile so the MXU contraction reads the narrow form
    straight out of VMEM (TPU-KNN's peak-FLOP/s recipe at a quarter
    of the HBM traffic). Per-row dequant scales and the centroid dot
    term are rank-1 postprocessing the caller applies. XLA parity
    fallback: score_int8_xla."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = codes.shape
    b = queries.shape[0]
    if n % SCORE_TILE_N != 0:
        raise ValueError(
            f"code rows {n} must be a multiple of {SCORE_TILE_N} "
            "(ops/ivf pads)")

    def kernel(c_ref, q_ref, out_ref):
        tile = c_ref[...].astype(jnp.float32)
        out_ref[...] = jnp.dot(q_ref[...], tile.T,
                               preferred_element_type=jnp.float32)

    return pl.pallas_call(
        kernel,
        grid=(n // SCORE_TILE_N,),
        in_specs=[
            pl.BlockSpec((SCORE_TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((b, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((b, SCORE_TILE_N), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=_INTERPRET_ON if interpret else False,
    )(codes, queries)


@jax.jit
def score_int8_xla(codes: jax.Array, queries: jax.Array) -> jax.Array:
    """The jitted XLA contraction score_int8_pallas must match
    bit-for-bit semantics-wise — CPU-parity fallback and the
    differential oracle for the tile kernel."""
    return jnp.dot(queries, codes.astype(jnp.float32).T,
                   preferred_element_type=jnp.float32)




# -- bitmap word-AND kernel (ops/setops compressed block plane) --------------

# bitmap blocks per grid step: each step ANDs one (TILE_B, W) slab of
# uint32 words in VMEM; W = 2048 uint32 lanes per 2^16-uid block (the
# uint64 bitmap split into two 32-bit lanes — TPUs have no 64-bit
# integer ALU), a multiple of the 128-lane VPU width
BITMAP_TILE_B = 8


def bitmap_and_pallas(a: jax.Array, b: jax.Array,
                      interpret: bool | None = None) -> jax.Array:
    """Elementwise AND of two stacked bitmap word matrices
    (uint32[B, W], W % 128 == 0): the compressed intersection's dense
    inner loop as an explicit VPU pipeline — each grid step DMAs one
    block row pair HBM->VMEM and ANDs it in one vector op (the SIMD
    bitmap-intersection kernel of "SIMD Compression and the
    Intersection of Sorted Integers", PAPERS.md).  Callers opt in via
    use_pallas (setops.bitmap_and_device), same convention as
    score_dot_pallas."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bsz, w = a.shape
    if w % 128 != 0:
        raise ValueError(f"W={w} must be a multiple of 128 lanes")
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    tile = BITMAP_TILE_B
    pad = (-bsz) % tile
    if pad:
        a = jnp.concatenate(
            [a, jnp.zeros((pad, w), jnp.uint32)])
        b = jnp.concatenate(
            [b, jnp.zeros((pad, w), jnp.uint32)])

    def kernel(a_ref, b_ref, out_ref):
        out_ref[...] = a_ref[...] & b_ref[...]

    out = pl.pallas_call(
        kernel,
        grid=((bsz + pad) // tile,),
        in_specs=[
            pl.BlockSpec((tile, w), lambda i: (i, 0)),
            pl.BlockSpec((tile, w), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bsz + pad, w), jnp.uint32),
        interpret=_INTERPRET_ON if interpret else False,
    )(a, b)
    return out[:bsz]
