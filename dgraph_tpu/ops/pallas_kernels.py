"""Pallas TPU kernels for the traversal hot path.

The batched BFS level (ops/bitgraph.make_bfs_bits_batched) is a
row-gather + OR-reduce: for every adjacency row r with in-neighbors
nb[r, 0..D), OR the frontier bitmap rows f[nb[r, d]] together. Under
XLA this is D separate gathers; the Pallas version maps it onto the
TPU memory system directly with the scalar-prefetch pattern
(pallas_guide: PrefetchScalarGridSpec): the in-neighbor indices are
prefetched to SMEM, the BlockSpec index_map uses them to DMA exactly
the frontier row each grid step needs HBM->VMEM, and the kernel is a
single VPU OR into the output row accumulated across the degree axis
(TPU grids execute sequentially, so revisiting the same output block
accumulates).

Interpret mode runs the same kernel on CPU for CI parity; real
compilation happens on TPU. Callers must pad the word axis W to a
multiple of 128 (lane width).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def bucket_or_pallas(f: jax.Array, in_nb: jax.Array,
                     interpret: bool | None = None) -> jax.Array:
    """OR of gathered frontier rows: f uint32[N+1, W], in_nb
    int32[M, D] -> uint32[M, W] where out[m] = OR_d f[in_nb[m, d]].
    Rows that pad with the dummy slot index N contribute zeros exactly
    like the XLA path (f's last row is the always-empty dummy)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, d = in_nb.shape
    w = f.shape[1]
    if w % 128 != 0:
        raise ValueError(f"W={w} must be a multiple of 128 lanes")

    def kernel(idx_ref, f_row, out_ref):
        del idx_ref  # consumed by the index_map, not the body
        step = pl.program_id(1)

        @pl.when(step == 0)
        def _init():
            out_ref[...] = f_row[...]

        @pl.when(step != 0)
        def _acc():
            out_ref[...] = out_ref[...] | f_row[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m, d),
        in_specs=[
            pl.BlockSpec((1, w), lambda i, j, idx: (idx[i, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, w), lambda i, j, idx: (i, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, w), jnp.uint32),
        # CPU CI simulates the TPU kernel (pltpu.InterpretParams);
        # on real TPU this compiles through Mosaic
        interpret=pltpu.InterpretParams() if interpret else False,
    )(in_nb, f)



